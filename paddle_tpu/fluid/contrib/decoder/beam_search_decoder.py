"""Decoder DSL: InitState / StateCell / TrainingDecoder / BeamSearchDecoder
(reference: python/paddle/fluid/contrib/decoder/beam_search_decoder.py:43,
159,384,523).

Same API, TPU-native mechanism.  The reference's TrainingDecoder drives a
DynamicRNN (while-op re-entering the interpreter per step); here the same
DynamicRNN lowers to ONE masked ``lax.scan``.  The reference's
BeamSearchDecoder grows beams through nested LoD inside a while-op; here
the beam dimension is the static ``[B*K]`` row layout (models/seq2seq.py
decode pattern): a StaticRNN scans ``max_len`` steps, the ``beam_search``
op selects per-step candidates, states are re-wired to their surviving
parents with a ``gather`` on ``parent_idx`` (replacing the reference's
``sequence_expand`` by prev_scores), and ``beam_search_decode`` backtracks
the parent pointers at the end.  ``early_stop`` is a no-op: the scan has a
static trip count and finished beams carry ``end_id`` forward inside the
beam_search op — same results, fixed schedule.
"""

import contextlib

from ... import unique_name
from ...framework import Variable
from ...layer_helper import LayerHelper
from ... import layers

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder']


class _DecoderType(object):
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial hidden state (reference beam_search_decoder.py:43): either
    an explicit variable or a constant-filled one shaped like
    ``init_boot``'s batch."""

    def __init__(self,
                 init=None,
                 shape=None,
                 value=0.0,
                 init_boot=None,
                 need_reorder=False,
                 dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the shape of InitState')
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """State held as an RNN memory (both decoder types use rnn.memory +
    update_memory here; the static [B*K] layout never needs the
    reference's separate array-state path)."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class StateCell(object):
    """Bookkeeping for an RNN cell's inputs/states and the user-defined
    updater (reference beam_search_decoder.py:159).  The updater runs once
    per step under whichever decoder is active."""

    def __init__(self, inputs, states, out_state, name=None):
        self.helper = LayerHelper('state_cell', name=name)
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError('state must be an InitState object')
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = inputs  # inputs is a map of {input_name: input}
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError('StateCell is already used in a decoder')
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder or self._cur_decoder_obj != decoder_obj:
            raise ValueError('not in this decoder')
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Materialize each InitState as a memory of the active decoder's
        RNN (lazy, on first get_state inside the block)."""
        if not self._in_decoder:
            raise ValueError('switched decoder outside a decoder block')
        if self._switched_decoder:
            raise ValueError('decoder switched twice')
        for state_name in self._state_names:
            state = self._cur_states.get(state_name)
            if not isinstance(state, InitState):
                raise ValueError('all states must be InitState before switch')
            self._states_holder[state_name] = _MemoryState(
                state_name, self._cur_decoder_obj._rnn_obj(), state)
            self._cur_states[state_name] = \
                self._states_holder[state_name].get_state()
        self._switched_decoder = True

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError('unknown state %r' % state_name)
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError('input %r not found or not initialized'
                             % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell == self:
                raise TypeError('updater should only accept a StateCell')
            updater(state_cell)

        return _decorator

    def compute_state(self, inputs):
        """Feed this step's inputs and run the user updater."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError('unknown input %r' % input_name)
            self._inputs[input_name] = input_value
        self._state_updater(self)

    def update_states(self):
        """Commit the computed states back into the RNN memories."""
        if self._in_decoder and not self._switched_decoder:
            raise ValueError('update_states before compute_state')
        for state_name, decoder_state in self._states_holder.items():
            decoder_state.update_state(self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Teacher-forced decoder block over a DynamicRNN (reference
    beam_search_decoder.py:384)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper('training_decoder', name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError('decoder.block() can only be invoked once')
        self._status = TrainingDecoder.IN_DECODER
        with self._dynamic_rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    def _rnn_obj(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def step_input(self, x):
        self._assert_in_decoder_block('step_input')
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block('static_input')
        return self._dynamic_rnn.static_input(x)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('output can only be visited outside the block')
        return self._dynamic_rnn(*args, **kwargs)

    def output(self, *outputs):
        self._assert_in_decoder_block('output')
        self._dynamic_rnn.output(*outputs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('%s should be invoked inside the block() of '
                             'TrainingDecoder' % method)


class BeamSearchDecoder(object):
    """Inference beam-search decoder (reference beam_search_decoder.py:523)
    on the static [B*K] beam layout: a StaticRNN of max_len steps; see
    module docstring for the mechanism mapping."""

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self,
                 state_cell,
                 init_ids,
                 init_scores,
                 target_dict_dim,
                 word_dim,
                 input_var_dict=None,
                 topk_size=50,
                 sparse_emb=True,
                 max_len=100,
                 beam_size=1,
                 end_id=1,
                 name=None):
        self._helper = LayerHelper('beam_search_decoder', name=name)
        self._rnn = layers.StaticRNN()
        self._type = _DecoderType.BEAM_SEARCH
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._arrays = {}  # read-value name -> memory var
        self._beam_size = beam_size
        self._end_id = end_id
        self._max_len = max_len
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = input_var_dict or {}
        self._ids_mem = None
        self._scores_mem = None
        self._outputs = None
        self._parent_idx = None

    def _rnn_obj(self):
        return self._rnn

    @contextlib.contextmanager
    def block(self):
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError('block() can only be invoked once')
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        # the ticker drives the StaticRNN for max_len steps; rows follow
        # the [B*K] beam layout of init_scores
        ticker = layers.fill_constant_batch_size_like(
            input=self._init_scores,
            shape=[self._max_len, -1, 1],
            value=0.0,
            dtype='float32',
            input_dim_idx=0,
            output_dim_idx=1)
        with self._rnn.step():
            self._rnn.step_input(ticker)
            yield
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def type(self):
        return self._type

    def early_stop(self):
        """No-op on the static layout: the scan runs its fixed trip count
        and finished beams carry end_id through the beam_search op."""

    def decode(self):
        """The default decode loop (reference beam_search_decoder.py:653),
        rebuilt on the [B*K] layout."""
        with self.block():
            prev_ids = self.read_array(init=self._init_ids, is_ids=True)
            prev_scores = self.read_array(
                init=self._init_scores, is_scores=True)
            prev_ids_embedding = layers.embedding(
                input=prev_ids,
                size=[self._target_dict_dim, self._word_dim],
                dtype='float32',
                is_sparse=self._sparse_emb)

            feed_dict = {}
            update_dict = {}
            for name, init_var in self._input_var_dict.items():
                if name not in self._state_cell._inputs:
                    raise ValueError('Variable %s not found in StateCell'
                                     % name)
                read_var = self.read_array(init=init_var)
                update_dict[name] = read_var
                feed_dict[name] = read_var

            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_ids_embedding

            self._state_cell.compute_state(inputs=feed_dict)
            current_state = self._state_cell.out_state()
            scores = layers.fc(input=current_state,
                               size=self._target_dict_dim,
                               act='softmax')
            topk_scores, topk_indices = layers.topk(
                scores, k=self._beam_size)
            accu_scores = layers.elementwise_add(
                layers.log(topk_scores), prev_scores)
            sel_ids, sel_scores, parent_idx = layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                self._beam_size, end_id=self._end_id)
            # re-wire every carried state to its surviving parent row:
            # gather-by-parent_idx replaces both the reference's
            # update_states() commit and its sequence_expand beam growth
            for state_name in self._state_cell._state_names:
                holder = self._state_cell._states_holder[state_name]
                gathered = layers.gather(
                    self._state_cell._cur_states[state_name], parent_idx)
                self._rnn.update_memory(holder.get_state(), gathered)
            self.update_array(prev_ids, sel_ids)
            self.update_array(prev_scores, sel_scores)
            for name, var in update_dict.items():
                self.update_array(var, feed_dict[name])
            self._parent_idx = parent_idx
            self._rnn.output(sel_ids, sel_scores, parent_idx)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Carried per-step value, initialized from ``init`` (an RNN
        memory on this layout rather than a tensor array)."""
        self._assert_in_decoder_block('read_array')
        if is_ids and is_scores:
            raise ValueError('an array cannot be both ids and scores')
        if not isinstance(init, Variable):
            raise TypeError('`init` must be a Variable')
        mem = self._rnn.memory(init=init)
        self._arrays[mem.name] = mem
        if is_ids:
            self._ids_mem = mem
        elif is_scores:
            self._scores_mem = mem
        return mem

    def update_array(self, array, value):
        self._assert_in_decoder_block('update_array')
        if not isinstance(array, Variable) or \
                not isinstance(value, Variable):
            raise TypeError('array and value must be Variables')
        if array.name not in self._arrays:
            raise ValueError('invoke read_array before update_array')
        self._rnn.update_memory(array, value)

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError('output can only be visited outside the block')
        ids_arr, scores_arr, parents_arr = self._rnn()
        return layers.beam_search_decode(
            ids_arr, scores_arr, parents_arr,
            beam_size=self._beam_size, end_id=self._end_id)

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_BEAM_SEARCH_DECODER:
            raise ValueError('%s should be invoked inside the block of '
                             'BeamSearchDecoder' % method)
