"""Contrib surface (reference: python/paddle/fluid/contrib/__init__.py):
the decoder DSL (InitState/StateCell/TrainingDecoder/BeamSearchDecoder)
and memory_usage."""

from .decoder import InitState, StateCell, TrainingDecoder, \
    BeamSearchDecoder
from .memory_usage_calc import memory_usage

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder',
           'memory_usage']
