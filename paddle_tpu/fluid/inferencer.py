"""High-level Inferencer (reference: python/paddle/fluid/inferencer.py:31)."""

import contextlib

from . import core
from .framework import Program, program_guard
from .executor import Executor, scope_guard
from . import io as fluid_io
from . import unique_name

__all__ = ['Inferencer']


class Inferencer(object):
    def __init__(self, infer_func, param_path, place=None, parallel=False):
        """infer_func rebuilds the inference program; param_path holds the
        persistables saved by Trainer.save_params."""
        self.param_path = param_path
        self.scope = core.Scope()
        self.parallel = parallel
        self.place = place if place is not None else core.CPUPlace()

        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program):
            with unique_name.guard():
                self.predict_var = infer_func()

        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            fluid_io.load_persistables(
                self.exe, param_path,
                main_program=self.inference_program)

        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs, return_numpy=True):
        if not isinstance(inputs, dict):
            raise ValueError('inputs should be a dict of {name: data}')
        with scope_guard(self.scope):
            results = self.exe.run(
                self.inference_program,
                feed=inputs,
                fetch_list=[self.predict_var.name],
                return_numpy=return_numpy)
        return results
