"""High-level Inferencer (reference: python/paddle/fluid/inferencer.py:31).

Re-implemented on paddle_tpu.serving.InferenceEngine: infer() routes
through the engine's synchronous (inline) mode — same micro-batch
padding/trim, shape-bucket, and run_eval_multi dispatch path the
request-facing server uses, so the two surfaces cannot drift.  A
single-caller Inferencer keeps its old behavior (one lot per call, no
background thread); pass ``parallel=True`` for dp-sharded eval over the
device mesh.
"""

from . import core
from .framework import Program, program_guard
from .executor import Executor, scope_guard
from . import io as fluid_io
from . import unique_name

__all__ = ['Inferencer']


class Inferencer(object):
    def __init__(self, infer_func, param_path, place=None, parallel=False):
        """infer_func rebuilds the inference program; param_path holds the
        persistables saved by Trainer.save_params."""
        self.param_path = param_path
        self.scope = core.Scope()
        self.parallel = parallel
        self.place = place if place is not None else core.CPUPlace()

        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program):
            with unique_name.guard():
                self.predict_var = infer_func()

        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            fluid_io.load_persistables(
                self.exe, param_path,
                main_program=self.inference_program)

        self.inference_program = self.inference_program.clone(for_test=True)

        # the serving package imports fluid submodules, so pull it in at
        # construction time (this module loads during fluid's own
        # package init, before serving exists)
        from .. import serving
        self._engine = serving.InferenceEngine(
            self.inference_program,
            fetch_list=[self.predict_var],
            place=self.place,
            scope=self.scope,
            executor=self.exe,
            parallel=parallel,
            config=serving.ServingConfig(steps_per_dispatch=1,
                                         pipeline_depth=1))

    def infer(self, inputs, return_numpy=True):
        """Run one inference request through the serving engine.  Feeds
        whose leading (batch) dims disagree raise a clear ValueError
        (mirroring run_multi's feed guards) instead of failing inside
        XLA."""
        if not isinstance(inputs, dict):
            raise ValueError('inputs should be a dict of {name: data}')
        with scope_guard(self.scope):
            return self._engine.infer(inputs, return_numpy=return_numpy)
