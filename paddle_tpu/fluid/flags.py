"""Typed module-level flags with environment-variable bootstrap.

TPU-native analog of the reference's gflags machinery: C++ modules
DEFINE_* flags (e.g. FLAGS_check_nan_inf in framework/operator.cc,
FLAGS_cpu_deterministic in details/build_strategy.h:41), and the Python
package bootstraps a whitelist of them from environment variables at
import via core.init_gflags(["--tryfromenv=..."])
(python/paddle/fluid/__init__.py:121-141, platform/init.cc:36).

Here flags are plain typed Python descriptors in one registry; the env
bootstrap reads the same ``FLAGS_<name>`` variable names the reference
honors, so launcher scripts keep working.  Flags with side effects (the
NaN debugger) apply them in their setter.
"""

import os

__all__ = ['DEFINE_bool', 'DEFINE_int32', 'DEFINE_double', 'DEFINE_string',
           'get_flag', 'set_flag', 'try_from_env', 'FLAGS']

_TRUE = ('1', 'true', 'yes', 'on')
_FALSE = ('0', 'false', 'no', 'off', '')


class _Flag(object):
    __slots__ = ('name', 'type', 'value', 'default', 'help', 'on_set')

    def __init__(self, name, type_, default, help_, on_set=None):
        self.name = name
        self.type = type_
        self.value = default
        self.default = default
        self.help = help_
        self.on_set = on_set


_registry = {}


def _define(name, type_, default, help_, on_set=None):
    if name in _registry:
        raise ValueError('flag %r already defined' % name)
    _registry[name] = _Flag(name, type_, default, help_, on_set)


def DEFINE_bool(name, default, help_=''):
    _define(name, bool, default, help_)


def DEFINE_int32(name, default, help_=''):
    _define(name, int, default, help_)


def DEFINE_double(name, default, help_=''):
    _define(name, float, default, help_)


def DEFINE_string(name, default, help_=''):
    _define(name, str, default, help_)


def _coerce(flag, value):
    if flag.type is bool:
        if isinstance(value, str):
            v = value.strip().lower()
            if v in _TRUE:
                return True
            if v in _FALSE:
                return False
            raise ValueError('invalid bool for flag %r: %r'
                             % (flag.name, value))
        return bool(value)
    return flag.type(value)


def get_flag(name):
    return _registry[name].value


def set_flag(name, value):
    flag = _registry[name]
    new = _coerce(flag, value)
    # on_set doubles as validator: a raise must leave the old value
    if flag.on_set is not None:
        flag.on_set(new)
    flag.value = new


def on_set(name, fn):
    """Attach a side-effect callback invoked on every set (and once now if
    the flag already differs from its default)."""
    flag = _registry[name]
    flag.on_set = fn
    if flag.value != flag.default:
        fn(flag.value)


def try_from_env(names):
    """Read ``FLAGS_<name>`` env vars for each whitelisted name — the
    reference's --tryfromenv contract: absent vars keep defaults, present
    ones are parsed per the flag's type."""
    for name in names:
        env = os.environ.get('FLAGS_' + name)
        if env is not None:
            set_flag(name, env)


class _FlagsView(object):
    """Attribute-style access mirroring gflags' FLAGS object."""

    def __getattr__(self, name):
        try:
            return _registry[name].value
        except KeyError:
            raise AttributeError('no flag named %r' % name)

    def __setattr__(self, name, value):
        set_flag(name, value)


FLAGS = _FlagsView()


def _toggle_jax_debug_nans(enabled):
    # the in-jit half of check_nan_inf: XLA inserts checks after every
    # primitive so failures name the op, like the reference's post-op scan
    # in operator.cc
    import jax
    jax.config.update('jax_debug_nans', bool(enabled))


# ---------------------------------------------------------------------------
# The flag set.  Names follow the reference's FLAGS_* spelling so existing
# launcher environments keep working; GPU-memory flags are accepted but
# inert (device memory belongs to PJRT on TPU) and documented as such.
# ---------------------------------------------------------------------------

DEFINE_bool('check_nan_inf', False,
            'Scan outputs for NaN/Inf after execution (reference '
            'operator.cc post-op scan); inside jit uses jax_debug_nans '
            'for per-op attribution.')
DEFINE_bool('cpu_deterministic', False,
            'Force deterministic execution: pins the program RNG stream '
            'and is asserted by distributed tests '
            '(reference build_strategy.h:41, test_dist_base.py:233).')
DEFINE_bool('cudnn_deterministic', False,
            'Accepted for reference launcher parity; XLA:TPU kernels are '
            'deterministic by construction so this is an alias of '
            'cpu_deterministic for the compiled path.')
DEFINE_bool('benchmark', False,
            'Log per-run wall time and fetch sizes (reference '
            'executor.cc:335 per-op sync + memory log).')
DEFINE_double('fraction_of_gpu_memory_to_use', 0.92,
              'Inert on TPU: device memory is managed by PJRT.')
DEFINE_bool('use_pinned_memory', True,
            'Use the pooled host staging allocator (csrc/host_pool.cc) '
            'for feed buffers.')
DEFINE_bool('init_allocated_mem', False,
            'Fill host-pool allocations with a debug pattern.')
DEFINE_bool('free_idle_memory', False,
            'Aggressively trim the host staging pool.')
DEFINE_int32('paddle_num_threads', 1,
             'Host-side worker threads for readers and host ops.')
DEFINE_int32('rpc_deadline', 180000,
             'Distributed control-plane timeout in ms '
             '(jax.distributed initialize timeout).')
DEFINE_bool('eager_delete_scope', True,
            'Drop executor kid scopes eagerly (scope lifetimes are '
            'Python-managed here; kept for launcher parity).')
DEFINE_string('xla_compile_cache_dir', '',
              'Persistent XLA compilation cache directory '
              '(jax_compilation_cache_dir): compiled executables are '
              'written to disk and reused across PROCESSES, cutting '
              'warm-start compile time — bench.py points every config '
              'child at one shared dir (override/disable via '
              'BENCH_XLA_CACHE).  Env-settable like every flag: '
              'FLAGS_xla_compile_cache_dir=/path.  Empty disables.')
DEFINE_bool('cost_accounting', False,
            'Capture XLA cost_analysis FLOPs + memory_analysis bytes '
            'for every executable the executors dispatch '
            '(fluid.trace.analyze_cost -> Executor.cost_report()): the '
            'per-executable ground truth behind achieved-MFU serving '
            'metrics and bench.py MFU.  Off by default — the AOT '
            'analysis compile does not share the jit call cache, so '
            'capture costs one extra XLA compile per executable '
            '(amortized by FLAGS_xla_compile_cache_dir).')
DEFINE_string('fused_lstm', 'auto',
              "lstm-op recurrence impl: 'auto' picks the fused Pallas "
              "cell kernel (ops/pallas/lstm.py) when the shape profile "
              "wins on TPU (256 <= D <= 512, lane-aligned, default "
              'activations, no peepholes - measured +14-15% fwd+bwd at '
              "D=512), 'never' always uses the lax.scan path, 'always' "
              'forces the kernel wherever it is legal.  lstmp (projected '
              'recurrence) always uses the scan path.')

on_set('check_nan_inf', _toggle_jax_debug_nans)


def _apply_xla_compile_cache(path):
    import jax
    if path:
        import os as _os
        _os.makedirs(path, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', path)
        try:
            # cache even fast compiles: the bench children are
            # short-lived, so every skipped retrace is wall clock
            jax.config.update(
                'jax_persistent_cache_min_compile_time_secs', 0.0)
        except AttributeError:
            pass  # older jax: keep its default threshold
    else:
        jax.config.update('jax_compilation_cache_dir', None)


on_set('xla_compile_cache_dir', _apply_xla_compile_cache)


def _validate_fused_lstm(value):
    if value not in ('auto', 'never', 'always'):
        raise ValueError(
            "FLAGS_fused_lstm must be 'auto', 'never' or 'always' "
            '(got %r)' % (value, ))


on_set('fused_lstm', _validate_fused_lstm)

# the reference whitelists which flags may come from the environment
# (__init__.py:121-141); everything defined above is eligible here
TRYFROMENV = tuple(sorted(_registry))
