"""Parameter-block -> endpoint placement policies (reference:
python/paddle/fluid/transpiler/ps_dispatcher.py:46,70).

Under the SPMD replacement the dense pserver path is gone, but the
dispatchers survive as the placement policy for host-sharded state: the
sparse DistributeTranspiler uses the same name->shard mapping contract
to place distributed lookup-table shards, and external launchers that
drove the reference through these classes keep working.
"""

__all__ = ['PSDispatcher', 'HashName', 'RoundRobin']


class PSDispatcher(object):
    """Base: holds the endpoint list and a reset/dispatch contract."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError('use HashName or RoundRobin')


def _name_of(var):
    # reference dispatch() receives VarBlock-ish objects exposing name();
    # accept plain strings and Variables too
    name = getattr(var, 'name', var)
    return name() if callable(name) else str(name)


class HashName(PSDispatcher):
    """Stable-hash var names onto endpoints (reference ps_dispatcher.py:46).
    Uses a deterministic FNV-1a instead of Python's salted hash() so the
    placement is reproducible across processes — the property the
    reference relied on PYTHONHASHSEED for."""

    def __init__(self, pserver_endpoints):
        super(HashName, self).__init__(pserver_endpoints)

    def _hash_block(self, block_str, total):
        h = 0xcbf29ce484222325
        for ch in block_str.encode('utf-8'):
            h = ((h ^ ch) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
        return h % total

    def dispatch(self, varlist):
        return [
            self._eps[self._hash_block(_name_of(v), len(self._eps))]
            for v in varlist
        ]


class RoundRobin(PSDispatcher):
    """Cycle endpoints in order (reference ps_dispatcher.py:70)."""

    def __init__(self, pserver_endpoints):
        super(RoundRobin, self).__init__(pserver_endpoints)

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out
