"""InferenceTranspiler (reference: transpiler/inference_transpiler.py:24).

Folds batch_norm into the preceding conv2d for inference: adjusts the conv
filter and bias with the BN statistics in the scope, then removes the
batch_norm op — the same w' = w * gamma/sqrt(var+eps) rewrite as the
reference.  (XLA would fuse the arithmetic anyway; folding still removes
the op and its params from the serialized model.)
"""

import numpy as np

from .. import core
from ..executor import global_scope

__all__ = ['InferenceTranspiler']


class InferenceTranspiler(object):
    def transpile(self, program, place=None, scope=None):
        if scope is None:
            scope = global_scope()
        self._fuse_batch_norm(program, scope)
        return program

    def _scope_np(self, scope, name):
        var = scope.find_var(name)
        if var is None or var.value() is None:
            return None
        val = var.value()
        return val.numpy() if isinstance(val, core.LoDTensor) else \
            np.asarray(val)

    def _fuse_batch_norm(self, program, scope):
        """Match conv2d [+ elementwise_add bias] + batch_norm and fold the
        BN statistics into the conv filter (and bias, when present) — the
        reference's two patterns, transpiler/inference_transpiler.py:40-58."""
        block = program.global_block()
        i = 0
        while i < len(block.ops) - 1:
            conv_op = block.ops[i]
            if conv_op.type not in ('conv2d', 'depthwise_conv2d'):
                i += 1
                continue
            j = i + 1
            bias_add = None
            if block.ops[j].type == 'elementwise_add' and \
                    block.ops[j].input('X') == conv_op.output('Output') and \
                    j + 1 < len(block.ops):
                bias_add = block.ops[j]
                j += 1
            bn = block.ops[j]
            prev_out = (bias_add.output('Out') if bias_add is not None
                        else conv_op.output('Output'))
            if bn.type != 'batch_norm' or bn.input('X') != prev_out:
                i += 1
                continue
            scale = self._scope_np(scope, bn.input('Scale')[0])
            bias = self._scope_np(scope, bn.input('Bias')[0])
            mean = self._scope_np(scope, bn.input('Mean')[0])
            var = self._scope_np(scope, bn.input('Variance')[0])
            w_name = conv_op.input('Filter')[0]
            w = self._scope_np(scope, w_name)
            if any(v is None for v in (scale, bias, mean, var, w)):
                i += 1
                continue
            eps = bn.attrs.get('epsilon', 1e-5)
            inv_std = 1.0 / np.sqrt(var + eps)
            factor = (scale * inv_std).astype(w.dtype)
            scope.var(w_name).set_value(w * factor[:, None, None, None])
            if bias_add is not None:
                # BN(conv + b) = conv' + factor*b + (bias - factor*mean):
                # scale the existing conv bias by factor too.  If Y is not
                # a scope param the add is a residual/skip connection —
                # undo the filter rescale and skip the fusion entirely.
                b_name = bias_add.input('Y')[0]
                b = self._scope_np(scope, b_name)
                if b is None or b.size != factor.size:
                    scope.var(w_name).set_value(w)
                    i += 1
                    continue
                scope.var(b_name).set_value(
                    (b * factor.reshape(b.shape)).astype(b.dtype))
            new_bias = (bias - mean * scale * inv_std).astype(w.dtype)
            # the BN op becomes a bias add: prev_out + new_bias -> BN's Y
            bn_out = bn.output('Y')[0]
            bias_name = bn.input('Bias')[0]
            scope.var(bias_name).set_value(new_bias)
            block.ops[j] = type(bn)(
                block, 'elementwise_add',
                inputs={'X': prev_out,
                        'Y': [bias_name]},
                outputs={'Out': [bn_out]},
                attrs={'axis': 1})
            program._bump_version()
            i += 1
