"""InferenceTranspiler (reference: transpiler/inference_transpiler.py:24).

Folds batch_norm into the preceding conv2d for inference: adjusts the conv
filter and bias with the BN statistics in the scope, then removes the
batch_norm op — the same w' = w * gamma/sqrt(var+eps) rewrite as the
reference.  (XLA would fuse the arithmetic anyway; folding still removes
the op and its params from the serialized model.)
"""

import numpy as np

from .. import core
from ..executor import global_scope

__all__ = ['InferenceTranspiler']


class InferenceTranspiler(object):
    def transpile(self, program, place=None, scope=None):
        if scope is None:
            scope = global_scope()
        self._fuse_batch_norm(program, scope)
        return program

    def _scope_np(self, scope, name):
        var = scope.find_var(name)
        if var is None or var.value() is None:
            return None
        val = var.value()
        return val.numpy() if isinstance(val, core.LoDTensor) else \
            np.asarray(val)

    def _fuse_batch_norm(self, program, scope):
        block = program.global_block()
        i = 0
        while i < len(block.ops) - 1:
            op = block.ops[i]
            next_op = block.ops[i + 1]
            if op.type in ('conv2d', 'depthwise_conv2d') and \
                    next_op.type == 'batch_norm' and \
                    next_op.input('X') == op.output('Output'):
                scale = self._scope_np(scope, next_op.input('Scale')[0])
                bias = self._scope_np(scope, next_op.input('Bias')[0])
                mean = self._scope_np(scope, next_op.input('Mean')[0])
                var = self._scope_np(scope, next_op.input('Variance')[0])
                w_name = op.input('Filter')[0]
                w = self._scope_np(scope, w_name)
                if any(v is None for v in (scale, bias, mean, var, w)):
                    i += 1
                    continue
                eps = next_op.attrs.get('epsilon', 1e-5)
                inv_std = 1.0 / np.sqrt(var + eps)
                factor = (scale * inv_std).astype(w.dtype)
                scope.var(w_name).set_value(
                    w * factor[:, None, None, None])
                new_bias = (bias - mean * scale * inv_std).astype(w.dtype)
                # rewrite: conv Output feeds where BN's Y went, plus an
                # elementwise bias add
                bn_out = next_op.output('Y')[0]
                bias_name = next_op.input('Bias')[0]
                scope.var(bias_name).set_value(new_bias)
                block.ops[i + 1] = type(next_op)(
                    block, 'elementwise_add',
                    inputs={'X': op.output('Output'),
                            'Y': [bias_name]},
                    outputs={'Out': [bn_out]},
                    attrs={'axis': 1})
                program._bump_version()
            i += 1
