"""DistributeTranspiler (reference: transpiler/distribute_transpiler.py:132).

Capability mapping (SURVEY §2.5, BASELINE north star): the reference
rewrites one program into trainer programs (send/recv ops) plus pserver
programs (listen_and_serv with per-param optimize blocks) over gRPC.  On
TPU the dense synchronous path is *replaced* by SPMD — one program, batch
sharded over the mesh, XLA cross-replica sums over ICI — so
``get_trainer_program`` returns the original program annotated for
ParallelExecutor, and multi-host scale-out uses the same program via
``jax.distributed`` (rendezvous owned by the TPU runtime, replacing
gen_nccl_id_op).  The pserver program surface is kept for API parity;
sparse/CTR models shard their embeddings with
``paddle_tpu.parallel.shard`` instead of remote prefetch.
"""

from ..framework import default_main_program, Program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig']


class DistributeTranspilerConfig(object):
    """(reference distribute_transpiler.py:116)"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self,
                  trainer_id,
                  program=None,
                  pservers='127.0.0.1:6174',
                  trainers=1,
                  sync_mode=True,
                  startup_program=None):
        if program is None:
            program = default_main_program()
        if not sync_mode:
            raise NotImplementedError(
                'async parameter-server updates have no TPU analog; the '
                'dense path is synchronous SPMD (SURVEY §2.5 row "async")')
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.pserver_endpoints = [
            ep.strip() for ep in pservers.split(',') if ep.strip()
        ]
        self.origin_program = program
        program._is_distributed = True
        program._trainers = trainers
        program._trainer_id = trainer_id
        self._transpiled = True

    def get_trainer_program(self):
        """The SPMD trainer program IS the original program: run it with
        fluid.ParallelExecutor over a mesh; gradient averaging happens via
        compiler-inserted collectives rather than send/recv ops."""
        if not self._transpiled:
            raise RuntimeError('call transpile() first')
        return self.origin_program

    def get_pserver_program(self, endpoint):
        """Dense pserver serving is intentionally bypassed on TPU
        (BASELINE.json north star).  Returns a stub program whose single
        listen_and_serv op documents the mapping."""
        if not self._transpiled:
            raise RuntimeError('call transpile() first')
        prog = Program()
        prog.global_block().append_op(
            type='listen_and_serv',
            inputs={},
            outputs={},
            attrs={
                'endpoint': endpoint,
                'note': 'dense sync-SGD is SPMD on TPU; no pserver needed',
            })
        return prog

    def get_startup_program(self, endpoint, pserver_program=None):
        return Program()
