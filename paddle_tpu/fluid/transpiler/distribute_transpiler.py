"""DistributeTranspiler (reference: transpiler/distribute_transpiler.py:132).

Capability mapping (SURVEY §2.5, BASELINE north star): the reference
rewrites one program into trainer programs (send/recv ops) plus pserver
programs (listen_and_serv with per-param optimize blocks) over gRPC.  On
TPU the dense synchronous path is *replaced* by SPMD — one program, batch
sharded over the mesh, XLA cross-replica sums over ICI — so
``get_trainer_program`` returns the original program annotated for
ParallelExecutor, and multi-host scale-out uses the same program via
``jax.distributed`` (rendezvous owned by the TPU runtime, replacing
gen_nccl_id_op).  The pserver program surface is kept for API parity.

The SPARSE path keeps the reference's program->program rewrite
architecture: where the reference replaces ``lookup_table`` ops over a
distributed table with split_ids -> (send/recv) prefetch -> merge_ids
(distribute_transpiler.py:939-1090
``_replace_lookup_table_op_with_prefetch``), ``transpile()`` here walks
the program, finds every ``lookup_table`` whose ``is_distributed`` attr
is set, row-shards the table and its optimizer accumulators over the
mesh, and marks the ops local — GSPMD then lowers the gather into the
exact all-to-all/all-gather exchange the pserver prefetch implemented
by hand, riding ICI instead of gRPC.
"""

from ..framework import default_main_program, Program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig']


class DistributeTranspilerConfig(object):
    """(reference distribute_transpiler.py:116)"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    # mesh axis the distributed lookup tables' rows shard over
    sparse_shard_axis = 'dp'


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self,
                  trainer_id,
                  program=None,
                  pservers='127.0.0.1:6174',
                  trainers=1,
                  sync_mode=True,
                  startup_program=None):
        if program is None:
            program = default_main_program()
        if not sync_mode:
            raise NotImplementedError(
                'dense async parameter-server updates have no TPU analog '
                '(the dense path is synchronous SPMD, SURVEY §2.5); the '
                'surviving async use case — barrier-free sparse embedding '
                'updates for CTR — is served by '
                'paddle_tpu.distributed.AsyncSparseEmbedding '
                '(listen_and_serv RunAsyncLoop analog, '
                'tests/test_async_sparse.py)')
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.pserver_endpoints = [
            ep.strip() for ep in pservers.split(',') if ep.strip()
        ]
        self.origin_program = program
        program._is_distributed = True
        program._trainers = trainers
        program._trainer_id = trainer_id
        # sparse path: the program rewrite (reference
        # _replace_lookup_table_op_with_prefetch analog)
        self.distributed_lookup_tables = _shard_distributed_tables(
            program, self.config.sparse_shard_axis)
        if startup_program is not None:
            _shard_distributed_tables(
                startup_program, self.config.sparse_shard_axis,
                only_names=set(self.distributed_lookup_tables))
        self._transpiled = True

    @property
    def has_distributed_lookup_table(self):
        """(reference distribute_transpiler.py has_distributed_lookup_table)"""
        if not self._transpiled:
            raise RuntimeError('call transpile() first')
        return bool(self.distributed_lookup_tables)

    def get_trainer_program(self):
        """The SPMD trainer program IS the original program: run it with
        fluid.ParallelExecutor over a mesh; gradient averaging happens via
        compiler-inserted collectives rather than send/recv ops."""
        if not self._transpiled:
            raise RuntimeError('call transpile() first')
        return self.origin_program

    def get_pserver_program(self, endpoint):
        """Dense pserver serving is intentionally bypassed on TPU
        (BASELINE.json north star).  Returns a stub program whose single
        listen_and_serv op documents the mapping."""
        if not self._transpiled:
            raise RuntimeError('call transpile() first')
        prog = Program()
        prog.global_block().append_op(
            type='listen_and_serv',
            inputs={},
            outputs={},
            attrs={
                'endpoint': endpoint,
                'note': 'dense sync-SGD is SPMD on TPU; no pserver needed',
            })
        return prog

    def get_startup_program(self, endpoint, pserver_program=None):
        return Program()

    def get_pserver_programs(self, endpoint):
        """(main, startup) pair for one endpoint (reference
        get_pserver_programs) — stubs under the SPMD replacement, like
        get_pserver_program."""
        return (self.get_pserver_program(endpoint),
                self.get_startup_program(endpoint))


def _shard_distributed_tables(program, axis, only_names=None):
    """Row-shard every ``lookup_table(is_distributed=True)`` table (and
    its optimizer accumulators) over ``axis``.

    This is the TPU shape of the reference's sparse rewrite: the table
    never lives whole on one device; the lookup's gather crosses the
    mesh via compiler-inserted collectives, and the sparse
    SelectedRows-gradient update runs against the local rows.
    Returns the sorted table names."""
    from ...parallel.api import shard, sharding_of, PartitionSpec

    if only_names is not None:
        # a startup program carries the same table VARS but no
        # lookup_table ops — the caller names the tables to annotate
        tables = set(only_names)
    else:
        tables = set()
        for block in program.blocks:
            for op in block.ops:
                if op.type not in ('lookup_table', 'lookup_table_grad'):
                    continue
                if not op.attrs.get('is_distributed'):
                    continue
                # the rewrite happened here; no remote prefetch remains
                op.attrs['remote_prefetch'] = False
                tables.add(op.input('W')[0])
    for block in program.blocks:
        for name in tables:
            w = block._find_var_recursive(name)
            if w is not None and sharding_of(w) is None:
                shard(w, PartitionSpec(axis, None))
        # optimizer accumulators co-locate with their table: exact
        # ownership is recorded at creation (Optimizer._add_accumulator
        # tags vars), never guessed from names
        for v in block.vars.values():
            if (getattr(v, '_accumulator_for', None) in tables
                    and len(v.shape or ()) >= 2
                    and sharding_of(v) is None):
                shard(v, PartitionSpec(axis, None))
    if only_names is None:
        program._distributed_lookup_tables = sorted(tables)
    return sorted(tables)
