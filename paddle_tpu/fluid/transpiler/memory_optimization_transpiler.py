"""memory_optimize (reference: transpiler/memory_optimization_transpiler.py).

The reference runs liveness analysis over the program and rewrites var
names to reuse buffers (ControlFlowGraph:47, memory_optimize:381).  The
TPU-native split of that job (VERDICT r3 next-#7):

- **Compiled (jit) path**: XLA buffer assignment already performs
  liveness-driven reuse, with fusion on top.  This is not an assertion:
  ``tests/test_memory_optimize.py`` measures the compiled executable's
  ``memory_analysis().temp_size_in_bytes`` on a long elementwise chain
  and shows temp memory is ZERO (full fusion) while the program's
  intermediates sum to O(N) — the rewrite the reference does by hand is
  already done below us, better.

- **Eager (host-op-segmented) path**: ops execute one by one against a
  name->array env that — without this pass — pins EVERY intermediate
  until the block ends.  There the reference's pass has a real analog:
  ``memory_optimize`` marks which vars are safe to free after their
  last use (``program._releasable``); the executor computes last-use
  positions over its own op list and drops dead entries as it walks the
  block, so peak live memory matches the true live set.  Same
  observable contract as the reference (results unchanged, memory
  reduced); instead of renaming vars into shared buffers we free dead
  ones — equivalent effect without aliasing hazards.
"""

from ..framework import default_main_program

__all__ = ['memory_optimize', 'release_memory']


def _liveness(program):
    block = program.global_block()
    last_use = {}
    first_def = {}
    for idx, op in enumerate(block.ops):
        for name in op.input_arg_names:
            last_use[name] = idx
        for name in op.output_arg_names:
            first_def.setdefault(name, idx)
            last_use[name] = idx
    return first_def, last_use


def _sub_block_names(block, acc):
    """Recursively collect every var name touched inside sub-blocks at
    ANY depth — their reads/writes don't appear in the global block's op
    lists, so they must never be released."""
    for op in block.ops:
        sub = op.attrs.get('sub_block') if op.attrs else None
        if sub is not None:
            for sop in sub.ops:
                acc.update(sop.input_arg_names)
                acc.update(sop.output_arg_names)
            _sub_block_names(sub, acc)


def _protected(program, skip_opt_set):
    """Names that must never be released: persistables (scope state),
    explicit skips, and vars consumed anywhere inside nested
    sub-blocks."""
    keep = set(skip_opt_set or ())
    for var in program.list_vars():
        if getattr(var, 'persistable', False):
            keep.add(var.name)
    _sub_block_names(program.global_block(), keep)
    return keep


def memory_optimize(input_program=None,
                    skip_opt_set=None,
                    print_log=False,
                    level=0):
    program = input_program or default_main_program()
    first_def, last_use = _liveness(program)
    keep = _protected(program, skip_opt_set)

    releasable = frozenset(n for n in last_use if n not in keep)
    if getattr(program, '_releasable', None) != releasable:
        program._releasable = releasable
        # a cached executable compiled before this pass has no release
        # plan; bumping the version makes the executor re-key (and
        # re-plan).  Skipped when the set is unchanged (e.g.
        # release_memory after memory_optimize) so identical plans don't
        # force a gratuitous recompile.
        program._bump_version()

    stats = {
        'num_vars': len(first_def),
        'releasable': len(releasable),
        'protected': len(keep),
    }
    program._memory_optimize_stats = stats
    if print_log:
        print('memory_optimize: %(num_vars)d vars, %(releasable)d '
              'releasable on the eager path (compiled-path reuse is '
              "XLA buffer assignment's)" % stats)
    return program


def release_memory(input_program=None, skip_opt_set=None):
    """Alias of memory_optimize's release planning (reference
    release_memory inserted delete_var ops at last use — the marking
    below is exactly that, applied by the eager executor)."""
    return memory_optimize(input_program, skip_opt_set=skip_opt_set)
