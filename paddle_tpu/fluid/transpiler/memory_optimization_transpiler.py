"""memory_optimize (reference: transpiler/memory_optimization_transpiler.py).

The reference runs liveness analysis over the program and rewrites var
names to reuse buffers (ControlFlowGraph:47, memory_optimize:381).  Under
whole-block XLA compilation the compiler's buffer assignment already does
exactly this (and better, with operator fusion), so the pass reduces to a
liveness *report*: it computes the same live-range statistics the reference
used and stores them on the program for inspection — no rewrite needed.
"""

import collections

from ..framework import default_main_program

__all__ = ['memory_optimize', 'release_memory']


def _liveness(program):
    block = program.global_block()
    last_use = {}
    first_def = {}
    for idx, op in enumerate(block.ops):
        for name in op.input_arg_names:
            last_use[name] = idx
        for name in op.output_arg_names:
            first_def.setdefault(name, idx)
            last_use[name] = idx
    return first_def, last_use


def memory_optimize(input_program=None,
                    skip_opt_set=None,
                    print_log=False,
                    level=0):
    program = input_program or default_main_program()
    first_def, last_use = _liveness(program)
    stats = {
        'num_vars': len(first_def),
        'reusable_pairs': 0,
    }
    # count reuse opportunities the XLA buffer assigner will exploit
    dead_at = collections.defaultdict(list)
    for name, idx in last_use.items():
        dead_at[idx].append(name)
    for name, def_idx in first_def.items():
        for d in range(def_idx):
            if dead_at.get(d):
                stats['reusable_pairs'] += 1
                break
    program._memory_optimize_stats = stats
    if print_log:
        print('memory_optimize: %(num_vars)d vars, %(reusable_pairs)d '
              'reusable (buffer reuse performed by XLA)' % stats)
    return program


def release_memory(input_program=None, skip_opt_set=None):
    """No-op under XLA: buffers are freed by the runtime at donation
    points (reference release_memory inserted delete_var ops)."""
    return input_program or default_main_program()
