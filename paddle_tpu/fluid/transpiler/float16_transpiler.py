"""Float16Transpiler (reference: paddle/contrib/float16/float16_transpiler.py:21).

Rewrites a saved (f32) inference program to run in half precision: every
f32 parameter in the scope is cast to the half dtype under a ``.fp16``
name, feed targets gain a cast-in op, fetch targets a cast-back-to-f32
op, and op inputs are renamed — so callers keep feeding/fetching f32
exactly as before while the compute graph runs half precision end to
end.

TPU-native default is **bfloat16** (the MXU's native half type — fp16
on TPU pays a convert at every matmul); ``dtype='float16'`` gives the
reference's CUDA-oriented behavior.  Run ``InferenceTranspiler`` (BN
fold) first, as the reference's float16_benchmark.md flow does; any
surviving batch_norm keeps f32 inputs (the reference's exclusion list).
"""

import numpy as np

from .. import core
from ..executor import global_scope
from ..framework import Operator

__all__ = ['Float16Transpiler']

_HALF_SUFFIX = '.fp16'


class Float16Transpiler(object):
    def transpile(self, program, place=None, scope=None, dtype='bfloat16',
                  feeded_var_names=None, fetch_var_names=None):
        """In-place program rewrite + scope param conversion.

        feeded_var_names / fetch_var_names: required when the program
        was loaded through this repo's load_inference_model (which
        strips the embedded feed/fetch ops and returns the names);
        programs still carrying feed/fetch ops need neither."""
        if scope is None:
            scope = global_scope()
        if dtype in ('bfloat16', 'bf16'):
            self._half = core.convert_dtype_to_np('bfloat16')
        elif dtype in ('float16', 'fp16'):
            self._half = np.dtype(np.float16)
        else:
            raise ValueError('half dtype must be bfloat16 or float16, '
                             'got %r' % (dtype,))
        self.scope = scope
        self.block = program.global_block()
        self.input_map = {}

        def _name(v):  # load_inference_model returns fetch Variables
            return v.name if hasattr(v, 'name') else str(v)

        feeds = [_name(v) for v in (feeded_var_names or [])]
        fetches = [_name(v) for v in (fetch_var_names or [])]
        for op in self.block.ops:
            if op.type == 'feed':
                feeds.append(op.output('Out')[0])
            elif op.type == 'fetch':
                fetches.append(op.input('X')[0])

        self._convert_params()
        self._cast_feeds(feeds)
        self._cast_fetches(fetches)
        self._adjust_input()
        self._remove_unused_vars()
        program._bump_version()
        return program

    # -- private ----------------------------------------------------------

    def _no_conversion_names(self):
        """batch_norm requires f32 statistics even in half mode — the
        reference's only exclusion (float16_transpiler.py:204)."""
        names = set()
        for op in self.block.ops:
            if op.type == 'batch_norm':
                names.update(op.input_arg_names)
        return names

    def _scope_np(self, name):
        var = self.scope.find_var(name)
        if var is None or var.value() is None:
            return None
        val = var.value()
        return val.numpy() if isinstance(val, core.LoDTensor) else \
            np.asarray(val)

    def _convert_params(self):
        no_convert = self._no_conversion_names()
        for name in list(self.block.vars):
            var = self.block.vars[name]
            if not getattr(var, 'persistable', False) \
                    or name in no_convert:
                continue
            value = self._scope_np(name)
            if value is None or value.dtype != np.float32:
                continue
            half_name = name + _HALF_SUFFIX
            self.block.create_var(name=half_name, shape=var.shape,
                                  dtype=self._half, persistable=True)
            self.scope.var(half_name).set_value(value.astype(self._half))
            self.input_map[name] = half_name
            del self.block.vars[name]

    def _cast_feeds(self, feeds):
        for name in dict.fromkeys(feeds):
            var = self.block.vars.get(name)
            if var is None or var.np_dtype != np.float32:
                continue  # int id feeds stay integral
            half_name = name + _HALF_SUFFIX
            half_var = self.block.create_var(
                name=half_name, shape=var.shape, dtype=self._half,
                persistable=False)
            # right after the feed op when embedded, else program start
            pos = 0
            for i, op in enumerate(self.block.ops):
                if op.type == 'feed' and op.output('Out')[0] == name:
                    pos = i + 1
                    break
            self.block._insert_op(
                pos, type='cast', inputs={'X': [name]},
                outputs={'Out': [half_name]},
                attrs={'in_dtype': var.dtype, 'out_dtype': half_var.dtype})
            self.input_map[name] = half_name

    def _cast_fetches(self, fetches):
        for name in dict.fromkeys(fetches):
            var = self.block.vars.get(name)
            if var is None or var.np_dtype != np.float32:
                continue
            half_name = name + _HALF_SUFFIX
            half_var = self.block.create_var(
                name=half_name, shape=var.shape, dtype=self._half,
                persistable=False)
            producer = None
            for i, op in enumerate(self.block.ops):
                if name in op.output_arg_names and op.type != 'cast':
                    producer = i
            if producer is None:
                continue
            self.block.ops[producer].rename_output(name, half_name)
            # immediately after the producer so later consumers (incl.
            # an embedded fetch op) still read a written f32 var
            self.block._insert_op(
                producer + 1, type='cast', inputs={'X': [half_name]},
                outputs={'Out': [name]},
                attrs={'in_dtype': half_var.dtype, 'out_dtype': var.dtype})

    def _adjust_input(self):
        for op in self.block.ops:
            if op.type == 'cast':
                continue  # the inserted casts must keep their f32 inputs
            for arg in list(op.input_arg_names):
                if arg in self.input_map:
                    op.rename_input(arg, self.input_map[arg])

    def _remove_unused_vars(self):
        used = set()
        for op in self.block.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        for name in list(self.block.vars):
            var = self.block.vars[name]
            if name not in used and not getattr(var, 'persistable', False):
                del self.block.vars[name]
