"""Graph program representation: Program / Block / Operator / Variable.

This is the TPU-native re-design of the reference's "program = data" layer
(python/paddle/fluid/framework.py:207,496,923,1407 and
paddle/fluid/framework/framework.proto).  The Python API surface matches the
reference; the representation is pure Python descs.  Instead of being
interpreted op-by-op by a C++ Executor (executor.cc:321-339), whole blocks are
compiled to XLA by :mod:`paddle_tpu.fluid.executor`.
"""

import collections
import contextlib
import copy

import numpy as np

from . import core
from . import unique_name

__all__ = [
    'Program', 'Block', 'Operator', 'Variable', 'Parameter', 'program_guard',
    'default_main_program', 'default_startup_program', 'switch_main_program',
    'switch_startup_program', 'name_scope', 'grad_var_name', 'in_dygraph_mode',
]

GRAD_VAR_SUFFIX = '@GRAD'
ZERO_VAR_SUFFIX = '@ZERO'
TEMP_VAR_NAME = '@TEMP@'


def grad_var_name(name):
    return name + GRAD_VAR_SUFFIX


def in_dygraph_mode():
    return False


class Variable(object):
    """A typed symbolic value in a Block (reference framework.py:207).

    Holds shape/dtype/lod_level metadata; runtime values live in a Scope.
    """

    def __init__(self,
                 block,
                 type=core.VarDesc.VarType.LOD_TENSOR,
                 name=None,
                 shape=None,
                 dtype=None,
                 lod_level=None,
                 capacity=None,
                 persistable=None,
                 error_clip=None,
                 stop_gradient=False,
                 is_data=False,
                 initializer=None,
                 **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate('_generated_var')
        self.name = name
        self.type = type
        self.shape = tuple(shape) if shape is not None else ()
        if dtype is None:
            dtype = core.VarDesc.VarType.FP32
        if not isinstance(dtype, int):
            dtype = core.convert_np_dtype_to_dtype_(dtype)
        self.dtype = dtype
        self.lod_level = lod_level if lod_level is not None else 0
        self.persistable = bool(persistable)
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.error_clip = error_clip
        self.capacity = capacity
        # op that produced this var (filled by Block.append_op)
        self.op = None

    @property
    def np_dtype(self):
        return core.convert_dtype_to_np(self.dtype)

    def to_string(self, throw_on_error=False, with_details=False):
        return 'var %s : shape=%s dtype=%s persistable=%s' % (
            self.name, self.shape, np.dtype(self.np_dtype).name,
            self.persistable)

    __repr__ = __str__ = lambda self: self.to_string()

    # ---- math operator sugar is patched in by layers.math_op_patch ----

    def clone_to(self, block):
        v = Variable(
            block,
            type=self.type,
            name=self.name,
            shape=self.shape,
            dtype=self.dtype,
            lod_level=self.lod_level,
            persistable=self.persistable,
            stop_gradient=self.stop_gradient,
            is_data=self.is_data)
        return v


class Parameter(Variable):
    """A persistable, trainable Variable (reference framework.py:1995)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError('Parameter needs shape and dtype')
        kwargs.setdefault('persistable', True)
        super(Parameter, self).__init__(
            block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get('trainable', True)
        self.optimize_attr = kwargs.get('optimize_attr', {'learning_rate': 1.0})
        self.regularizer = kwargs.get('regularizer', None)
        self.gradient_clip_attr = kwargs.get('gradient_clip_attr', None)
        self.do_model_average = kwargs.get('do_model_average', None)

    def astype(self, dtype):
        """Graph-side cast (reference Parameter.astype via math_op_patch):
        returns a new Variable carrying this parameter cast to dtype."""
        from .layers import tensor as tensor_layers
        return tensor_layers.cast(self, dtype)


class Operator(object):
    """One operation: type + named input/output var lists + attrs
    (reference framework.py:496, framework.proto OpDesc)."""

    OP_WITHOUT_KERNEL_SET = {
        'feed', 'fetch', 'save', 'load', 'save_combine', 'load_combine',
        'recurrent', 'go', 'print', 'while', 'conditional_block',
    }

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # slot name -> list of var names
        self.inputs = {}
        self.outputs = {}
        if inputs:
            for slot, arg in inputs.items():
                self.inputs[slot] = self._to_name_list(arg)
        if outputs:
            for slot, arg in outputs.items():
                self.outputs[slot] = self._to_name_list(arg)
        self.attrs = dict(attrs) if attrs else {}

    @staticmethod
    def _to_name_list(arg):
        if arg is None:
            return []
        if isinstance(arg, (list, tuple)):
            return [a.name if isinstance(a, Variable) else a for a in arg]
        return [arg.name if isinstance(arg, Variable) else arg]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_names(self):
        return list(self.inputs.keys())

    @property
    def output_names(self):
        return list(self.outputs.keys())

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name):
        return self.attrs.get(name)

    def has_attr(self, name):
        return name in self.attrs

    def set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    _set_attr = set_attr

    def all_attrs(self):
        return dict(self.attrs)

    def attr_type(self, name):
        """Python-type stand-in for the reference's proto AttrType enum.
        Raises on unknown names like the reference pybind surface."""
        if name not in self.attrs:
            raise ValueError('op %r has no attr %r' % (self.type, name))
        return type(self.attrs[name])

    def has_kernel(self, op_type=None):
        return (op_type or self.type) not in self.OP_WITHOUT_KERNEL_SET

    def block_attr_id(self, name):
        """Index of a sub-block attr (reference block_attr_id)."""
        v = self.attrs.get(name)
        return v.idx if isinstance(v, Block) else int(v)

    def block_attr(self, name):
        return self.block_attr_id(name)

    def blocks_attr_ids(self, name):
        v = self.attrs.get(name) or []
        return [b.idx if isinstance(b, Block) else int(b) for b in v]

    def blocks_attr(self, name):
        return self.blocks_attr_ids(name)

    def rename_input(self, old_name, new_name):
        for slot, names in self.inputs.items():
            self.inputs[slot] = [new_name if n == old_name else n
                                 for n in names]
        self.block.program._bump_version()

    def rename_output(self, old_name, new_name):
        for slot, names in self.outputs.items():
            self.outputs[slot] = [new_name if n == old_name else n
                                  for n in names]
        self.block.program._bump_version()

    def to_string(self, throw_on_error=False):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return '{%s} = %s(%s) attrs=%s' % (outs, self.type, ins, {
            k: v
            for k, v in self.attrs.items() if not k.startswith('_')
        })

    __repr__ = __str__ = lambda self: self.to_string()


class Block(object):
    """An ordered op list plus a var symbol table (reference framework.py:923,
    framework.proto BlockDesc:170)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []
        # sub-block ops (while/cond) keep attrs pointing at Block objects

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def create_var(self, *args, **kwargs):
        var = Variable(self, *args, **kwargs)
        self.vars[var.name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, *args, **kwargs):
        global_block = self.program.global_block()
        param = Parameter(global_block, *args, **kwargs)
        global_block.vars[param.name] = param
        self.program._bump_version()
        return param

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError('var %r not in block %d' % (name, self.idx))
        return v

    def has_var(self, name):
        return name in self.vars

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def var_recursive(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError('var %r not found (block %d)' % (name, self.idx))
        return v

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def append_op(self, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        for names in op.outputs.values():
            for n in names:
                v = self._find_var_recursive(n)
                if v is not None and v.op is None:
                    v.op = op
        self.program._bump_version()
        return op

    def _prepend_op(self, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    prepend_op = _prepend_op

    def _insert_op(self, index, type=None, inputs=None, outputs=None,
                   attrs=None):
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def to_string(self, throw_on_error=False, with_details=False):
        lines = ['block %d (parent %d):' % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append('  ' + v.to_string())
        for op in self.ops:
            lines.append('  ' + op.to_string())
        return '\n'.join(lines)

    __repr__ = __str__ = lambda self: self.to_string()


class Program(object):
    """A list of Blocks; block 0 is the global block
    (reference framework.py:1407, framework.proto ProgramDesc:183)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._op_role_var = []
        self._is_distributed = False

    # executor compile-cache invalidation
    def _bump_version(self):
        self._version += 1

    @property
    def num_blocks(self):
        return len(self.blocks)

    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        self._bump_version()
        return self.current_block()

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def all_parameters(self):
        return self.global_block().all_parameters()

    def clone(self, for_test=False):
        """Deep-copy the program.  With ``for_test=True``, ops behave in
        inference mode (is_test attr set; dropout/batch_norm switched)."""
        p = copy.deepcopy(self)
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    # batch_norm note: is_test only stops the running-
                    # statistics update; WHICH statistics normalize is
                    # the lowering's use_global_stats decision, so an
                    # explicit use_global_stats=False still gets batch
                    # statistics at test time without eval batches
                    # polluting the moving averages (ops/nn_ops.py)
                    if 'is_test' in _IS_TEST_OPS.get(op.type, ()):
                        op.attrs['is_test'] = True
        p._bump_version()
        return p

    def __deepcopy__(self, memo):
        cls = self.__class__
        p = cls.__new__(cls)
        memo[id(self)] = p
        for k, v in self.__dict__.items():
            setattr(p, k, copy.deepcopy(v, memo))
        return p

    def prune(self, targets):
        """Keep only ops needed to compute ``targets`` (framework/prune.h)."""
        if not isinstance(targets, (list, tuple)):
            targets = [targets]
        target_names = set(
            t.name if isinstance(t, Variable) else t for t in targets)
        p = copy.deepcopy(self)
        blk = p.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(blk.ops):
            if op.type == 'fetch' or set(op.output_arg_names) & needed or (
                    op.type == 'feed' and set(op.output_arg_names) & needed):
                kept.append(op)
                needed.update(op.input_arg_names)
        blk.ops = list(reversed(kept))
        p._bump_version()
        return p

    def inference_optimize(self, prune_read_op=True):
        p = self.clone(for_test=True)
        if prune_read_op:
            blk = p.global_block()
            blk.ops = [op for op in blk.ops if op.type not in ('read', )]
        return p

    def to_string(self, throw_on_error=False, with_details=False):
        return '\n'.join(b.to_string() for b in self.blocks)

    __repr__ = __str__ = lambda self: self.to_string()

    def copy_data_info_from(self, other):
        """Copy is_data/stop_gradient marks from ``other``'s global block
        onto same-named vars here (reference Program.copy_data_info_from —
        used after clone/prune so feed vars keep their data semantics)."""
        for name, src in other.global_block().vars.items():
            dst = self.global_block().vars.get(name)
            if dst is not None:
                dst.is_data = getattr(src, 'is_data', False)
                dst.stop_gradient = src.stop_gradient

    def get_desc(self):
        """The program's wire-level description (the reference returns the
        C++ ProgramDesc; here the structural dict the serde round-trips)."""
        return self.desc_dict()

    @contextlib.contextmanager
    def optimized_guard(self, param_and_grads):
        """Scope marking appended ops as optimizer ops (reference
        Program.optimized_guard sets OpRole.Optimize + the param/grad
        pair on every op built inside)."""
        prior = self._op_role_var
        self._op_role_var = [
            v.name if isinstance(v, Variable) else v
            for v in (param_and_grads or [])
        ]
        try:
            yield
        finally:
            self._op_role_var = prior

    # ---- serialization (program-is-data contract) ----
    def desc_dict(self):
        from . import program_serde
        return program_serde.program_to_dict(self)

    def serialize_to_string(self):
        """framework.proto ProgramDesc bytes — the reference's public
        model contract (framework.proto:183)."""
        from . import proto_serde
        return proto_serde.serialize_program(self)

    @staticmethod
    def parse_from_string(data):
        if isinstance(data, str):
            data = data.encode('utf-8')
        if data[:1] == b'{':
            # legacy structural-JSON artifact (pre-protobuf rounds)
            from . import program_serde
            return program_serde.deserialize_program(data)
        from . import proto_serde
        return proto_serde.deserialize_program(data)


# ops whose clone(for_test) should set is_test
_IS_TEST_OPS = {
    'dropout': ('is_test', ),
    'batch_norm': ('is_test', ),
    'layer_norm': (),
}

# ----------------------------------------------------------------------------
# default programs + guards (reference framework.py:2100-2230)
# ----------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_startup_program():
    return _startup_program_


def default_main_program():
    return _main_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_start = None
    if startup_program is not None:
        prev_start = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_start is not None:
            switch_startup_program(prev_start)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(prefix or '')
    try:
        yield
    finally:
        _name_scope_stack.pop()


def get_var(name, program=None):
    """Look up a Variable by name in ``program``'s global block
    (reference framework.get_var)."""
    program = program if program is not None else default_main_program()
    v = program.global_block().vars.get(name)
    if v is None:
        raise ValueError('var %r not found in program' % name)
    return v
