"""Parameter initializers (reference: python/paddle/fluid/initializer.py).

Each initializer appends an init op (fill_constant / uniform_random /
gaussian_random) to the startup program block holding the parameter — the
same program-as-initialization design as the reference.
"""

import numpy as np

from . import framework

__all__ = [
    'Constant', 'Uniform', 'Normal', 'TruncatedNormal', 'Xavier', 'MSRA',
    'Bilinear', 'force_init_on_cpu', 'init_on_cpu',
    'ConstantInitializer', 'UniformInitializer', 'NormalInitializer',
    'TruncatedNormalInitializer', 'XavierInitializer', 'MSRAInitializer',
    'BilinearInitializer', 'NumpyArrayInitializer',
]

_force_init_on_cpu_ = False


def force_init_on_cpu():
    return _force_init_on_cpu_


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu_
    prev = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    yield
    _force_init_on_cpu_ = prev


class Initializer(object):
    def __init__(self):
        pass

    def __call__(self, param, block):
        raise NotImplementedError()

    @staticmethod
    def _compute_fans(var):
        shape = var.shape
        if not shape:
            return 1, 1
        if len(shape) == 2:
            return shape[0], shape[1]
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 1 else shape[0]
        fan_out = shape[0] * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        super(ConstantInitializer, self).__init__()
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type='fill_constant',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'value': float(self._value)
            })


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        super(UniformInitializer, self).__init__()
        self._low = low
        self._high = high
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type='uniform_random',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'min': self._low,
                'max': self._high,
                'seed': self._seed
            })


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        super(NormalInitializer, self).__init__()
        self._mean = loc
        self._std_dev = scale
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type='gaussian_random',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'mean': self._mean,
                'std': self._std_dev,
                'seed': self._seed
            })


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        super(TruncatedNormalInitializer, self).__init__()
        self._mean = loc
        self._std_dev = scale
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type='truncated_gaussian_random',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'mean': self._mean,
                'std': self._std_dev,
                'seed': self._seed
            })


class XavierInitializer(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        super(XavierInitializer, self).__init__()
        self._uniform = uniform
        self._fan_in = fan_in
        self._fan_out = fan_out
        self._seed = seed

    def __call__(self, var, block):
        f_in, f_out = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        fan_out = f_out if self._fan_out is None else self._fan_out
        if self._uniform:
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            return block.append_op(
                type='uniform_random',
                outputs={'Out': [var.name]},
                attrs={
                    'shape': list(var.shape),
                    'dtype': var.dtype,
                    'min': -limit,
                    'max': limit,
                    'seed': self._seed
                })
        std = np.sqrt(2.0 / (fan_in + fan_out))
        return block.append_op(
            type='gaussian_random',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'mean': 0.0,
                'std': std,
                'seed': self._seed
            })


class MSRAInitializer(Initializer):
    """He/Kaiming init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        super(MSRAInitializer, self).__init__()
        self._uniform = uniform
        self._fan_in = fan_in
        self._seed = seed

    def __call__(self, var, block):
        f_in, _ = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        if self._uniform:
            limit = np.sqrt(6.0 / fan_in)
            return block.append_op(
                type='uniform_random',
                outputs={'Out': [var.name]},
                attrs={
                    'shape': list(var.shape),
                    'dtype': var.dtype,
                    'min': -limit,
                    'max': limit,
                    'seed': self._seed
                })
        std = np.sqrt(2.0 / fan_in)
        return block.append_op(
            type='gaussian_random',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(var.shape),
                'dtype': var.dtype,
                'mean': 0.0,
                'std': std,
                'seed': self._seed
            })


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv2d_transpose
    (reference initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError('BilinearInitializer needs a 4-D weight')
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        vals = np.zeros(size, dtype=np.float32)
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            vals[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return NumpyArrayInitializer(vals.reshape(shape))(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        super(NumpyArrayInitializer, self).__init__()
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type='assign_value',
            outputs={'Out': [var.name]},
            attrs={
                'shape': list(self._value.shape),
                'dtype': var.dtype,
                'values': self._value,
            })


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
