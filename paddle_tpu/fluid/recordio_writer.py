"""Convert python readers to recordio files
(reference: python/paddle/fluid/recordio_writer.py).

Records are npz-framed numpy tuples (data-only) inside the chunked
recordio container implemented in csrc/recordio.cc.
"""

import contextlib
import io as _io

import numpy as np

from ..runtime import RecordIOWriter
from . import core

__all__ = ['convert_reader_to_recordio_file',
           'convert_reader_to_recordio_files']


def _serialize_batch(arrays):
    buf = _io.BytesIO()
    np.savez(buf, *[np.asarray(a if not isinstance(a, core.LoDTensor)
                               else a.numpy()) for a in arrays])
    return buf.getvalue()


def convert_reader_to_recordio_file(filename,
                                    reader_creator,
                                    feeder,
                                    compressor='zlib',
                                    max_num_records=1000,
                                    feed_order=None):
    """Drain a batched reader through a DataFeeder into one recordio file;
    returns the record count (reference recordio_writer.py:36)."""
    if feed_order is None:
        feed_order = feeder.feed_names
    counter = 0
    with contextlib.closing(_WriterCM(filename, compressor)) as w:
        for batch in reader_creator():
            feed_dict = feeder.feed(batch)
            arrays = [feed_dict[name] for name in feed_order]
            w.write(_serialize_batch(arrays))
            counter += 1
            if counter >= max_num_records:
                break
    return counter


def convert_reader_to_recordio_files(filename,
                                     batch_per_file,
                                     reader_creator,
                                     feeder,
                                     compressor='zlib',
                                     max_num_records=1000,
                                     feed_order=None):
    if feed_order is None:
        feed_order = feeder.feed_names
    f_name, f_ext = filename.rsplit('.', 1)
    files = []
    batch_id = 0
    w = None
    for batch in reader_creator():
        if batch_id % batch_per_file == 0:
            if w is not None:
                w.close()
            name = '%s-%05d.%s' % (f_name, batch_id // batch_per_file,
                                   f_ext)
            files.append(name)
            w = _WriterCM(name, compressor)
        feed_dict = feeder.feed(batch)
        w.write(_serialize_batch([feed_dict[n] for n in feed_order]))
        batch_id += 1
        if batch_id >= max_num_records:
            break
    if w is not None:
        w.close()
    return files


class _WriterCM(object):
    def __init__(self, filename, compressor):
        self._w = RecordIOWriter(filename, compressor=compressor)

    def write(self, data):
        self._w.write(data)

    def close(self):
        self._w.close()
