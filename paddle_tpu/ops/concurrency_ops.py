"""Host implementations of the CSP ops: channel_create/send/recv/close,
go, select (reference: paddle/fluid/operators/concurrency/channel_*_op.cc,
go_op.cc, select_op.cc over framework/channel.h).

Channels are native (csrc/channel.cc).  go spawns a Python thread that
executes its sub-block eagerly against the shared scope — the analog of
go_op.cc launching the sub-program on a framework thread; per-op compute
still lowers through the XLA registry (eager-executed with concrete
values)."""

import threading
import time

import numpy as np

from .registry import (register_host_op, get_host_op, run_op,
                       LoweringContext)


class _ScopeEnv(dict):
    """env dict that falls back to scope-held values — Go sub-blocks read
    parent program state the way reference sub-scopes chain to parents."""

    def __init__(self, scope, *a, **kw):
        super(_ScopeEnv, self).__init__(*a, **kw)
        self._scope = scope

    def _from_scope(self, key):
        var = self._scope.find_var(key)
        if var is None:
            return None, False
        v = var.value()
        if v is None:
            return None, False
        return v, True

    def __missing__(self, key):
        v, ok = self._from_scope(key)
        if not ok:
            raise KeyError(key)
        self[key] = v
        return v

    def __contains__(self, key):
        if super(_ScopeEnv, self).__contains__(key):
            return True
        return self._from_scope(key)[1]

    def get(self, key, default=None):
        if super(_ScopeEnv, self).__contains__(key):
            return super(_ScopeEnv, self).get(key)
        v, ok = self._from_scope(key)
        return v if ok else default


def _run_block_eager(block, scope, env):
    """Execute a block's ops sequentially with concrete values (the host
    fallback interpreter — reference Executor::Run over a sub-block)."""
    from ..fluid import core
    # goroutine bodies run detached from the spawning trace: treat as a
    # conditional scope (no cond-uninit checks or clears)
    ctx = LoweringContext(block, env, rng_key=None, place=core.CPUPlace(),
                          conditional_scope=True)
    ctx.scope = scope
    for op in block.ops:
        host_impl = get_host_op(op.type)
        if host_impl is not None:
            host_impl(ctx, op, scope)
        else:
            run_op(ctx, op)
    return ctx


@register_host_op('channel_create')
def _channel_create(ctx, op, scope):
    from ..runtime.native import NativeChannel
    ch = NativeChannel(int(op.attrs.get('capacity', 0)))
    name = op.output('Out')[0]
    scope.var(name).set_value(ch)
    ctx.store(name, ch)


def _get_channel(ctx, op, scope, slot='Channel'):
    ch = ctx.get(op, slot)
    if ch is None:
        names = op.input(slot)
        var = scope.find_var(names[0]) if names else None
        ch = var.value() if var is not None else None
    return ch


@register_host_op('channel_send')
def _channel_send(ctx, op, scope):
    from ..fluid.concurrency import _serialize
    ch = _get_channel(ctx, op, scope)
    x = ctx.get(op, 'X')
    ok = ch.send(_serialize(np.asarray(x)))
    names = op.output('Status')
    if names:
        st = np.asarray([ok])
        scope.var(names[0]).set_value(st)
        ctx.store(names[0], st)


@register_host_op('channel_recv')
def _channel_recv(ctx, op, scope):
    from ..fluid.concurrency import _deserialize
    from ..runtime.native import NativeChannel
    ch = _get_channel(ctx, op, scope)
    data = ch.recv()
    out_name = op.output('Out')[0]
    if data is NativeChannel.CLOSED:
        ok = False
        # zero value with the return variable's own shape/dtype (Go
        # semantics); Out is an output slot, so read its current value
        prev = ctx.env.get(out_name)
        if prev is None:
            var = scope.find_var(out_name)
            prev = var.value() if var is not None else None
        out = (np.zeros_like(np.asarray(prev))
               if prev is not None else np.zeros((1, ), np.float32))
    else:
        ok = True
        out = _deserialize(data)
    scope.var(out_name).set_value(out)
    ctx.store(out_name, out)
    names = op.output('Status')
    if names:
        st = np.asarray([ok])
        scope.var(names[0]).set_value(st)
        ctx.store(names[0], st)


@register_host_op('channel_close')
def _channel_close(ctx, op, scope):
    ch = _get_channel(ctx, op, scope)
    ch.close()


@register_host_op('go')
def _go(ctx, op, scope):
    sub_block = op.attrs['sub_block']
    snapshot = _ScopeEnv(scope, dict(ctx.env))

    def body():
        _run_block_eager(sub_block, scope, snapshot)

    t = threading.Thread(target=body, daemon=True)
    t.start()


@register_host_op('select')
def _select(ctx, op, scope):
    from ..fluid.concurrency import _serialize, _deserialize
    from ..runtime.native import NativeChannel
    kinds = op.attrs['case_kinds']
    channels = op.attrs['case_channels']
    values = op.attrs['case_values']
    blocks = op.attrs['sub_blocks']
    env = _ScopeEnv(scope, dict(ctx.env))

    def chan(name):
        v = env.get(name)
        if v is None:
            var = scope.find_var(name)
            v = var.value() if var is not None else None
        return v

    def finish(blk):
        _run_block_eager(blk, scope, env)
        # select runs on a scope-backed env copy; surface its writes to the
        # enclosing block so later ops / fetches observe case results
        for k, v in env.items():
            ctx.env[k] = v

    while True:
        default_block = None
        for kind, ch_name, val_name, blk in zip(kinds, channels, values,
                                                blocks):
            if kind == 'default':
                default_block = blk
                continue
            ch = chan(ch_name)
            if kind == 'send':
                r = ch.try_send(_serialize(np.asarray(env[val_name])))
                if r is True:
                    finish(blk)
                    return
            else:  # recv
                r = ch.try_recv()
                if r is not NativeChannel.WOULD_BLOCK:
                    if r is NativeChannel.CLOSED:
                        # recv-from-closed is immediately ready with the
                        # zero value (Go semantics; matches _channel_recv)
                        prev = env.get(val_name)
                        out = (np.zeros_like(np.asarray(prev))
                               if prev is not None
                               else np.zeros((1, ), np.float32))
                    else:
                        out = _deserialize(r)
                    env[val_name] = out
                    scope.var(val_name).set_value(out)
                    finish(blk)
                    return
        if default_block is not None:
            finish(default_block)
            return
        time.sleep(0.001)
