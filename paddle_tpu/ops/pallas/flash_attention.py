"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

Never materialises the [L, L] score matrix in HBM: Q is blocked over the
grid, K/V stream through VMEM in `block_k` tiles folded into a blockwise
online softmax (running max / running sum).  The backward pass recomputes
probabilities from the saved log-sum-exp (the flash-attention trick) in
two kernels: one accumulating dQ over K blocks, one accumulating dK/dV
over Q blocks.

Layout: [batch, seq, heads, head_dim] END TO END.  The kernels see the
row-major [B, L, H*D] view and loop the heads INSIDE (unrolled — each
head is a static D-column slice), so the [B,L,H,D] -> [B,H,L,D]
transpose the usual formulation forces is never materialised.  In a
6-layer transformer those transposes (4 per attention forward + their
VJPs) were ~23% of the training step on hardware.
Variable-length rows mask K/V columns at ``seq_lengths`` — identical
semantics to parallel.context_parallel.dense_attention.

Scope: K/V for one batch row live in VMEM whole across all heads
(2 * L * H * D * 2 bytes bf16) — fine to L ≈ 4-8k at H*D = 512; longer
sequences belong to ring attention over the 'sp' mesh axis
(parallel/context_parallel.py), which shards L before the kernel runs.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['flash_attention']

_NEG_INF = -1e30


def _fwd_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, block_q, block_k, kv_len, heads, d):
    iq = pl.program_id(1)
    length = lens_ref[pl.program_id(0), 0]
    bq = q_ref.shape[1]
    nk = kv_len // block_k
    if causal:
        # only K blocks intersecting col <= row can contribute
        hi = jnp.minimum(((iq + 1) * block_q + block_k - 1) // block_k, nk)
    else:
        hi = nk
    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 0)

    for h in range(heads):
        q = q_ref[0, :, h * d:(h + 1) * d].astype(jnp.float32)  # [bq, D]

        def body(j, carry, h=h):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(j * block_k, block_k),
                       h * d:(h + 1) * d].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * block_k, block_k),
                       h * d:(h + 1) * d].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kb, (((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32) * scale
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = col < length
            if causal:
                mask = jnp.logical_and(mask, col <= row)
            s = jnp.where(mask, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(mask, p, 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jnp.dot(p, vb,
                                        preferred_element_type=jnp.float32)
            return m_new, l, acc

        m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq, 1), jnp.float32)
        acc0 = jnp.zeros((bq, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
        o_ref[0, :, h * d:(h + 1) * d] = (
            acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0, :, h] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _dq_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale, causal, block_q, block_k, kv_len, heads,
               d):
    iq = pl.program_id(1)
    length = lens_ref[pl.program_id(0), 0]
    bq = q_ref.shape[1]
    nk = kv_len // block_k
    hi = (jnp.minimum(((iq + 1) * block_q + block_k - 1) // block_k, nk)
          if causal else nk)
    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 0)

    for h in range(heads):
        q = q_ref[0, :, h * d:(h + 1) * d].astype(jnp.float32)
        do = do_ref[0, :, h * d:(h + 1) * d].astype(jnp.float32)
        lse = lse_ref[0, :, h][:, None]      # [bq, 1]
        delta = delta_ref[0, :, h][:, None]  # [bq, 1]

        def body(j, dq, h=h, q=q, do=do, lse=lse, delta=delta):
            kb = k_ref[0, pl.ds(j * block_k, block_k),
                       h * d:(h + 1) * d].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * block_k, block_k),
                       h * d:(h + 1) * d].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kb, (((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32) * scale
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = col < length
            if causal:
                mask = jnp.logical_and(mask, col <= row)
            p = jnp.exp(jnp.where(mask, s, _NEG_INF) - lse)
            p = jnp.where(mask, p, 0.0)
            dp = jax.lax.dot_general(do, vb, (((1, ), (1, )), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

        dq = jax.lax.fori_loop(0, hi, body,
                               jnp.zeros((bq, d), jnp.float32))
        dq_ref[0, :, h * d:(h + 1) * d] = dq.astype(dq_ref.dtype)


def _dkv_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k, q_len,
                heads, d):
    ik = pl.program_id(1)
    length = lens_ref[pl.program_id(0), 0]
    bk = k_ref.shape[1]
    nq = q_len // block_q
    # with causal masking, Q blocks strictly above the diagonal contribute 0
    lo = (ik * block_k) // block_q if causal else 0
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_k, block_q), 0)

    for h in range(heads):
        kb = k_ref[0, :, h * d:(h + 1) * d].astype(jnp.float32)  # [bk, D]
        vb = v_ref[0, :, h * d:(h + 1) * d].astype(jnp.float32)

        def body(j, carry, h=h, kb=kb, vb=vb):
            dk, dv = carry
            qb = q_ref[0, pl.ds(j * block_q, block_q),
                       h * d:(h + 1) * d].astype(jnp.float32)
            dob = do_ref[0, pl.ds(j * block_q, block_q),
                         h * d:(h + 1) * d].astype(jnp.float32)
            lseb = lse_ref[0, pl.ds(j * block_q, block_q), h][None, :]
            deltab = delta_ref[0, pl.ds(j * block_q, block_q), h][None, :]
            # s_T[bk, bq] = (K Q^T) * scale
            s = jax.lax.dot_general(
                kb, qb, (((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32) * scale
            rowq = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            mask = col < length
            if causal:
                mask = jnp.logical_and(mask, col <= rowq)
            p = jnp.exp(jnp.where(mask, s, _NEG_INF) - lseb)
            p = jnp.where(mask, p, 0.0)
            dv = dv + jnp.dot(p, dob, preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(vb, dob, (((1, ), (1, )), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - deltab) * scale
            dk = dk + jnp.dot(ds, qb, preferred_element_type=jnp.float32)
            return dk, dv

        z = jnp.zeros((bk, d), jnp.float32)
        dk, dv = jax.lax.fori_loop(lo, nq, body, (z, z))
        dk_ref[0, :, h * d:(h + 1) * d] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, h * d:(h + 1) * d] = dv.astype(dv_ref.dtype)


def _interpret_default():
    return jax.default_backend() == 'cpu'


def _pad_len(l, block):
    return ((l + block - 1) // block) * block


def _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k, interpret,
              heads):
    """q,k,v: [B,Lq,H*D] / [B,Lk,H*D]; lens: [B,1] int32 -> (o, lse)."""
    b, lq, hd = q.shape
    lk = k.shape[1]
    d = hd // heads
    grid = (b, lq // block_q)
    qspec = pl.BlockSpec((1, block_q, hd), lambda bi, i: (bi, i, 0))
    kvspec = pl.BlockSpec((1, lk, hd), lambda bi, i: (bi, 0, 0))
    lsespec = pl.BlockSpec((1, block_q, heads), lambda bi, i: (bi, i, 0))
    lspec = pl.BlockSpec((b, 1), lambda bi, i: (0, 0),
                         memory_space=pltpu.SMEM)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=lk,
                          heads=heads, d=d),
        grid=grid,
        in_specs=[lspec, qspec, kvspec, kvspec],
        out_specs=[qspec, lsespec],
        out_shape=[
            jax.ShapeDtypeStruct((b, lq, hd), q.dtype),
            jax.ShapeDtypeStruct((b, lq, heads), jnp.float32),
        ],
        interpret=interpret)(lens, q, k, v)
    return o, lse


def _bwd_impl(q, k, v, lens, o, lse, do, causal, scale, block_q, block_k,
              interpret, heads):
    b, lq, hd = q.shape
    lk = k.shape[1]
    d = hd // heads
    # delta[b, t, h] = sum_d do * o per head
    delta = jnp.sum(
        (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
            b, lq, heads, d), axis=-1)
    qspec = pl.BlockSpec((1, block_q, hd), lambda bi, i: (bi, i, 0))
    qfull = pl.BlockSpec((1, lq, hd), lambda bi, i: (bi, 0, 0))
    kvspec = pl.BlockSpec((1, lk, hd), lambda bi, i: (bi, 0, 0))
    kvblk = pl.BlockSpec((1, block_k, hd), lambda bi, i: (bi, i, 0))
    rowblk = pl.BlockSpec((1, block_q, heads), lambda bi, i: (bi, i, 0))
    rowfull = pl.BlockSpec((1, lq, heads), lambda bi, i: (bi, 0, 0))
    lspec = pl.BlockSpec((b, 1), lambda bi, i: (0, 0),
                         memory_space=pltpu.SMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=lk,
                          heads=heads, d=d),
        grid=(b, lq // block_q),
        in_specs=[lspec, qspec, kvspec, kvspec, qspec, rowblk, rowblk],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, lq, hd), q.dtype),
        interpret=interpret)(lens, q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, q_len=lq,
                          heads=heads, d=d),
        grid=(b, lk // block_k),
        in_specs=[lspec, qfull, kvblk, kvblk, qfull, rowfull, rowfull],
        out_specs=[kvblk, kvblk],
        out_shape=[
            jax.ShapeDtypeStruct((b, lk, hd), k.dtype),
            jax.ShapeDtypeStruct((b, lk, hd), v.dtype),
        ],
        interpret=interpret)(lens, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, lens, causal, scale, block_q, block_k, interpret,
           heads):
    o, _ = _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k,
                     interpret, heads)
    return o


def _flash_fwd(q, k, v, lens, causal, scale, block_q, block_k, interpret,
               heads):
    o, lse = _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k,
                       interpret, heads)
    return o, (q, k, v, lens, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, heads, res, do):
    q, k, v, lens, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, lens, o, lse, do, causal, scale,
                           block_q, block_k, interpret, heads)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, seq_lengths=None,
                    block_q=128, block_k=128, interpret=None):
    """Blocked flash attention.  q,k,v: [B, L, H, D] (Lq may differ from
    Lk for cross attention); seq_lengths: [B] valid K/V lengths."""
    scale = float(scale) if scale is not None else q.shape[-1]**-0.5
    if interpret is None:
        interpret = _interpret_default()
    b, lq, heads, d = q.shape
    lk = k.shape[1]
    block_q = min(block_q, _pad_len(lq, 8))
    block_k = min(block_k, _pad_len(lk, 8))
    lq_p = _pad_len(lq, block_q)
    lk_p = _pad_len(lk, block_k)
    if seq_lengths is None:
        lens = jnp.full((b, 1), lk, jnp.int32)
    else:
        lens = jnp.asarray(seq_lengths, jnp.int32).reshape(b, 1)

    def flat_pad(x, lpad):
        x = x.reshape(x.shape[0], x.shape[1], heads * d)
        pad = lpad - x.shape[1]
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    o = _flash(flat_pad(q, lq_p), flat_pad(k, lk_p), flat_pad(v, lk_p),
               lens, bool(causal), scale, block_q, block_k,
               bool(interpret), heads)
    return o[:, :lq].reshape(b, lq, heads, d)
