"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

Never materialises the [L, L] score matrix in HBM: Q is blocked over the
grid, K/V stream through VMEM in `block_k` tiles folded into a blockwise
online softmax (running max / running sum).  The backward pass recomputes
probabilities from the saved log-sum-exp (the flash-attention trick) in
two kernels: one accumulating dQ over K blocks, one accumulating dK/dV
over Q blocks.

Layout: [batch, seq, heads, head_dim] at the API (matching
ops/attention_ops.py); kernels run on [batch, heads, seq, head_dim].
Variable-length rows mask K/V columns at ``seq_lengths`` — identical
semantics to parallel.context_parallel.dense_attention.

v1 scope: K/V for one (batch, head) pair live in VMEM whole
(L * head_dim * 4 bytes each) — fine to L ≈ 16k at D=128; block the K/V
grid dimension too before going past that.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['flash_attention']

_NEG_INF = -1e30


def _fwd_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, block_q, block_k, kv_len):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
    length = lens_ref[pl.program_id(0), 0]
    bq, d = q.shape
    nk = kv_len // block_k
    if causal:
        # only K blocks intersecting col <= row can contribute
        hi = jnp.minimum(((iq + 1) * block_q + block_k - 1) // block_k, nk)
    else:
        hi = nk
    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1, ), (1, )), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < length
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, vb,
                                    preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(jnp.maximum(l, 1e-30))  # [bq, 1]


def _dq_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale, causal, block_q, block_k, kv_len):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]      # [bq, 1]
    delta = delta_ref[0, 0]  # [bq, 1]
    length = lens_ref[pl.program_id(0), 0]
    bq, d = q.shape
    nk = kv_len // block_k
    hi = (jnp.minimum(((iq + 1) * block_q + block_k - 1) // block_k, nk)
          if causal else nk)
    row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_q, block_k), 0)

    def body(j, dq):
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1, ), (1, )), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < length
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        p = jnp.exp(jnp.where(mask, s, _NEG_INF) - lse)
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(do, vb, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k, q_len):
    ik = pl.program_id(2)
    kb = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
    vb = v_ref[0, 0].astype(jnp.float32)
    length = lens_ref[pl.program_id(0), 0]
    bk, d = kb.shape
    nq = q_len // block_q
    # with causal masking, Q blocks strictly above the diagonal contribute 0
    lo = (ik * block_k) // block_q if causal else 0
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                  (block_k, block_q), 0)

    def body(j, carry):
        dk, dv = carry
        qb = q_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(
            jnp.float32)
        lseb = jnp.transpose(
            lse_ref[0, 0, pl.ds(j * block_q, block_q), :], (1, 0))
        deltab = jnp.transpose(
            delta_ref[0, 0, pl.ds(j * block_q, block_q), :], (1, 0))
        # s_T[bk, bq] = (K Q^T) * scale
        s = jax.lax.dot_general(
            kb, qb, (((1, ), (1, )), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rowq = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        mask = col < length
        if causal:
            mask = jnp.logical_and(mask, col <= rowq)
        p = jnp.exp(jnp.where(mask, s, _NEG_INF) - lseb)
        p = jnp.where(mask, p, 0.0)
        dv = dv + jnp.dot(p, dob, preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(vb, dob, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab) * scale
        dk = dk + jnp.dot(ds, qb, preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _interpret_default():
    return jax.default_backend() == 'cpu'


def _pad_len(l, block):
    return ((l + block - 1) // block) * block


def _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k, interpret):
    """q,k,v: [B,H,L,D]; lens: [B,1] int32.  Returns (o, lse)."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    grid = (b, h, lq // block_q)
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0))
    kvspec = pl.BlockSpec((1, 1, lk, d), lambda bi, hi, i: (bi, hi, 0, 0))
    lspec = pl.BlockSpec((b, 1), lambda bi, hi, i: (0, 0),
                         memory_space=pltpu.SMEM)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=lk),
        grid=grid,
        in_specs=[lspec, qspec, kvspec, kvspec],
        out_specs=[
            qspec,
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, i: (bi, hi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
        interpret=interpret)(lens, q, k, v)
    return o, lse


def _bwd_impl(q, k, v, lens, o, lse, do, causal, scale, block_q, block_k,
              interpret):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,Lq,1]
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0))
    qfull = pl.BlockSpec((1, 1, lq, d), lambda bi, hi, i: (bi, hi, 0, 0))
    kvspec = pl.BlockSpec((1, 1, lk, d), lambda bi, hi, i: (bi, hi, 0, 0))
    kvblk = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i: (bi, hi, i, 0))
    rowblk = pl.BlockSpec((1, 1, block_q, 1),
                          lambda bi, hi, i: (bi, hi, i, 0))
    rowfull = pl.BlockSpec((1, 1, lq, 1), lambda bi, hi, i: (bi, hi, 0, 0))
    lspec = pl.BlockSpec((b, 1), lambda bi, hi, i: (0, 0),
                         memory_space=pltpu.SMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=lk),
        grid=(b, h, lq // block_q),
        in_specs=[lspec, qspec, kvspec, kvspec, qspec, rowblk, rowblk],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
        interpret=interpret)(lens, q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, q_len=lq),
        grid=(b, h, lk // block_k),
        in_specs=[lspec, qfull, kvblk, kvblk, qfull, rowfull, rowfull],
        out_specs=[kvblk, kvblk],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, lk, d), v.dtype),
        ],
        interpret=interpret)(lens, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, lens, causal, scale, block_q, block_k, interpret):
    o, _ = _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k,
                     interpret)
    return o


def _flash_fwd(q, k, v, lens, causal, scale, block_q, block_k, interpret):
    o, lse = _fwd_impl(q, k, v, lens, causal, scale, block_q, block_k,
                       interpret)
    return o, (q, k, v, lens, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, lens, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, lens, o, lse, do, causal, scale,
                           block_q, block_k, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, seq_lengths=None,
                    block_q=128, block_k=128, interpret=None):
    """Blocked flash attention.  q,k,v: [B, L, H, D] (Lq may differ from
    Lk for cross attention); seq_lengths: [B] valid K/V lengths."""
    scale = float(scale) if scale is not None else q.shape[-1]**-0.5
    if interpret is None:
        interpret = _interpret_default()
    b, lq, heads, d = q.shape
    lk = k.shape[1]
    block_q = min(block_q, _pad_len(lq, 8))
    block_k = min(block_k, _pad_len(lk, 8))
    lq_p = _pad_len(lq, block_q)
    lk_p = _pad_len(lk, block_k)
    if seq_lengths is None:
        lens = jnp.full((b, 1), lk, jnp.int32)
    else:
        lens = jnp.asarray(seq_lengths, jnp.int32).reshape(b, 1)

    def to_bhld(x, lpad):
        x = jnp.transpose(x, (0, 2, 1, 3))  # [B,H,L,D]
        pad = lpad - x.shape[2]
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return x

    qt = to_bhld(q, lq_p)
    kt = to_bhld(k, lk_p)
    vt = to_bhld(v, lk_p)
    o = _flash(qt, kt, vt, lens, bool(causal), scale, block_q, block_k,
               bool(interpret))
    o = o[:, :, :lq, :]
    return jnp.transpose(o, (0, 2, 1, 3))
