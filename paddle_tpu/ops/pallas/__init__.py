"""Hand-written Pallas TPU kernels for the hot ops.

The reference ships hand-written CUDA kernels where cuBLAS/cuDNN fall
short (paddle/legacy/cuda/src/hl_*.cu, operators/math/*.cu); the TPU
analog is Pallas: VMEM-blocked kernels feeding the MXU, used where XLA's
automatic fusion can't deliver (flash attention's online softmax).
Kernels run compiled on TPU and in interpreter mode on CPU (tests).
"""
