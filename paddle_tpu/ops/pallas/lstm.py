"""Fused LSTM recurrence as a Pallas TPU kernel (forward + custom VJP).

The TPU-native answer to the reference's fused cell
(`paddle/fluid/operators/math/lstm_compute.h` +
`math/detail/lstm_cpu_kernel.h` — reference fuses the gate math per
timestep; `sequence2batch.h` handles reordering).  Here the WHOLE
recurrence is one kernel: the grid walks T sequentially, the hidden and
cell state live in VMEM scratch across grid steps, each step does one
[B,D]x[D,4D] MXU matmul plus VPU gate math, and the per-step gate
activations are saved as bf16 residuals for the backward kernel.  The
backward kernel walks the grid REVERSED (via index_map) carrying
dh/dc/dW/db accumulators in VMEM scratch.

Semantics match ops/sequence_ops.py:_lstm exactly (gate order
candidate/input/forget/output, bf16 h + f32 c under AMP, per-step
length masking); peepholes are not fused — the lowering falls back to
the lax.scan path for those.

Layout: x arrives [T, B, 4D] (time-major, as the scan path uses);
D and 4D must be multiples of 128 lanes for clean VMEM tiling.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ['lstm_fused', 'lstm_fused_tm']


def _interpret_default():
    return jax.default_backend() == 'cpu'


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _fwd_kernel(x_ref, w_ref, b_ref, h0_ref, c0_ref, m_ref,
                *refs, d, save_acts):
    if save_acts:
        hs_ref, cs_ref, acts_ref, h_scr, c_scr = refs
    else:
        hs_ref, cs_ref, h_scr, c_scr = refs
    t = pl.program_id(1)  # grid = (batch_blocks, T); T iterates fastest

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...]
        c_scr[...] = c0_ref[...]

    h = h_scr[...]
    c = c_scr[...]
    gates = x_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h, w_ref[...], (((1, ), (0, )), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0].astype(jnp.float32)
    gc = gates[:, :d]
    gi = gates[:, d:2 * d]
    gf = gates[:, 2 * d:3 * d]
    go = gates[:, 3 * d:]
    i = _sigmoid(gi)
    f = _sigmoid(gf)
    o = _sigmoid(go)
    cand = jnp.tanh(gc)
    c_new = f * c + i * cand
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0, 0][:, None]
    h_out = (m * h_new + (1 - m) * h.astype(jnp.float32)).astype(hs_ref.dtype)
    c_out = m * c_new + (1 - m) * c
    h_scr[...] = h_out
    c_scr[...] = c_out
    hs_ref[0] = h_out
    cs_ref[0] = c_out
    if save_acts:
        acts_ref[0, :, :d] = cand.astype(acts_ref.dtype)
        acts_ref[0, :, d:2 * d] = i.astype(acts_ref.dtype)
        acts_ref[0, :, 2 * d:3 * d] = f.astype(acts_ref.dtype)
        acts_ref[0, :, 3 * d:] = o.astype(acts_ref.dtype)


def _bwd_kernel(w_ref, m_ref, acts_ref, csp_ref, hsp_ref, h0_ref, c0_ref,
                dhs_ref, dcs_ref, dx_ref, dw_ref, db_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dw_scr, db_scr, *, d, t_total):
    bi = pl.program_id(0)
    t = pl.program_id(1)  # 0..T-1 walking REVERSED logical time, fastest
    # csp/hsp blocks are cs/hs read at logical time-1 (shifted index map,
    # clamped at 0); at the first logical step the real prev state is h0/c0
    first = t == t_total - 1
    c_prev_blk = csp_ref[0]
    h_prev_blk = hsp_ref[0]
    c_prev = jnp.where(first, c0_ref[...], c_prev_blk)
    h_prev = jnp.where(first, h0_ref[...], h_prev_blk)

    @pl.when(t == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)

    @pl.when(jnp.logical_and(bi == 0, t == 0))
    def _init_wb():
        dw_scr[...] = jnp.zeros_like(dw_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    cand = acts_ref[0, :, :d].astype(jnp.float32)
    i = acts_ref[0, :, d:2 * d].astype(jnp.float32)
    f = acts_ref[0, :, 2 * d:3 * d].astype(jnp.float32)
    o = acts_ref[0, :, 3 * d:].astype(jnp.float32)
    c_new = f * c_prev + i * cand  # pre-mask cell, recomputed
    tanh_c = jnp.tanh(c_new)
    m = m_ref[0, 0][:, None]

    dh_tot = dhs_ref[0].astype(jnp.float32) + dh_scr[...]
    dc_tot = dcs_ref[0] + dc_scr[...]
    dh_new = m * dh_tot
    do = dh_new * tanh_c
    dc_new = m * dc_tot + dh_new * o * (1 - tanh_c * tanh_c)
    di = dc_new * cand
    df = dc_new * c_prev
    dcand = dc_new * i
    dgi = di * i * (1 - i)
    dgf = df * f * (1 - f)
    dgo = do * o * (1 - o)
    dgc = dcand * (1 - cand * cand)
    dgates = jnp.concatenate([dgc, dgi, dgf, dgo], axis=1)
    dx_ref[0] = dgates.astype(dx_ref.dtype)

    dg16 = dgates.astype(w_ref.dtype)
    # dh_prev = (1-m)*dh_tot + dgates @ W^T
    dh_scr[...] = (1 - m) * dh_tot + jax.lax.dot_general(
        dg16, w_ref[...], (((1, ), (1, )), ((), ())),
        preferred_element_type=jnp.float32)
    dc_scr[...] = (1 - m) * dc_tot + dc_new * f
    # dW += h_prev^T @ dgates ; db += sum_b dgates
    dw_scr[...] += jax.lax.dot_general(
        h_prev.astype(dg16.dtype), dg16, (((0, ), (0, )), ((), ())),
        preferred_element_type=jnp.float32)
    db_scr[...] += jnp.sum(dgates, axis=0, keepdims=True)

    @pl.when(t == t_total - 1)
    def _finish():
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[...]

    @pl.when(jnp.logical_and(bi == pl.num_programs(0) - 1,
                             t == t_total - 1))
    def _finish_wb():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)
        db_ref[...] = db_scr[...].astype(db_ref.dtype)


def _batch_block(b, d4):
    """Batch tile dividing b, sized so the backward kernel's VMEM budget
    (dw accumulator + double-buffered per-step blocks) stays under the
    ~16MB scoped limit; measured: bq=256 at 4D=2048 overflows by 0.3MB."""
    cap = 256 if d4 <= 1024 else 128
    if b <= cap:
        return b
    for bq in (cap, 128, 64, 32, 16, 8):
        if bq <= cap and b % bq == 0:
            return bq
    return b


def _fwd_impl(xs, w16, bias, h0, c0, mask, interpret, save_acts=True):
    t, b, d4 = xs.shape
    d = d4 // 4
    bq = _batch_block(b, d4)
    step = pl.BlockSpec((1, bq, d4), lambda bi, i: (i, bi, 0))
    steph = pl.BlockSpec((1, bq, d), lambda bi, i: (i, bi, 0))
    stepm = pl.BlockSpec((1, 1, bq), lambda bi, i: (i, 0, bi))
    blkh = pl.BlockSpec((bq, d), lambda bi, i: (bi, 0))
    full = lambda shape: pl.BlockSpec(shape, lambda bi, i: tuple(
        0 for _ in shape))
    out_specs = [steph, steph]
    out_shape = [
        jax.ShapeDtypeStruct((t, b, d), h0.dtype),
        jax.ShapeDtypeStruct((t, b, d), jnp.float32),
    ]
    if save_acts:
        out_specs.append(step)
        out_shape.append(jax.ShapeDtypeStruct((t, b, d4), w16.dtype))
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel, d=d, save_acts=save_acts),
        grid=(b // bq, t),
        in_specs=[step, full((d, d4)), full((1, d4)), blkh, blkh, stepm],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, d), h0.dtype),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('arbitrary', 'arbitrary')),
        interpret=interpret)(xs, w16, bias, h0, c0, mask)
    if save_acts:
        return outs
    hs, cs = outs
    return hs, cs, None


def _bwd_impl(w16, mask, acts, cs, hs, h0, c0, dhs, dcs, interpret,
              x_dtype):
    t, b, d4 = acts.shape
    d = d4 // 4
    bq = _batch_block(b, d4)
    rev = lambda bi, i: (t - 1 - i, bi, 0)
    revm = lambda bi, i: (t - 1 - i, 0, bi)
    # cs/hs read at logical time-1: array index T-2-i, clamped at 0 (the
    # i == T-1 block is discarded in-kernel in favor of h0/c0) — avoids
    # materializing shifted [T,B,D] copies in HBM
    revp = lambda bi, i: (jnp.maximum(t - 2 - i, 0), bi, 0)
    step = pl.BlockSpec((1, bq, d4), rev)
    steph = pl.BlockSpec((1, bq, d), rev)
    stephp = pl.BlockSpec((1, bq, d), revp)
    stepm = pl.BlockSpec((1, 1, bq), revm)
    blkh = pl.BlockSpec((bq, d), lambda bi, i: (bi, 0))
    full = lambda shape: pl.BlockSpec(shape, lambda bi, i: tuple(
        0 for _ in shape))
    dx, dw, db, dh0, dc0 = pl.pallas_call(
        functools.partial(_bwd_kernel, d=d, t_total=t),
        grid=(b // bq, t),
        in_specs=[full((d, d4)), stepm, step, stephp, stephp, blkh, blkh,
                  steph, steph],
        out_specs=[step, full((d, d4)), full((1, d4)), blkh, blkh],
        out_shape=[
            jax.ShapeDtypeStruct((t, b, d4), x_dtype),
            jax.ShapeDtypeStruct((d, d4), jnp.float32),
            jax.ShapeDtypeStruct((1, d4), jnp.float32),
            jax.ShapeDtypeStruct((b, d), h0.dtype),
            jax.ShapeDtypeStruct((b, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((d, d4), jnp.float32),
            pltpu.VMEM((1, d4), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('arbitrary', 'arbitrary')),
        interpret=interpret)(w16, mask, acts, cs, hs, h0, c0, dhs, dcs)
    return dx, dw, db, dh0, dc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, ))
def _lstm_core(xs, w16, bias, h0, c0, mask, interpret):
    # primal (no grad requested): skip the [T,B,4D] acts residual write
    hs, cs, _ = _fwd_impl(xs, w16, bias, h0, c0, mask, interpret,
                          save_acts=False)
    return hs, cs


def _lstm_core_fwd(xs, w16, bias, h0, c0, mask, interpret):
    hs, cs, acts = _fwd_impl(xs, w16, bias, h0, c0, mask, interpret)
    return (hs, cs), (w16, mask, acts, cs, hs, h0, c0)


def _lstm_core_bwd(interpret, res, grads):
    w16, mask, acts, cs, hs, h0, c0 = res
    x_dtype = w16.dtype  # w16 was cast to x's dtype in lstm_fused_tm
    dhs, dcs = grads
    dx, dw, db, dh0, dc0 = _bwd_impl(
        w16, mask, acts, cs, hs, h0, c0, dhs,
        dcs.astype(jnp.float32), interpret, x_dtype)
    return (dx, dw.astype(w16.dtype), db.astype(jnp.float32), dh0, dc0,
            None)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_fused_tm(xs, w, bias, h0, c0, mask=None, interpret=None):
    """Time-major fused LSTM: xs [T,B,4D] pre-projected gates, w [D,4D],
    bias [1,4D], h0 [B,D] (hidden dtype), c0 [B,D] f32, mask [T,B] or
    None.  Returns (hs [T,B,D] in h0.dtype, cs [T,B,D] f32)."""
    if interpret is None:
        interpret = _interpret_default()
    t, b, d4 = xs.shape
    if mask is None:
        mask = jnp.ones((t, b), jnp.float32)
    mask = mask.reshape(t, 1, b)
    w16 = w.astype(xs.dtype)
    bias = jnp.asarray(bias, jnp.float32).reshape(1, d4)
    return _lstm_core(xs, w16, bias, h0, c0, mask, bool(interpret))


def lstm_fused(x, w, bias, h0, c0, mask=None, interpret=None):
    """Batch-major convenience wrapper: x [B,T,4D] -> hs [B,T,D]."""
    xs = jnp.swapaxes(x, 0, 1)
    m = None if mask is None else jnp.swapaxes(mask, 0, 1)
    hs, _ = lstm_fused_tm(xs, w, bias, h0, c0, m, interpret)
    return jnp.swapaxes(hs, 0, 1)
