"""Tensor creation / manipulation op lowerings.

Reference kernels: paddle/fluid/operators/fill_constant_op.cc,
uniform_random_op.cc, gaussian_random_op.cc, cast_op.cc, reshape_op.cc,
transpose_op.cc, concat_op.cc, split_op.cc, gather_op.cc, one_hot_op.cc, etc.
Random ops draw from the block's carried PRNG key (pure-functional analog of
the reference's per-device curand generators).
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register_lowering, register_grad_lowering,
                       fwd_structure, GRAD_SUFFIX)
from ..fluid import core


def _np_dtype(attr_dtype, default=np.float32):
    if attr_dtype is None:
        return np.dtype(default)
    return core.convert_dtype_to_np(attr_dtype)


@register_lowering('fill_constant')
def _fill_constant(ctx, op):
    dtype = _np_dtype(op.attrs.get('dtype'))
    value = op.attrs.get('value', 0.0)
    shape = op.attrs.get('shape', [1])
    ctx.set(op, 'Out', jnp.full(tuple(shape), value, dtype=dtype))
    if tuple(shape) == (1, ):  # scalar: track for index constant folding
        ctx.concrete[op.output('Out')[0]] = value


@register_lowering('fill_constant_batch_size_like')
def _fill_constant_bsl(ctx, op):
    ref = ctx.get(op, 'Input')
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = list(op.attrs.get('shape'))
    in_idx = op.attrs.get('input_dim_idx', 0)
    out_idx = op.attrs.get('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    ctx.set(op, 'Out',
            jnp.full(tuple(shape), op.attrs.get('value', 0.0), dtype=dtype))


@register_lowering('fill_zeros_like')
def _fill_zeros_like(ctx, op):
    ctx.set(op, 'Out', jnp.zeros_like(ctx.get(op, 'X')))


@register_lowering('uniform_random')
def _uniform_random(ctx, op):
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = tuple(op.attrs.get('shape'))
    lo = op.attrs.get('min', -1.0)
    hi = op.attrs.get('max', 1.0)
    seed = op.attrs.get('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set(op, 'Out',
            jax.random.uniform(key, shape, dtype=jnp.float32, minval=lo,
                               maxval=hi).astype(dtype))


@register_lowering('gaussian_random')
def _gaussian_random(ctx, op):
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = tuple(op.attrs.get('shape'))
    mean = op.attrs.get('mean', 0.0)
    std = op.attrs.get('std', 1.0)
    seed = op.attrs.get('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set(op, 'Out',
            (mean +
             std * jax.random.normal(key, shape, dtype=jnp.float32)).astype(
                 dtype))


@register_lowering('truncated_gaussian_random')
def _truncated_gaussian_random(ctx, op):
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = tuple(op.attrs.get('shape'))
    mean = op.attrs.get('mean', 0.0)
    std = op.attrs.get('std', 1.0)
    seed = op.attrs.get('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set(op, 'Out',
            (mean + std * jax.random.truncated_normal(
                key, -2.0, 2.0, shape, dtype=jnp.float32)).astype(dtype))


@register_lowering('uniform_random_batch_size_like')
def _uniform_random_bsl(ctx, op):
    ref = ctx.get(op, 'Input')
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = list(op.attrs.get('shape'))
    shape[op.attrs.get('output_dim_idx', 0)] = ref.shape[op.attrs.get(
        'input_dim_idx', 0)]
    seed = op.attrs.get('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set(op, 'Out',
            jax.random.uniform(
                key,
                tuple(shape),
                dtype=jnp.float32,
                minval=op.attrs.get('min', -1.0),
                maxval=op.attrs.get('max', 1.0)).astype(dtype))


@register_lowering('gaussian_random_batch_size_like')
def _gaussian_random_bsl(ctx, op):
    ref = ctx.get(op, 'Input')
    dtype = _np_dtype(op.attrs.get('dtype'))
    shape = list(op.attrs.get('shape'))
    shape[op.attrs.get('output_dim_idx', 0)] = ref.shape[op.attrs.get(
        'input_dim_idx', 0)]
    seed = op.attrs.get('seed', 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.next_rng()
    ctx.set(op, 'Out',
            (op.attrs.get('mean', 0.0) + op.attrs.get('std', 1.0) *
             jax.random.normal(key, tuple(shape),
                               dtype=jnp.float32)).astype(dtype))


@register_lowering('cast')
def _cast(ctx, op):
    x = ctx.get(op, 'X')
    dtype = _np_dtype(op.attrs.get('out_dtype'))
    ctx.set(op, 'Out', x.astype(dtype))


def _infer_reshape(x, shape):
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:  # 0 means "copy from input dim i"
            shape[i] = x.shape[i]
    return jnp.reshape(x, tuple(shape))


@register_lowering('reshape')
def _reshape(ctx, op):
    x = ctx.get(op, 'X')
    shape_in = ctx.get(op, 'Shape')
    shape = None
    if shape_in is not None:
        # XLA needs static shapes: a concrete Shape tensor wins, a traced one
        # falls back to the compile-time attr (the reference's runtime
        # actual_shape override has no static-shape analog)
        try:
            shape = [int(s) for s in np.asarray(shape_in)]
        except Exception:
            shape = None
    if shape is None:
        shape = op.attrs['shape']
    ctx.set(op, 'Out', _infer_reshape(x, shape))


@register_lowering('reshape2')
def _reshape2(ctx, op):
    x = ctx.get(op, 'X')
    shape = op.attrs['shape']
    ctx.set(op, 'Out', _infer_reshape(x, shape))
    ctx.set(op, 'XShape', jnp.zeros((0, ) + x.shape, x.dtype))


@register_lowering('transpose')
def _transpose(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.transpose(x, op.attrs['axis']))


@register_lowering('transpose2')
def _transpose2(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.transpose(x, op.attrs['axis']))
    ctx.set(op, 'XShape', jnp.zeros((0, ) + x.shape, x.dtype))


@register_lowering('squeeze')
def _squeeze(ctx, op):
    x = ctx.get(op, 'X')
    axes = op.attrs.get('axes', [])
    if axes:
        out = jnp.squeeze(x, tuple(a for a in axes if x.shape[a] == 1))
    else:
        out = jnp.squeeze(x)
    ctx.set(op, 'Out', out)


@register_lowering('unsqueeze')
def _unsqueeze(ctx, op):
    x = ctx.get(op, 'X')
    out = x
    for a in sorted(op.attrs['axes']):
        out = jnp.expand_dims(out, a)
    ctx.set(op, 'Out', out)


@register_lowering('concat')
def _concat(ctx, op):
    xs = ctx.get_list(op, 'X')
    ctx.set(op, 'Out', jnp.concatenate(xs, axis=op.attrs.get('axis', 0)))


@register_lowering('split')
def _split(ctx, op):
    x = ctx.get(op, 'X')
    axis = op.attrs.get('axis', 0)
    num = op.attrs.get('num', 0)
    sections = op.attrs.get('sections', [])
    if num:
        outs = jnp.split(x, num, axis=axis)
    else:
        idx = np.cumsum(sections)[:-1]
        outs = jnp.split(x, idx, axis=axis)
    ctx.set_list(op, 'Out', outs)


@register_lowering('assign')
def _assign(ctx, op):
    ctx.set(op, 'Out', ctx.get(op, 'X'))
    out_name = op.output('Out')[0]
    cin = ctx.concrete.get(op.input('X')[0])
    if cin is not None:
        ctx.concrete[out_name] = cin
    else:
        ctx.concrete.pop(out_name, None)


@register_grad_lowering('assign')
def _assign_grad(ctx, op):
    """Identity pass-through.  Explicit (not generic-vjp) because assign is
    used to snapshot loop-carried state (While Init): by backward time the
    source name holds the FINAL loop value, so recomputing the primal
    would mismatch the cotangent's pre-loop structure."""
    fwd_inputs, fwd_outputs, _ = fwd_structure(op)
    gsrc = fwd_outputs['Out'][0] + GRAD_SUFFIX
    gnames = op.output('X' + GRAD_SUFFIX)
    if ctx.has(gsrc) and gnames and gnames[0]:
        ctx.store(gnames[0], ctx.lookup(gsrc))


@register_lowering('assign_value')
def _assign_value(ctx, op):
    vals = np.asarray(op.attrs['values'])
    dtype = _np_dtype(op.attrs.get('dtype'))
    arr = vals.reshape(tuple(op.attrs['shape'])).astype(dtype)
    ctx.set(op, 'Out', jnp.asarray(arr))
    # the values are program constants: record them so consumers needing
    # concrete data (lod_reset offsets) can fold them at trace time
    ctx.concrete[op.output('Out')[0]] = arr


@register_lowering('shape')
def _shape(ctx, op):
    x = ctx.get(op, 'Input')
    ctx.set(op, 'Out', jnp.asarray(x.shape, dtype=jnp.int32))


@register_lowering('slice')
def _slice(ctx, op):
    x = ctx.get(op, 'Input')
    axes = op.attrs['axes']
    starts = op.attrs['starts']
    ends = op.attrs['ends']
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    ctx.set(op, 'Out', x[tuple(idx)])


@register_lowering('expand')
def _expand(ctx, op):
    x = ctx.get(op, 'X')
    times = op.attrs['expand_times']
    ctx.set(op, 'Out', jnp.tile(x, tuple(times)))


@register_lowering('stack')
def _stack(ctx, op):
    xs = ctx.get_list(op, 'X')
    ctx.set(op, 'Y', jnp.stack(xs, axis=op.attrs.get('axis', 0)))


@register_lowering('unstack')
def _unstack(ctx, op):
    x = ctx.get(op, 'X')
    axis = op.attrs.get('axis', 0)
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]
    ctx.set_list(op, 'Y', outs)


@register_lowering('gather')
def _gather(ctx, op):
    x = ctx.get(op, 'X')
    index = ctx.get(op, 'Index')
    ctx.set(op, 'Out', jnp.take(x, jnp.reshape(index, (-1, )), axis=0))


@register_lowering('scatter')
def _scatter(ctx, op):
    x = ctx.get(op, 'X')
    ids = jnp.reshape(ctx.get(op, 'Ids'), (-1, ))
    updates = ctx.get(op, 'Updates')
    ctx.set(op, 'Out', x.at[ids].set(updates))


@register_lowering('one_hot')
def _one_hot(ctx, op):
    x = ctx.get(op, 'X')
    depth = op.attrs['depth']
    flat = jnp.reshape(x, x.shape[:-1] if x.shape and x.shape[-1] == 1 else
                       x.shape)
    ctx.set(op, 'Out', jax.nn.one_hot(flat, depth, dtype=jnp.float32))


@register_lowering('reverse')
def _reverse(ctx, op):
    x = ctx.get(op, 'X')
    axes = op.attrs['axis']
    if isinstance(axes, int):
        axes = [axes]
    out = x
    for a in axes:
        out = jnp.flip(out, a)
    ctx.set(op, 'Out', out)


@register_lowering('pad')
def _pad(ctx, op):
    x = ctx.get(op, 'X')
    paddings = op.attrs['paddings']
    pad_value = op.attrs.get('pad_value', 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set(op, 'Out', jnp.pad(x, cfg, constant_values=pad_value))


@register_lowering('pad2d')
def _pad2d(ctx, op):
    x = ctx.get(op, 'X')  # NCHW
    p = op.attrs['paddings']  # [top, bottom, left, right]
    mode = op.attrs.get('mode', 'constant')
    value = op.attrs.get('pad_value', 0.0)
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == 'constant':
        ctx.set(op, 'Out', jnp.pad(x, cfg, constant_values=value))
    else:
        jmode = {'reflect': 'reflect', 'edge': 'edge'}[mode]
        ctx.set(op, 'Out', jnp.pad(x, cfg, mode=jmode))


@register_lowering('multiplex')
def _multiplex(ctx, op):
    ids = jnp.reshape(ctx.get(op, 'Ids'), (-1, ))
    xs = jnp.stack(ctx.get_list(op, 'X'), axis=0)  # (K, N, D)
    rows = jnp.arange(xs.shape[1])
    ctx.set(op, 'Out', xs[ids, rows])


@register_lowering('label_smooth')
def _label_smooth(ctx, op):
    x = ctx.get(op, 'X')
    eps = op.attrs.get('epsilon', 0.0)
    dist = ctx.get(op, 'PriorDist')
    k = x.shape[-1]
    if dist is not None:
        out = (1 - eps) * x + eps * jnp.reshape(dist, (1, -1))
    else:
        out = (1 - eps) * x + eps / k
    ctx.set(op, 'Out', out)


@register_lowering('argmax')
def _argmax(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.argmax(x, axis=op.attrs.get('axis', 0)))


@register_lowering('argmin')
def _argmin(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.argmin(x, axis=op.attrs.get('axis', 0)))


@register_lowering('argsort')
def _argsort(ctx, op):
    x = ctx.get(op, 'X')
    axis = op.attrs.get('axis', -1)
    idx = jnp.argsort(x, axis=axis)
    ctx.set(op, 'Indices', idx.astype(jnp.int64))
    ctx.set(op, 'Out', jnp.sort(x, axis=axis))


@register_lowering('top_k')
def _top_k(ctx, op):
    x = ctx.get(op, 'X')
    k = op.attrs['k']
    vals, idx = jax.lax.top_k(x, k)
    ctx.set(op, 'Out', vals)
    ctx.set(op, 'Indices', idx.astype(jnp.int64))


@register_lowering('crop')
def _crop(ctx, op):
    x = ctx.get(op, 'X')
    offsets = op.attrs.get('offsets')
    shape = op.attrs.get('shape')
    y = ctx.get(op, 'Y')
    if y is not None:
        shape = y.shape
    idx = tuple(
        slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set(op, 'Out', x[idx])


@register_lowering('random_crop')
def _random_crop(ctx, op):
    x = ctx.get(op, 'X')
    shape = op.attrs['shape']  # crop shape for trailing dims
    key = ctx.next_rng()
    nlead = x.ndim - len(shape)
    starts = []
    for i, s in enumerate(shape):
        key, k = jax.random.split(key)
        limit = x.shape[nlead + i] - s
        starts.append(
            jax.random.randint(k, (), 0, max(limit, 0) + 1))
    start_idx = [jnp.zeros((), jnp.int32)] * nlead + [
        s.astype(jnp.int32) for s in starts
    ]
    sizes = list(x.shape[:nlead]) + list(shape)
    ctx.set(op, 'Out', jax.lax.dynamic_slice(x, start_idx, sizes))


@register_lowering('lod_reset')
def _lod_reset(ctx, op):
    """Reference lod_reset_op.cc: keep the flat payload, replace the LoD.
    Under the padded+SEQLEN lowering a re-segmentation is a RE-LAYOUT:
    the flat rows move from the old [B, T, ...] padding to a new
    [B', T', ...] one.  Three forms of the new segmentation: a concrete
    attr target_lod; a Y whose values are trace-time-known offsets
    (concrete fill); or a runtime LoD sequence Y — there Y's padded
    layout [B2, T2] fixes the output bucket statically and only the
    per-row lengths stay traced (the round-4 bucketed form).  Total-
    length agreement with X (reference enforce: last offset == X rows)
    is checked where trace-time-knowable — concrete offsets against a
    flat X — and is the caller's contract in the traced cases."""
    from .registry import SEQLEN_SUFFIX
    x = ctx.get(op, 'X')
    out_name = op.output('Out')[0]
    lens_arr = None   # traced or concrete new lengths [B2]
    off_start = None  # traced or concrete new start offsets [B2]
    b2 = t2 = None
    if op.attrs.get('target_lod'):
        offsets = np.asarray(op.attrs['target_lod'], np.int64)
    elif op.input('Y'):
        y_name = op.input('Y')[0]
        conc = ctx.concrete.get(y_name)
        if conc is not None:
            offsets = np.asarray(conc, np.int64).reshape(-1)
        elif (y_name + SEQLEN_SUFFIX) in ctx.env:
            # the BUCKETED traced-Y form (closes the round-2/3 delta):
            # Y is itself a padded sequence, so its STATIC layout
            # [B2, T2] fixes the output bucket at trace time; only the
            # per-row lengths are traced, and the re-layout below is
            # pure gathers, which XLA takes with traced indices.  The
            # one semantic bound vs the reference: a Y row longer than
            # its padded bucket T2 cannot be represented (the feed
            # bucketing guarantees it isn't)
            offsets = None
            y = ctx.lookup(y_name)
            lens_arr = ctx.env[y_name + SEQLEN_SUFFIX].astype(jnp.int32)
            b2, t2 = int(y.shape[0]), int(y.shape[1])
            cum2 = jnp.cumsum(lens_arr)
            off_start = cum2 - lens_arr
        else:
            raise ValueError(
                'lod_reset: Y carries neither concrete offsets nor a '
                'padded-sequence layout')
    else:
        raise ValueError('lod_reset needs Y or target_lod')
    if lens_arr is None:
        new_lens = offsets[1:] - offsets[:-1]
        b2 = len(new_lens)
        t2 = int(max(((int(new_lens.max()) + 15) // 16) * 16, 16)) \
            if b2 else 16
        lens_arr = jnp.asarray(new_lens, jnp.int32)
        off_start = jnp.asarray(offsets[:-1], jnp.int32)

    in_lens = ctx.env.get(op.input('X')[0] + SEQLEN_SUFFIX)
    feat = x.shape[2:] if in_lens is not None else x.shape[1:]
    if (offsets is not None and len(offsets) and in_lens is None
            and int(offsets[-1]) != int(x.shape[0])):
        raise ValueError(
            'lod_reset: target offsets end at %d but X has %d rows '
            '(reference lod_reset_op enforce)' %
            (int(offsets[-1]), int(x.shape[0])))
    # flat index each output slot reads: n = off_start[b2] + t
    n_grid = off_start[:, None] + jnp.arange(t2)[None, :]
    valid = jnp.arange(t2)[None, :] < lens_arr[:, None]
    n_flat = jnp.where(valid, n_grid, 0)
    if in_lens is None:
        # x is flat [N, ...]; jnp.take clips out-of-range indices
        out = jnp.take(x, n_flat.reshape(-1), axis=0)
    else:
        # x is padded [B, T, ...]: flat n lives at row r, col n-start[r]
        in_lens = in_lens.astype(jnp.int32)
        cum = jnp.cumsum(in_lens)
        starts = cum - in_lens
        n1 = n_flat.reshape(-1)
        r = jnp.searchsorted(cum, n1, side='right').astype(jnp.int32)
        r = jnp.clip(r, 0, x.shape[0] - 1)
        c = (n1 - jnp.take(starts, r)).astype(jnp.int32)
        c = jnp.clip(c, 0, x.shape[1] - 1)
        out = x[r, c]
    out = out.reshape((b2, t2) + feat)
    mask = valid.reshape((b2, t2) + (1, ) * len(feat))
    out = jnp.where(mask, out, jnp.zeros_like(out))
    ctx.store(out_name, out)
    ctx.env[out_name + SEQLEN_SUFFIX] = lens_arr


@register_lowering('increment')
def _increment(ctx, op):
    x = ctx.get(op, 'X')
    step = op.attrs.get('step', 1.0)
    ctx.set(op, 'Out', x + jnp.asarray(step, x.dtype))
    out_name = op.output('Out')[0]
    cin = ctx.concrete.get(op.input('X')[0])
    if cin is not None:
        ctx.concrete[out_name] = cin + step
    else:
        ctx.concrete.pop(out_name, None)


def _register_compare(name, fn):
    @register_lowering(name)
    def _lower(ctx, op, fn=fn):
        x = ctx.get(op, 'X')
        y = ctx.get(op, 'Y')
        ctx.set(op, 'Out', fn(x, y))


_register_compare('less_than', jnp.less)
_register_compare('less_equal', jnp.less_equal)
_register_compare('greater_than', jnp.greater)
_register_compare('greater_equal', jnp.greater_equal)
_register_compare('equal', jnp.equal)
_register_compare('not_equal', jnp.not_equal)
_register_compare('logical_and', jnp.logical_and)
_register_compare('logical_or', jnp.logical_or)
_register_compare('logical_xor', jnp.logical_xor)


@register_lowering('logical_not')
def _logical_not(ctx, op):
    ctx.set(op, 'Out', jnp.logical_not(ctx.get(op, 'X')))


@register_lowering('where_select')
def _where_select(ctx, op):
    cond = ctx.get(op, 'Cond')
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    ctx.set(op, 'Out', jnp.where(jnp.reshape(cond, ()).astype(bool), x, y))


@register_lowering('isfinite')
def _isfinite(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.reshape(jnp.all(jnp.isfinite(x)), (1, )))
