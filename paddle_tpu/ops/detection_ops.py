"""Detection op lowerings (reference: paddle/fluid/operators/detection/).

TPU-first design notes:

* ``prior_box`` / ``anchor_generator`` / ``box_coder`` / ``iou_similarity`` /
  ``polygon_box_transform`` are pure static-shape math and lower straight into
  the XLA trace (reference files: prior_box_op.cc, anchor_generator_op.cc,
  box_coder_op.{cc,h}, iou_similarity_op.{cc,h}, polygon_box_transform_op.cc).
* ``bipartite_match`` / ``target_assign`` / ``mine_hard_examples`` are
  CPU-only kernels in the reference (bipartite_match_op.cc:15 registers CPU
  only); here they are compiled lowerings over *batched, padded* inputs:
  ground-truth LoD rows become a dense (B, G, ...) tensor with a per-instance
  valid count side-band (``@SEQLEN``, SURVEY §5.7), and the greedy match runs
  as a ``lax.fori_loop`` so the whole SSD loss stays on-device.
* ``multiclass_nms`` and ``detection_map`` keep the reference's host
  placement (CPU-only kernels with variable-size LoD outputs:
  multiclass_nms_op.cc, detection_map_op.cc) and run as host ops.
* ``ssd_loss`` additionally exists as ONE fused lowering: on TPU the
  match/assign/mine pipeline is fused into the loss computation instead of
  materializing LoD index lists (layers/detection.py ssd_loss composes the
  same steps op-by-op in the reference).
"""

import math

import numpy as np
import jax
import jax.numpy as jnp

from .registry import (register_lowering, register_host_op, SEQLEN_SUFFIX)


# ---------------------------------------------------------------------------
# pure static-shape geometry ops
# ---------------------------------------------------------------------------


def _expand_aspect_ratios(aspect_ratios, flip):
    """ExpandAspectRatios (reference prior_box_op.h): dedup, keep 1.0 first,
    optionally add reciprocals."""
    out = [1.0]
    for ar in aspect_ratios:
        exists = any(abs(ar - o) < 1e-6 for o in out)
        if not exists:
            out.append(float(ar))
            if flip:
                out.append(1.0 / float(ar))
    return out


@register_lowering('prior_box')
def _prior_box(ctx, op):
    x = ctx.get(op, 'Input')  # (N, C, H, W) feature map
    image = ctx.get(op, 'Image')  # (N, C, Him, Wim)
    min_sizes = [float(s) for s in op.attrs['min_sizes']]
    max_sizes = [float(s) for s in op.attrs.get('max_sizes', []) or []]
    aspect_ratios = op.attrs.get('aspect_ratios', [1.0]) or [1.0]
    variances = op.attrs.get('variances', [0.1, 0.1, 0.2, 0.2])
    flip = op.attrs.get('flip', False)
    clip = op.attrs.get('clip', False)
    step_w = float(op.attrs.get('step_w', 0.0) or 0.0)
    step_h = float(op.attrs.get('step_h', 0.0) or 0.0)
    offset = float(op.attrs.get('offset', 0.5))

    feat_h, feat_w = int(x.shape[2]), int(x.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    if step_w == 0.0:
        step_w = float(img_w) / feat_w
    if step_h == 0.0:
        step_h = float(img_h) / feat_h

    ars = _expand_aspect_ratios(aspect_ratios, flip)
    # per-cell (w, h) box sizes in pixels, reference iteration order
    # (prior_box_op.h:113-170): with min_max_aspect_ratios_order=false
    # (the reference default) the ar != 1 boxes come first and the
    # sqrt(min*max) box last; with true, min then max then ar boxes.
    mm_order = bool(op.attrs.get('min_max_aspect_ratios_order', False))
    whs = []
    for k, ms in enumerate(min_sizes):
        ar_boxes = [(ms * math.sqrt(ar), ms / math.sqrt(ar))
                    for ar in ars if abs(ar - 1.0) >= 1e-6]
        whs.append((ms, ms))
        if mm_order:
            if max_sizes:
                s = math.sqrt(ms * max_sizes[k])
                whs.append((s, s))
            whs.extend(ar_boxes)
        else:
            whs.extend(ar_boxes)
            if max_sizes:
                s = math.sqrt(ms * max_sizes[k])
                whs.append((s, s))
    num_priors = len(whs)

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * step_h
    cx = jnp.broadcast_to(cx[None, :, None], (feat_h, feat_w, num_priors))
    cy = jnp.broadcast_to(cy[:, None, None], (feat_h, feat_w, num_priors))
    bw = jnp.asarray([w / 2.0 for w, _ in whs], jnp.float32)
    bh = jnp.asarray([h / 2.0 for _, h in whs], jnp.float32)
    boxes = jnp.stack(
        [(cx - bw) / img_w, (cy - bh) / img_h, (cx + bw) / img_w,
         (cy + bh) / img_h],
        axis=-1)  # (H, W, P, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), boxes.shape)
    ctx.set(op, 'Boxes', boxes)
    ctx.set(op, 'Variances', var)


@register_lowering('anchor_generator')
def _anchor_generator(ctx, op):
    """Unnormalized RPN anchors (reference anchor_generator_op.h): for each
    aspect ratio r and size s: area = stride_w*stride_h, w0 = sqrt(area/r),
    anchor half-sizes scaled by s/stride."""
    x = ctx.get(op, 'Input')
    anchor_sizes = [float(s) for s in op.attrs['anchor_sizes']]
    aspect_ratios = [float(a) for a in op.attrs['aspect_ratios']]
    variances = op.attrs.get('variances', [0.1, 0.1, 0.2, 0.2])
    stride = [float(s) for s in op.attrs['stride']]
    offset = float(op.attrs.get('offset', 0.5))
    feat_h, feat_w = int(x.shape[2]), int(x.shape[3])
    stride_w, stride_h = stride[0], stride[1]

    whs = []
    for ar in aspect_ratios:
        area = stride_w * stride_h
        base_w = round(math.sqrt(area / ar))
        base_h = round(base_w * ar)
        for s in anchor_sizes:
            scale_w = s / stride_w
            scale_h = s / stride_h
            whs.append((scale_w * base_w, scale_h * base_h))
    num_anchors = len(whs)

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * stride_w
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * stride_h
    cx = jnp.broadcast_to(cx[None, :, None], (feat_h, feat_w, num_anchors))
    cy = jnp.broadcast_to(cy[:, None, None], (feat_h, feat_w, num_anchors))
    hw = jnp.asarray([w / 2.0 for w, _ in whs], jnp.float32)
    hh = jnp.asarray([h / 2.0 for _, h in whs], jnp.float32)
    anchors = jnp.stack([cx - hw, cy - hh, cx + hw, cy + hh], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    ctx.set(op, 'Anchors', anchors)
    ctx.set(op, 'Variances', var)


def _box_wh(box, normalized):
    extra = 0.0 if normalized else 1.0
    w = box[..., 2] - box[..., 0] + extra
    h = box[..., 3] - box[..., 1] + extra
    return w, h


@register_lowering('box_coder')
def _box_coder(ctx, op):
    prior = ctx.get(op, 'PriorBox')  # (M, 4)
    prior_var = ctx.get(op, 'PriorBoxVar')  # optional (M, 4)
    target = ctx.get(op, 'TargetBox')
    code_type = op.attrs.get('code_type', 'encode_center_size')
    normalized = op.attrs.get('box_normalized', True)

    pw, ph = _box_wh(prior, normalized)
    pcx = (prior[..., 2] + prior[..., 0]) / 2.0
    pcy = (prior[..., 3] + prior[..., 1]) / 2.0

    if code_type == 'encode_center_size':
        # target (N, 4) x prior (M, 4) -> (N, M, 4)  (box_coder_op.h
        # EncodeCenterSize)
        tw, th = _box_wh(target, normalized)
        tcx = (target[..., 2] + target[..., 0]) / 2.0
        tcy = (target[..., 3] + target[..., 1]) / 2.0
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if prior_var is not None:
            out = out / prior_var[None, :, :]
    else:
        # decode: target (N, M, 4) against prior (M, 4) (DecodeCenterSize)
        if target.ndim == 2:
            target = target[None, :, :]
        t = target
        if prior_var is not None:
            t = t * prior_var[None, :, :]
        w = jnp.exp(t[..., 2]) * pw[None, :]
        h = jnp.exp(t[..., 3]) * ph[None, :]
        cx = t[..., 0] * pw[None, :] + pcx[None, :]
        cy = t[..., 1] * ph[None, :] + pcy[None, :]
        extra = 0.0 if normalized else 1.0
        out = jnp.stack(
            [cx - w / 2.0, cy - h / 2.0, cx + w / 2.0 - extra,
             cy + h / 2.0 - extra],
            axis=-1)
    ctx.set(op, 'OutputBox', out)


def _iou_matrix(x, y, normalized=True):
    """Pairwise IoU (reference iou_similarity_op.h IOUSimilarityFunctor):
    x (..., N, 4), y (M, 4) -> (..., N, M)."""
    extra = 0.0 if normalized else 1.0
    area_x = (x[..., 2] - x[..., 0] + extra) * (x[..., 3] - x[..., 1] + extra)
    area_y = (y[..., 2] - y[..., 0] + extra) * (y[..., 3] - y[..., 1] + extra)
    xmin = jnp.maximum(x[..., :, None, 0], y[..., None, :, 0])
    ymin = jnp.maximum(x[..., :, None, 1], y[..., None, :, 1])
    xmax = jnp.minimum(x[..., :, None, 2], y[..., None, :, 2])
    ymax = jnp.minimum(x[..., :, None, 3], y[..., None, :, 3])
    iw = jnp.maximum(xmax - xmin + extra, 0.0)
    ih = jnp.maximum(ymax - ymin + extra, 0.0)
    inter = iw * ih
    union = area_x[..., :, None] + area_y[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_lowering('iou_similarity')
def _iou_similarity(ctx, op):
    x = ctx.get(op, 'X')  # (N, 4) or (B, G, 4) padded gt
    y = ctx.get(op, 'Y')  # (M, 4)
    ctx.set(op, 'Out', _iou_matrix(x, y))


@register_lowering('polygon_box_transform')
def _polygon_box_transform(ctx, op):
    """(reference polygon_box_transform_op.cc): input (N, K*2, H, W) of
    offsets; even channels add column index * 4, odd channels add row
    index * 4 (EAST-style geometry maps)."""
    x = ctx.get(op, 'Input')
    n, c, h, w = x.shape
    col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :], (h, w))
    row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, col[None, None], row[None, None]) * 4.0
    ctx.set(op, 'Output', base - x)


# ---------------------------------------------------------------------------
# matching / assignment / mining — compiled over batched padded gt
# ---------------------------------------------------------------------------


def _batched_gt(ctx, op, slot):
    """Return (value, valid_counts) for a ground-truth style input: a padded
    (B, G, ...) tensor plus per-instance valid row counts from the @SEQLEN
    side-band (or all-G when absent).  2-D inputs get a singleton batch."""
    names = op.input(slot)
    val = ctx.get(op, slot)
    if val is None:
        return None, None
    squeeze = val.ndim == 2 and names and (
        names[0] + SEQLEN_SUFFIX) not in ctx.env
    if squeeze:
        val = val[None]
    lens = None
    if names and (names[0] + SEQLEN_SUFFIX) in ctx.env:
        lens = ctx.env[names[0] + SEQLEN_SUFFIX]
    if lens is None:
        lens = jnp.full((val.shape[0], ), val.shape[1], jnp.int32)
    return val, lens.astype(jnp.int32)


def _bipartite_match_one(dist, valid_g, match_type, overlap_threshold):
    """Greedy global-max bipartite matching on one (G, M) distance matrix
    (reference bipartite_match_op.cc BipartiteMatch): repeatedly take the
    largest remaining entry, bind its row+col, until rows are exhausted."""
    g, m = dist.shape
    row_valid = jnp.arange(g) < valid_g
    masked = jnp.where(row_valid[:, None], dist, -jnp.inf)

    def body(_, carry):
        match_idx, match_dist, row_used, col_used = carry
        cur = jnp.where(row_used[:, None] | col_used[None, :], -jnp.inf,
                        masked)
        flat = jnp.argmax(cur)
        r, c = flat // m, flat % m
        best = cur[r, c]
        ok = jnp.isfinite(best)
        match_idx = jnp.where(
            ok, match_idx.at[c].set(r.astype(jnp.int32)), match_idx)
        match_dist = jnp.where(ok, match_dist.at[c].set(best), match_dist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        col_used = jnp.where(ok, col_used.at[c].set(True), col_used)
        return match_idx, match_dist, row_used, col_used

    init = (jnp.full((m, ), -1, jnp.int32), jnp.zeros((m, ), dist.dtype),
            jnp.zeros((g, ), bool), jnp.zeros((m, ), bool))
    match_idx, match_dist, _, col_used = jax.lax.fori_loop(0, g, body, init)

    if match_type == 'per_prediction':
        # unmatched cols additionally match their argmax row when the
        # distance clears the threshold (bipartite_match_op.cc:
        # ArgMaxMatch)
        best_row = jnp.argmax(masked, axis=0).astype(jnp.int32)
        best_val = jnp.max(masked, axis=0)
        extra = (~col_used) & (best_val >= overlap_threshold)
        match_idx = jnp.where(extra, best_row, match_idx)
        match_dist = jnp.where(extra, best_val, match_dist)
    return match_idx, match_dist


@register_lowering('bipartite_match')
def _bipartite_match(ctx, op):
    dist, lens = _batched_gt(ctx, op, 'DistMat')  # (B, G, M)
    match_type = op.attrs.get('match_type', 'bipartite')
    thr = float(op.attrs.get('dist_threshold', 0.5))
    match_idx, match_dist = jax.vmap(
        lambda d, l: _bipartite_match_one(d, l, match_type, thr))(dist, lens)
    ctx.set(op, 'ColToRowMatchIndices', match_idx)
    ctx.set(op, 'ColToRowMatchDist', match_dist)


@register_lowering('target_assign')
def _target_assign(ctx, op):
    x, _ = _batched_gt(ctx, op, 'X')  # (B, G, K)
    match = ctx.get(op, 'MatchIndices')  # (B, M) int32, -1 = unmatched
    neg = ctx.get(op, 'NegIndices')  # optional (B, M) negative mask
    mismatch_value = op.attrs.get('mismatch_value', 0)
    b, m = match.shape
    safe = jnp.maximum(match, 0)
    gathered = jax.vmap(lambda xb, ib: xb[ib])(x, safe)  # (B, M, K)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch_value, x.dtype))
    w = matched.astype(jnp.float32)
    if neg is not None:
        w = jnp.maximum(w, (neg > 0)[:, :, None].astype(jnp.float32))
    ctx.set(op, 'Out', out)
    ctx.set(op, 'OutWeight', w)


def _mine_negatives(cls_loss, loc_loss, match, match_dist, neg_pos_ratio,
                    neg_dist_threshold, sample_size, mining_type):
    """max_negative mining (reference mine_hard_examples_op.cc): negatives
    are unmatched priors with match overlap below neg_dist_threshold; keep
    the top (neg_pos_ratio * num_pos) by confidence loss.  Returns a (B, M)
    bool mask — the static-shape stand-in for the reference's NegIndices
    LoD index list."""
    if mining_type == 'hard_example':
        # reference mine_hard_examples_op.cc: IsEligibleMining (:34) makes
        # ALL priors eligible, loss = cls + loc (:95-99), the cap is
        # sample_size alone (:113), selected-but-unmatched become the
        # negatives and matched-but-unselected are demoted to -1 (:125-132)
        loss = cls_loss + (loc_loss if loc_loss is not None else 0.0)
        num_neg = jnp.minimum(jnp.int32(sample_size), loss.shape[1])
        order = jnp.argsort(-loss, axis=1)
        ranks = jnp.argsort(order, axis=1)
        sel = ranks < num_neg
        keep = sel & (match < 0)
        updated = jnp.where((match >= 0) & ~sel,
                            jnp.full_like(match, -1), match)
        return keep, updated
    loss = cls_loss
    is_neg_cand = (match < 0) & (match_dist < neg_dist_threshold)
    num_pos = jnp.sum((match >= 0).astype(jnp.int32), axis=1)  # (B,)
    num_neg = (num_pos.astype(jnp.float32) *
               neg_pos_ratio).astype(jnp.int32)
    masked_loss = jnp.where(is_neg_cand, loss, -jnp.inf)
    # rank of each candidate by loss, descending; keep rank < num_neg
    order = jnp.argsort(-masked_loss, axis=1)
    ranks = jnp.argsort(order, axis=1)
    keep = (ranks < num_neg[:, None]) & is_neg_cand
    return keep, match


@register_lowering('mine_hard_examples')
def _mine_hard_examples(ctx, op):
    cls_loss = ctx.get(op, 'ClsLoss')
    loc_loss = ctx.get(op, 'LocLoss')
    match = ctx.get(op, 'MatchIndices')
    match_dist = ctx.get(op, 'MatchDist')
    if cls_loss.ndim == 3:
        cls_loss = cls_loss[..., 0]
    if loc_loss is not None and loc_loss.ndim == 3:
        loc_loss = loc_loss[..., 0]
    mining_type = op.attrs.get('mining_type', 'max_negative')
    sample_size = int(op.attrs.get('sample_size', 0))
    if mining_type == 'hard_example' and sample_size <= 0:
        # reference enforce (mine_hard_examples_op.cc:238-240)
        raise ValueError(
            'sample_size must be greater than zero in hard_example mode')
    neg_mask, updated_match = _mine_negatives(
        cls_loss, loc_loss, match, match_dist,
        float(op.attrs.get('neg_pos_ratio', 1.0)),
        float(op.attrs.get('neg_dist_threshold', 0.5)),
        sample_size, mining_type)
    ctx.set(op, 'NegIndices', neg_mask.astype(jnp.int32))
    ctx.set(op, 'UpdatedMatchIndices', updated_match)


@register_lowering('ssd_loss')
def _ssd_loss(ctx, op):
    """Fused SSD multibox loss — the whole match/assign/mine pipeline in one
    XLA computation (reference composes it from 11 ops in
    layers/detection.py ssd_loss:563; here fusion keeps every intermediate
    in VMEM/registers and avoids LoD index materialization)."""
    loc = ctx.get(op, 'Location')  # (B, M, 4)
    conf = ctx.get(op, 'Confidence')  # (B, M, C)
    gt_box, lens = _batched_gt(ctx, op, 'GtBox')  # (B, G, 4)
    gt_label, _ = _batched_gt(ctx, op, 'GtLabel')  # (B, G, 1)
    prior_box = ctx.get(op, 'PriorBox')  # (M, 4)
    prior_var = ctx.get(op, 'PriorBoxVar')  # optional

    a = op.attrs
    background_label = int(a.get('background_label', 0))
    overlap_threshold = float(a.get('overlap_threshold', 0.5))
    neg_pos_ratio = float(a.get('neg_pos_ratio', 3.0))
    neg_overlap = float(a.get('neg_overlap', 0.5))
    loc_w = float(a.get('loc_loss_weight', 1.0))
    conf_w = float(a.get('conf_loss_weight', 1.0))
    match_type = a.get('match_type', 'per_prediction')
    mining_type = a.get('mining_type', 'max_negative')
    normalize = a.get('normalize', True)
    sample_size = int(a.get('sample_size', 0) or 0)

    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    gt_label = gt_label.astype(jnp.int32)
    b, m = loc.shape[:2]
    g = gt_box.shape[1]

    # 1. match priors to gt by IoU
    iou = _iou_matrix(gt_box, prior_box)  # (B, G, M)
    match, match_dist = jax.vmap(
        lambda d, l: _bipartite_match_one(d, l, match_type,
                                          overlap_threshold))(iou, lens)

    safe = jnp.maximum(match, 0)
    matched = match >= 0

    # 2. targets: conf label per prior, encoded loc offsets per prior
    tgt_label = jnp.where(matched,
                          jax.vmap(lambda lb, ib: lb[ib])(gt_label, safe),
                          background_label)  # (B, M)
    matched_box = jax.vmap(lambda bx, ib: bx[ib])(gt_box, safe)  # (B, M, 4)

    pw, ph = _box_wh(prior_box, True)
    pcx = (prior_box[:, 2] + prior_box[:, 0]) / 2.0
    pcy = (prior_box[:, 3] + prior_box[:, 1]) / 2.0
    tcx = (matched_box[..., 2] + matched_box[..., 0]) / 2.0
    tcy = (matched_box[..., 3] + matched_box[..., 1]) / 2.0
    tw = matched_box[..., 2] - matched_box[..., 0]
    th = matched_box[..., 3] - matched_box[..., 1]
    eps = 1e-10
    enc = jnp.stack(
        [(tcx - pcx[None]) / pw[None], (tcy - pcy[None]) / ph[None],
         jnp.log(jnp.maximum(jnp.abs(tw / pw[None]), eps)),
         jnp.log(jnp.maximum(jnp.abs(th / ph[None]), eps))],
        axis=-1)  # (B, M, 4)
    if prior_var is not None:
        enc = enc / prior_var[None, :, :]

    # 3. confidence loss (softmax CE) for mining + final loss
    logp = jax.nn.log_softmax(conf, axis=-1)
    conf_loss = -jnp.take_along_axis(
        logp, tgt_label[..., None], axis=-1)[..., 0]  # (B, M)

    # 4. localization smooth-L1 per prior (before mining so hard_example
    # mode can mine on cls+loc loss like the reference ssd_loss pipeline)
    diff = loc - jax.lax.stop_gradient(enc)
    abs_diff = jnp.abs(diff)
    smooth = jnp.where(abs_diff < 1.0, 0.5 * diff * diff, abs_diff - 0.5)
    loc_loss_all = jnp.sum(smooth, axis=-1)  # (B, M)

    # 5. hard negative mining; hard_example may demote matched priors
    neg_mask, updated_match = _mine_negatives(
        conf_loss, loc_loss_all if mining_type == 'hard_example' else None,
        match, match_dist, neg_pos_ratio, neg_overlap, sample_size,
        mining_type)
    matched = updated_match >= 0
    loc_loss = loc_loss_all * matched.astype(loc.dtype)

    conf_weight = (matched | neg_mask).astype(conf.dtype)
    tgt_label = jax.lax.stop_gradient(tgt_label)
    loss = (loc_w * loc_loss +
            conf_w * conf_loss * conf_weight)  # (B, M)
    if normalize:
        num_pos = jnp.sum(matched.astype(loss.dtype))
        loss = loss / jnp.maximum(num_pos, 1.0)
        out = jnp.sum(loss, axis=1, keepdims=True)  # (B, 1)
    else:
        out = jnp.sum(loss, axis=1, keepdims=True)
    ctx.set(op, 'Loss', out)


# ---------------------------------------------------------------------------
# host post-processing (CPU-only kernels in the reference, too)
# ---------------------------------------------------------------------------


def _nms_one_class(boxes, scores, score_threshold, nms_top_k, nms_threshold,
                   nms_eta):
    """Greedy NMS over one class (reference multiclass_nms_op.cc
    NMSFast): returns kept indices into `boxes`."""
    idx = np.where(scores > score_threshold)[0]
    if idx.size == 0:
        return []
    idx = idx[np.argsort(-scores[idx], kind='stable')]
    if nms_top_k > -1 and idx.size > nms_top_k:
        idx = idx[:nms_top_k]
    keep = []
    adaptive_threshold = nms_threshold
    while idx.size > 0:
        i = idx[0]
        keep.append(int(i))
        if idx.size == 1:
            break
        rest = idx[1:]
        bi = boxes[i]
        area_i = max(bi[2] - bi[0], 0) * max(bi[3] - bi[1], 0)
        br = boxes[rest]
        iw = np.maximum(
            np.minimum(bi[2], br[:, 2]) - np.maximum(bi[0], br[:, 0]), 0)
        ih = np.maximum(
            np.minimum(bi[3], br[:, 3]) - np.maximum(bi[1], br[:, 1]), 0)
        inter = iw * ih
        area_r = np.maximum(br[:, 2] - br[:, 0], 0) * np.maximum(
            br[:, 3] - br[:, 1], 0)
        union = area_i + area_r - inter
        iou = np.where(union > 0, inter / np.maximum(union, 1e-10), 0)
        idx = rest[iou <= adaptive_threshold]
        if nms_eta < 1.0 and adaptive_threshold > 0.5:
            adaptive_threshold *= nms_eta
    return keep


@register_host_op('multiclass_nms')
def _multiclass_nms(ctx, op, scope):
    from ..fluid import core
    bboxes = np.asarray(ctx.get(op, 'BBoxes'))  # (B, M, 4)
    scores = np.asarray(ctx.get(op, 'Scores'))  # (B, C, M)
    a = op.attrs
    background_label = int(a.get('background_label', 0))
    score_threshold = float(a['score_threshold'])
    nms_top_k = int(a.get('nms_top_k', -1))
    nms_threshold = float(a.get('nms_threshold', 0.3))
    nms_eta = float(a.get('nms_eta', 1.0))
    keep_top_k = int(a.get('keep_top_k', -1))

    all_out = []
    lod = [0]
    for b in range(bboxes.shape[0]):
        dets = []  # (label, score, box idx)
        for c in range(scores.shape[1]):
            if c == background_label:
                continue
            keep = _nms_one_class(bboxes[b], scores[b, c], score_threshold,
                                  nms_top_k, nms_threshold, nms_eta)
            for i in keep:
                dets.append((c, scores[b, c, i], i))
        if keep_top_k > -1 and len(dets) > keep_top_k:
            dets.sort(key=lambda d: -d[1])
            dets = dets[:keep_top_k]
        for c, s, i in dets:
            all_out.append([float(c), float(s)] + list(bboxes[b, i]))
        lod.append(len(all_out))
    if all_out:
        arr = np.asarray(all_out, np.float32)
    else:
        # reference emits a (1, 1) tensor holding -1 when nothing is kept
        arr = np.full((1, 1), -1.0, np.float32)
        lod = [0, 1]
    out_name = op.output('Out')[0]
    lt = core.LoDTensor(arr, [lod])
    scope.var(out_name).set_value(lt)
    ctx.store(out_name, arr)
    ctx.env[out_name + SEQLEN_SUFFIX] = np.diff(np.asarray(lod))


def _average_precision(tp, fp, num_gt, ap_type):
    """AP from sorted tp/fp flags (reference detection_map_op.h)."""
    if num_gt == 0 or len(tp) == 0:
        return None
    tp_cum = np.cumsum(tp)
    fp_cum = np.cumsum(fp)
    precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-10)
    recall = tp_cum / num_gt
    if ap_type == '11point':
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = precision[recall >= t].max() if np.any(recall >= t) else 0.0
            ap += p / 11.0
        return ap
    # integral
    ap = 0.0
    prev_recall = 0.0
    for p, r in zip(precision, recall):
        ap += p * (r - prev_recall)
        prev_recall = r
    return ap


@register_host_op('detection_map')
def _detection_map(ctx, op, scope):
    """mAP over one batch (reference detection_map_op.cc — CPU only).
    DetectRes: LoD (Nd, 6) [label, score, x1, y1, x2, y2]; Label: LoD
    (Ng, 6) [label, x1, y1, x2, y2, difficult] or (Ng, 5) w/o difficult.

    Cross-batch accumulation (reference PosCount/TruePos/FalsePos state
    tensors): when the op declares Accum* outputs, per-class gt counts and
    scored tp/fp entries are merged with any previous state found in those
    scope vars and written back, and MAP is computed over the accumulated
    state."""
    det = np.asarray(ctx.get(op, 'DetectRes'))
    gt = np.asarray(ctx.get(op, 'Label'))
    det_names = op.input('DetectRes')
    gt_names = op.input('Label')
    det_lens = ctx.env.get(det_names[0] + SEQLEN_SUFFIX)
    gt_lens = ctx.env.get(gt_names[0] + SEQLEN_SUFFIX)
    overlap_threshold = float(op.attrs.get('overlap_threshold', 0.5))
    evaluate_difficult = op.attrs.get('evaluate_difficult', True)
    ap_type = op.attrs.get('ap_type', 'integral')
    background_label = int(op.attrs.get('background_label', -1))

    def to_lod_list(arr, lens):
        if arr.ndim == 3:  # padded batch (B, K, D): lens gives valid rows
            if lens is None:
                lens = [arr.shape[1]] * arr.shape[0]
            return [arr[i, :int(l)] for i, l in enumerate(lens)]
        if lens is None:
            return [arr]
        out, ofs = [], 0
        for l in lens:
            out.append(arr[ofs:ofs + int(l)])
            ofs += int(l)
        return out

    if det.ndim < 2 or det.shape[-1] < 6:
        # multiclass_nms empty-result sentinel: (1, 1) tensor holding -1
        det_per_img = []
    else:
        det_per_img = to_lod_list(det, det_lens)
    gt_per_img = to_lod_list(gt, gt_lens)

    num_gt = {}
    for g in gt_per_img:
        for row in g:
            label = int(row[0])
            if label == background_label:
                continue
            difficult = row[5] if row.shape[0] >= 6 else 0.0
            if evaluate_difficult or not difficult:
                num_gt[label] = num_gt.get(label, 0) + 1

    scored = {}  # label -> list of (score, tp, fp)
    for img, d in enumerate(det_per_img):
        g = gt_per_img[img] if img < len(gt_per_img) else np.zeros((0, 6))
        by_label = {}
        for row in g:
            by_label.setdefault(int(row[0]), []).append(row)
        for label in sorted(set(int(r[0]) for r in d)):
            if label == background_label:
                continue
            rows = [r for r in d if int(r[0]) == label]
            rows.sort(key=lambda r: -r[1])
            gt_rows = by_label.get(label, [])
            used = [False] * len(gt_rows)
            for r in rows:
                best_iou, best_j = 0.0, -1
                for j, grow in enumerate(gt_rows):
                    gb = grow[1:5]
                    iw = min(r[4], gb[2]) - max(r[2], gb[0])
                    ih = min(r[5], gb[3]) - max(r[3], gb[1])
                    inter = max(iw, 0) * max(ih, 0)
                    area_d = max(r[4] - r[2], 0) * max(r[5] - r[3], 0)
                    area_g = max(gb[2] - gb[0], 0) * max(gb[3] - gb[1], 0)
                    union = area_d + area_g - inter
                    iou = inter / union if union > 0 else 0.0
                    if iou > best_iou:
                        best_iou, best_j = iou, j
                entry = scored.setdefault(label, [])
                if best_iou > overlap_threshold:
                    difficult = (gt_rows[best_j][5]
                                 if gt_rows[best_j].shape[0] >= 6 else 0.0)
                    if not evaluate_difficult and difficult:
                        continue  # ignored: neither tp nor fp
                    if not used[best_j]:
                        used[best_j] = True
                        entry.append((float(r[1]), 1, 0))
                    else:
                        entry.append((float(r[1]), 0, 1))
                else:
                    entry.append((float(r[1]), 0, 1))

    # ---- merge with accumulated state (AccumPosCount: (C, 2) rows of
    # [label, count]; AccumTruePos/AccumFalsePos: (N, 3) rows of
    # [label, score, flag]) ----
    def _accum_name(slot):
        names = op.output(slot)
        return names[0] if names else None

    pos_name = _accum_name('AccumPosCount')
    tp_name = _accum_name('AccumTruePos')
    fp_name = _accum_name('AccumFalsePos')
    has_state = ctx.get(op, 'HasState')
    use_state = (has_state is not None and
                 int(np.asarray(has_state).flatten()[0]) > 0)
    if use_state:
        prev = scope.find_var(pos_name) if pos_name else None
        if prev is not None and prev.value() is not None:
            for label, count in np.asarray(prev.value()).reshape(-1, 2):
                num_gt[int(label)] = num_gt.get(int(label), 0) + int(count)
        for state_name, flag_col in ((tp_name, 1), (fp_name, 2)):
            var = scope.find_var(state_name) if state_name else None
            if var is not None and var.value() is not None:
                for label, score, flag in np.asarray(
                        var.value()).reshape(-1, 3):
                    e = [0.0, 0, 0]
                    e[0] = float(score)
                    e[flag_col] = int(flag)
                    scored.setdefault(int(label), []).append(tuple(e))

    aps = []
    for label in sorted(num_gt):
        entries = sorted(scored.get(label, []), key=lambda e: -e[0])
        tp = np.asarray([e[1] for e in entries], np.float64)
        fp = np.asarray([e[2] for e in entries], np.float64)
        ap = _average_precision(tp, fp, num_gt.get(label, 0), ap_type)
        if ap is not None:
            aps.append(ap)
    m_ap = float(np.mean(aps)) if aps else 0.0
    out_name = op.output('MAP')[0]
    val = np.asarray([m_ap], np.float32)
    scope.var(out_name).set_value(val)
    ctx.store(out_name, val)

    if pos_name:
        pos_rows = np.asarray(
            [[l, c] for l, c in sorted(num_gt.items())], np.float32).reshape(
                -1, 2)
        scope.var(pos_name).set_value(pos_rows)
        ctx.store(pos_name, pos_rows)
    for state_name, flag_col in ((tp_name, 1), (fp_name, 2)):
        if not state_name:
            continue
        rows = []
        for label in sorted(scored):
            for e in scored[label]:
                if e[flag_col]:
                    rows.append([label, e[0], e[flag_col]])
        arr = np.asarray(rows, np.float32).reshape(-1, 3)
        scope.var(state_name).set_value(arr)
        ctx.store(state_name, arr)


@register_host_op('rpn_target_assign')
def _rpn_target_assign(ctx, op, scope):
    """Sample anchors for RPN training (reference
    rpn_target_assign_op.cc — CPU kernel with random subsampling).  Static
    deviation: emits fixed-size index arrays padded with -1 instead of LoD
    lists.  Accepts a single-instance (G, A) IoU matrix or the batched
    padded (B, G, A) form produced for LoD ground-truth; batched instances
    contribute indices offset by b * A (the reference flattens per-image
    index lists the same way, rpn_target_assign_op.cc)."""
    iou = np.asarray(ctx.get(op, 'DistMat'))
    dist_names = op.input('DistMat')
    lens = ctx.env.get(dist_names[0] + SEQLEN_SUFFIX)
    a = op.attrs
    rpn_batch_size = int(a.get('rpn_batch_size_per_im', 256))
    fg_fraction = float(a.get('rpn_fg_fraction', 0.25))
    pos_thr = float(a.get('rpn_positive_overlap', 0.7))
    neg_thr = float(a.get('rpn_negative_overlap', 0.3))
    fix_seed = a.get('fix_seed', False)
    seed = int(a.get('seed', 0))
    rng = np.random.RandomState(seed if fix_seed else None)

    if iou.ndim == 2:
        iou = iou[None]
    if lens is None:
        lens = [iou.shape[1]] * iou.shape[0]

    def sample_one(iou_i):
        num_a = iou_i.shape[1]
        anchor_best = iou_i.max(axis=0) if iou_i.size else np.zeros((num_a, ))
        anchor_argbest = iou_i.argmax(axis=0) if iou_i.size else np.zeros(
            (num_a, ), np.int64)
        fg = set(np.where(anchor_best >= pos_thr)[0].tolist())
        # each gt's best anchor is positive regardless of threshold
        if iou_i.size:
            fg.update(iou_i.argmax(axis=1).tolist())
        fg = np.asarray(sorted(fg), np.int64)
        num_fg = min(int(rpn_batch_size * fg_fraction), fg.size)
        if fg.size > num_fg:
            fg = rng.choice(fg, size=num_fg, replace=False)
        bg_cand = np.where(anchor_best < neg_thr)[0]
        bg_cand = np.setdiff1d(bg_cand, fg)
        num_bg = min(rpn_batch_size - num_fg, bg_cand.size)
        bg = rng.choice(bg_cand, size=num_bg,
                        replace=False) if bg_cand.size > num_bg else bg_cand
        return fg, bg, anchor_argbest

    anchor_boxes = None
    if op.input('Anchor'):
        anchor_boxes = np.asarray(ctx.get(op, 'Anchor'),
                                  np.float32).reshape(-1, 4)
    gt_rows = None
    if op.input('GtBox'):
        gt = np.asarray(ctx.get(op, 'GtBox'), np.float32)
        # split per image like the reference's gt_bbox->Slice(lod[i],
        # lod[i+1]) (rpn_target_assign_op.cc:115): padded (B, G, 4)
        # batches and concatenated LoD rows both go through the seqlen
        # side-band helper
        gt_rows = _rows_per_image(ctx, op, 'GtBox', gt)

    num_anchors = iou.shape[2]
    loc_parts, score_parts, lbl_parts, bbox_parts = [], [], [], []
    for b in range(iou.shape[0]):
        fg, bg, anchor_argbest = sample_one(iou[b, :int(lens[b])])
        loc_i = np.sort(fg).astype(np.int64)
        score_i = np.sort(np.concatenate([fg, bg])).astype(np.int64)
        lbl_parts.append(np.isin(score_i, fg).astype(np.int64))
        if anchor_boxes is not None and gt_rows is not None:
            # reference rpn_target_assign_op.cc:128-141: gather the fg
            # anchors and their matched gt boxes, emit BoxToDelta-encoded
            # (fg, 4) regression targets (bbox_util.h:23, normalized=false)
            bbox_parts.append(
                _box_to_delta(anchor_boxes[loc_i],
                              gt_rows[b][anchor_argbest[loc_i]]))
        else:
            bbox_parts.append(
                anchor_argbest[loc_i].astype(np.float32).reshape(-1, 1))
        loc_parts.append(loc_i + b * num_anchors)
        score_parts.append(score_i + b * num_anchors)
    loc_index = np.concatenate(loc_parts) if loc_parts else np.zeros(
        (0, ), np.int64)
    score_index = np.concatenate(score_parts) if score_parts else np.zeros(
        (0, ), np.int64)
    tgt_lbl = (np.concatenate(lbl_parts) if lbl_parts else np.zeros(
        (0, ), np.int64)).reshape(-1, 1)
    bbox_w = 4 if (anchor_boxes is not None and gt_rows is not None) else 1
    tgt_bbox = (np.concatenate(bbox_parts) if bbox_parts else np.zeros(
        (0, bbox_w), np.float32)).reshape(-1, bbox_w).astype(np.float32)
    for slot, val in (('LocationIndex', loc_index),
                      ('ScoreIndex', score_index), ('TargetLabel', tgt_lbl),
                      ('TargetBBox', tgt_bbox)):
        names = op.output(slot)
        if names:
            scope.var(names[0]).set_value(val)
            ctx.store(names[0], val)


def _box_to_delta(ex_boxes, gt_boxes):
    """Encode gt boxes as regression deltas from anchor (ex) boxes —
    reference bbox_util.h:23 BoxToDelta with normalized=false (+1 pixel
    width convention) and no weights."""
    ex_w = ex_boxes[:, 2] - ex_boxes[:, 0] + 1.0
    ex_h = ex_boxes[:, 3] - ex_boxes[:, 1] + 1.0
    ex_cx = ex_boxes[:, 0] + 0.5 * ex_w
    ex_cy = ex_boxes[:, 1] + 0.5 * ex_h
    gt_w = gt_boxes[:, 2] - gt_boxes[:, 0] + 1.0
    gt_h = gt_boxes[:, 3] - gt_boxes[:, 1] + 1.0
    gt_cx = gt_boxes[:, 0] + 0.5 * gt_w
    gt_cy = gt_boxes[:, 1] + 0.5 * gt_h
    return np.stack([(gt_cx - ex_cx) / ex_w, (gt_cy - ex_cy) / ex_h,
                     np.log(gt_w / ex_w), np.log(gt_h / ex_h)],
                    axis=1).astype(np.float32)


def _decode_proposals(anchors, deltas, variances):
    """RPN box decode in pixel coords (reference
    generate_proposals_op.cc BoxCoder): widths use the +1 convention."""
    w = anchors[:, 2] - anchors[:, 0] + 1.0
    h = anchors[:, 3] - anchors[:, 1] + 1.0
    cx = anchors[:, 0] + 0.5 * w
    cy = anchors[:, 1] + 0.5 * h
    if variances is None:
        variances = np.ones_like(deltas)
    dx, dy, dw, dh = (deltas[:, 0] * variances[:, 0],
                      deltas[:, 1] * variances[:, 1],
                      deltas[:, 2] * variances[:, 2],
                      deltas[:, 3] * variances[:, 3])
    # clamp dw/dh like the reference (log(1000/16) cap)
    cap = np.log(1000.0 / 16.0)
    dw = np.minimum(dw, cap)
    dh = np.minimum(dh, cap)
    ncx = dx * w + cx
    ncy = dy * h + cy
    nw = np.exp(dw) * w
    nh = np.exp(dh) * h
    return np.stack([ncx - 0.5 * nw, ncy - 0.5 * nh,
                     ncx + 0.5 * nw - 1.0, ncy + 0.5 * nh - 1.0], axis=1)


@register_host_op('generate_proposals')
def _generate_proposals(ctx, op, scope):
    """RPN proposal generation (reference
    detection/generate_proposals_op.cc — CPU kernel): per image, top
    pre_nms_topN anchors by score, decode, clip, min-size filter, NMS,
    keep post_nms_topN.  Outputs RpnRois LoD (sum, 4) + RpnRoiProbs."""
    from ..fluid import core
    scores = np.asarray(ctx.get(op, 'Scores'))  # (N, A, H, W)
    deltas = np.asarray(ctx.get(op, 'BboxDeltas'))  # (N, 4A, H, W)
    im_info = np.asarray(ctx.get(op, 'ImInfo'))  # (N, 3)
    anchors = np.asarray(ctx.get(op, 'Anchors')).reshape(-1, 4)
    variances = ctx.get(op, 'Variances')
    if variances is not None:
        variances = np.asarray(variances).reshape(-1, 4)
    a = op.attrs
    pre_n = int(a.get('pre_nms_topN', 6000))
    post_n = int(a.get('post_nms_topN', 1000))
    nms_thresh = float(a.get('nms_thresh', 0.5))
    min_size = float(a.get('min_size', 0.1))

    all_rois, all_probs, lod = [], [], [0]
    n, num_a, fh, fw = scores.shape
    for i in range(n):
        # (A, H, W) -> (H, W, A) flattened to match anchors' (H, W, A, 4)
        sc = scores[i].transpose(1, 2, 0).reshape(-1)
        dl = deltas[i].reshape(num_a, 4, fh, fw).transpose(
            2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc, kind='stable')[:pre_n]
        props = _decode_proposals(
            anchors[order], dl[order],
            variances[order] if variances is not None else None)
        imh, imw = im_info[i, 0], im_info[i, 1]
        props[:, 0] = np.clip(props[:, 0], 0, imw - 1)
        props[:, 1] = np.clip(props[:, 1], 0, imh - 1)
        props[:, 2] = np.clip(props[:, 2], 0, imw - 1)
        props[:, 3] = np.clip(props[:, 3], 0, imh - 1)
        # reference FilterBoxes (generate_proposals_op.cc:155-175): min_size
        # is in original-image units so it scales by im_scale, and the box
        # center must lie inside the image
        ms = min_size * float(im_info[i, 2])
        ws = props[:, 2] - props[:, 0] + 1
        hs = props[:, 3] - props[:, 1] + 1
        xc = props[:, 0] + ws / 2
        yc = props[:, 1] + hs / 2
        keep = (ws >= ms) & (hs >= ms) & (xc <= imw) & (yc <= imh)
        props, probs = props[keep], sc[order][keep]
        kept = _nms_one_class(props, probs, -np.inf, -1, nms_thresh,
                              float(a.get('eta', 1.0)))
        kept = kept[:post_n]
        all_rois.append(props[kept])
        all_probs.append(probs[kept].reshape(-1, 1))
        lod.append(lod[-1] + len(kept))
    rois = (np.concatenate(all_rois) if all_rois
            else np.zeros((0, 4), np.float32)).astype(np.float32)
    probs = (np.concatenate(all_probs) if all_probs
             else np.zeros((0, 1), np.float32)).astype(np.float32)
    for slot, arr in (('RpnRois', rois), ('RpnRoiProbs', probs)):
        names = op.output(slot)
        if names:
            lt = core.LoDTensor(arr, [lod])
            scope.var(names[0]).set_value(lt)
            ctx.store(names[0], arr)
            ctx.env[names[0] + SEQLEN_SUFFIX] = np.diff(np.asarray(lod))


def _rows_per_image(ctx, op, slot, arr):
    """Split a host-side array into per-image row lists using its LoD
    side-band (padded 3-D batches use their seqlen; 2-D without a
    side-band is a single image)."""
    names = op.input(slot)
    lens = ctx.env.get(names[0] + SEQLEN_SUFFIX) if names else None
    if arr.ndim == 3:
        if lens is None:
            lens = [arr.shape[1]] * arr.shape[0]
        return [arr[i, :int(l)] for i, l in enumerate(np.asarray(lens))]
    if lens is None:
        return [arr]
    out, ofs = [], 0
    for l in np.asarray(lens).astype(int):
        out.append(arr[ofs:ofs + l])
        ofs += l
    return out


def _sample_rois_one_image(rois, gt_boxes, gt_classes, is_crowd, im_scale,
                           rng, batch_size_per_im, fg_fraction, fg_thresh,
                           bg_hi, bg_lo, class_nums, weights):
    """One image's RoI sampling (reference generate_proposal_labels_op.cc
    SampleRoisForOneImage): rescale proposals to original coords, drop
    crowd gt, label by IoU, sample fg/bg, build per-class targets."""
    rois = rois.reshape(-1, 4) / max(float(im_scale), 1e-6)
    not_crowd = (is_crowd.reshape(-1) == 0 if is_crowd is not None and
                 is_crowd.size else np.ones(len(gt_boxes), bool))
    gt_boxes = gt_boxes[not_crowd]
    gt_classes = gt_classes[not_crowd]
    rois2 = np.concatenate([rois, gt_boxes]) if gt_boxes.size else rois
    ious = np.zeros((rois2.shape[0], max(gt_boxes.shape[0], 1)))
    for j, gb in enumerate(gt_boxes):
        iw = np.minimum(rois2[:, 2], gb[2]) - np.maximum(rois2[:, 0],
                                                         gb[0]) + 1
        ih = np.minimum(rois2[:, 3], gb[3]) - np.maximum(rois2[:, 1],
                                                         gb[1]) + 1
        inter = np.maximum(iw, 0) * np.maximum(ih, 0)
        area_r = ((rois2[:, 2] - rois2[:, 0] + 1) *
                  (rois2[:, 3] - rois2[:, 1] + 1))
        area_g = (gb[2] - gb[0] + 1) * (gb[3] - gb[1] + 1)
        ious[:, j] = inter / np.maximum(area_r + area_g - inter, 1e-10)
    max_iou = ious.max(axis=1) if gt_boxes.size else np.zeros(
        rois2.shape[0])
    arg_gt = ious.argmax(axis=1) if gt_boxes.size else np.zeros(
        rois2.shape[0], np.int64)

    fg = np.where(max_iou >= fg_thresh)[0]
    bg = np.where((max_iou < bg_hi) & (max_iou >= bg_lo))[0]
    fg_num = min(int(batch_size_per_im * fg_fraction), fg.size)
    if fg.size > fg_num:
        fg = rng.choice(fg, size=fg_num, replace=False)
    bg_num = min(batch_size_per_im - fg_num, bg.size)
    if bg.size > bg_num:
        bg = rng.choice(bg, size=bg_num, replace=False)
    keep = np.concatenate([fg, bg]).astype(np.int64)

    sampled = rois2[keep].astype(np.float32)
    labels = np.zeros(keep.size, np.int32)
    labels[:fg.size] = gt_classes[arg_gt[fg]] if gt_classes.size else 1

    targets = np.zeros((keep.size, 4 * class_nums), np.float32)
    inside = np.zeros_like(targets)
    for k in range(fg.size):
        gb = gt_boxes[arg_gt[fg[k]]]
        rb = sampled[k]
        w = rb[2] - rb[0] + 1
        h = rb[3] - rb[1] + 1
        gcx = (gb[0] + gb[2]) / 2
        gcy = (gb[1] + gb[3]) / 2
        rcx = (rb[0] + rb[2]) / 2
        rcy = (rb[1] + rb[3]) / 2
        t = np.asarray([(gcx - rcx) / w / weights[0],
                        (gcy - rcy) / h / weights[1],
                        np.log((gb[2] - gb[0] + 1) / w) / weights[2],
                        np.log((gb[3] - gb[1] + 1) / h) / weights[3]],
                       np.float32)
        cls = int(labels[k])
        targets[k, 4 * cls:4 * cls + 4] = t
        inside[k, 4 * cls:4 * cls + 4] = 1.0
    return sampled, labels, targets, inside


@register_host_op('generate_proposal_labels')
def _generate_proposal_labels(ctx, op, scope):
    """Second-stage RoI sampling + bbox target assembly (reference
    detection/generate_proposal_labels_op.cc): per image, label proposals
    by IoU with (non-crowd) gt, sample batch_size_per_im RoIs at
    fg_fraction, emit per-class regression targets and weights."""
    from ..fluid import core
    rois = np.asarray(ctx.get(op, 'RpnRois'))
    gt_classes = np.asarray(ctx.get(op, 'GtClasses'))
    gt_boxes = np.asarray(ctx.get(op, 'GtBoxes'))
    crowd_in = ctx.get(op, 'IsCrowd')
    im_info = np.asarray(ctx.get(op, 'ImInfo')).reshape(-1, 3)
    a = op.attrs
    batch_size_per_im = int(a.get('batch_size_per_im', 256))
    fg_fraction = float(a.get('fg_fraction', 0.25))
    fg_thresh = float(a.get('fg_thresh', 0.5))
    bg_hi = float(a.get('bg_thresh_hi', 0.5))
    bg_lo = float(a.get('bg_thresh_lo', 0.0))
    class_nums = int(a.get('class_nums', 81))
    weights = a.get('bbox_reg_weights', [0.1, 0.1, 0.2, 0.2])
    fix_seed = a.get('fix_seed', False)
    rng = np.random.RandomState(int(a.get('seed', 0))
                                if fix_seed else None)

    rois_per = _rows_per_image(ctx, op, 'RpnRois', rois)
    gt_per = _rows_per_image(ctx, op, 'GtBoxes', gt_boxes)
    cls_per = _rows_per_image(ctx, op, 'GtClasses', gt_classes)
    crowd_per = (_rows_per_image(ctx, op, 'IsCrowd',
                                 np.asarray(crowd_in))
                 if crowd_in is not None else [None] * len(rois_per))

    parts = {k: [] for k in ('Rois', 'LabelsInt32', 'BboxTargets',
                             'BboxInsideWeights', 'BboxOutsideWeights')}
    lod = [0]
    for i, img_rois in enumerate(rois_per):
        gt_b = gt_per[min(i, len(gt_per) - 1)].reshape(-1, 4)
        gt_c = cls_per[min(i, len(cls_per) - 1)].reshape(-1)
        crowd = crowd_per[min(i, len(crowd_per) - 1)]
        scale = im_info[min(i, im_info.shape[0] - 1), 2]
        sampled, labels, targets, inside = _sample_rois_one_image(
            img_rois, gt_b, gt_c,
            np.asarray(crowd) if crowd is not None else None, scale, rng,
            batch_size_per_im, fg_fraction, fg_thresh, bg_hi, bg_lo,
            class_nums, weights)
        parts['Rois'].append(sampled)
        parts['LabelsInt32'].append(labels.reshape(-1, 1))
        parts['BboxTargets'].append(targets)
        parts['BboxInsideWeights'].append(inside)
        parts['BboxOutsideWeights'].append(inside.copy())
        lod.append(lod[-1] + sampled.shape[0])
    for slot, arrs in parts.items():
        names = op.output(slot)
        if names:
            arr = np.concatenate(arrs) if arrs else np.zeros((0, 4),
                                                             np.float32)
            lt = core.LoDTensor(arr, [lod])
            scope.var(names[0]).set_value(lt)
            ctx.store(names[0], arr)
            ctx.env[names[0] + SEQLEN_SUFFIX] = np.diff(np.asarray(lod))
