"""XLA lowering registry for all operator families.

Importing this package registers every op lowering (the analog of the
reference's static REGISTER_OPERATOR blocks linking into one binary).
"""

from .registry import (register_lowering, register_grad_lowering,
                       get_lowering, has_lowering, LoweringContext, run_op)

from . import math_ops  # noqa: F401
from . import activation_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import host_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import beam_search_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import ctc_ops  # noqa: F401
from . import quantize_ops  # noqa: F401
from . import concurrency_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import sparse  # noqa: F401

# wrap every optimizer lowering with SelectedRows (SparseRows) handling —
# the analog of the reference's separate SelectedRows optimizer kernels
for _opt in ('sgd', 'momentum', 'adam', 'adamax', 'adagrad',
             'decayed_adagrad', 'rmsprop', 'adadelta', 'ftrl'):
    sparse.sparsify_optimizer(_opt)
del _opt
