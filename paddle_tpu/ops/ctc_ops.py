"""CTC family: warpctc loss, ctc_align, edit_distance.

Reference kernels: paddle/fluid/operators/warpctc_op.{cc,h} (wraps the
dynloaded warp-ctc library), ctc_align_op.{cc,h}, edit_distance_op.{cc,h}.

TPU-first design: the CTC log-likelihood is computed directly in the XLA
trace as a ``lax.scan`` over time with the standard interleaved-blank alpha
recursion in log space — no external warp-ctc library, and the gradient
falls out of autodiff through the scan (the reference stores an explicit
WarpCTCGrad tensor instead).  Padded (B, T, C) logits + (B, L) labels with
``@SEQLEN`` side-bands replace the reference's LoD layout (SURVEY §5.7).
ctc_align and edit_distance keep the reference's CPU-only placement as host
ops (variable-size LoD outputs / sequential DP).
"""

import numpy as np
import jax
import jax.numpy as jnp

from .registry import (register_lowering, register_host_op, SEQLEN_SUFFIX)

_NEG_INF = -1e30


def _seqlen_of(ctx, op, slot, default_len, batch):
    from .sequence_ops import _seqlen  # single home of the side-band idiom
    lens = _seqlen(ctx, op, slot)
    if lens is None:
        return jnp.full((batch, ), default_len, jnp.int32)
    return lens.astype(jnp.int32)


def _ctc_loss_one(logp, label, t_len, l_len, blank):
    """Negative log-likelihood of one (T, C) log-prob sequence against one
    padded (L,) label row. Standard CTC alpha recursion over the
    blank-interleaved label z of static length S = 2L+1."""
    t_total, _ = logp.shape
    l_pad = label.shape[0]
    s_pad = 2 * l_pad + 1

    s_idx = jnp.arange(s_pad)
    is_lbl = (s_idx % 2) == 1
    lbl_pos = jnp.where(is_lbl, (s_idx - 1) // 2, 0)
    z = jnp.where(is_lbl, label[lbl_pos], blank)  # (S,)
    s_valid = s_idx < (2 * l_len + 1)
    # skip connection allowed when z[s] != blank and z[s] != z[s-2]
    z_m2 = jnp.concatenate([jnp.full((2, ), -1, z.dtype), z[:-2]])
    skip_ok = is_lbl & (z != z_m2)

    def emis(t):
        e = logp[t][z]  # (S,)
        return jnp.where(s_valid, e, _NEG_INF)

    alpha0 = jnp.where((s_idx < 2) & s_valid, emis(0), _NEG_INF)

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.array([_NEG_INF]), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2, ), _NEG_INF), alpha[:-2]])
        prev2 = jnp.where(skip_ok, prev2, _NEG_INF)
        stacked = jnp.stack([alpha, prev1, prev2])
        m = jnp.max(stacked, axis=0)
        cand = m + jnp.log(
            jnp.sum(jnp.exp(stacked - m[None]), axis=0) + 1e-37)
        new = cand + emis(t)
        # timesteps beyond the valid length carry alpha through unchanged
        new = jnp.where(t < t_len, new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_total))
    # final: logsumexp of alpha[S_valid-1], alpha[S_valid-2]
    last = 2 * l_len  # index of final blank
    a1 = alpha[last]
    a2 = jnp.where(l_len > 0, alpha[jnp.maximum(last - 1, 0)], _NEG_INF)
    m = jnp.maximum(a1, a2)
    ll = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m) + 1e-37)
    return -ll


@register_lowering('warpctc')
def _warpctc(ctx, op):
    logits = ctx.get(op, 'Logits')  # (B, T, C) padded
    label = ctx.get(op, 'Label')  # (B, L) or (B, L, 1) padded int
    blank = int(op.attrs.get('blank', 0))
    norm_by_times = bool(op.attrs.get('norm_by_times', False))
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    b, t, _ = logits.shape
    t_lens = _seqlen_of(ctx, op, 'Logits', t, b)
    l_lens = _seqlen_of(ctx, op, 'Label', label.shape[1], b)

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = jax.vmap(
        lambda lp, lb, tl, ll: _ctc_loss_one(lp, lb, tl, ll, blank))(
            logp, label, t_lens, l_lens)
    if norm_by_times:
        loss = loss / jnp.maximum(t_lens.astype(loss.dtype), 1.0)
    ctx.set(op, 'Loss', loss[:, None].astype(logits.dtype))


def _rows_of(ctx, op, slot):
    """Host-side view of a sequence input: list of per-instance 1-D numpy
    rows (from a padded batch + lengths side-band, or a single row)."""
    arr = np.asarray(ctx.get(op, slot))
    names = op.input(slot)
    lens = ctx.env.get(names[0] + SEQLEN_SUFFIX) if names else None
    if arr.ndim >= 2 and arr.shape[-1] == 1:
        arr = arr[..., 0]
    if arr.ndim == 1:
        if lens is not None and np.ndim(lens) and len(lens) > 1:
            # concatenated LoD rows
            out, ofs = [], 0
            for l in np.asarray(lens).astype(int):
                out.append(arr[ofs:ofs + l])
                ofs += l
            return out
        return [arr]
    lens = (np.asarray(lens).astype(int)
            if lens is not None else [arr.shape[1]] * arr.shape[0])
    return [arr[i, :lens[i]] for i in range(arr.shape[0])]


@register_host_op('ctc_align')
def _ctc_align(ctx, op, scope):
    """Merge repeated tokens, drop blanks (reference ctc_align_op.h — the
    decode side of CTC).  Variable-length output rows -> LoD host op."""
    from ..fluid import core
    blank = int(op.attrs.get('blank', 0))
    merge_repeated = bool(op.attrs.get('merge_repeated', True))
    rows = _rows_of(ctx, op, 'Input')
    out_rows = []
    for r in rows:
        r = np.asarray(r).astype(np.int64).reshape(-1)
        kept = []
        prev = None
        for v in r:
            if merge_repeated and prev is not None and v == prev:
                prev = v
                continue
            prev = v
            if v != blank:
                kept.append(int(v))
        out_rows.append(kept)
    lod = [0]
    flat = []
    for kr in out_rows:
        flat.extend(kr)
        lod.append(len(flat))
    arr = np.asarray(flat, np.int64).reshape(-1, 1)
    if arr.size == 0:
        # reference pads a single -1 so downstream shapes stay non-empty
        arr = np.full((1, 1), -1, np.int64)
        lod = [0, 1]
    out_name = op.output('Output')[0]
    lt = core.LoDTensor(arr, [lod])
    scope.var(out_name).set_value(lt)
    ctx.store(out_name, arr)
    ctx.env[out_name + SEQLEN_SUFFIX] = np.diff(np.asarray(lod))


@register_host_op('edit_distance')
def _edit_distance(ctx, op, scope):
    """Levenshtein distance per (hyp, ref) sequence pair (reference
    edit_distance_op.h — O(|h|*|r|) DP, CPU only)."""
    normalized = bool(op.attrs.get('normalized', True))
    hyps = _rows_of(ctx, op, 'Hyps')
    refs = _rows_of(ctx, op, 'Refs')
    out = np.zeros((len(hyps), 1), np.float32)
    for i, (h, r) in enumerate(zip(hyps, refs)):
        h = [int(v) for v in np.asarray(h).reshape(-1)]
        r = [int(v) for v in np.asarray(r).reshape(-1)]
        m, n = len(h), len(r)
        if n == 0:
            dist = float(m)
        elif m == 0:
            dist = float(n)
        else:
            dp = np.arange(n + 1, dtype=np.float32)
            for x in range(1, m + 1):
                prev_diag = dp[0]
                dp[0] = x
                for y in range(1, n + 1):
                    cur = dp[y]
                    cost = 0.0 if h[x - 1] == r[y - 1] else 1.0
                    dp[y] = min(dp[y] + 1, dp[y - 1] + 1, prev_diag + cost)
                    prev_diag = cur
            dist = float(dp[n])
        if normalized:
            dist = dist / max(n, 1)
        out[i, 0] = dist
    out_name = op.output('Out')[0]
    scope.var(out_name).set_value(out)
    ctx.store(out_name, out)
    seq_names = op.output('SequenceNum')
    if seq_names:
        sn = np.asarray([len(hyps)], np.int64)
        scope.var(seq_names[0]).set_value(sn)
        ctx.store(seq_names[0], sn)
