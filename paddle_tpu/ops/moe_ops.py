"""Mixture-of-Experts op lowering (fluid.layers.moe_ffn).

The GShard DENSE dispatch formulation (parallel/moe.py moe_ffn): every
tensor is static-shaped, the expert dimension is a real array axis, and
parallelism comes from the expert weights' PartitionSpec over the 'ep'
mesh axis — GSPMD partitions the dispatch and combine einsums and
inserts the collectives, exactly the mechanism tensor-parallel fc uses.
(The hand-scheduled all_to_all variant for shard_map users lives in
parallel/moe.py moe_ffn_spmd; this lowering is the Program-IR path and
delegates its math to parallel.moe.moe_ffn so routing has one source of
truth.)
"""

import jax.numpy as jnp

from .registry import register_lowering
from ..parallel import moe as _moe


@register_lowering('moe_ffn')
def _moe_ffn(ctx, op):
    x = ctx.get(op, 'X')
    w1 = ctx.get(op, 'W1')
    w2 = ctx.get(op, 'W2')
    b1 = ctx.get(op, 'B1')
    b2 = ctx.get(op, 'B2')
    params = {
        'gate_w': ctx.get(op, 'GateW'),
        'w1': w1,
        # bias_attr=False omits the bias inputs entirely (no frozen
        # zero parameters); the math sees zeros
        'b1': b1 if b1 is not None else jnp.zeros(
            (w1.shape[0], w1.shape[2]), w1.dtype),
        'w2': w2,
        'b2': b2 if b2 is not None else jnp.zeros(
            (w2.shape[0], w2.shape[2]), w2.dtype),
    }
    cf = op.attrs.get('capacity_factor', 1.25)
    lead = x.shape[:-1]
    tok = x.reshape((-1, x.shape[-1]))
    y = _moe.moe_ffn(params, tok, capacity_factor=cf)
    ctx.set(op, 'Out', y.reshape(lead + (x.shape[-1], )))
