"""Math op lowerings: matmul family, elementwise broadcast family, reductions.

Reference kernels: paddle/fluid/operators/mul_op.cc, matmul_op.cc,
elementwise_*_op.cc (broadcast semantics in elementwise_op_function.h),
reduce_*_op.cc, sum_op.cc, scale_op.cc, clip_op.cc.  On TPU these all lower
to jnp/lax inside one compiled block; matmuls hit the MXU.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_lowering, amp_matmul, amp_harmonize, \
    SAMPLE_MASK_NAME


def _flatten_2d(x, num_col_dims):
    """Flatten leading num_col_dims axes into rows, rest into cols
    (mul_op's x_num_col_dims semantics)."""
    rows = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return jnp.reshape(x, (rows, -1))


@register_lowering('mul')
def _mul(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    xn = op.attrs.get('x_num_col_dims', 1)
    yn = op.attrs.get('y_num_col_dims', 1)
    y2 = _flatten_2d(y, yn)
    k = y2.shape[0]
    # choose x's split point from the right so trailing dims contract with k;
    # handles LoD tensors whose padded runtime rank exceeds the desc rank
    # (a (B,T,D) @ (D,M) per-token projection where the graph said (N,D))
    split = x.ndim
    acc = 1
    while split > 0 and acc != k:
        split -= 1
        acc *= x.shape[split]
    if acc != k:
        split = xn  # fall back to declared semantics (will raise clearly)
    x2 = jnp.reshape(x, (-1, int(np.prod(x.shape[split:], dtype=np.int64))
                         if split < x.ndim else 1))
    out = amp_matmul(x2, y2)
    out_shape = tuple(x.shape[:split]) + tuple(y.shape[yn:])
    ctx.set(op, 'Out', jnp.reshape(out, out_shape))


@register_lowering('matmul')
def _matmul(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    tx = op.attrs.get('transpose_X', False)
    ty = op.attrs.get('transpose_Y', False)
    alpha = op.attrs.get('alpha', 1.0)
    # fluid matmul: 1-D inputs get promoted; batch dims broadcast
    squeeze_front = squeeze_back = False
    if x.ndim == 1:
        x = x[None, :]
        squeeze_front = True
    if y.ndim == 1:
        y = y[:, None]
        squeeze_back = True
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = amp_matmul(x, y)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    if squeeze_front:
        out = jnp.squeeze(out, -2)
    if squeeze_back:
        out = jnp.squeeze(out, -1)
    ctx.set(op, 'Out', out)


def _bcast_y(x, y, axis):
    """Reference broadcast: Y's shape aligns into X starting at `axis`
    (elementwise_op_function.h); axis=-1 aligns trailing dims.  If the
    requested axis does not fit (e.g. LoD tensors lowered to padded rank-3
    where the graph assumed rank-2), fall back to trailing alignment."""
    if x.shape == y.shape:
        return y
    # trim trailing 1s of y (fluid allows y shape (C,1,1) matching mid dims)
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > 1:
        yshape = yshape[:-1]

    def _aligned(ax):
        if ax < 0 or ax + len(yshape) > x.ndim:
            return None
        if any(ys not in (1, x.shape[ax + i])
               for i, ys in enumerate(yshape)):
            return None
        return [1] * ax + yshape + [1] * (x.ndim - ax - len(yshape))

    if axis == -1 or axis is None:
        axis = x.ndim - len(yshape)
    new_shape = _aligned(axis)
    if new_shape is None:
        new_shape = _aligned(x.ndim - len(yshape))
    if new_shape is None:
        return y  # let jnp's own broadcasting rules apply (or raise)
    return jnp.reshape(y, new_shape)


def _register_elementwise(name, fn):
    @register_lowering('elementwise_' + name)
    def _lower(ctx, op, fn=fn):
        x = ctx.get(op, 'X')
        y = ctx.get(op, 'Y')
        axis = op.attrs.get('axis', -1)
        # the axis attr was chosen for X's DECLARED rank; when the runtime
        # rank differs (LoD tensor lowered to padded [B,T,...]) the only
        # meaningful alignment is trailing — never trust the stale axis
        xnames = op.input('X')
        if xnames:
            xd = ctx.var_desc(xnames[0])
            if xd is not None and xd.shape and len(xd.shape) != x.ndim:
                axis = -1
        y = _bcast_y(x, y, axis)
        # bf16 activation + f32 parameter (fc bias, scales) computes
        # bf16 under AMP — promotion would re-widen the activation
        x, y = amp_harmonize(x, y)
        ctx.set(op, 'Out', fn(x, y))


_register_elementwise('add', jnp.add)
_register_elementwise('sub', jnp.subtract)
_register_elementwise('mul', jnp.multiply)
_register_elementwise('div', jnp.divide)
_register_elementwise('max', jnp.maximum)
_register_elementwise('min', jnp.minimum)
_register_elementwise('pow', jnp.power)
_register_elementwise('mod', jnp.mod)
_register_elementwise('floordiv', jnp.floor_divide)


@register_lowering('sum')
def _sum(ctx, op):
    from .sparse import sparse_add
    xs = ctx.get_list(op, 'X')
    out = xs[0]
    for x in xs[1:]:
        out = sparse_add(out, x)
    ctx.set(op, 'Out', out)


@register_lowering('scale')
def _scale(ctx, op):
    from .sparse import SparseRows
    x = ctx.get(op, 'X')
    if isinstance(x, SparseRows):
        # SelectedRows scale (math/selected_rows_functor.cc) — loss-grad
        # 1/N scaling reaches sparse grads through this path
        if op.attrs.get('bias', 0.0) != 0.0:
            raise NotImplementedError(
                'scale with bias!=0 on a SelectedRows value')
        ctx.set(op, 'Out', x.scale(op.attrs.get('scale', 1.0)))
        return
    scale = jnp.asarray(op.attrs.get('scale', 1.0), x.dtype)
    bias = jnp.asarray(op.attrs.get('bias', 0.0), x.dtype)
    if op.attrs.get('bias_after_scale', True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    ctx.set(op, 'Out', out)


@register_lowering('mean')
def _mean(ctx, op):
    # fluid MeanOp fixes the output dim to {1} (operators/mean_op.cc)
    x = ctx.get(op, 'X')
    mask = _batch_mask_for(ctx, op, x)
    if mask is not None:
        # ragged-batch lot: rows past the real sample count are padding
        # the data-parallel executor appended for dp divisibility.  The
        # mean (and, through jax.vjp, every gradient flowing out of it)
        # must weight by the REAL count: pad rows contribute 0 to the
        # numerator and nothing to the denominator, so the padded step
        # equals the unpadded step bit-for-bit in expectation.
        m = mask.astype(x.dtype).reshape(
            (mask.shape[0], ) + (1, ) * (x.ndim - 1))
        per_row = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        denom = jnp.maximum(jnp.sum(mask.astype(x.dtype)), 1) * per_row
        ctx.set(op, 'Out', jnp.reshape(jnp.sum(x * m) / denom, (1, )))
        return
    ctx.set(op, 'Out', jnp.reshape(jnp.mean(x), (1, )))


def _reduce_dims(x, op):
    if op.attrs.get('reduce_all', False):
        return None
    dim = op.attrs.get('dim', [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % x.ndim for d in dim)


def _batch_mask_for(ctx, op, x):
    """The ragged-batch sample mask, iff it applies to this op's input:
    the value must be BATCH-LED (derived from the feeds with the batch
    still on dim 0, per run_op's provenance tracking) — a weight-derived
    tensor (weight decay on a [56, ...] parameter, or mean(square(w)))
    whose dim 0 merely coincides with the padded batch size never
    masks."""
    mask = ctx.env.get(SAMPLE_MASK_NAME)
    if mask is None or x.ndim < 1:
        return None
    name = op.input('X')[0]
    if x.shape[0] == mask.shape[0] and name in ctx.batch_led:
        return mask
    if (name in ctx.batch_tainted and x.shape[0] != mask.shape[0]
            and x.shape[0] % mask.shape[0] == 0):
        # batch ancestry but a [B*k] leading dim: a flattened batch
        # (reshape [B,T,..] -> [B*T,..] before the loss) — the sample
        # mask cannot reach this reduction, so the padding rows WILL
        # contribute.  Trace-time warning (once per compile), loud
        # enough to catch the seq-model CE idiom on ragged lots.
        import warnings
        warnings.warn(
            'ragged-batch mask cannot reach %r over %r: its leading dim '
            '%d looks like a FLATTENED batch (mask covers %d rows) — '
            'padding rows will contribute to this reduction; keep the '
            'batch on dim 0 through the loss, or drop the ragged tail'
            % (op.type, name, x.shape[0], mask.shape[0]))
    return None


def _register_reduce(name, fn):
    @register_lowering('reduce_' + name)
    def _lower(ctx, op, fn=fn):
        x = ctx.get(op, 'X')
        dims = _reduce_dims(x, op)
        keep = op.attrs.get('keep_dim', False)
        # ragged-batch lots: reduce_mean/reduce_sum over the batch dim
        # must not count the padding rows (same contract as the 'mean'
        # op; max/min are naturally immune — the padding replicates a
        # real row — and prod over batch is not masked)
        if name in ('mean', 'sum') and (dims is None or 0 in dims):
            mask = _batch_mask_for(ctx, op, x)
            if mask is not None:
                m = mask.astype(x.dtype).reshape(
                    (mask.shape[0], ) + (1, ) * (x.ndim - 1))
                out = jnp.sum(x * m, axis=dims, keepdims=keep)
                if name == 'mean':
                    axes = tuple(range(x.ndim)) if dims is None else dims
                    other = int(np.prod([x.shape[a] for a in axes
                                         if a != 0])) if axes else 1
                    out = out / (jnp.maximum(
                        jnp.sum(mask.astype(x.dtype)), 1) * other)
                if dims is None and not keep:
                    out = jnp.reshape(out, (1, ))
                ctx.set(op, 'Out', out)
                return
        out = fn(x, axis=dims, keepdims=keep)
        if dims is None and not keep:
            out = jnp.reshape(out, (1, ))  # fluid keeps rank-1 [1] output
        ctx.set(op, 'Out', out)


_register_reduce('sum', jnp.sum)
_register_reduce('mean', jnp.mean)
_register_reduce('max', jnp.max)
_register_reduce('min', jnp.min)
_register_reduce('prod', jnp.prod)


@register_lowering('clip')
def _clip(ctx, op):
    x = ctx.get(op, 'X')
    lo = op.attrs.get('min', float('-inf'))
    hi = op.attrs.get('max', float('inf'))
    ctx.set(op, 'Out', jnp.clip(x, lo, hi))


@register_lowering('clip_by_norm')
def _clip_by_norm(ctx, op):
    x = ctx.get(op, 'X')
    max_norm = op.attrs['max_norm']
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      jnp.ones((), x.dtype))
    ctx.set(op, 'Out', x * scale)


@register_lowering('squared_l2_norm')
def _squared_l2_norm(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.reshape(jnp.sum(jnp.square(x)), (1, )))


@register_lowering('squared_l2_distance')
def _squared_l2_distance(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    sub = x - y
    ctx.set(op, 'sub_result', sub)
    ctx.set(op, 'Out', jnp.sum(jnp.square(sub), axis=-1, keepdims=True))


@register_lowering('cumsum')
def _cumsum(ctx, op):
    x = ctx.get(op, 'X')
    axis = op.attrs.get('axis', -1)
    exclusive = op.attrs.get('exclusive', False)
    reverse = op.attrs.get('reverse', False)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    ctx.set(op, 'Out', out)


@register_lowering('pow')
def _pow(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.power(x, op.attrs.get('factor', 1.0)))


@register_lowering('sign')
def _sign(ctx, op):
    ctx.set(op, 'Out', jnp.sign(ctx.get(op, 'X')))


@register_lowering('l1_norm')
def _l1_norm(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.sum(jnp.abs(x)))


@register_lowering('norm')
def _norm(ctx, op):
    x = ctx.get(op, 'X')
    axis = op.attrs.get('axis', -1)
    eps = op.attrs.get('epsilon', 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    ctx.set(op, 'Norm', norm)
    ctx.set(op, 'Out', x / norm)


@register_lowering('cos_sim')
def _cos_sim(ctx, op):
    """Row-wise cosine similarity (reference operators/cos_sim_op.cc);
    Y broadcasts when it has one row."""
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    eps = 1e-12
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)  # broadcasts [1,D] y
    ctx.set(op, 'Out', dot / jnp.maximum(xn * yn, eps))
    ctx.set(op, 'XNorm', xn)
    ctx.set(op, 'YNorm', yn)
