"""Metric op lowerings (reference: paddle/fluid/operators/accuracy_op.cc,
auc_op.cc, precision_recall_op.cc)."""

import jax.numpy as jnp

from .registry import register_lowering


@register_lowering('accuracy')
def _accuracy(ctx, op):
    indices = ctx.get(op, 'Indices')  # (N, k) from top_k
    label = ctx.get(op, 'Label')  # (N, 1) int64
    if label.ndim == 1:
        label = label[:, None]
    hit = jnp.any(indices == label.astype(indices.dtype), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(indices.shape[0], jnp.int32)
    ctx.set(op, 'Accuracy',
            jnp.reshape(correct.astype(jnp.float32) / total, (1, )))
    ctx.set(op, 'Correct', jnp.reshape(correct, (1, )))
    ctx.set(op, 'Total', jnp.reshape(total, (1, )))


@register_lowering('auc')
def _auc(ctx, op):
    probs = ctx.get(op, 'Predict')
    if probs is None:
        probs = ctx.get(op, 'Out')
    label = jnp.reshape(ctx.get(op, 'Label'), (-1, ))
    num_thresholds = op.attrs.get('num_thresholds', 200)
    pos_prob = probs[:, -1] if probs.ndim > 1 else probs
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
    pos = (label > 0)
    # (T, N) comparisons
    pred_pos = pos_prob[None, :] >= thresholds[:, None]
    tp = jnp.sum(pred_pos & pos[None, :], axis=1).astype(jnp.float64)
    fp = jnp.sum(pred_pos & ~pos[None, :], axis=1).astype(jnp.float64)
    fn = jnp.sum(~pred_pos & pos[None, :], axis=1).astype(jnp.float64)
    tn = jnp.sum(~pred_pos & ~pos[None, :], axis=1).astype(jnp.float64)
    tpr = tp / jnp.maximum(tp + fn, 1e-12)
    fpr = fp / jnp.maximum(fp + tn, 1e-12)
    # trapezoid over descending thresholds (ROC)
    auc = jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)
    ctx.set(op, 'AUC', jnp.reshape(jnp.abs(auc).astype(jnp.float32), (1, )))


@register_lowering('precision_recall')
def _precision_recall(ctx, op):
    # per-class precision/recall/F1 for multi-class classification
    indices = jnp.reshape(ctx.get(op, 'Indices'), (-1, ))
    label = jnp.reshape(ctx.get(op, 'Labels'), (-1, ))
    cls_num = op.attrs['class_number']
    pred_oh = (indices[:, None] == jnp.arange(cls_num)[None, :])
    label_oh = (label[:, None] == jnp.arange(cls_num)[None, :])
    tp = jnp.sum(pred_oh & label_oh, axis=0).astype(jnp.float32)
    fp = jnp.sum(pred_oh & ~label_oh, axis=0).astype(jnp.float32)
    fn = jnp.sum(~pred_oh & label_oh, axis=0).astype(jnp.float32)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    ctx.set(op, 'BatchMetrics',
            jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)]))


@register_lowering('positive_negative_pair')
def _positive_negative_pair(ctx, op):
    """Ranking pair statistics within query groups (reference
    operators/positive_negative_pair_op.cc): over all item pairs sharing a
    QueryID with different labels, count score-order agreements (positive),
    disagreements (negative) and ties (neutral); supports running
    accumulation via the Accumulate* inputs."""
    score = jnp.reshape(ctx.get(op, 'Score'), (-1, ))
    label = jnp.reshape(ctx.get(op, 'Label'), (-1, ))
    qid = jnp.reshape(ctx.get(op, 'QueryID'), (-1, ))
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones(same_q.shape, bool), k=1)
    ldiff = label[:, None] - label[None, :]
    sdiff = score[:, None] - score[None, :]
    cand = same_q & upper & (ldiff != 0)
    pos = jnp.sum((cand & (ldiff * sdiff > 0)).astype(jnp.float32))
    neg = jnp.sum((cand & (ldiff * sdiff < 0)).astype(jnp.float32))
    neu = jnp.sum((cand & (sdiff == 0)).astype(jnp.float32))
    for in_slot, out_slot, v in (
            ('AccumulatePositivePair', 'PositivePair', pos),
            ('AccumulateNegativePair', 'NegativePair', neg),
            ('AccumulateNeutralPair', 'NeutralPair', neu)):
        prev = ctx.get(op, in_slot)
        if prev is not None:
            v = v + jnp.reshape(prev, ())
        ctx.set(op, out_slot, jnp.reshape(v, (1, )))
