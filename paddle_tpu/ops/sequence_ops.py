"""Sequence op lowerings: LoD semantics on static shapes.

The reference stores variable-length batches concatenated with LoD offset
tables and runs LoD-aware kernels (framework/lod_tensor.h:58,
operators/sequence_*); dynamic RNNs reorder via math/sequence2batch.h.
XLA needs static shapes, so (SURVEY §5.7) LoD feeds are lowered to padded
``[B, T, ...]`` tensors plus an int32 ``lengths[B]`` carried in the env
under ``<name>@SEQLEN`` (propagated by registry.run_op).  Every sequence op
is a masked dense op; RNNs are ``lax.scan`` over the time axis — which is
exactly the TPU-friendly formulation (big batched matmuls per step).
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register_lowering, register_grad_lowering,
                       fwd_structure, SEQLEN_SUFFIX, ROWS_SUFFIX)


def _seqlen(ctx, op, slot='X'):
    names = op.input(slot)
    if not names:
        return None
    return ctx.env.get(names[0] + SEQLEN_SUFFIX)


def _fused_lstm_ok(d, b_sz, use_peepholes, gate_act_name, cell_act_name,
                   cand_act_name):
    """Policy for the fused Pallas LSTM cell (ops/pallas/lstm.py).

    Measured on v5e (tools/lstm_kernel_lab.py): the kernel wins +14-22%
    fwd+bwd at the ISOLATED-layer level at D=512, but END TO END it is
    neutral-to-negative in every whole model measured — NMT seq2seq
    0.99 (tools/nmt_ab_lab.py, r4+r5) and a 3-layer D=512 stacked-LSTM
    classifier 0.90-0.98 (r5 same-process A/B): inside a whole-block
    program XLA fuses the scan path with its surrounding ops, while
    the custom call is a fusion barrier.  So 'auto' does NOT engage it
    (VERDICT r4 weak-#4: complexity must pay e2e or stay off);
    ``FLAGS_fused_lstm='always'`` keeps the kernel reachable (it also
    runs in interpret mode on CPU so the lowering glue stays tested).
    D is capped at 512: the backward's dW VMEM accumulator is D*4D*4
    bytes regardless of batch tiling (16MB alone at D=1024, the whole
    scoped-VMEM budget)."""
    from ..fluid import flags
    mode = flags.FLAGS.fused_lstm
    if mode != 'always':
        return False
    return (not use_peepholes
            and gate_act_name == 'sigmoid'
            and cell_act_name == 'tanh'
            and cand_act_name == 'tanh'
            and d % 128 == 0 and d <= 512 and b_sz % 8 == 0)


def _nested_segments(rows, r):
    """Packed nested layout bookkeeping: per-sample row starts and each
    global row's owning sample (rows [B] may be traced)."""
    cum = jnp.cumsum(rows)
    start = cum - rows
    seg = jnp.clip(jnp.searchsorted(cum, jnp.arange(r), side='right'),
                   0, int(rows.shape[0]) - 1)
    return start, seg


def _mask(x, lengths, dtype=None):
    """[B, T] validity mask broadcastable against x [B, T, ...]."""
    t = x.shape[1]
    m = jnp.arange(t)[None, :] < lengths[:, None]
    if dtype is not None:
        m = m.astype(dtype)
    return m


def _expand_mask(m, x):
    return jnp.reshape(m, m.shape + (1, ) * (x.ndim - 2))


@register_lowering('sequence_pool')
def _sequence_pool(ctx, op):
    x = ctx.get(op, 'X')  # [B, T, ...]
    lengths = _seqlen(ctx, op)
    ptype = op.attrs.get('pooltype', 'AVERAGE').upper()
    if lengths is None:
        lengths = jnp.full((x.shape[0], ), x.shape[1], jnp.int32)
    m = _expand_mask(_mask(x, lengths, x.dtype), x)
    lens = jnp.maximum(lengths, 1).astype(x.dtype)
    lens = jnp.reshape(lens, (x.shape[0], ) + (1, ) * (x.ndim - 2))
    if ptype == 'SUM':
        out = jnp.sum(x * m, axis=1)
    elif ptype == 'AVERAGE':
        out = jnp.sum(x * m, axis=1) / lens
    elif ptype == 'SQRT':
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(lens)
    elif ptype == 'MAX':
        neg = jnp.full_like(x, -jnp.inf)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
        out = jnp.where(jnp.reshape(lengths, lens.shape) > 0, out,
                        jnp.zeros_like(out))
    elif ptype == 'LAST':
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, jnp.reshape(idx, (-1, 1) + (1, ) * (x.ndim - 2)),
            axis=1)[:, 0]
    elif ptype == 'FIRST':
        out = x[:, 0]
    else:
        raise NotImplementedError('sequence_pool type %r' % ptype)
    rows = ctx.env.get(op.input('X')[0] + ROWS_SUFFIX)
    if rows is not None and op.attrs.get('agg_to_no_sequence', False):
        # nested input + AggregateLevel.TO_NO_SEQUENCE (the reference
        # default, layers.py:302): aggregate over ALL timesteps of each
        # TOP-level sequence.  The inner pooling above gives one value
        # per sub-sequence row; reduce those per sample with the same
        # pool semantics (average = total/total-count, not
        # average-of-averages).
        b = int(rows.shape[0])
        r = x.shape[0]
        start, seg = _nested_segments(rows, r)
        row_cnt = lengths.astype(jnp.float32)
        tot_cnt = jax.ops.segment_sum(row_cnt, seg, num_segments=b)
        safe_cnt = jnp.maximum(tot_cnt, 1.0).reshape(
            (b, ) + (1, ) * (out.ndim - 1)).astype(out.dtype)
        if ptype in ('SUM', 'AVERAGE', 'SQRT'):
            # the inner pool already produced the masked time-sum (out
            # IS it for SUM; AVERAGE/SQRT divided it by lens)
            if ptype == 'SUM':
                row_tot = out
            elif ptype == 'AVERAGE':
                row_tot = out * lens
            else:
                row_tot = out * jnp.sqrt(lens)
            tot = jax.ops.segment_sum(row_tot, seg, num_segments=b)
            if ptype == 'SUM':
                out = tot
            elif ptype == 'AVERAGE':
                out = tot / safe_cnt
            else:
                out = tot / jnp.sqrt(safe_cnt)
        elif ptype == 'MAX':
            row_max = jnp.where(
                jnp.reshape(lengths, lens.shape) > 0, out,
                jnp.full_like(out, -jnp.inf))
            out = jax.ops.segment_max(row_max, seg, num_segments=b)
            out = jnp.where(jnp.isfinite(out), out, jnp.zeros_like(out))
        elif ptype in ('LAST', 'FIRST'):
            # the sample's true last/first timestep lives in its last/
            # first NON-EMPTY sub-sequence (an empty trailing/leading
            # row would otherwise contribute its padding); a sample
            # with no non-empty rows pools to zeros
            valid_row = lengths > 0
            idx = jnp.arange(r)
            if ptype == 'LAST':
                pick = jax.ops.segment_max(
                    jnp.where(valid_row, idx, -1), seg, num_segments=b)
            else:
                pick = jax.ops.segment_min(
                    jnp.where(valid_row, idx, r + 1), seg,
                    num_segments=b)
            has_any = (pick >= 0) & (pick <= r - 1)
            out = jnp.take(out, jnp.clip(pick, 0, r - 1), axis=0)
            out = jnp.where(
                has_any.reshape((b, ) + (1, ) * (out.ndim - 1)), out,
                jnp.zeros_like(out))
        ctx.set(op, 'Out', out)
        if ptype == 'MAX':
            ctx.set(op, 'MaxIndex', jnp.zeros(out.shape, jnp.int32))
        return
    if rows is not None:
        # TO_SEQUENCE on a nested input: the per-row pooled values form
        # a plain sequence — REPAD into the canonical [B, T, ...] +
        # @SEQLEN runtime form so downstream sequence ops compose
        # (T bound: no sample can own more than all R rows)
        b = int(rows.shape[0])
        r = out.shape[0]
        start, seg = _nested_segments(rows, r)
        slot = jnp.arange(r) - jnp.take(start, seg)
        padded = jnp.zeros((b, r) + out.shape[1:], out.dtype)
        padded = padded.at[seg, slot].set(out)
        ctx.set(op, 'Out', padded)
        ctx.env[op.output('Out')[0] + SEQLEN_SUFFIX] = \
            rows.astype(jnp.int32)
        if ptype == 'MAX':
            ctx.set(op, 'MaxIndex', jnp.zeros(padded.shape, jnp.int32))
        return
    ctx.set(op, 'Out', out)
    if ptype == 'MAX':
        ctx.set(op, 'MaxIndex',
                jnp.zeros(out.shape, jnp.int32))  # index output (unused)


@register_lowering('sequence_last_step')
def _sequence_last_step(ctx, op):
    op.attrs['pooltype'] = 'LAST'
    _sequence_pool(ctx, op)


@register_lowering('sequence_first_step')
def _sequence_first_step(ctx, op):
    op.attrs['pooltype'] = 'FIRST'
    _sequence_pool(ctx, op)


@register_lowering('sequence_softmax')
def _sequence_softmax(ctx, op):
    x = ctx.get(op, 'X')  # [B, T] or [B, T, 1]
    lengths = _seqlen(ctx, op)
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x[..., 0] if squeeze else x
    if lengths is None:
        out = jax.nn.softmax(v, axis=1)
    else:
        m = _mask(v, lengths)
        out = jax.nn.softmax(jnp.where(m, v, -1e30), axis=1)
        out = jnp.where(m, out, jnp.zeros_like(out))
    ctx.set(op, 'Out', out[..., None] if squeeze else out)


@register_lowering('sequence_reverse')
def _sequence_reverse(ctx, op):
    """Mask-aware per-sequence time reversal: out[b, t] = x[b, L_b-1-t]
    for t < L_b, padding stays zero in place (the reference's
    reverse-recurrence input transform; reverse_op.cc is the dense-axis
    cousin).  Lengths propagate unchanged."""
    x = ctx.get(op, 'X')
    lengths = _seqlen(ctx, op)
    t = x.shape[1]
    if lengths is None:
        ctx.set(op, 'Out', jnp.flip(x, axis=1))
        return
    lengths = lengths.astype(jnp.int32)
    pos = jnp.arange(t)[None, :]
    src = jnp.clip(lengths[:, None] - 1 - pos, 0, t - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1, ) * (x.ndim - 2)), axis=1)
    m = _expand_mask(_mask(x, lengths, x.dtype), x)
    ctx.set(op, 'Out', out * m)


@register_lowering('sequence_expand')
def _sequence_expand(ctx, op):
    """Broadcast each batch row of X across its ref sequence's steps
    (reference sequence_expand_op.cc, level-1 semantics on padded form).

    With attr ``expand_from_sequence`` and a NESTED ref (the legacy
    ExpandLevel.FROM_SEQUENCE, reference layers.py:1838): X is a plain
    sequence whose j-th item of sample b broadcasts across the j-th
    sub-sequence of the nested ref — SEQUENCE expands to SUB_SEQUENCE."""
    x = ctx.get(op, 'X')  # [B, D] or [B, 1, D]
    y = ctx.get(op, 'Y')  # [B, T, ...] provides the target lengths
    ynames = op.input('Y')
    rows = (ctx.env.get(ynames[0] + ROWS_SUFFIX) if ynames else None)
    if op.attrs.get('expand_from_sequence') and rows is None:
        raise ValueError(
            'sequence_expand(FROM_SEQUENCE): the expand_as ref %r is '
            'not a nested (2-level LoD) sequence — the reference '
            'errors on this level mismatch; use FROM_NO_SEQUENCE for '
            'a plain ref' % (ynames[0] if ynames else None))
    if op.attrs.get('expand_from_sequence') and rows is not None:
        # X [B, Tx, D] items -> ref rows [R, T2, ...]
        if x.ndim < 3:
            raise ValueError(
                'sequence_expand(FROM_SEQUENCE): X must be a SEQUENCE '
                '(padded [B, T, D]), got shape %s — FROM_NO_SEQUENCE '
                'is the level for per-sample inputs' % (x.shape, ))
        b = int(rows.shape[0])
        r = y.shape[0]
        start, seg = _nested_segments(rows, r)
        raw_slot = jnp.arange(r) - jnp.take(start, seg)
        slot = jnp.clip(raw_slot, 0, x.shape[1] - 1)
        vals = x[seg, slot]                      # [R, D]
        # a ref sub-sequence beyond X's own item count gets zeros, not
        # clipped garbage (reference errors on the length mismatch;
        # lengths are traced here, so mask instead — caller contract)
        x_lens = ctx.env.get(op.input('X')[0] + SEQLEN_SUFFIX)
        if x_lens is not None:
            ok = raw_slot < jnp.take(x_lens.astype(jnp.int32), seg)
            vals = jnp.where(
                ok.reshape((-1, ) + (1, ) * (vals.ndim - 1)), vals,
                jnp.zeros_like(vals))
        t2 = y.shape[1]
        out = jnp.repeat(vals[:, None], t2, axis=1)  # [R, T2, D]
        inner = ctx.env.get(ynames[0] + SEQLEN_SUFFIX)
        if inner is not None:
            m = _mask(out, inner.astype(jnp.int32), out.dtype)
            out = out * jnp.reshape(
                m, m.shape + (1, ) * (out.ndim - 2))
            ctx.env[op.output('Out')[0] + SEQLEN_SUFFIX] = \
                inner.astype(jnp.int32)
        ctx.env[op.output('Out')[0] + ROWS_SUFFIX] = \
            rows.astype(jnp.int32)
        ctx.set(op, 'Out', out)
        return
    if x.ndim == y.ndim:  # already time-major: tile per-step
        ctx.set(op, 'Out', x)
        return
    t = y.shape[1]
    out = jnp.repeat(x[:, None], t, axis=1)
    ctx.set(op, 'Out', out)
    if ynames and (ynames[0] + SEQLEN_SUFFIX) in ctx.env:
        for n in op.output('Out'):
            ctx.env[n + SEQLEN_SUFFIX] = ctx.env[ynames[0] + SEQLEN_SUFFIX]


@register_lowering('sequence_concat')
def _sequence_concat(ctx, op):
    """Per-instance TIME concatenation with summed lengths (reference
    sequence_concat_op default axis=0 semantics, on padded form)."""
    xs = ctx.get_list(op, 'X')
    names = op.input('X')
    lens = []
    for name, x in zip(names, xs):
        l = ctx.env.get(name + SEQLEN_SUFFIX)
        if l is None:
            l = jnp.full((x.shape[0], ), x.shape[1], jnp.int32)
        lens.append(l)
    total_t = sum(x.shape[1] for x in xs)
    b = xs[0].shape[0]
    out = jnp.zeros((b, total_t) + xs[0].shape[2:], xs[0].dtype)
    pos = jnp.arange(total_t)[None, :]  # [1, total_t]
    offset = jnp.zeros((b, ), jnp.int32)
    for x, l in zip(xs, lens):
        # place x[b, 0:l_b] at out[b, offset_b:offset_b+l_b]
        j = pos - offset[:, None]
        valid = (j >= 0) & (j < l[:, None])
        j_cl = jnp.clip(j, 0, x.shape[1] - 1)
        gathered = jnp.take_along_axis(
            x, jnp.reshape(j_cl, (b, total_t) + (1, ) * (x.ndim - 2)),
            axis=1)
        mask = jnp.reshape(valid, (b, total_t) + (1, ) * (x.ndim - 2))
        out = jnp.where(mask, gathered, out)
        offset = offset + l
    ctx.set(op, 'Out', out)
    for n in op.output('Out'):
        ctx.env[n + SEQLEN_SUFFIX] = offset


@register_lowering('sequence_reshape')
def _sequence_reshape(ctx, op):
    x = ctx.get(op, 'X')  # [B, T, D]
    new_dim = op.attrs['new_dim']
    b, t, d = x.shape
    ctx.set(op, 'Out', jnp.reshape(x, (b, t * d // new_dim, new_dim)))
    # lengths rescale by d/new_dim (reference sequence_reshape_op.cc)
    lengths = _seqlen(ctx, op)
    if lengths is not None:
        for n in op.output('Out'):
            ctx.env[n + SEQLEN_SUFFIX] = lengths * d // new_dim


@register_lowering('sequence_conv')
def _sequence_conv(ctx, op):
    """Context-window projection over time
    (reference operators/sequence_conv_op.cc + math/context_project.h)."""
    x = ctx.get(op, 'X')  # [B, T, D]
    w = ctx.get(op, 'Filter')  # [ctx_len * D, M]
    lengths = _seqlen(ctx, op)
    ctx_len = op.attrs.get('contextLength', 3)
    ctx_start = op.attrs.get('contextStart', -(ctx_len // 2))
    b, t, d = x.shape
    if lengths is not None:
        x = x * _expand_mask(_mask(x, lengths, x.dtype), x)
    # pad time so every window is in-bounds, then gather shifted views
    pad_lo = max(-ctx_start, 0)
    pad_hi = max(ctx_start + ctx_len - 1, 0)
    xp = jnp.pad(x, ((0, 0), (pad_lo, pad_hi), (0, 0)))
    views = [
        xp[:, pad_lo + ctx_start + i:pad_lo + ctx_start + i + t]
        for i in range(ctx_len)
    ]
    ctx_mat = jnp.concatenate(views, axis=-1)  # [B, T, ctx_len*D]
    ctx.set(op, 'Out', jnp.einsum('btc,cm->btm', ctx_mat, w))


@register_lowering('sequence_slice')
def _sequence_slice(ctx, op):
    """Per-sequence window (reference sequence_slice_op.cc: each row i
    keeps [offset_i, offset_i + length_i)).  Static layout: rows are
    gathered to the front of the same padded buffer and the lengths
    side-band becomes length_i — offsets/lengths may be traced per-row
    values or concrete scalars."""
    x = ctx.get(op, 'X')
    offset = jnp.reshape(ctx.get(op, 'Offset'), (-1, )).astype(jnp.int32)
    length = jnp.reshape(ctx.get(op, 'Length'), (-1, )).astype(jnp.int32)
    b, t = x.shape[0], x.shape[1]
    if offset.shape[0] == 1 and b > 1:
        offset = jnp.broadcast_to(offset, (b, ))
    if length.shape[0] == 1 and b > 1:
        length = jnp.broadcast_to(length, (b, ))
    pos = jnp.arange(t)[None, :]  # [1, T]
    idx = jnp.clip(offset[:, None] + pos, 0, t - 1)
    gathered = jnp.take_along_axis(
        x, jnp.reshape(idx, (b, t) + (1, ) * (x.ndim - 2)), axis=1)
    valid = pos < length[:, None]
    out = jnp.where(
        jnp.reshape(valid, (b, t) + (1, ) * (x.ndim - 2)), gathered,
        jnp.zeros_like(gathered))
    ctx.set(op, 'Out', out)
    for n in op.output('Out'):
        ctx.env[n + SEQLEN_SUFFIX] = length


@register_lowering('sequence_enumerate')
def _sequence_enumerate(ctx, op):
    x = ctx.get(op, 'X')  # [B, T] or [B, T, 1] int ids
    win = op.attrs['win_size']
    pad_value = op.attrs.get('pad_value', 0)
    squeeze = x.ndim == 3
    v = x[..., 0] if squeeze else x
    b, t = v.shape
    vp = jnp.pad(v, ((0, 0), (0, win - 1)), constant_values=pad_value)
    out = jnp.stack([vp[:, i:i + t] for i in range(win)], axis=-1)
    ctx.set(op, 'Out', out)


@register_lowering('sequence_erase')
def _sequence_erase(ctx, op):
    """Remove listed tokens (reference sequence_erase_op.cc shrinks the LoD
    rows).  Static shapes forbid true erasure, so kept tokens are compacted
    to the front of the padded buffer and the @SEQLEN side-band shrinks to
    the new per-row counts — downstream sequence ops see the same semantics
    as the reference's re-lodded output."""
    x = ctx.get(op, 'X')
    tokens = op.attrs.get('tokens', [])
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    xv = x[..., 0] if squeeze else x
    if xv.ndim == 1:
        xv = xv[None]
        batchless = True
    else:
        batchless = False
    b, t = xv.shape[0], xv.shape[1]
    lens = _seqlen(ctx, op)
    if lens is None:
        lens = jnp.full((b, ), t, jnp.int32)
    valid = jnp.arange(t)[None, :] < lens[:, None]
    keep = valid
    for tok in tokens:
        keep = keep & (xv != tok)
    dest = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    # route dropped entries to a scratch column, then slice it off
    dest = jnp.where(keep, dest, t)
    out = jnp.zeros((b, t + 1), xv.dtype)
    out = out.at[jnp.arange(b)[:, None], dest].set(xv)[:, :t]
    new_lens = jnp.sum(keep.astype(jnp.int32), axis=1)
    if batchless:
        out = out[0]
    if squeeze:
        out = out[..., None]
    ctx.set(op, 'Out', out)
    for n in op.output('Out'):
        ctx.env[n + SEQLEN_SUFFIX] = new_lens


@register_lowering('sequence_pad')
def _sequence_pad(ctx, op):
    # inputs are already padded in this lowering scheme
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', x)
    lengths = _seqlen(ctx, op)
    if lengths is not None:
        ctx.set(op, 'Length', lengths.astype(jnp.int64))


@register_lowering('sequence_unpad')
def _sequence_unpad(ctx, op):
    ctx.set(op, 'Out', ctx.get(op, 'X'))


# ----------------------------------------------------------------------------
# Recurrent nets: lax.scan over the time axis
# ----------------------------------------------------------------------------
def _act(name):
    return {
        'sigmoid': jax.nn.sigmoid,
        'tanh': jnp.tanh,
        'relu': jax.nn.relu,
        'identity': lambda v: v,
    }[name or 'tanh']


@register_lowering('lstm')
def _lstm(ctx, op):
    """Dynamic LSTM (reference operators/lstm_op.cc).  Input is the
    pre-projected gate matrix [B, T, 4D]; the op runs the recurrence
    h_t = f(x_t + h_{t-1} W + b) with per-step masking replacing the
    reference's sequence2batch reordering.  Gate order: i, f, c, o."""
    x = ctx.get(op, 'Input')  # [B, T, 4D]
    w = ctx.get(op, 'Weight')  # [D, 4D]
    bias = ctx.get(op, 'Bias')  # [1, 4D] (+ [1, 3D] peephole tail)
    h0 = ctx.get(op, 'H0')
    c0 = ctx.get(op, 'C0')
    lengths = _seqlen(ctx, op, 'Input')
    use_peepholes = op.attrs.get('use_peepholes', False)
    is_reverse = op.attrs.get('is_reverse', False)
    gate_act = _act(op.attrs.get('gate_activation', 'sigmoid'))
    cell_act = _act(op.attrs.get('cell_activation', 'tanh'))
    cand_act = _act(op.attrs.get('candidate_activation', 'tanh'))

    b_sz, t, d4 = x.shape
    d = d4 // 4
    gate_bias = bias[:, :4 * d] if bias is not None else 0.0
    if use_peepholes and bias is not None:
        w_ic = bias[0, 4 * d:5 * d]
        w_fc = bias[0, 5 * d:6 * d]
        w_oc = bias[0, 6 * d:7 * d]
    # dtype flow under AMP: the sequence x and hidden state h stay in
    # x's dtype (bf16 — the recurrent matmul rides the MXU fast path via
    # the bf16-cast weight), while gates and the CELL state compute and
    # carry in f32: c accumulates across T steps, exactly the drift an
    # 8-bit mantissa cannot hold
    cd = x.dtype
    w_r = w.astype(cd)
    h_prev = (h0.astype(cd) if h0 is not None
              else jnp.zeros((b_sz, d), cd))
    c_prev = (c0.astype(jnp.float32) if c0 is not None
              else jnp.zeros((b_sz, d), jnp.float32))

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, 4D]
    if is_reverse:
        xs = jnp.flip(xs, 0)
    if lengths is None:
        step_mask = jnp.ones((t, b_sz), jnp.float32)
    else:
        step_mask = _mask(x, lengths, jnp.float32).T  # [T, B]
        if is_reverse:
            step_mask = jnp.flip(step_mask, 0)

    if _fused_lstm_ok(d, b_sz, use_peepholes,
                      op.attrs.get('gate_activation', 'sigmoid'),
                      op.attrs.get('cell_activation', 'tanh'),
                      op.attrs.get('candidate_activation', 'tanh')):
        from .pallas import lstm as pl_lstm
        bias_arr = (gate_bias if bias is not None
                    else jnp.zeros((1, 4 * d), jnp.float32))
        hs, cs = pl_lstm.lstm_fused_tm(xs, w, bias_arr, h_prev, c_prev,
                                       mask=step_mask)
        if is_reverse:
            hs = jnp.flip(hs, 0)
            cs = jnp.flip(cs, 0)
        ctx.set(op, 'Hidden', jnp.swapaxes(hs, 0, 1))
        ctx.set(op, 'Cell', jnp.swapaxes(cs, 0, 1).astype(cd))
        ctx.set(op, 'BatchGate', x)
        ctx.set(op, 'BatchCellPreAct', jnp.swapaxes(cs, 0, 1).astype(cd))
        return

    def step(carry, inp):
        h, c = carry
        x_t, m_t = inp
        gates = (x_t + h @ w_r).astype(jnp.float32) + gate_bias
        # reference gate layout: [candidate(in), input, forget, output]
        # (math/detail/lstm_cpu_kernel.h:44-47)
        gc, gi, gf, go = jnp.split(gates, 4, axis=1)
        if use_peepholes:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        if use_peepholes:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        m = m_t[:, None]
        h_out = (m * h_new + (1 - m) * h.astype(jnp.float32)).astype(cd)
        c_out = m * c_new + (1 - m) * c
        return (h_out, c_out), (h_out, c_out)

    (_, _), (hs, cs) = jax.lax.scan(step, (h_prev, c_prev), (xs, step_mask))
    if is_reverse:
        hs = jnp.flip(hs, 0)
        cs = jnp.flip(cs, 0)
    ctx.set(op, 'Hidden', jnp.swapaxes(hs, 0, 1))
    ctx.set(op, 'Cell', jnp.swapaxes(cs, 0, 1).astype(cd))
    ctx.set(op, 'BatchGate', x)
    ctx.set(op, 'BatchCellPreAct', jnp.swapaxes(cs, 0, 1).astype(cd))


@register_lowering('gru')
def _gru(ctx, op):
    """Dynamic GRU (reference operators/gru_op.cc).  Input [B, T, 3D]
    pre-projected; weight [D, 3D] = [W_update | W_reset | W_candidate]."""
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Weight')
    bias = ctx.get(op, 'Bias')
    h0 = ctx.get(op, 'H0')
    lengths = _seqlen(ctx, op, 'Input')
    is_reverse = op.attrs.get('is_reverse', False)
    gate_act = _act(op.attrs.get('gate_activation', 'sigmoid'))
    cand_act = _act(op.attrs.get('activation', 'tanh'))

    b_sz, t, d3 = x.shape
    d = d3 // 3
    # same AMP dtype flow as _lstm: x/h in x's dtype for the MXU, the
    # gate math in f32; the bias adds INSIDE the step so the whole
    # [B, T, 3D] sequence is never widened to f32 in HBM
    cd = x.dtype
    w_g = w[:, :2 * d].astype(cd)  # update+reset recurrent weights
    w_c = w[:, 2 * d:].astype(cd)
    if bias is not None:
        bias_g = bias.reshape(1, -1)[:, :2 * d].astype(jnp.float32)
        bias_c = bias.reshape(1, -1)[:, 2 * d:].astype(jnp.float32)
    else:
        bias_g = bias_c = 0.0
    h_prev = h0.astype(cd) if h0 is not None else jnp.zeros((b_sz, d), cd)

    xs = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, 0)
    if lengths is None:
        step_mask = jnp.ones((t, b_sz), jnp.float32)
    else:
        step_mask = _mask(x, lengths, jnp.float32).T
        if is_reverse:
            step_mask = jnp.flip(step_mask, 0)

    def step(h, inp):
        x_t, m_t = inp
        gu_gr = gate_act(
            (x_t[:, :2 * d] + h @ w_g).astype(jnp.float32) + bias_g)
        u, r = jnp.split(gu_gr, 2, axis=1)
        c = cand_act((x_t[:, 2 * d:] +
                      (r.astype(cd) * h) @ w_c).astype(jnp.float32) +
                     bias_c)
        # reference: h = (1-u)*h_prev + u*c (math/detail/gru_kernel.h:62)
        h_new = (1 - u) * h.astype(jnp.float32) + u * c
        m = m_t[:, None]
        h_out = (m * h_new + (1 - m) * h.astype(jnp.float32)).astype(cd)
        return h_out, h_out

    _, hs = jax.lax.scan(step, h_prev, (xs, step_mask))
    if is_reverse:
        hs = jnp.flip(hs, 0)
    out = jnp.swapaxes(hs, 0, 1)
    ctx.set(op, 'Hidden', out)
    ctx.set(op, 'BatchGate', x)
    ctx.set(op, 'BatchResetHiddenPrev', out)
    ctx.set(op, 'BatchHidden', out)


@register_lowering('gru_unit')
def _gru_unit(ctx, op):
    """Single GRU step (reference operators/gru_unit_op.cc)."""
    x = ctx.get(op, 'Input')  # [B, 3D]
    h_prev = ctx.get(op, 'HiddenPrev')
    w = ctx.get(op, 'Weight')  # [D, 3D]
    bias = ctx.get(op, 'Bias')
    gate_act = _act({1: 'sigmoid', 0: 'identity', 2: 'tanh',
                     3: 'relu'}.get(op.attrs.get('gate_activation', 1)))
    cand_act = _act({1: 'sigmoid', 0: 'identity', 2: 'tanh',
                     3: 'relu'}.get(op.attrs.get('activation', 2)))
    d = h_prev.shape[1]
    if bias is not None:
        x = x + bias
    w_g = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    g = gate_act(x[:, :2 * d] + h_prev @ w_g)
    u, r = jnp.split(g, 2, axis=1)
    c = cand_act(x[:, 2 * d:] + (r * h_prev) @ w_c)
    # reference: h = u*(c - h_prev) + h_prev (gru_unit_op.h:116)
    h = (1 - u) * h_prev + u * c
    ctx.set(op, 'Gate', jnp.concatenate([g, c], axis=1))
    ctx.set(op, 'ResetHiddenPrev', r * h_prev)
    ctx.set(op, 'Hidden', h)


@register_lowering('row_conv')
def _row_conv(ctx, op):
    """Lookahead row convolution (reference operators/row_conv_op.cc)."""
    x = ctx.get(op, 'X')  # [B, T, D]
    w = ctx.get(op, 'Filter')  # [future_ctx, D]
    k = w.shape[0]
    b, t, d = x.shape
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(xp[:, i:i + t] * w[i][None, None, :] for i in range(k))
    ctx.set(op, 'Out', out)


@register_lowering('sequence_mask')
def _sequence_mask_op(ctx, op):
    """lengths [B] -> mask [B, maxlen] (reference sequence_mask op /
    math/sequence_padding.h mask generation)."""
    lengths = ctx.get(op, 'X').reshape(-1)
    maxlen = int(op.attrs.get('maxlen', -1))
    if maxlen <= 0:
        raise NotImplementedError(
            'sequence_mask needs a static maxlen attr under XLA '
            '(dynamic maxlen = data-dependent shape)')
    dummy = jnp.zeros((lengths.shape[0], maxlen))
    out_dtype = op.attrs.get('out_dtype', 'int64')
    ctx.set(op, 'Out', _mask(dummy, lengths, dtype=jnp.dtype(out_dtype)))


@register_lowering('lstmp')
def _lstmp(ctx, op):
    """LSTM with recurrent projection (reference operators/lstmp_op.cc):
    the recurrence feeds the projected state r_t = proj_act(h_t @ P) back
    into the gates instead of h_t, shrinking the recurrent matmul for
    large-vocab speech models.  Outputs Projection [B, T, P], Cell."""
    x = ctx.get(op, 'Input')  # [B, T, 4D]
    w = ctx.get(op, 'Weight')  # [P, 4D]
    w_proj = ctx.get(op, 'ProjWeight')  # [D, P]
    bias = ctx.get(op, 'Bias')
    h0 = ctx.get(op, 'H0')  # [B, P] projected initial state
    c0 = ctx.get(op, 'C0')  # [B, D]
    lengths = _seqlen(ctx, op, 'Input')
    use_peepholes = op.attrs.get('use_peepholes', False)
    is_reverse = op.attrs.get('is_reverse', False)
    gate_act = _act(op.attrs.get('gate_activation', 'sigmoid'))
    cell_act = _act(op.attrs.get('cell_activation', 'tanh'))
    cand_act = _act(op.attrs.get('candidate_activation', 'tanh'))
    proj_act = _act(op.attrs.get('proj_activation', 'tanh'))

    b_sz, t, d4 = x.shape
    d = d4 // 4
    p_dim = w_proj.shape[1]
    gate_bias = bias[:, :4 * d] if bias is not None else 0.0
    if use_peepholes and bias is not None:
        w_ic = bias[0, 4 * d:5 * d]
        w_fc = bias[0, 5 * d:6 * d]
        w_oc = bias[0, 6 * d:7 * d]
    r_prev = h0 if h0 is not None else jnp.zeros((b_sz, p_dim), x.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((b_sz, d), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, 0)
    if lengths is None:
        step_mask = jnp.ones((t, b_sz), x.dtype)
    else:
        step_mask = _mask(x, lengths, x.dtype).T
        if is_reverse:
            step_mask = jnp.flip(step_mask, 0)

    def step(carry, inp):
        r, c = carry
        x_t, m_t = inp
        gates = x_t + r @ w + gate_bias
        gc, gi, gf, go = jnp.split(gates, 4, axis=1)
        if use_peepholes:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        if use_peepholes:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        m = m_t[:, None]
        r_out = m * r_new + (1 - m) * r
        c_out = m * c_new + (1 - m) * c
        return (r_out, c_out), (r_out, c_out)

    (_, _), (rs, cs) = jax.lax.scan(step, (r_prev, c_prev), (xs, step_mask))
    if is_reverse:
        rs = jnp.flip(rs, 0)
        cs = jnp.flip(cs, 0)
    ctx.set(op, 'Projection', jnp.swapaxes(rs, 0, 1))
    ctx.set(op, 'Cell', jnp.swapaxes(cs, 0, 1))
    ctx.set(op, 'BatchGate', x)
    ctx.set(op, 'BatchHidden', jnp.swapaxes(rs, 0, 1))


@register_lowering('lod_rank_table')
def _lod_rank_table(ctx, op):
    """Length-descending stable sort permutation (reference
    framework/lod_rank_table.h built by operators/lod_rank_table_op.cc).
    On the padded layout the 'table' is the [B] int32 row permutation."""
    x = ctx.get(op, 'X')
    lengths = _seqlen(ctx, op)
    b = x.shape[0]
    if lengths is None:
        lengths = jnp.full((b, ), x.shape[1] if x.ndim > 1 else 1,
                           jnp.int32)
    # stable argsort on (-length, row) keeps the reference's tie order
    perm = jnp.argsort(-lengths.astype(jnp.int32), stable=True)
    ctx.set(op, 'Out', perm.astype(jnp.int32))


@register_lowering('reorder_lod_tensor_by_rank')
def _reorder_lod_tensor_by_rank(ctx, op):
    """Gather rows by a rank-table permutation (reference
    operators/reorder_lod_tensor_by_rank_op.cc); the sequence-length
    side-band is permuted alongside the data."""
    x = ctx.get(op, 'X')
    perm = ctx.get(op, 'RankTable')
    out = jnp.take(x, perm, axis=0)
    ctx.set(op, 'Out', out)
    lengths = _seqlen(ctx, op)
    if lengths is not None:
        out_name = op.output('Out')[0]
        ctx.env[out_name + SEQLEN_SUFFIX] = jnp.take(lengths, perm, axis=0)


@register_lowering('context_project')
def _context_project(ctx, op):
    """Parameter-free context-window concatenation (reference
    math/context_project.h, the substrate of context_projection):
    out[:, t] = concat(x[:, t+start], ..., x[:, t+start+L-1]) with zero
    padding outside the time range."""
    x = ctx.get(op, 'X')  # [B, T, D]
    ctx_len = int(op.attrs['context_len'])
    start = int(op.attrs.get('context_start',
                             -((ctx_len - 1) // 2)))
    b, t, d = x.shape
    parts = []
    for j in range(ctx_len):
        off = start + j
        if off == 0:
            parts.append(x)
        elif off > 0:
            pad = jnp.zeros((b, off, d), x.dtype)
            parts.append(jnp.concatenate([x[:, off:], pad], axis=1))
        else:
            pad = jnp.zeros((b, -off, d), x.dtype)
            parts.append(jnp.concatenate([pad, x[:, :off]], axis=1))
    ctx.set(op, 'Out', jnp.concatenate(parts, axis=2))


@register_lowering('sub_nested_seq')
def _sub_nested_seq(ctx, op):
    """Select whole sub-sequences of a nested sequence by per-sequence
    row indices (reference sub_nested_seq_layer;
    legacy/gserver/layers/SubNestedSequenceLayer.cpp).

    Static-shape design: the nested input arrives padded [R, T, ...]
    with inner lengths ``X@SEQLEN`` [R] and the outer level ``X@ROWS``
    [B] (sub-sequences per sequence).  ``SelectedIndices`` is [B, k]
    (-1 padded) of row indices RELATIVE to each sequence's own rows —
    the reference's selected_indices contract.  Output keeps the nested
    form: [B*k, T, ...] rows (invalid selections zeroed, length 0) with
    fresh @SEQLEN/@ROWS sidecars, so downstream sequence ops and a
    second selection round both compose."""
    x = ctx.get(op, 'X')
    sel = ctx.get(op, 'SelectedIndices')
    inner = _seqlen(ctx, op, 'X')
    rows = ctx.env.get(op.input('X')[0] + ROWS_SUFFIX)
    if inner is None:
        inner = jnp.full((x.shape[0], ), x.shape[1], jnp.int32)
    if rows is None:
        raise ValueError(
            'sub_nested_seq: input %r carries no @ROWS outer level — '
            'feed it as a 2-level LoD tensor' % op.input('X')[0])
    if sel.ndim == 3 and sel.shape[-1] == 1:
        sel = sel[..., 0]
    sel = sel.astype(jnp.int32)
    b, k = sel.shape
    row_start = jnp.cumsum(rows) - rows            # [B]
    valid = (sel >= 0) & (sel < rows[:, None])     # [B, k]
    abs_rows = jnp.clip(row_start[:, None] + jnp.clip(sel, 0), 0,
                        x.shape[0] - 1).reshape(-1)  # [B*k]
    flat_valid = valid.reshape(-1)
    # compact valid rows to packed order (rows of sequence b start at
    # cumsum of previous sequences' counts) so the output honors the
    # same nested-layout invariant as the input; invalid selections
    # scatter into a scratch row that is sliced off
    n_out = b * k
    pos = jnp.cumsum(flat_valid) - 1               # rank among valid
    target = jnp.where(flat_valid, pos, n_out)
    gathered = x[abs_rows]
    out = jnp.zeros((n_out + 1, ) + x.shape[1:], x.dtype)
    out = out.at[target].set(gathered)[:n_out]
    out_inner = jnp.zeros((n_out + 1, ), jnp.int32).at[target].set(
        inner[abs_rows].astype(jnp.int32))[:n_out]
    ctx.set(op, 'Out', out)
    ctx.env[op.output('Out')[0] + SEQLEN_SUFFIX] = out_inner
    ctx.env[op.output('Out')[0] + ROWS_SUFFIX] = valid.sum(
        axis=1).astype(jnp.int32)


@register_lowering('kmax_seq_score')
def _kmax_seq_score(ctx, op):
    """Top-k INDICES per sequence (reference KmaxSeqScoreLayer.cpp:52 —
    "output ... is some selected indices of the given sequence", carried
    as real values, -1 beyond min(k, seq_len)).  Scores arrive [B, T] or
    [B, T, 1] padded; padding is masked out of the per-row top_k.  The
    index output is exactly what sub_nested_seq_layer consumes as
    selected_indices in the reference beam-training flow."""
    x = ctx.get(op, 'X')
    k = int(op.attrs.get('beam_size', 1))
    lengths = _seqlen(ctx, op)
    v = x[..., 0] if x.ndim == 3 and x.shape[-1] == 1 else x
    if k > v.shape[1]:
        raise ValueError(
            'kmax_seq_score: beam_size %d exceeds the padded time dim %d'
            % (k, v.shape[1]))
    if lengths is not None:
        m = _mask(v, lengths)
        v = jnp.where(m, v, -jnp.inf)
        n_valid = jnp.minimum(lengths.astype(jnp.int32), k)
    else:
        n_valid = jnp.full((v.shape[0], ), min(v.shape[1], k), jnp.int32)
    _, idx = jax.lax.top_k(v, k)
    slot_ok = jnp.arange(k)[None, :] < n_valid[:, None]
    ctx.set(op, 'Out',
            jnp.where(slot_ok, idx, -1).astype(jnp.float32))
