"""SelectedRows / sparse-gradient support, TPU-native.

Reference surface: ``SelectedRows`` (framework/selected_rows.h:32) — a
row-subset tensor {rows, value, height} used chiefly for embedding
gradients (operators/lookup_table_op.cc grad with ``is_sparse``), with
optimizer kernels that update only the touched rows
(operators/sgd_op.h SelectedRows branch, operators/adam_op.h
SparseAdamFunctor, merge/scale math in math/selected_rows_functor.cc).

TPU-native design: inside a compiled block a sparse gradient is a
``SparseRows`` pytree — rows (int32 [N]) + values ([N, D]) + static
height — so the [V, D] dense gradient is never materialized.  The SGD
update lowers to one XLA scatter-add; momentum, adam (ISSUE 11),
adagrad (ISSUE 12), rmsprop (ISSUE 14) and ftrl (ISSUE 17) run the
reference's *lazy* row-subset kernels directly — duplicate ids merge
by an in-domain scatter-add (``merge_rows``), the touched rows of
param + moments gather to an [N, D] subset, the dense optimizer math
runs there, and one scatter-update writes back, O(rows x D) per step
with untouched rows' moments never decaying.  Remaining adaptive
optimizers (adadelta/adamax/…) fall back to ``lazy_apply``'s
dense-materialize + mask emulation (identical semantics, O(V x D)).

ISSUE 12 adds the hot-row cache slab exchange kernels at the bottom:
the two-tier embedding store's device half (one padded gather of
dirty evicted rows out, one padded scatter of host-fetched miss rows
in) — see ``distributed.embed_cache``.
Everything stays jit-compatible: rows/values have static shapes (one
row per looked-up id), duplicates are resolved by scatter addition —
the pytree rides ``run_multi``'s scanned train step on both executors.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (GRAD_SUFFIX, fwd_structure, register_grad_lowering,
                       register_lowering)


@jax.tree_util.register_pytree_node_class
class SparseRows(object):
    """Traced stand-in for the reference SelectedRows."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, values = children
        return cls(rows, values, height)

    @property
    def dense_shape(self):
        return (self.height, ) + tuple(self.values.shape[1:])

    def to_dense(self):
        """Scatter-add into the dense [height, D] gradient (duplicate rows
        accumulate, matching math/selected_rows_functor.cc MergeAdd)."""
        zeros = jnp.zeros(self.dense_shape, self.values.dtype)
        return zeros.at[self.rows].add(self.values)

    def touched_mask(self):
        """Boolean [height] mask of rows present in this gradient."""
        m = jnp.zeros((self.height, ), jnp.bool_)
        return m.at[self.rows].set(True)

    def scale(self, s):
        return SparseRows(self.rows, self.values * s, self.height)

    def __repr__(self):
        return 'SparseRows(n=%s, height=%d, dim=%s)' % (
            self.values.shape[0], self.height, self.values.shape[1:])


def sparse_add(a, b):
    """Gradient accumulation closed over {dense, SparseRows, tensor-array
    list} operands.  Lists add elementwise — python `+` would concatenate,
    silently corrupting summed tensor-array gradients."""
    if isinstance(a, list) and isinstance(b, list):
        return [sparse_add(x, y) for x, y in zip(a, b)]
    a_sparse = isinstance(a, SparseRows)
    b_sparse = isinstance(b, SparseRows)
    if a_sparse and b_sparse:
        return SparseRows(
            jnp.concatenate([a.rows, b.rows]),
            jnp.concatenate([a.values, b.values]), a.height)
    if a_sparse:
        return b + a.to_dense()
    if b_sparse:
        return a + b.to_dense()
    return a + b


# ----------------------------------------------------------------------------
# lookup_table grad: dense scatter-add or SparseRows depending on is_sparse
# (reference lookup_table_op.cc LookupTableGradKernel / ..GradCUDAKernel)
# ----------------------------------------------------------------------------
@register_grad_lowering('lookup_table')
def _lookup_table_grad(ctx, op):
    fwd_inputs, fwd_outputs, fwd_attrs = fwd_structure(op)
    gnames = op.output('W' + GRAD_SUFFIX)
    if not gnames or not gnames[0]:
        return
    gname = gnames[0]
    w = ctx.lookup(fwd_inputs['W'][0])
    ids = ctx.lookup(fwd_inputs['Ids'][0])
    gout = ctx.lookup(fwd_outputs['Out'][0] + GRAD_SUFFIX)
    flat = jnp.reshape(ids, (-1, )).astype(jnp.int32)
    vals = jnp.reshape(gout, (flat.shape[0], w.shape[-1]))
    padding_idx = fwd_attrs.get('padding_idx', -1)
    if padding_idx is not None and padding_idx >= 0:
        vals = jnp.where((flat == padding_idx)[:, None],
                         jnp.zeros_like(vals), vals)
    if fwd_attrs.get('is_sparse', False):
        g = SparseRows(flat, vals, w.shape[0])
    else:
        g = jnp.zeros_like(w).at[flat].add(vals)
    if ctx.has(gname):
        g = sparse_add(ctx.lookup(gname), g)
    ctx.store(gname, g)


# ----------------------------------------------------------------------------
# Optimizer wrapping: lazy row-subset updates for SparseRows grads
# ----------------------------------------------------------------------------
def sparse_sgd_update(p, g, lr):
    """Exact sparse SGD: one scatter-add, no dense grad materialized
    (reference sgd_op.h SelectedRows branch)."""
    return p.at[g.rows].add((-lr * g.values).astype(p.dtype))


def merge_rows(rows, values, height):
    """Merge duplicate ids by scatter-add WITHIN the [N, D] row domain
    (reference math/selected_rows_functor.cc MergeAdd), jit-safe with
    static shapes: sort the ids, segment-sum each duplicate run onto
    its first occurrence's slot, and park every leftover slot on the
    out-of-range id ``height``.

    Returns (slot_rows [N] int, merged [N, D]): the leading num-unique
    slots hold each unique row id and its accumulated gradient; the
    rest point past the table, so a scatter with ``mode='drop'``
    ignores them — the dense [height, D] gradient never exists.  (The
    matching gather ``p[slot_rows]`` clamps those slots to the last
    row; their computed updates are dropped by the same scatter.)"""
    order = jnp.argsort(rows)
    r = rows[order]
    v = values[order]
    n = r.shape[0]
    if n == 0:
        return r, v
    first = jnp.concatenate(
        [jnp.ones((1, ), jnp.bool_), r[1:] != r[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1  # slot of each id's run
    merged = jnp.zeros_like(v).at[seg].add(v)
    slot_rows = jnp.full((n, ), height, r.dtype).at[seg].set(r)
    return slot_rows, merged


def _scatter_rows(dense, rows, new_rows):
    """One scatter-update of the touched rows; out-of-range (merged
    duplicate) slots drop instead of clamping onto a real row."""
    return dense.at[rows].set(new_rows.astype(dense.dtype), mode='drop')


def _rows_sgd(ctx, op, g):
    """SelectedRows SGD (sgd_op.h): duplicates accumulate through the
    scatter-add itself — exactly the dense path's grad merge, so sparse
    and dense SGD agree to float addition order."""
    p = ctx.get(op, 'Param')
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    ctx.set(op, 'ParamOut', sparse_sgd_update(p, g, lr))


def _rows_momentum(ctx, op, g):
    """Lazy row-subset momentum: gather the touched rows of param +
    velocity, run the dense momentum math on the [N, D] subset against
    the MERGED per-row gradient, scatter both back.  Untouched rows'
    velocity does not decay — the reference's SelectedRows momentum
    semantics (momentum_op.h sparse branch)."""
    p = ctx.get(op, 'Param')
    vel = ctx.get(op, 'Velocity')
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    mu = op.attrs['mu']
    rows, grad = merge_rows(g.rows, g.values, g.height)
    v_new = mu * vel[rows] + grad
    if op.attrs.get('use_nesterov', False):
        p_new = p[rows] - (grad + mu * v_new) * lr
    else:
        p_new = p[rows] - lr * v_new
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p_new))
    ctx.set(op, 'VelocityOut', _scatter_rows(vel, rows, v_new))


def _rows_adam(ctx, op, g):
    """Lazy row-subset adam (adam_op.h SparseAdamFunctor): moments
    update — and decay — ONLY at rows present in the gradient; the
    dense [V, D] grad is never formed, and the per-step work is
    O(rows x D), not O(V x D)."""
    p = ctx.get(op, 'Param')
    m1 = ctx.get(op, 'Moment1')
    m2 = ctx.get(op, 'Moment2')
    b1p = jnp.reshape(ctx.get(op, 'Beta1Pow'), ())
    b2p = jnp.reshape(ctx.get(op, 'Beta2Pow'), ())
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    b1 = op.attrs.get('beta1', 0.9)
    b2 = op.attrs.get('beta2', 0.999)
    eps = op.attrs.get('epsilon', 1e-8)
    rows, grad = merge_rows(g.rows, g.values, g.height)
    m1_new = b1 * m1[rows] + (1 - b1) * grad
    m2_new = b2 * m2[rows] + (1 - b2) * jnp.square(grad)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p[rows] - lr_t * m1_new / (jnp.sqrt(m2_new) + eps)
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p_new))
    ctx.set(op, 'Moment1Out', _scatter_rows(m1, rows, m1_new))
    ctx.set(op, 'Moment2Out', _scatter_rows(m2, rows, m2_new))


def _rows_adagrad(ctx, op, g):
    """Lazy row-subset adagrad (adagrad_op.cc SelectedRows branch):
    gather the touched rows of param + accumulator, run the dense
    adagrad math on the [N, D] subset against the MERGED gradient,
    scatter both back.  Untouched rows are exactly the dense lane's
    (their grad is zero, so moment += 0 and the param is untouched) —
    adagrad's sparse kernel is dense-equivalent, unlike momentum/adam
    whose untouched moments would decay densely."""
    p = ctx.get(op, 'Param')
    mom = ctx.get(op, 'Moment')
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    eps = op.attrs.get('epsilon', 1e-6)
    rows, grad = merge_rows(g.rows, g.values, g.height)
    m_new = mom[rows] + jnp.square(grad)
    p_new = p[rows] - lr * grad / (jnp.sqrt(m_new) + eps)
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p_new))
    ctx.set(op, 'MomentOut', _scatter_rows(mom, rows, m_new))


def _rows_rmsprop(ctx, op, g):
    """Lazy row-subset rmsprop (ISSUE 14 satellite; rmsprop_op.cc
    SelectedRows branch): gather the touched rows of param + mean-
    square + momentum accumulators, run the dense rmsprop math on the
    [N, D] subset against the MERGED gradient, scatter all three back.
    Untouched rows' mean-square does NOT decay (the same lazy
    semantics as momentum/adam — the reference's sparse functors only
    visit gradient rows); with fresh (zero) state a single step is
    dense-equivalent everywhere, which is what the duplicate-id parity
    pins."""
    p = ctx.get(op, 'Param')
    ms = ctx.get(op, 'MeanSquare')
    mom = ctx.get(op, 'Moment')
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    eps = op.attrs.get('epsilon', 1e-10)
    decay = op.attrs.get('decay', 0.9)
    momentum = op.attrs.get('momentum', 0.0)
    rows, grad = merge_rows(g.rows, g.values, g.height)
    ms_new = decay * ms[rows] + (1 - decay) * jnp.square(grad)
    mom_new = momentum * mom[rows] + lr * grad / jnp.sqrt(ms_new + eps)
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p[rows] - mom_new))
    ctx.set(op, 'MomentOut', _scatter_rows(mom, rows, mom_new))
    ctx.set(op, 'MeanSquareOut', _scatter_rows(ms, rows, ms_new))


def _rows_ftrl(ctx, op, g):
    """Lazy row-subset ftrl (ISSUE 17 satellite; ftrl_op.cc): gather
    the touched rows of param + squared/linear accumulators, run the
    dense ftrl math on the [N, D] subset against the MERGED gradient,
    scatter all three back.  FTRL re-derives the param from
    accumulator state at every visit — a dense step with zero grad
    still rewrites a row toward the l1-shrunk solution of its
    accumulators — so untouched rows keeping param AND accumulators is
    the meaningful lazy semantics here (and exactly what lazy_apply's
    masked fallback computed, O(V x D); this kernel is O(rows x D))."""
    p = ctx.get(op, 'Param')
    sq = ctx.get(op, 'SquaredAccumulator')
    lin = ctx.get(op, 'LinearAccumulator')
    lr = jnp.reshape(ctx.get(op, 'LearningRate'), ())
    l1 = op.attrs.get('l1', 0.0)
    l2 = op.attrs.get('l2', 0.0)
    lr_power = op.attrs.get('lr_power', -0.5)
    rows, grad = merge_rows(g.rows, g.values, g.height)
    sq_old = sq[rows]
    sq_new = sq_old + jnp.square(grad)
    pow_new = jnp.power(sq_new, -lr_power)
    pow_old = jnp.power(sq_old, -lr_power)
    lin_new = lin[rows] + grad - (pow_new - pow_old) / lr * p[rows]
    x = l1 * jnp.sign(lin_new) - lin_new
    y = pow_new / lr + 2 * l2
    p_new = jnp.where(jnp.abs(lin_new) > l1, x / y,
                      jnp.zeros_like(lin_new))
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p_new))
    ctx.set(op, 'SquaredAccumOut', _scatter_rows(sq, rows, sq_new))
    ctx.set(op, 'LinearAccumOut', _scatter_rows(lin, rows, lin_new))


def _rows_adadelta(ctx, op, g):
    """Lazy row-subset adadelta (ISSUE 19 satellite; adadelta_op.h):
    gather the touched rows of param + avg-squared-grad/-update
    accumulators, run the dense adadelta math on the [N, D] subset
    against the MERGED gradient, scatter all three back.  Untouched
    rows' running averages do NOT decay (the same lazy semantics as
    rmsprop); with fresh (zero) state a zero-grad dense step is a
    no-op (update = -sqrt(eps/eps)*0), so a single step is dense-
    equivalent everywhere — what the duplicate-id parity pins.  Note
    adadelta takes no LearningRate input (optimizer_ops.py mirrors
    this)."""
    p = ctx.get(op, 'Param')
    asg = ctx.get(op, 'AvgSquaredGrad')
    asu = ctx.get(op, 'AvgSquaredUpdate')
    rho = op.attrs.get('rho', 0.95)
    eps = op.attrs.get('epsilon', 1e-6)
    rows, grad = merge_rows(g.rows, g.values, g.height)
    asg_new = rho * asg[rows] + (1 - rho) * jnp.square(grad)
    update = -jnp.sqrt((asu[rows] + eps) / (asg_new + eps)) * grad
    asu_new = rho * asu[rows] + (1 - rho) * jnp.square(update)
    ctx.set(op, 'ParamOut', _scatter_rows(p, rows, p[rows] + update))
    ctx.set(op, 'AvgSquaredGradOut', _scatter_rows(asg, rows, asg_new))
    ctx.set(op, 'AvgSquaredUpdateOut', _scatter_rows(asu, rows, asu_new))


# The FAST sparse lane (ISSUE 11/12/14/17/19): gather/merge/scatter
# row-subset kernels for the optimizers the reference ships
# SelectedRows branches for.  Everything else falls back to
# lazy_apply's dense-materialize + mask emulation (semantically
# identical, O(V x D) per step).
_ROW_SUBSET_APPLY = {
    'sgd': _rows_sgd,
    'momentum': _rows_momentum,
    'adam': _rows_adam,
    'adagrad': _rows_adagrad,
    'rmsprop': _rows_rmsprop,
    'ftrl': _rows_ftrl,
    'adadelta': _rows_adadelta,
}


# ----------------------------------------------------------------------------
# Hot-row cache slab exchange (ISSUE 12): the device half of the
# two-tier embedding store.  A cached table's [C, D] HBM slab swaps
# rows with the host master between scan dispatches: one gather reads
# the dirty evicted rows out (handed to the writeback worker), one
# scatter stages the host-fetched miss rows in.  Both run over
# POWER-OF-TWO-padded slot vectors (pad_exchange) so the executable
# count stays bounded as the per-block miss count varies; padded slots
# carry the out-of-range sentinel ``C`` — the scatter drops them and
# the gather clamps harmlessly (the host slices to the real count).
# ----------------------------------------------------------------------------
def exchange_width(n):
    """Smallest power of two >= n (>= 1): the padded slot-vector width
    one exchange executable serves — bounded compiles over arbitrary
    per-block miss counts, like the serving engine's batch ladder."""
    n = max(int(n), 1)
    w = 1
    while w < n:
        w *= 2
    return w


def pad_exchange(slots, width, height):
    """Pad an int slot vector to ``width`` with the sentinel ``height``
    (one past the slab), as int32 — the no-op slots a drop-mode scatter
    ignores."""
    slots = np.asarray(slots, np.int32).reshape(-1)
    out = np.full((int(width), ), int(height), np.int32)
    out[:len(slots)] = slots
    return out


_slab_gather_jit = jax.jit(
    lambda s, i: jnp.take(s, jnp.clip(i, 0, s.shape[0] - 1), axis=0))
_slab_scatter_jit = jax.jit(
    lambda s, i, r: s.at[i].set(r.astype(s.dtype), mode='drop'))


def slab_gather_rows(slab, slots):
    """Gather [W] slot rows out of the [C, D] slab (clip mode: padded
    sentinel slots read the last row; the host discards them)."""
    return _slab_gather_jit(slab, slots)


def slab_scatter_rows(slab, slots, rows):
    """Scatter [W] fetched rows into the slab at ``slots``; sentinel
    (out-of-range) slots drop — the padded tail never lands."""
    return _slab_scatter_jit(slab, slots, rows)


def lazy_apply(ctx, op, dense_fn):
    """Run a dense optimizer lowering against the merged dense gradient,
    then keep untouched rows unchanged in every row-shaped output slot —
    the reference's lazy SelectedRows optimizer semantics
    (adam_op.h SparseAdamFunctor: update only rows present in the grad)."""
    g = ctx.get(op, 'Grad')
    if not isinstance(g, SparseRows):
        return dense_fn(ctx, op)
    grad_name = op.input('Grad')[0]
    # inputs an output may alias (ParamOut<-Param etc.) for masking
    in_by_slot = {s: [ctx.lookup(n) for n in op.input(s)]
                  for s in op.inputs if all(ctx.has(n) for n in op.input(s))}
    ctx.store(grad_name, g.to_dense())
    try:
        dense_fn(ctx, op)
    finally:
        ctx.store(grad_name, g)
    touched = g.touched_mask()
    for out_slot in op.outputs:
        in_slot = out_slot[:-3] if out_slot.endswith('Out') else None
        if in_slot is None or in_slot not in in_by_slot:
            continue
        olds = in_by_slot[in_slot]
        for n, old in zip(op.output(out_slot), olds):
            if not ctx.has(n):
                continue
            new = ctx.lookup(n)
            shape = jnp.shape(new)
            if not shape or shape[0] != g.height or shape != jnp.shape(old):
                continue  # scalar slots (Beta1Pow etc.) update densely
            mask = jnp.reshape(touched, (g.height, ) + (1, ) *
                               (len(shape) - 1))
            ctx.store(n, jnp.where(mask, new, old))


def sparsify_optimizer(op_type):
    """Re-register ``op_type``'s lowering wrapped with SparseRows
    handling: the row-subset fast path for sgd/momentum/adam (one
    gather + merge + scatter over the touched rows — the dense [V, D]
    gradient is never built inside the jit), lazy_apply's dense
    emulation for the rest."""
    from . import registry
    dense_fn = registry._LOWERINGS[op_type]
    row_fn = _ROW_SUBSET_APPLY.get(op_type)

    def wrapped(ctx, op):
        g = ctx.get(op, 'Grad')
        if isinstance(g, SparseRows) and row_fn is not None:
            row_fn(ctx, op, g)
            return
        lazy_apply(ctx, op, dense_fn)

    register_lowering(op_type)(wrapped)
