"""Host-side op implementations (run outside XLA, eager path).

The reference runs save/load/print as ordinary kernels inside the Executor's
interpreter loop (operators/save_op.cc, load_op.cc, print_op.cc).  Here they
register in the host-op registry: a block containing any of them executes
eagerly, op by op, with these impls receiving concrete arrays and the Scope.
"""

import os

import numpy as np

from .registry import register_host_op


@register_host_op('print')
def _print(ctx, op, scope):
    x = ctx.get(op, 'In')
    if x is None:
        x = ctx.get(op, 'X')
    message = op.attrs.get('message', '')
    first_n = op.attrs.get('first_n', -1)
    count = op.attrs.setdefault('__print_count__', 0)
    if first_n < 0 or count < first_n:
        arr = np.asarray(x)
        print('%s %s  shape=%s\n%s' % (message, op.input('In') or
                                       op.input('X'), arr.shape, arr))
        op.attrs['__print_count__'] = count + 1
    out_names = op.output('Out')
    if out_names and x is not None:
        ctx.store(out_names[0], x)


@register_host_op('save')
def _save(ctx, op, scope):
    """version-0 LoDTensor stream (reference operators/save_op.cc ->
    framework/lod_tensor.cc:251 SerializeToStream)."""
    from ..fluid import io as fluid_io
    x = ctx.get(op, 'X')
    path = op.attrs['file_path']
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    fluid_io._save_one(path, np.asarray(x))


@register_host_op('load')
def _load(ctx, op, scope):
    from ..fluid import io as fluid_io
    arr = fluid_io._load_one(op.attrs['file_path'])
    names = op.output('Out')
    if names:
        ctx.store(names[0], arr)
        scope.var(names[0]).set_value(arr)


@register_host_op('save_combine')
def _save_combine(ctx, op, scope):
    """Streams back-to-back in input order (reference save_combine_op.cc)."""
    from ..fluid import proto_serde
    xs = ctx.get_list(op, 'X')
    path = op.attrs['file_path']
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    with open(path, 'wb') as f:
        for x in xs:
            f.write(proto_serde.serialize_lod_tensor(np.asarray(x)))


@register_host_op('load_combine')
def _load_combine(ctx, op, scope):
    from ..fluid import proto_serde
    from ..fluid import io as fluid_io
    path = op.attrs['file_path']
    names = op.output('Out')
    with open(path, 'rb') as f:
        magic = f.read(2)
        f.seek(0)
        if magic == b'PK':  # legacy npz artifact
            with np.load(path, allow_pickle=False) as blob:
                for n in names:
                    ctx.store(n, blob[n])
                    scope.var(n).set_value(blob[n])
            return
        for n in names:
            arr, _lod = proto_serde.read_lod_tensor(f)
            var = op.block._find_var_recursive(n)
            if var is not None:
                # combined streams carry no names; order misassignment
                # must fail loudly, not silently swap weights
                fluid_io.check_tensor_matches_var(arr, var, path)
            ctx.store(n, arr)
            scope.var(n).set_value(arr)


# ---- chunk evaluation (reference operators/chunk_eval_op.cc — CPU-only
# kernel there too; chunk parsing is inherently sequential host work) ----
_CHUNK_SCHEMES = {
    # scheme -> (num_tag_types, begin_tag_ids, inside_tag_ids, single_ids)
    'iob': 2, 'ioe': 2, 'iobes': 4, 'plain': 1,
}


def _extract_chunks(seq, scheme, num_chunk_types):
    """Return set of (begin, end, chunk_type) segments from a tag sequence.
    Tag layout matches the reference: tag = chunk_type * num_tag_types +
    tag_type; the 'other' (outside) tag is any id >= num_chunk_types *
    num_tag_types."""
    ntt = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types * ntt
    chunks = []
    start, ctype = None, None

    def flush(end):
        if start is not None:
            chunks.append((start, end, ctype))

    for i, tag in enumerate(seq):
        tag = int(tag)
        if tag >= other or tag < 0:
            flush(i)
            start, ctype = None, None
            continue
        t_type, t_tag = tag // ntt, tag % ntt
        if scheme == 'plain':
            begins, ends = True, True
        elif scheme == 'iob':
            begins = (t_tag == 0) or (ctype != t_type)
            ends = False
        elif scheme == 'ioe':
            begins = (ctype != t_type)
            ends = (t_tag == 1)
        else:  # iobes: B=0 I=1 E=2 S=3
            begins = t_tag in (0, 3) or (ctype != t_type)
            ends = t_tag in (2, 3)
        if begins:
            flush(i)
            start, ctype = i, t_type
        if ends:
            flush(i + 1)
            start, ctype = None, None
    flush(len(seq))
    return set(chunks)


@register_host_op('chunk_eval')
def _chunk_eval(ctx, op, scope):
    from .registry import SEQLEN_SUFFIX
    inference = np.asarray(ctx.get(op, 'Inference'))
    label = np.asarray(ctx.get(op, 'Label'))
    if inference.ndim == 3:
        inference = inference[..., 0]
    if label.ndim == 3:
        label = label[..., 0]
    lengths = ctx.env.get(op.input('Inference')[0] + SEQLEN_SUFFIX)
    if lengths is None:
        lengths = ctx.env.get(op.input('Label')[0] + SEQLEN_SUFFIX)
    b, t = inference.shape
    lengths = (np.full((b, ), t, np.int64) if lengths is None
               else np.asarray(lengths))
    scheme = op.attrs['chunk_scheme'].lower()
    num_chunk_types = int(op.attrs['num_chunk_types'])
    excluded = set(op.attrs.get('excluded_chunk_types') or [])
    n_infer = n_label = n_correct = 0
    for i in range(b):
        l = int(lengths[i])
        inf_chunks = {c for c in _extract_chunks(
            inference[i, :l], scheme, num_chunk_types)
            if c[2] not in excluded}
        lab_chunks = {c for c in _extract_chunks(
            label[i, :l], scheme, num_chunk_types)
            if c[2] not in excluded}
        n_infer += len(inf_chunks)
        n_label += len(lab_chunks)
        n_correct += len(inf_chunks & lab_chunks)
    precision = n_correct / n_infer if n_infer else 0.0
    recall = n_correct / n_label if n_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if n_correct else 0.0)
    ctx.set(op, 'Precision', np.array([precision], np.float32))
    ctx.set(op, 'Recall', np.array([recall], np.float32))
    ctx.set(op, 'F1-Score', np.array([f1], np.float32))
    ctx.set(op, 'NumInferChunks', np.array([n_infer], np.int64))
    ctx.set(op, 'NumLabelChunks', np.array([n_label], np.int64))
    ctx.set(op, 'NumCorrectChunks', np.array([n_correct], np.int64))


# ---------------------------------------------------------------------------
# Distributed-sparse plumbing ops (reference: operators/split_ids_op.cc,
# merge_ids_op.cc, split_selected_rows_op.cc, lookup_sparse_table_op.cc).
# These drive the sharded-embedding path: ids are routed to table shards,
# rows fetched, and reassembled in original order.  They are control-plane
# host work in the reference too (CPU-only kernels).
# ---------------------------------------------------------------------------
@register_host_op('split_ids')
def _split_ids(ctx, op, scope):
    ids = np.asarray(ctx.get(op, 'Ids')).reshape(-1)
    outs = op.output('Out')
    n = len(outs)
    for k, name in enumerate(outs):
        shard = np.unique(ids[ids % n == k])
        ctx.store(name, shard.reshape(-1, 1).astype(ids.dtype))


@register_host_op('merge_ids')
def _merge_ids(ctx, op, scope):
    """Reassemble per-shard embedding rows into original id order."""
    ids = np.asarray(ctx.get(op, 'Ids')).reshape(-1)
    shard_ids = [np.asarray(ctx.lookup(n)).reshape(-1)
                 for n in op.input('Rows')]
    shard_vals = [np.asarray(ctx.lookup(n)) for n in op.input('X')]
    dim = shard_vals[0].shape[-1]
    lut = {}
    for sid, sval in zip(shard_ids, shard_vals):
        for j, i in enumerate(sid):
            lut[int(i)] = sval[j]
    out = np.stack([lut[int(i)] for i in ids]).reshape(len(ids), dim)
    ctx.set(op, 'Out', out)


@register_host_op('split_selected_rows')
def _split_selected_rows(ctx, op, scope):
    from ..fluid import core
    from .sparse import SparseRows
    x = ctx.get(op, 'X')
    if isinstance(x, SparseRows):
        rows = np.asarray(x.rows)
        vals = np.asarray(x.values)
        height = x.height
    else:
        rows = np.asarray(x.rows())
        vals = x.get_tensor().numpy()
        height = x.height()
    sections = list(op.attrs['height_sections'])
    offsets = np.cumsum([0] + sections)
    for k, name in enumerate(op.output('Out')):
        lo, hi = offsets[k], offsets[k + 1]
        sel = (rows >= lo) & (rows < hi)
        sr = core.SelectedRows(rows=(rows[sel] - lo).tolist(),
                               height=sections[k])
        sr.get_tensor().set(vals[sel])
        ctx.store(name, sr)


@register_host_op('lookup_sparse_table')
def _lookup_sparse_table(ctx, op, scope):
    """Auto-growing sparse table lookup: the table lives host-side as an
    id->row dict (the analog of the pserver's SelectedRows table); unseen
    ids are initialized uniform(-init_range, init_range)."""
    w_name = op.input('W')[0]
    var = scope.var(w_name)
    table = var.value()
    if not isinstance(table, dict):
        table = {}
        var.set_value(table)
    ids = np.asarray(ctx.get(op, 'Ids')).reshape(-1)
    dim = int(op.attrs['embedding_dim'])
    init_range = float(op.attrs.get('init_range', 0.05))
    seed = int(op.attrs.get('seed', 0))
    out = np.empty((len(ids), dim), np.float32)
    for j, i in enumerate(ids):
        i = int(i)
        if i not in table:
            if not op.attrs.get('auto_grown_table', True):
                raise KeyError('id %d not in sparse table %r' % (i, w_name))
            rng = np.random.RandomState((seed + i) % (2**31))
            table[i] = rng.uniform(-init_range, init_range,
                                   dim).astype(np.float32)
        out[j] = table[i]
    ctx.set(op, 'Out', out)


@register_host_op('sparse_table_apply_grad')
def _sparse_table_apply_grad(ctx, op, scope):
    """Apply a SelectedRows gradient to a host sparse table with SGD —
    the pserver-side optimize block for the distributed lookup table
    (listen_and_serv optimize sub-blocks, SURVEY §3.3)."""
    from ..fluid import core
    from .sparse import SparseRows
    w_name = op.input('W')[0]
    table = scope.var(w_name).value()
    assert isinstance(table, dict), 'run lookup_sparse_table first'
    g = ctx.get(op, 'Grad')
    lr = float(np.asarray(ctx.get(op, 'LearningRate')).reshape(()))
    if isinstance(g, SparseRows):
        rows, vals = np.asarray(g.rows), np.asarray(g.values)
    elif isinstance(g, core.SelectedRows):
        rows, vals = np.asarray(g.rows()), g.get_tensor().numpy()
    else:
        raise TypeError('sparse_table_apply_grad needs a SelectedRows grad')
    for j, i in enumerate(rows):
        table[int(i)] = table[int(i)] - lr * vals[j]
