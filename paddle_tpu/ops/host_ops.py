"""Host-side op implementations (run outside XLA, eager path).

The reference runs save/load/print as ordinary kernels inside the Executor's
interpreter loop (operators/save_op.cc, load_op.cc, print_op.cc).  Here they
register in the host-op registry: a block containing any of them executes
eagerly, op by op, with these impls receiving concrete arrays and the Scope.
"""

import os

import numpy as np

from .registry import register_host_op


@register_host_op('print')
def _print(ctx, op, scope):
    x = ctx.get(op, 'In')
    if x is None:
        x = ctx.get(op, 'X')
    message = op.attrs.get('message', '')
    first_n = op.attrs.get('first_n', -1)
    count = op.attrs.setdefault('__print_count__', 0)
    if first_n < 0 or count < first_n:
        arr = np.asarray(x)
        print('%s %s  shape=%s\n%s' % (message, op.input('In') or
                                       op.input('X'), arr.shape, arr))
        op.attrs['__print_count__'] = count + 1
    out_names = op.output('Out')
    if out_names and x is not None:
        ctx.store(out_names[0], x)


@register_host_op('save')
def _save(ctx, op, scope):
    x = ctx.get(op, 'X')
    path = op.attrs['file_path']
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    with open(path, 'wb') as f:
        np.lib.format.write_array(f, np.asarray(x))


@register_host_op('load')
def _load(ctx, op, scope):
    path = op.attrs['file_path']
    with open(path, 'rb') as f:
        arr = np.lib.format.read_array(f)
    names = op.output('Out')
    if names:
        ctx.store(names[0], arr)
        scope.var(names[0]).set_value(arr)


@register_host_op('save_combine')
def _save_combine(ctx, op, scope):
    xs = ctx.get_list(op, 'X')
    names = op.input('X')
    path = op.attrs['file_path']
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    with open(path, 'wb') as f:
        np.savez(f, **{n: np.asarray(x) for n, x in zip(names, xs)})


@register_host_op('load_combine')
def _load_combine(ctx, op, scope):
    path = op.attrs['file_path']
    names = op.output('Out')
    with np.load(path, allow_pickle=False) as blob:
        for n in names:
            ctx.store(n, blob[n])
            scope.var(n).set_value(blob[n])
