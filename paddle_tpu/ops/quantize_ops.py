"""Fake quantization ops (reference: paddle/fluid/operators/fake_quantize_op.cc,
fake_dequantize_op.cc) — simulate int8/intN inference during fp training.

Gradients use the straight-through estimator written as
``x + stop_gradient(q - x)`` so autodiff yields the identity pass-through the
reference implements with a dedicated grad kernel.
"""

import jax
import jax.numpy as jnp

from .registry import register_lowering


def _quant(x, scale, bit_length):
    bound = float((1 << (bit_length - 1)) - 1)
    s = jnp.maximum(scale, 1e-10)
    q = jnp.round(jnp.clip(x, -s, s) / s * bound)
    # straight-through: forward = q, backward = identity
    return x + jax.lax.stop_gradient(q - x)


@register_lowering('fake_quantize_abs_max')
def _fake_quantize_abs_max(ctx, op):
    x = ctx.get(op, 'X')
    bit_length = int(op.attrs.get('bit_length', 8))
    scale = jnp.max(jnp.abs(x))
    ctx.set(op, 'Out', _quant(x, scale, bit_length))
    ctx.set(op, 'OutScale', jnp.reshape(scale, (1, )))


@register_lowering('fake_quantize_range_abs_max')
def _fake_quantize_range_abs_max(ctx, op):
    """Training keeps a running max-abs scale over a window (reference
    FakeQuantizeRangeAbsMaxOp); inference (is_test) freezes InScale."""
    x = ctx.get(op, 'X')
    in_scale = ctx.get(op, 'InScale')
    bit_length = int(op.attrs.get('bit_length', 8))
    is_test = bool(op.attrs.get('is_test', False)) or ctx.is_test
    cur = jnp.max(jnp.abs(x))
    if is_test and in_scale is not None:
        scale = jnp.reshape(in_scale, ())
    elif in_scale is not None:
        scale = jnp.maximum(cur, jnp.reshape(in_scale, ()))
    else:
        scale = cur
    ctx.set(op, 'Out', _quant(x, scale, bit_length))
    ctx.set(op, 'OutScale', jnp.reshape(scale, (1, )))


@register_lowering('fake_dequantize_max_abs')
def _fake_dequantize_max_abs(ctx, op):
    x = ctx.get(op, 'X')
    scale = jnp.reshape(ctx.get(op, 'Scale'), ())
    max_range = float(op.attrs['max_range'])
    ctx.set(op, 'Out', x.astype(jnp.float32) * scale / max_range)
