"""Fused scaled-dot-product attention op with context-parallel lowering.

The reference composes attention from primitive ops (matmul + softmax +
dropout, python/paddle/fluid/nets.py scaled_dot_product_attention) and has
no sequence parallelism (SURVEY §5.7).  TPU-natively attention is the hot
op of every transformer, so it gets ONE op whose lowering picks the best
implementation for where it runs:

- SPMD executor with an 'sp' (sequence/context parallel) mesh axis:
  **ring attention** (K/V blocks rotate on ICI neighbor links) or
  **Ulysses** all-to-all head resharding, per the ``impl`` attr;
- single device on TPU: Pallas flash-attention kernel (VMEM-blocked online
  softmax — never materialises the [L, L] score matrix in HBM);
- otherwise: dense XLA attention.

Layout: Q, K, V are [batch, seq, heads, head_dim].  Variable-length
batches feed through the LoD sideband (``@SEQLEN``) and mask K/V columns
past each row's length, matching LoD semantics on static shapes.
"""

from . import registry
from .registry import register_lowering


def _pick_impl(ctx, op):
    impl = op.attrs.get('impl', 'auto')
    mesh = ctx.mesh
    sp = op.attrs.get('sp_axis', 'sp')
    has_sp = (mesh is not None and sp in getattr(mesh, 'axis_names', ())
              and mesh.shape[sp] > 1)
    if impl == 'auto':
        if has_sp:
            return 'ring'
        try:
            on_tpu = (ctx.place is not None and
                      ctx.place.jax_device().platform != 'cpu')
        except Exception:
            on_tpu = False
        if on_tpu:
            return 'pallas'
        return 'dense'
    if impl in ('ring', 'ulysses') and not has_sp:
        import warnings
        warnings.warn(
            'flash_attention: impl=%r requested but the executor mesh has '
            'no %r axis (mesh=%s) — falling back to dense XLA attention, '
            'which materialises the full [L, L] score matrix' %
            (impl, sp, None if mesh is None else dict(mesh.shape)))
        return 'dense'
    return impl


@register_lowering('flash_attention')
def flash_attention_lowering(ctx, op):
    from ..parallel import context_parallel as cp
    from .registry import amp_cast_in
    q = ctx.get(op, 'Q')
    k = ctx.get(op, 'K')
    v = ctx.get(op, 'V')
    # under AMP the projections arrive fp32 (matmul accumulation dtype);
    # cast HERE so the layout transposes into the kernel move half the
    # bytes, the kernel's matmuls run at bf16 MXU rate, and the output
    # stays bf16 in HBM (amp_cast_out policy)
    q, k, v = amp_cast_in(q, k, v)
    causal = bool(op.attrs.get('causal', False))
    scale = op.attrs.get('scale', None)
    if scale is not None and scale <= 0:
        scale = None
    # LoD sideband: valid lengths of the K/V sequences.  Only K's own
    # sideband applies — Q's lengths describe the query sequence and must
    # NOT mask encoder memory in cross-attention
    lens = None
    names = op.input('K')
    if names and ctx.has(names[0] + registry.SEQLEN_SUFFIX):
        lens = ctx.lookup(names[0] + registry.SEQLEN_SUFFIX)
    impl = _pick_impl(ctx, op)
    if impl in ('ring', 'ulysses'):
        sp = op.attrs.get('sp_axis', 'sp')
        mesh = ctx.mesh
        batch_axis = ctx.batch_axis
        if batch_axis not in mesh.axis_names or mesh.shape[batch_axis] <= 1:
            batch_axis = None
        fn = cp.ring_attention if impl == 'ring' else cp.ulysses_attention
        out = fn(q, k, v, mesh, axis=sp, causal=causal, scale=scale,
                 seq_lengths=lens, batch_axis=batch_axis)
    elif impl == 'pallas':
        try:
            from .pallas import flash_attention as pl_fa
        except ImportError:
            pl_fa = None
        if pl_fa is not None and v.shape[-1] != q.shape[-1]:
            # the Pallas kernel tiles one head_dim for Q/K/V; mixed
            # Dv != Dq cross-attention runs on the dense path instead
            pl_fa = None
        if pl_fa is None:
            import warnings
            warnings.warn('flash_attention: Pallas kernel unavailable or '
                          'shapes unsupported, falling back to dense XLA '
                          'attention (materialises the [L, L] score matrix)')
            out = cp.dense_attention(q, k, v, causal=causal, scale=scale,
                                     seq_lengths=lens)
        else:
            out = pl_fa.flash_attention(q, k, v, causal=causal, scale=scale,
                                        seq_lengths=lens)
    else:
        out = cp.dense_attention(q, k, v, causal=causal, scale=scale,
                                 seq_lengths=lens)
    ctx.set(op, 'Out', out.astype(q.dtype))
