"""Fused scaled-dot-product attention op with context-parallel lowering.

The reference composes attention from primitive ops (matmul + softmax +
dropout, python/paddle/fluid/nets.py scaled_dot_product_attention) and has
no sequence parallelism (SURVEY §5.7).  TPU-natively attention is the hot
op of every transformer, so it gets ONE op whose lowering picks the best
implementation for where it runs:

- SPMD executor with an 'sp' (sequence/context parallel) mesh axis:
  **ring attention** (K/V blocks rotate on ICI neighbor links) or
  **Ulysses** all-to-all head resharding, per the ``impl`` attr;
- single device on TPU: dense XLA attention while the [B,H,Lq,Lk] score
  tensor fits the budget (measured faster than the v1 Pallas kernel at
  every length that fits), switching to the Pallas flash kernel
  (VMEM-blocked online softmax, O(L) memory — never materialises the
  [L, L] scores in HBM) beyond it;
- otherwise: dense XLA attention.

Layout: Q, K, V are [batch, seq, heads, head_dim].  Variable-length
batches feed through the LoD sideband (``@SEQLEN``) and mask K/V columns
past each row's length, matching LoD semantics on static shapes.
"""

from . import registry
from .registry import register_lowering


# 'auto' switches dense -> pallas when the materialised [B,H,Lq,Lk] f32
# score tensor would exceed this budget.  Measured on v5e (fwd+bwd, AMP):
# XLA's fused dense attention beats the v1 Pallas kernel on raw speed at
# every length that FITS (256..4096), so the kernel's job is the O(L)
# memory profile that keeps long contexts compiling at all.
_DENSE_SCORE_BYTES_BUDGET = 2 << 30


def _pick_impl(ctx, op, q=None, k=None):
    impl = op.attrs.get('impl', 'auto')
    mesh = ctx.mesh
    sp = op.attrs.get('sp_axis', 'sp')
    has_sp = (mesh is not None and sp in getattr(mesh, 'axis_names', ())
              and mesh.shape[sp] > 1)
    if impl == 'auto':
        if has_sp:
            return 'ring'
        try:
            on_tpu = (ctx.place is not None and
                      ctx.place.jax_device().platform != 'cpu')
        except Exception:
            on_tpu = False
        if on_tpu and q is not None and k is not None:
            b, lq = q.shape[0], q.shape[1]
            lk, h = k.shape[1], (q.shape[2] if q.ndim == 4 else 1)
            # dense-path scores carry q's dtype (bf16 under AMP, f32
            # otherwise) — budget by the ACTUAL element size, not 4
            # (ADVICE r2 #4: assuming f32 halved the usable budget and
            # flipped 'auto' to the slower flash kernel too early)
            itemsize = getattr(getattr(q, 'dtype', None), 'itemsize', 4)
            if b * h * lq * lk * itemsize > _DENSE_SCORE_BYTES_BUDGET:
                return 'pallas'
        return 'dense'
    if impl in ('ring', 'ulysses') and not has_sp:
        import warnings
        warnings.warn(
            'flash_attention: impl=%r requested but the executor mesh has '
            'no %r axis (mesh=%s) — falling back to dense XLA attention, '
            'which materialises the full [L, L] score matrix' %
            (impl, sp, None if mesh is None else dict(mesh.shape)))
        return 'dense'
    return impl


@register_lowering('flash_attention')
def flash_attention_lowering(ctx, op):
    from ..parallel import context_parallel as cp
    from .registry import amp_cast_in
    q = ctx.get(op, 'Q')
    k = ctx.get(op, 'K')
    v = ctx.get(op, 'V')
    # under AMP the projections normally arrive bf16 already (amp_matmul
    # lands bf16); this cast is the safety net for fp32 producers (e.g.
    # a biased path before harmonization, or AMP-off callers of a mixed
    # graph) so the kernel never runs a widened layout
    q, k, v = amp_cast_in(q, k, v)
    causal = bool(op.attrs.get('causal', False))
    scale = op.attrs.get('scale', None)
    if scale is not None and scale <= 0:
        scale = None
    # LoD sideband: valid lengths of the K/V sequences.  Only K's own
    # sideband applies — Q's lengths describe the query sequence and must
    # NOT mask encoder memory in cross-attention
    lens = None
    names = op.input('K')
    if names and ctx.has(names[0] + registry.SEQLEN_SUFFIX):
        lens = ctx.lookup(names[0] + registry.SEQLEN_SUFFIX)
    impl = _pick_impl(ctx, op, q=q, k=k)
    if impl in ('ring', 'ulysses'):
        sp = op.attrs.get('sp_axis', 'sp')
        mesh = ctx.mesh
        batch_axis = ctx.batch_axis
        if batch_axis not in mesh.axis_names or mesh.shape[batch_axis] <= 1:
            batch_axis = None
        fn = cp.ring_attention if impl == 'ring' else cp.ulysses_attention
        out = fn(q, k, v, mesh, axis=sp, causal=causal, scale=scale,
                 seq_lengths=lens, batch_axis=batch_axis)
    elif impl == 'pallas':
        try:
            from .pallas import flash_attention as pl_fa
        except ImportError:
            pl_fa = None
        if pl_fa is not None and v.shape[-1] != q.shape[-1]:
            # the Pallas kernel tiles one head_dim for Q/K/V; mixed
            # Dv != Dq cross-attention runs on the dense path instead
            pl_fa = None
        if pl_fa is None:
            import warnings
            warnings.warn('flash_attention: Pallas kernel unavailable or '
                          'shapes unsupported, falling back to dense XLA '
                          'attention (materialises the [L, L] score matrix)')
            out = cp.dense_attention(q, k, v, causal=causal, scale=scale,
                                     seq_lengths=lens)
        else:
            out = pl_fa.flash_attention(q, k, v, causal=causal, scale=scale,
                                        seq_lengths=lens)
    else:
        out = cp.dense_attention(q, k, v, causal=causal, scale=scale,
                                 seq_lengths=lens)
    ctx.set(op, 'Out', out.astype(q.dtype))
