"""Op lowering registry: OpDesc -> JAX/XLA.

The reference dispatches each op to a hand-written CPU/CUDA kernel at runtime
(paddle/fluid/framework/operator.cc:657-714, registered via
REGISTER_OP_CPU_KERNEL / REGISTER_OP_CUDA_KERNEL, op_registry.h:214-217).
Here every op type instead registers a *lowering*: a function that, while the
enclosing block is being traced for XLA compilation, reads its input values
from the tracing environment and writes its outputs.  The whole block becomes
ONE fused XLA computation (the TPU-first swap for the per-op interpreter hot
loop, executor.cc:332-339).

Gradients: the reference synthesizes grad OpDescs with per-op C++
GradOpDescMakers (framework/grad_op_desc_maker.h:34).  We synthesize the same
grad-op graph structure (backward.py) but lower ``<op>_grad`` generically via
``jax.vjp`` of the forward lowering — XLA's CSE merges the recomputed forward
with the original, so this costs nothing inside one compiled block.  Ops whose
forward draws randomness (dropout) register explicit grad lowerings.
"""

import numpy as np

_LOWERINGS = {}
_GRAD_LOWERINGS = {}
# host ops run outside XLA on concrete values (save/load/print/readers);
# impl signature: fn(ctx, op, scope) with ctx.env holding concrete arrays
_HOST_OPS = {}


def register_host_op(op_type):
    def deco(fn):
        _HOST_OPS[op_type] = fn
        return fn

    return deco


def get_host_op(op_type):
    return _HOST_OPS.get(op_type)


def is_host_op_type(op_type):
    return op_type in _HOST_OPS


def register_lowering(op_type):
    def deco(fn):
        _LOWERINGS[op_type] = fn
        return fn

    return deco


def register_grad_lowering(op_type):
    """Register an explicit lowering for ``<op_type>_grad``."""

    def deco(fn):
        _GRAD_LOWERINGS[op_type] = fn
        return fn

    return deco


def has_lowering(op_type):
    return op_type in _LOWERINGS or (op_type.endswith('_grad') and
                                     op_type[:-5] in _LOWERINGS)


def get_lowering(op_type):
    fn = _LOWERINGS.get(op_type)
    if fn is not None:
        return fn
    if op_type.endswith('_grad'):
        fwd = op_type[:-5]
        if fwd in _GRAD_LOWERINGS:
            return _GRAD_LOWERINGS[fwd]
        if fwd in _LOWERINGS:
            return _make_generic_grad(fwd)
    raise NotImplementedError('no XLA lowering registered for op %r' %
                              op_type)


class LoweringContext(object):
    """Tracing environment handed to every lowering.

    ``env`` maps var name -> traced jax value.  ``block`` gives access to var
    descs (shape/dtype metadata).  RNG keys are derived from a carried key so
    compiled functions stay pure.
    """

    def __init__(self, block, env, rng_key=None, is_test=False, place=None,
                 mesh=None, batch_axis=None, cond_uninit=None,
                 conditional_scope=False):
        self.block = block
        self.env = env
        self._rng = rng_key
        self.is_test = is_test
        self.place = place
        # the SPMD executor's device mesh (None single-device) and the mesh
        # axis the batch dim is sharded over: lowerings with a sharded
        # implementation (ring attention over 'sp') consult these at trace
        # time
        self.mesh = mesh
        self.batch_axis = batch_axis
        # trace-time constant folding for scalar index chains: under
        # whole-block jit every value is a tracer, but tensor-array ops
        # need concrete indices to keep list state (the reference keeps
        # them concrete by interpreting op-by-op).  fill_constant /
        # increment / assign record known scalar values here; run_op
        # invalidates entries any other op overwrites.
        self.concrete = {}
        # per-array log of resolved indices, appended at forward-lowering
        # time and popped (reverse order) by the array ops' backwards —
        # in-place index vars make self.concrete stale by backward time
        self.array_log = {}
        # names whose ONLY assignment so far is inside a single
        # conditional_block: the reference leaves such a var
        # uninitialized when the cond is false and errors on read
        # (conditional_block_op.cc); the blended lowering zero-fills
        # instead, which is unobservable once a second branch (or any
        # unconditional op) writes the name — until then, a read is a
        # may-read-before-write program error and is rejected at
        # lowering time.  The set is SHARED down nested contexts (pass
        # cond_uninit); conditional_scope=True marks a context whose ops
        # execute conditionally (branch/loop bodies) — there, reads are
        # not checked (a same-cond guarded read is legal in the
        # reference) and writes do not clear the flag (the write itself
        # may never execute).
        self.cond_uninit = cond_uninit if cond_uninit is not None else set()
        self.conditional_scope = conditional_scope
        # ragged-batch provenance: env names whose value is derived from
        # batch-led feeds AND still carries the batch on dim 0.  Seeded
        # by the executor from the feed dict when a @SAMPLE_MASK rides
        # along; propagated per op by run_op.  Batch-reduction lowerings
        # apply the mask ONLY to members — a weight-derived tensor whose
        # dim 0 merely coincides with the padded batch size never masks.
        self.batch_led = set()
        # ...and names with batch ANCESTRY regardless of current dim 0
        # (a reshape [B,T,..]->[B*T,..] drops out of batch_led but stays
        # tainted) — lets the masked lowerings WARN when a flattened
        # batch reaches a reduction the mask can no longer protect
        self.batch_tainted = set()

    # ---- value access ----
    def get(self, op, slot, default=None):
        names = op.input(slot)
        if not names:
            return default
        return self.env[names[0]]

    def get_list(self, op, slot):
        return [self.env[n] for n in op.input(slot)]

    def set(self, op, slot, value):
        names = op.output(slot)
        if names:
            self.env[names[0]] = value

    def set_list(self, op, slot, values):
        names = op.output(slot)
        for n, v in zip(names, values):
            self.env[n] = v

    def lookup(self, name):
        return self.env[name]

    def has(self, name):
        return name in self.env

    def store(self, name, value):
        self.env[name] = value

    def var_desc(self, name):
        return self.block._find_var_recursive(name)

    def next_rng(self):
        import jax
        if self._rng is None:
            raise RuntimeError('op requested randomness but no RNG key was '
                               'threaded into this block')
        self._rng, key = jax.random.split(self._rng)
        return key

    def sub_context(self, block=None, env=None):
        sub = LoweringContext(
            block if block is not None else self.block,
            env if env is not None else self.env,
            rng_key=None,
            is_test=self.is_test,
            place=self.place,
            mesh=self.mesh,
            batch_axis=self.batch_axis,
            cond_uninit=self.cond_uninit,
            conditional_scope=self.conditional_scope)
        # trace-time constants survive into re-traces (grad synthesis,
        # sub-blocks): lowerings that need concrete values (lod_reset
        # offsets, tensor-array indices) behave identically there
        sub.concrete = dict(self.concrete)
        # grad replays and sub-blocks reuse the parent's names: a
        # forward value's batch-led provenance must survive into them
        sub.batch_led = set(self.batch_led)
        sub.batch_tainted = set(self.batch_tainted)
        return sub


# op types that maintain ctx.concrete themselves (their lowerings set or
# propagate entries); every other op's outputs invalidate stale entries
_CONCRETE_PRESERVING = {'fill_constant', 'increment', 'assign',
                        'assign_value'}

# reserved feed name for the ragged-batch sample mask (float [B]; 1.0 =
# real row, 0.0 = padding the data-parallel executor appended to make the
# lot divisible by the mesh's dp extent).  Batch-mean lowerings consult it
# so loss/grad means weight by REAL sample count — the DataBalance parity
# answer (details/data_balance_op_handle.cc) under static SPMD shapes.
SAMPLE_MASK_NAME = '@SAMPLE_MASK'

SEQLEN_SUFFIX = '@SEQLEN'
# nested (2-level LoD) tensors additionally carry the OUTER level — the
# number of sub-sequences each top-level sequence owns — as `<name>@ROWS`
# int32[B]; the padded data rows are then grouped per sequence by
# cumulative offsets (SURVEY §5.7 nested case)
ROWS_SUFFIX = '@ROWS'
# ops that consume sequence structure and emit dense outputs — sequence
# lengths must NOT propagate through them
_SEQ_CONSUMERS = {
    'sequence_pool', 'sequence_last_step', 'sequence_first_step',
}


def check_cond_uninit(ctx, names, what):
    """Reject a read of a var whose only assignment sits inside a single
    conditional_block — when the cond is false the var is uninitialized
    and the reference's conditional_block_op.cc enforce errors on the
    read.  One helper for every call site (jit op inputs, host-op
    inputs, fetches) so the rule cannot drift between paths."""
    if not ctx.cond_uninit:
        return
    for n in names:
        if n in ctx.cond_uninit:
            raise RuntimeError(
                '%s reads var %r, whose only assignment is inside a '
                'single conditional_block: when the cond is false the '
                'var is uninitialized (reference conditional_block_op.cc '
                'errors on such a read) — write it unconditionally or '
                'in both branches first' % (what, n))


def run_op(ctx, op):
    """Lower one op into the trace, propagating sequence-length metadata
    (the static-shape stand-in for LoD, SURVEY §5.7)."""
    guarded = ctx.conditional_scope or op.type == 'conditional_block'
    if not guarded:
        check_cond_uninit(
            ctx, (n for names in op.inputs.values() for n in names),
            'op %r' % op.type)
    if op.type not in _CONCRETE_PRESERVING:
        for names in op.outputs.values():
            for n in names:
                ctx.concrete.pop(n, None)
    get_lowering(op.type)(ctx, op)
    if ctx.cond_uninit and not guarded:
        # an unconditional write covers the name; writes inside
        # branch/loop bodies (conditional_scope) may never execute and
        # must NOT clear it
        for names in op.outputs.values():
            for n in names:
                ctx.cond_uninit.discard(n)
    mask = ctx.env.get(SAMPLE_MASK_NAME)
    if mask is not None and not op.type.endswith('_grad'):
        # ragged-batch provenance: an output is batch-led iff any input
        # was AND it still carries the batch on dim 0 (a transposed-away
        # batch conservatively drops out — the masked lowerings then
        # leave that value alone); batch ANCESTRY (tainted) survives any
        # shape change so the lowerings can warn on flattened batches
        led = any(n in ctx.batch_led
                  for names in op.inputs.values() for n in names)
        tainted = led or any(n in ctx.batch_tainted
                             for names in op.inputs.values() for n in names)
        b = mask.shape[0]
        for names in op.outputs.values():
            for n in names:
                v = ctx.env.get(n)
                if led and getattr(v, 'ndim', 0) >= 1 and v.shape[0] == b:
                    ctx.batch_led.add(n)
                else:
                    ctx.batch_led.discard(n)
                if tainted:
                    ctx.batch_tainted.add(n)
                else:
                    ctx.batch_tainted.discard(n)
    if op.type in _SEQ_CONSUMERS or op.type.endswith('_grad'):
        return
    for suffix in (SEQLEN_SUFFIX, ROWS_SUFFIX):
        meta = None
        for names in op.inputs.values():
            for n in names:
                if (n + suffix) in ctx.env:
                    meta = ctx.env[n + suffix]
                    break
            if meta is not None:
                break
        if meta is not None:
            for names in op.outputs.values():
                for n in names:
                    ctx.env.setdefault(n + suffix, meta)


GRAD_SUFFIX = '@GRAD'
# attr keys on grad ops recording the forward op's slot structure
FWD_IN_SLOTS_ATTR = '__fwd_in_slots__'
FWD_OUT_SLOTS_ATTR = '__fwd_out_slots__'


def fwd_structure(grad_op):
    """Recover (fwd_inputs, fwd_outputs, fwd_attrs) slot->names maps from a
    grad OpDesc built by backward.append_backward."""
    in_slots = grad_op.attrs[FWD_IN_SLOTS_ATTR]
    out_slots = grad_op.attrs[FWD_OUT_SLOTS_ATTR]
    fwd_inputs = {s: grad_op.input(s) for s in in_slots}
    fwd_outputs = {s: grad_op.input(s) for s in out_slots}
    fwd_attrs = {
        k: v
        for k, v in grad_op.attrs.items()
        if k not in (FWD_IN_SLOTS_ATTR, FWD_OUT_SLOTS_ATTR)
    }
    return fwd_inputs, fwd_outputs, fwd_attrs


def _make_generic_grad(fwd_type):
    """Build a grad lowering from the forward lowering via jax.vjp.

    The grad OpDesc (built by backward.py) carries the forward op's inputs,
    outputs and attrs; declared grad outputs ``<slot>@GRAD`` name which inputs
    need gradients.  Missing output-grads are treated as zeros (the analog of
    fill_zeros_like insertion in the reference backward pass).
    """
    import jax
    import jax.numpy as jnp
    fwd_lower = _LOWERINGS[fwd_type]

    def grad_lowering(ctx, op):
        from ..fluid.framework import Operator
        fwd_inputs, fwd_outputs, fwd_attrs = fwd_structure(op)

        # differentiable primal args: those with a declared <slot>@GRAD output
        diff_specs = []  # (slot, idx, grad_out_name)
        for slot, in_names in fwd_inputs.items():
            gnames = op.output(slot + GRAD_SUFFIX)
            for i, gname in enumerate(gnames):
                if gname and i < len(in_names):
                    diff_specs.append((slot, i, gname))
        if not diff_specs:
            return

        fwd_input_vals = {
            slot: [ctx.lookup(n) for n in names]
            for slot, names in fwd_inputs.items()
        }
        # only outputs the forward pass actually produced (some lowerings
        # write optional outputs conditionally, e.g. sequence_pool MaxIndex)
        # and only float ones: integer/bool outputs carry no gradient and
        # jax.vjp rejects non-float0 cotangents for them (bounded While
        # emits its bool condition and int counters as outputs)
        def _inexact(v):
            if isinstance(v, (list, tuple)):
                return bool(v) and _inexact(v[0])
            return jnp.issubdtype(jnp.result_type(v), jnp.inexact)

        out_names = [(slot, n) for slot in fwd_outputs
                     for n in fwd_outputs[slot]
                     if ctx.has(n) and _inexact(ctx.lookup(n))]
        faux = Operator(
            ctx.block, fwd_type,
            inputs={s: list(n) for s, n in fwd_inputs.items()},
            outputs={s: list(n) for s, n in fwd_outputs.items()},
            attrs=fwd_attrs)
        # sequence-length side-band entries the lowering may consult
        seq_entries = {}
        for names in fwd_inputs.values():
            for n in names:
                for suffix in (SEQLEN_SUFFIX, ROWS_SUFFIX):
                    key = n + suffix
                    if ctx.has(key):
                        seq_entries[key] = ctx.lookup(key)
        # the ragged-batch sample mask is a global side-band: the vjp
        # replay of a batch-mean forward must see the same mask the
        # primal trace saw, or pad rows would re-enter the denominator
        if ctx.has(SAMPLE_MASK_NAME):
            seq_entries[SAMPLE_MASK_NAME] = ctx.lookup(SAMPLE_MASK_NAME)

        def primal(*diff_vals):
            env2 = dict(seq_entries)
            vals = {s: list(v) for s, v in fwd_input_vals.items()}
            for (slot, i, _), v in zip(diff_specs, diff_vals):
                vals[slot][i] = v
            for slot, names in fwd_inputs.items():
                for n, v in zip(names, vals[slot]):
                    env2[n] = v
            sub = ctx.sub_context(env=env2)
            fwd_lower(sub, faux)
            return tuple(env2[n] for _, n in out_names)

        diff_vals = [fwd_input_vals[s][i] for s, i, _ in diff_specs]
        primal_outs, vjp_fn = jax.vjp(primal, *diff_vals)

        def _match_ct(ct, ref):
            # cotangents may be pytrees (tensor-array lists); match leaf
            # dtypes to the primal structure
            if isinstance(ref, (list, tuple)):
                return [_match_ct(c, r) for c, r in zip(ct, ref)]
            ct = jnp.asarray(ct)
            return ct.astype(ref.dtype) if ct.dtype != ref.dtype else ct

        cotangents = []
        for k, (_, n) in enumerate(out_names):
            gname = n + GRAD_SUFFIX
            if ctx.has(gname):
                cotangents.append(_match_ct(ctx.lookup(gname),
                                            primal_outs[k]))
            else:
                cotangents.append(jax.tree_util.tree_map(
                    jnp.zeros_like, primal_outs[k]))
        grads = vjp_fn(tuple(cotangents))
        # when an op writes a var it also reads (loop-carried While state),
        # the input-grad name coincides with the output-cotangent name;
        # that pre-existing value is this op's own cotangent, not a sibling
        # contribution, so it must be overwritten rather than accumulated
        cotangent_names = {n + GRAD_SUFFIX for _, n in out_names}
        for (slot, i, gname), g in zip(diff_specs, grads):
            if ctx.has(gname) and gname not in cotangent_names:
                g = ctx.lookup(gname) + g  # rename pass didn't split it
            ctx.store(gname, g)

    return grad_lowering


# ---- mixed precision (bf16 compute / fp32 master weights) ----
# The reference era's float16 work is an inference-only transpiler
# (paddle/contrib/float16/float16_transpiler.py); on TPU the right shape is
# training-time bf16 matmul/conv inputs with fp32 accumulation on the MXU.
_AMP = {'enabled': False}


def set_amp(enabled):
    _AMP['enabled'] = bool(enabled)


def amp_enabled():
    return _AMP['enabled']


def amp_cast_in(*xs):
    """Cast f32 operands to bf16 for an MXU op when AMP is on; leave
    everything else untouched.  Pair with preferred_element_type=f32 so
    accumulation stays fp32."""
    import jax.numpy as jnp
    if not _AMP['enabled']:
        return xs
    return tuple(
        x.astype(jnp.bfloat16)
        if x is not None and hasattr(x, 'dtype') and x.dtype == jnp.float32
        else x for x in xs)


def amp_cast_out(out):
    """AMP output policy for convolutions: activations LAND in HBM as
    bf16.

    Under AMP every conv call site runs amp_cast_in first, so its bf16
    operands yield a bf16 result directly (the TPU MXU accumulates
    bf16 products in fp32 internally regardless of the output dtype) —
    the materialized [B,C,H,W] tensor is 2 bytes/element, and keeping
    it fp32 would double HBM read+write traffic for every activation,
    the dominant cost of a conv net on TPU.  This hook is the safety
    net for any call site whose result comes back fp32 (e.g. a future
    preferred_element_type).  bf16 activations flow through BN (which
    upcasts in-register for its statistics, ops/nn_ops.py
    _batch_norm), relu, pooling and residual adds; master weights and
    optimizer state stay fp32 throughout."""
    import jax.numpy as jnp
    if _AMP['enabled'] and hasattr(out, 'dtype') and \
            out.dtype == jnp.float32:
        return out.astype(jnp.bfloat16)
    return out


def amp_upcast_f32(x):
    """Precision-sensitive math (softmax/norm statistics, loss
    exp/log paths) computes f32 even when AMP lands activations bf16;
    the upcast fuses into the consuming reduction, so HBM still sees
    bf16.  The ONE home of the upcast policy — lowerings call this
    instead of hand-rolling dtype checks."""
    import jax.numpy as jnp
    if x is not None and hasattr(x, 'dtype') and x.dtype == jnp.bfloat16:
        return x.astype(jnp.float32)
    return x


def amp_harmonize(x, y):
    """Mixed bf16/f32 elementwise operands compute bf16 under AMP: the
    f32 side is a parameter (bias, scale) whose in-register cast fuses,
    and promoting instead would re-widen every biased fc activation back
    to f32 in HBM.  Without AMP, normal promotion applies untouched."""
    import jax.numpy as jnp
    if not _AMP['enabled']:
        return x, y
    dx = getattr(x, 'dtype', None)
    dy = getattr(y, 'dtype', None)
    if dx == jnp.bfloat16 and dy == jnp.float32:
        y = y.astype(jnp.bfloat16)
    elif dy == jnp.bfloat16 and dx == jnp.float32:
        x = x.astype(jnp.bfloat16)
    return x, y


def amp_matmul(x, y):
    """The one home of the AMP matmul policy: bf16 operands, bf16
    result.  The TPU MXU accumulates bf16 products in fp32 internally
    regardless of the requested output dtype, so a bf16 output is
    bit-identical to preferred_element_type=f32 followed by a bf16
    cast — but WITHOUT the f32 intermediate: asking for f32 made every
    cotangent in the backward pass f32, which re-widened all gradient
    matmuls and their HBM traffic (r5 transformer A/B: the pure-JAX
    bound emitting bf16 ran the same matmuls ~45% faster end to end)."""
    import jax.numpy as jnp
    x, y = amp_cast_in(x, y)
    return amp_cast_out(jnp.matmul(x, y))
