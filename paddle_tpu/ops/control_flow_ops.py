"""Control-flow op lowerings: sub-blocks -> lax.scan / lax.while_loop.

The reference's while_op re-enters the interpreter per iteration with
step-scopes (operators/while_op.cc:50-66) and recurrent_op manages its own
scope stack (recurrent_op.cc).  Here a sub-block lowers exactly once into a
functional body; carried state is explicit — the design SURVEY §7 calls out
as the core control-flow translation.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register_lowering, register_grad_lowering,
                       LoweringContext, run_op, fwd_structure,
                       GRAD_SUFFIX, SEQLEN_SUFFIX)


def _block_reads_writes(block):
    reads, writes = [], []
    seen_r, seen_w = set(), set()
    for op in block.ops:
        for n in op.input_arg_names:
            if n not in seen_r:
                seen_r.add(n)
                reads.append(n)
        for n in op.output_arg_names:
            if n not in seen_w:
                seen_w.add(n)
                writes.append(n)
    return reads, writes


def _run_block(ctx, block, env):
    # may-read-before-write tracking spans nested blocks (shared set);
    # the body executes conditionally, so its reads aren't checked and
    # its writes don't clear the flag (registry.LoweringContext)
    sub = LoweringContext(block, env, rng_key=None, is_test=ctx.is_test,
                          place=ctx.place, cond_uninit=ctx.cond_uninit,
                          conditional_scope=True)
    for op in block.ops:
        run_op(sub, op)
    return env


def _reject_host_ops(block, where):
    """Blended control flow (conditional_block / ifelse / switch_case)
    executes EVERY branch and selects results — sound only for pure
    blocks.  A host op (save/print/reader) in a branch would run its side
    effect unconditionally, so reject it with a clear error instead of
    silently mis-executing (VERDICT round-1 weak #7)."""
    from .registry import is_host_op_type
    for op in block.ops:
        if is_host_op_type(op.type):
            raise RuntimeError(
                '%s: branch contains host op %r; all branches of blended '
                'control flow execute, so side-effecting ops are invalid '
                'inside them — hoist it out of the branch' % (where, op.type))


@register_lowering('while')
def _while(ctx, op):
    """Reference while_op.cc RunImpl re-enters the interpreter per step
    with step-scopes; here the body lowers once.  Two modes:

    - default: ``lax.while_loop``; carry = condition + every parent var
      the body writes.  Cheap (early exit) but not reverse-differentiable.
    - ``max_trip_count`` attr set: a bounded ``lax.scan`` running the
      bound with a pass-through blend once the condition goes false.
      ``jax.vjp`` differentiates through it — the scan residual stack is
      the functional analog of while_grad's step-scope stack
      (while_op.cc:36, grad maker at the file bottom).  Carried tensor
      arrays are preallocated to len+bound so traced-index writes land.

    The 'Init' input slot (aligned with attr carry_names) carries
    pre-loop snapshots of the carried vars, so a recomputation of this op
    in the backward pass starts from initial, not final, values."""
    block = op.attrs['sub_block']
    cond_name = op.input('Condition')[0]
    reads, writes = _block_reads_writes(block)
    attr_carry = op.attrs.get('carry_names')
    init_names = op.input('Init') or []
    if attr_carry:
        carry_names = list(attr_carry)
        snapshot = dict(zip(attr_carry, init_names))
    else:
        carry_names = [cond_name] + [
            n for n in writes if ctx.has(n) and n != cond_name
        ]
        snapshot = {}
    closure = {
        n: ctx.lookup(n)
        for n in reads if ctx.has(n) and n not in carry_names
    }

    def init_val(n):
        s = snapshot.get(n)
        return ctx.lookup(s) if s is not None and ctx.has(s) \
            else ctx.lookup(n)

    max_trip = int(op.attrs.get('max_trip_count', 0) or 0)
    if max_trip > 0:
        _while_scan(ctx, block, closure, carry_names, cond_name, init_val,
                    max_trip)
        return

    def cond_fn(carry):
        return jnp.reshape(carry[cond_name], ()).astype(bool)

    def body_fn(carry):
        env = dict(closure)
        env.update(carry)
        _run_block(ctx, block, env)
        return {n: env[n] for n in carry_names}

    init = {n: init_val(n) for n in carry_names}
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    for n, v in final.items():
        ctx.store(n, v)


def _while_scan(ctx, block, closure, carry_names, cond_name, init_val,
                max_trip):
    """Differentiable bounded While: run the body max_trip times under
    lax.scan, blending each carried var with its previous value once the
    condition is false (so post-exit iterations are identity)."""
    init = {}
    for n in carry_names:
        v = init_val(n)
        if isinstance(v, list):
            if not v:
                raise RuntimeError(
                    'while(max_trip_count): carried tensor array %r is '
                    'empty at loop entry; write its first element before '
                    'the loop so the element shape is known' % n)
            # preallocate so traced-index writes inside the body land
            pads = [jnp.zeros_like(v[0])] * max_trip
            v = jnp.stack(list(v) + pads)
        init[n] = v

    def step(carry, _):
        alive = jnp.reshape(carry[cond_name], ()).astype(bool)
        env = dict(closure)
        env.update(carry)
        _run_block(ctx, block, env)
        new_carry = {}
        for n in carry_names:
            new = env[n]
            if isinstance(new, list):  # body rebuilt an array statically
                new = jnp.stack(new)
            old = carry[n]
            if new.shape != old.shape:
                raise RuntimeError(
                    'while(max_trip_count): carried var %r changed shape '
                    '%s -> %s inside the body; bounded loops need '
                    'fixed-shape carries' % (n, old.shape, new.shape))
            new_carry[n] = jnp.where(alive, new, old)
        return new_carry, ()

    final, _ = jax.lax.scan(step, init, None, length=max_trip)
    for n, v in final.items():
        ctx.store(n, v)


@register_lowering('recurrent')
def _recurrent(ctx, op):
    """StaticRNN / DynamicRNN: one lax.scan over the time axis.

    Sequence inputs arrive padded [B, T, ...]; memories carry across steps;
    with attrs['masked'] the carry only advances within each sequence's
    true length (replacing shrink_rnn_memory_op's shrinking batch)."""
    block = op.attrs['sub_block']
    seq_names = op.input('SeqInputs')
    step_names = op.attrs['step_input_names']
    mem_names = op.attrs['mem_names']
    mem_update_names = op.attrs['mem_update_names']
    mem_init_names = op.input('MemInits')
    out_names = op.attrs['output_names']
    masked = op.attrs.get('masked', False)

    time_major = op.attrs.get('time_major', False)
    seqs = [ctx.lookup(n) for n in seq_names]
    if time_major:
        t, b = seqs[0].shape[0], seqs[0].shape[1]
        xs = list(seqs)  # already [T, B, ...]
    else:
        t, b = seqs[0].shape[1], seqs[0].shape[0]
        xs = [jnp.swapaxes(s, 0, 1) for s in seqs]  # [T, B, ...]

    lengths = None
    if masked:
        for n in seq_names:
            if (n + SEQLEN_SUFFIX) in ctx.env:
                lengths = ctx.env[n + SEQLEN_SUFFIX]
                break
    if lengths is not None:
        step_mask = (jnp.arange(t)[None, :] <
                     lengths[:, None]).T  # [T, B] bool
    else:
        step_mask = jnp.ones((t, b), bool)

    reads, _ = _block_reads_writes(block)
    closure = {}
    for n in reads:
        if n in step_names or n in mem_names:
            continue
        if ctx.has(n):
            closure[n] = ctx.lookup(n)
        key = n + SEQLEN_SUFFIX
        if key in ctx.env:
            closure[key] = ctx.env[key]

    mem_init = {
        m: ctx.lookup(init)
        for m, init in zip(mem_names, mem_init_names)
    }

    def step(carry, inp):
        x_ts, m_t = inp
        env = dict(closure)
        env.update({sn: x for sn, x in zip(step_names, x_ts)})
        env.update(carry)
        _run_block(ctx, block, env)
        new_carry = {}
        for m, upd in zip(mem_names, mem_update_names):
            new_val = env[upd] if upd is not None else env[m]
            old_val = carry[m]
            mm = jnp.reshape(m_t, (b, ) + (1, ) * (new_val.ndim - 1))
            # the carry type must be stable across steps: in-block math
            # may promote (bf16 state + f32 gate math under AMP) — fold
            # the update back to the memory's own dtype.  boolean select
            # keeps integer memories (e.g. beam ids) exact
            new_carry[m] = jnp.where(mm, new_val.astype(old_val.dtype),
                                     old_val)
        outs = []
        for on in out_names:
            o = env[on]
            mm = jnp.reshape(m_t, (b, ) + (1, ) * (o.ndim - 1))
            outs.append(jnp.where(mm, o, jnp.zeros_like(o)))
        return new_carry, tuple(outs)

    # Remat the step body, keeping matmul outputs: without this the scan
    # stacks every per-step intermediate (e.g. the [B, T, D] attention
    # tanh inside a DynamicRNN decoder) as a backward residual — O(T^2)
    # HBM traffic; with dots_saveable only the small dot outputs are
    # stored and the elementwise chains are recomputed in the backward
    # scan (the standard TPU remat-scan recipe).
    step = jax.checkpoint(
        step, policy=jax.checkpoint_policies.dots_saveable)
    _, collected = jax.lax.scan(step, mem_init, (tuple(xs), step_mask))
    for out_var_name, col in zip(op.output('Out'), collected):
        out = col if time_major else jnp.swapaxes(col, 0, 1)
        ctx.store(out_var_name, out)
        if lengths is not None:
            ctx.env[out_var_name + SEQLEN_SUFFIX] = lengths


@register_lowering('switch_case')
def _switch_case(ctx, op):
    """All case blocks execute; written vars blend by the first matching
    condition (XLA select semantics; side-effect-free cases only)."""
    case_conds = op.attrs['case_conds']
    case_blocks = op.attrs['case_blocks']
    for blk in case_blocks:
        _reject_host_ops(blk, 'switch_case')
    written = op.output('Out')
    results = []  # per case: dict of written var values
    for blk in case_blocks:
        env = dict(ctx.env)
        _run_block(ctx, blk, env)
        results.append({n: env[n] for n in written if n in env})
    # fold from the last (default) case backwards
    final = {}
    for n in written:
        val = None
        for cond_name, res in zip(reversed(case_conds), reversed(results)):
            if n not in res:
                continue
            if val is None or cond_name is None:
                val = res[n]
            else:
                c = jnp.reshape(ctx.lookup(cond_name), ()).astype(bool)
                val = jnp.where(c, res[n], val)
        if val is not None:
            ctx.store(n, val)


def _split_compact(x, mask_rows):
    """Rows where mask, compacted to the front in original order (static
    shape; the tail holds the complementary rows and is never read by
    merge — branch ops compute over it and the results are discarded,
    with zero cotangent flowing back through the unconsumed positions)."""
    # stable argsort of (not mask) floats selected rows first, in order
    order = jnp.argsort(jnp.logical_not(mask_rows).astype(jnp.int32),
                        stable=True)
    return jnp.take(x, order, axis=0)


@register_lowering('split_lod_tensor')
def _split_lod_tensor(ctx, op):
    """Reference operators/split_lod_tensor_op.cc on static shapes: both
    outputs keep the input's buffer size with their selected rows
    compacted to the front; merge_lod_tensor's mask-driven reconstruction
    never reads the tail.  The row count rides the @SEQLEN-style
    side-band for any consumer that needs it."""
    x = ctx.get(op, 'X')
    mask = ctx.get(op, 'Mask')
    m = jnp.reshape(mask, (-1, )).astype(bool)
    out_true = _split_compact(x, m)
    out_false = _split_compact(x, jnp.logical_not(m))
    ctx.set(op, 'OutTrue', out_true)
    ctx.set(op, 'OutFalse', out_false)
    n_true = jnp.sum(m.astype(jnp.int32))
    for slot, n in (('OutTrue', n_true), ('OutFalse', x.shape[0] - n_true)):
        names = op.output(slot)
        if names:
            ctx.env[names[0] + '@ROWCOUNT'] = n


@register_lowering('merge_lod_tensor')
def _merge_lod_tensor(ctx, op):
    """Reference operators/merge_lod_tensor_op.cc: out row r is the next
    unconsumed compacted row of InTrue when mask[r] else of InFalse —
    the exact inverse of split_lod_tensor's compaction."""
    mask = ctx.get(op, 'Mask')
    in_true = ctx.get(op, 'InTrue')
    in_false = ctx.get(op, 'InFalse')
    m = jnp.reshape(mask, (-1, )).astype(bool)
    ti = jnp.cumsum(m.astype(jnp.int32)) - 1
    fi = jnp.cumsum(jnp.logical_not(m).astype(jnp.int32)) - 1
    tv = jnp.take(in_true, jnp.clip(ti, 0, in_true.shape[0] - 1), axis=0)
    fv = jnp.take(in_false, jnp.clip(fi, 0, in_false.shape[0] - 1), axis=0)
    mm = jnp.reshape(m, (m.shape[0], ) + (1, ) * (tv.ndim - 1))
    ctx.set(op, 'Out', jnp.where(mm, tv, fv))


@register_lowering('ifelse')
def _ifelse(ctx, op):
    """Routed mode (branches read their row subsets via split_lod_tensor
    ops inside the blocks): outputs reassemble with merge_lod_tensor
    semantics.  Unrouted mode: both branches run on the full batch and a
    defined rule — cond with matching leading dim selects per row, a
    1-element cond selects whole tensors — picks each output."""
    cond = ctx.get(op, 'Cond')
    true_block = op.attrs['true_block']
    false_block = op.attrs['false_block']
    true_out = op.attrs['true_out']
    false_out = op.attrs['false_out']
    routed_true = op.attrs.get('routed_true',
                               op.attrs.get('routed', False))
    routed_false = op.attrs.get('routed_false',
                                op.attrs.get('routed', False))
    for blk in (true_block, false_block):
        if blk is not None:
            _reject_host_ops(blk, 'ifelse')
    env_t = dict(ctx.env)
    env_f = dict(ctx.env)
    if true_block is not None:
        _run_block(ctx, true_block, env_t)
    if false_block is not None:
        _run_block(ctx, false_block, env_f)
    c = jnp.reshape(cond, (-1, ))
    m = c.astype(bool)
    ti = jnp.cumsum(m.astype(jnp.int32)) - 1
    fi = jnp.cumsum(jnp.logical_not(m).astype(jnp.int32)) - 1
    for out_name, tn, fn_ in zip(op.output('Out'), true_out, false_out):
        tv, fv = env_t[tn], env_f[fn_]
        rowwise = tv.ndim >= 1 and tv.shape[0] == c.shape[0]
        if (routed_true or routed_false) and rowwise:
            # a routed branch's output is compacted in split order and
            # needs the cumsum re-expansion; an unrouted branch's output
            # is already row-aligned and is read in place (mixed usage
            # is legal: each side is indexed by ITS OWN layout)
            tvr = (jnp.take(tv, jnp.clip(ti, 0, tv.shape[0] - 1), axis=0)
                   if routed_true else tv)
            fvr = (jnp.take(fv, jnp.clip(fi, 0, fv.shape[0] - 1), axis=0)
                   if routed_false else fv)
            mm = jnp.reshape(m, (m.shape[0], ) + (1, ) * (tv.ndim - 1))
            ctx.store(out_name, jnp.where(mm, tvr, fvr))
            continue
        if tv.ndim > 1 and c.shape[0] == tv.shape[0] and c.shape[0] > 1:
            cc = jnp.reshape(m, (c.shape[0], ) + (1, ) * (tv.ndim - 1))
        else:
            cc = jnp.reshape(cond, ()).astype(bool) if cond.size == 1 \
                else jnp.reshape(m, (c.shape[0], ) +
                                 (1, ) * (tv.ndim - 1))
        ctx.store(out_name, jnp.where(cc, tv, fv))


@register_lowering('conditional_block')
def _conditional_block(ctx, op):
    """Reference conditional_block_op.cc: run sub-block if cond; written
    vars keep old values otherwise (select blend).

    A var whose FIRST assignment is this block gets a zero-filled
    else-value — unobservable once a second branch (the IfElse pattern)
    or any later unconditional write covers it; until then the name is
    tracked in ctx.cond_uninit and any read of it is rejected at
    lowering time, reproducing the reference's uninitialized-read error
    (there: a runtime enforce on the cond-false path)."""
    conds = ctx.get_list(op, 'X') if op.input('X') else ctx.get_list(
        op, 'Cond')
    block = op.attrs['sub_block']
    _reject_host_ops(block, 'conditional_block')
    c = jnp.reshape(conds[0], ()).astype(bool)
    env = dict(ctx.env)
    _run_block(ctx, block, env)
    _, writes = _block_reads_writes(block)
    for n in writes:
        if n in block.vars:
            continue  # block-local temp
        new = env[n]
        if ctx.has(n):
            old = ctx.lookup(n)
            # a second conditional write is treated as covering the
            # name (the IfElse complementary-branch pattern).  Cond
            # EQUIVALENCE is not decidable at desc level, so two blocks
            # with unrelated conds also clear — a documented
            # approximation; the reference would error at run time only
            # if both conds were false AND the var was then read
            ctx.cond_uninit.discard(n)
        else:
            old = jnp.zeros_like(new)
            ctx.cond_uninit.add(n)
        ctx.store(n, jnp.where(c, new, old))


# ---- tensor-array ops (statically indexed inside lowered loops) ----
@register_lowering('write_to_array')
def _write_to_array(ctx, op):
    """Tensor-array write.  Concrete index: python-list state, growable.
    Traced index (inside a lowered loop): the array must already be dense
    (preallocated by while's max_trip_count mode) or a non-empty list —
    a dynamic ``.at[i].set`` cannot invent storage, and XLA drops
    out-of-bounds writes, so under-sized arrays lose elements."""
    x = ctx.get(op, 'X')
    i = jnp.reshape(ctx.get(op, 'I'), ()).astype(jnp.int32)
    name = op.output('Out')[0]
    prev = ctx.env.get(name)
    idx = ctx.concrete.get(op.input('I')[0])
    if idx is not None:
        idx = int(idx)
    else:
        try:
            idx = int(i)  # concrete only when not traced
        except Exception:
            idx = None
    op_id = op.attrs.get('_array_op_id')
    if op_id is not None:
        ctx.array_log[op_id] = idx
    if idx is not None:
        lst = (list(prev) if isinstance(prev, list) else
               [] if prev is None else
               [prev[j] for j in range(prev.shape[0])])
        while len(lst) <= idx:
            lst.append(jnp.zeros_like(x))
        lst[idx] = x
        ctx.store(name, lst)
        return
    if prev is None or (isinstance(prev, list) and not prev):
        raise RuntimeError(
            'write_to_array %r: traced index into an empty tensor array — '
            'preallocate it (while max_trip_count mode does) or write a '
            'first element with a concrete index before the loop' % name)
    stacked = prev if not isinstance(prev, list) else jnp.stack(prev)
    ctx.store(name, stacked.at[i].set(x))


@register_grad_lowering('write_to_array')
def _write_to_array_grad(ctx, op):
    """Backward of a tensor-array write (reference
    tensor_array_read_write_op.cc WriteToArrayGradMaker = a read at the
    same index).  Tensor-array gradients share the array's own name +
    @GRAD; each write's backward pops its slot's cotangent into X@GRAD
    and zeroes the slot before earlier writes' backwards consume it."""
    fwd_inputs, fwd_outputs, fwd_attrs = fwd_structure(op)
    arr_name = fwd_outputs['Out'][0]
    arr_gname = arr_name + GRAD_SUFFIX
    logged_idx = ctx.array_log.get(fwd_attrs.get('_array_op_id'))
    if not ctx.has(arr_gname):
        return
    g = ctx.lookup(arr_gname)
    i = ctx.lookup(fwd_inputs['I'][0])
    xg_names = op.output('X' + GRAD_SUFFIX)
    if isinstance(g, list):
        idx = logged_idx if logged_idx is not None else int(
            np.asarray(i).flatten()[0])
        if idx < len(g):
            xg = g[idx]
            rest = list(g)
            rest[idx] = jnp.zeros_like(xg)
        else:  # cotangent never covered this slot
            xg = jnp.zeros_like(ctx.lookup(fwd_inputs['X'][0]))
            rest = g
    else:
        # prefer the logged forward-time index: the index VAR may have
        # been incremented in place since this write ran
        ii = (jnp.int32(logged_idx) if logged_idx is not None
              else jnp.reshape(i, ()).astype(jnp.int32))
        xg = g[ii]
        rest = g.at[ii].set(jnp.zeros_like(xg))
    if xg_names and xg_names[0]:
        prev = ctx.lookup(xg_names[0]) if ctx.has(xg_names[0]) else None
        ctx.store(xg_names[0], xg if prev is None else prev + xg)
    ctx.store(arr_gname, rest)


@register_grad_lowering('read_from_array')
def _read_from_array_grad(ctx, op):
    """Backward of a tensor-array read = scatter-add of the out-grad into
    the array's grad at the same index (reference ReadFromArrayGradMaker
    = a write).  The array grad is created dense (zeros shaped like the
    final array) on first touch."""
    fwd_inputs, fwd_outputs, fwd_attrs = fwd_structure(op)
    arr_name = fwd_inputs['X'][0]
    logged_idx = ctx.array_log.get(fwd_attrs.get('_array_op_id'))
    og_name = fwd_outputs['Out'][0] + GRAD_SUFFIX
    if not ctx.has(og_name):
        return
    og = ctx.lookup(og_name)
    gnames = op.output('X' + GRAD_SUFFIX)
    if not gnames or not gnames[0]:
        return
    gname = gnames[0]
    i = ctx.lookup(fwd_inputs['I'][0])
    if ctx.has(gname):
        cur = ctx.lookup(gname)
    else:
        arr = ctx.lookup(arr_name)
        cur = ([jnp.zeros_like(a) for a in arr] if isinstance(arr, list)
               else jnp.zeros_like(arr))
    if isinstance(cur, list):
        idx = logged_idx
        if idx is None:
            try:
                idx = int(np.asarray(i).flatten()[0])
            except Exception:
                idx = None
        if idx is None:
            cur = jnp.stack(cur)
        else:
            cur = list(cur)
            cur[idx] = cur[idx] + og
            ctx.store(gname, cur)
            return
    ii = (jnp.int32(logged_idx) if logged_idx is not None
          else jnp.reshape(i, ()).astype(jnp.int32))
    ctx.store(gname, cur.at[ii].add(og))


@register_lowering('read_from_array')
def _read_from_array(ctx, op):
    arr = ctx.get(op, 'X')
    i = ctx.get(op, 'I')
    if isinstance(arr, list):
        idx = ctx.concrete.get(op.input('I')[0])
        if idx is None:
            try:
                idx = int(np.asarray(i).flatten()[0])
            except Exception:
                idx = None
        op_id = op.attrs.get('_array_op_id')
        if op_id is not None:
            ctx.array_log[op_id] = int(idx) if idx is not None else None
        if idx is not None:
            ctx.set(op, 'Out', arr[int(idx)])
            return
        arr = jnp.stack(arr)
    idx = jnp.reshape(i, ()).astype(jnp.int32)
    ctx.set(op, 'Out', arr[idx])


@register_lowering('lod_array_length')
def _lod_array_length(ctx, op):
    arr = ctx.get(op, 'X')
    n = len(arr) if isinstance(arr, list) else arr.shape[0]
    ctx.set(op, 'Out', jnp.asarray([n], jnp.int64))


@register_lowering('max_sequence_len')
def _max_sequence_len(ctx, op):
    rank_table = ctx.get(op, 'RankTable')
    ctx.set(op, 'Out', jnp.asarray([rank_table.shape[0]], jnp.int64))
