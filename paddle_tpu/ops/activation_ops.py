"""Activation op lowerings (reference: paddle/fluid/operators/activation_op.cc).

Each is a one-liner into jnp/jax.nn; XLA fuses them into adjacent matmuls so
there is no bandwidth cost on TPU.
"""

import jax
import jax.numpy as jnp

from .registry import register_lowering


def _register_unary(name, fn):
    @register_lowering(name)
    def _lower(ctx, op, fn=fn):
        ctx.set(op, 'Out', fn(ctx.get(op, 'X')))


_register_unary('relu', jax.nn.relu)
_register_unary('sigmoid', jax.nn.sigmoid)
_register_unary('logsigmoid', jax.nn.log_sigmoid)
_register_unary('tanh', jnp.tanh)
_register_unary('tanh_shrink', lambda x: x - jnp.tanh(x))
_register_unary('exp', jnp.exp)
_register_unary('log', jnp.log)
_register_unary('sqrt', jnp.sqrt)
_register_unary('square', jnp.square)
_register_unary('abs', jnp.abs)
_register_unary('ceil', jnp.ceil)
_register_unary('floor', jnp.floor)
_register_unary('round', jnp.round)
_register_unary('reciprocal', jnp.reciprocal)
_register_unary('sin', jnp.sin)
_register_unary('cos', jnp.cos)
_register_unary('softsign', jax.nn.soft_sign)
_register_unary('softplus', jax.nn.softplus)
_register_unary('relu6', lambda x: jnp.clip(x, 0.0, 6.0))


@register_lowering('leaky_relu')
def _leaky_relu(ctx, op):
    x = ctx.get(op, 'X')
    alpha = op.attrs.get('alpha', 0.02)
    ctx.set(op, 'Out', jnp.where(x >= 0, x, alpha * x))


@register_lowering('elu')
def _elu(ctx, op):
    x = ctx.get(op, 'X')
    alpha = op.attrs.get('alpha', 1.0)
    ctx.set(op, 'Out', jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0)))


@register_lowering('brelu')
def _brelu(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out',
            jnp.clip(x, op.attrs.get('t_min', 0.0), op.attrs.get('t_max',
                                                                 24.0)))


@register_lowering('soft_relu')
def _soft_relu(ctx, op):
    x = ctx.get(op, 'X')
    t = op.attrs.get('threshold', 40.0)
    ctx.set(op, 'Out', jnp.log1p(jnp.exp(jnp.clip(x, -t, t))))


@register_lowering('hard_sigmoid')
def _hard_sigmoid(ctx, op):
    x = ctx.get(op, 'X')
    slope = op.attrs.get('slope', 0.2)
    offset = op.attrs.get('offset', 0.5)
    ctx.set(op, 'Out', jnp.clip(slope * x + offset, 0.0, 1.0))


@register_lowering('thresholded_relu')
def _thresholded_relu(ctx, op):
    x = ctx.get(op, 'X')
    t = op.attrs.get('threshold', 1.0)
    ctx.set(op, 'Out', jnp.where(x > t, x, jnp.zeros_like(x)))


@register_lowering('hard_shrink')
def _hard_shrink(ctx, op):
    x = ctx.get(op, 'X')
    t = op.attrs.get('threshold', 0.5)
    ctx.set(op, 'Out', jnp.where(jnp.abs(x) > t, x, jnp.zeros_like(x)))


@register_lowering('softshrink')
def _softshrink(ctx, op):
    x = ctx.get(op, 'X')
    lam = op.attrs.get('lambda', 0.5)
    ctx.set(op, 'Out',
            jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam,
                                                  jnp.zeros_like(x))))


@register_lowering('stanh')
def _stanh(ctx, op):
    x = ctx.get(op, 'X')
    a = op.attrs.get('scale_a', 0.67)
    b = op.attrs.get('scale_b', 1.7159)
    ctx.set(op, 'Out', b * jnp.tanh(a * x))


@register_lowering('swish')
def _swish(ctx, op):
    x = ctx.get(op, 'X')
    beta = op.attrs.get('beta', 1.0)
    ctx.set(op, 'Out', x * jax.nn.sigmoid(beta * x))


@register_lowering('softmax')
def _softmax(ctx, op):
    # fluid softmax normalizes the trailing axis (operators/softmax_op.cc);
    # the exp/sum runs f32 even for bf16 inputs (AMP) — over wide axes a
    # bf16 denominator drifts — and the output lands back in input dtype
    from .registry import amp_upcast_f32
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out',
            jax.nn.softmax(amp_upcast_f32(x), axis=-1).astype(x.dtype))


@register_lowering('prelu')
def _prelu(ctx, op):
    x = ctx.get(op, 'X')
    alpha = ctx.get(op, 'Alpha')
    mode = op.attrs.get('mode', 'all')
    if mode == 'all':
        a = jnp.reshape(alpha, ())
    elif mode == 'channel':
        a = jnp.reshape(alpha, (1, -1) + (1, ) * (x.ndim - 2))
    else:  # element
        a = jnp.reshape(alpha, (1, ) + x.shape[1:])
    ctx.set(op, 'Out', jnp.where(x > 0, x, a * x))


@register_lowering('maxout')
def _maxout(ctx, op):
    x = ctx.get(op, 'X')  # NCHW
    groups = op.attrs['groups']
    n, c, h, w = x.shape
    ctx.set(op, 'Out',
            jnp.max(jnp.reshape(x, (n, c // groups, groups, h, w)), axis=2))
