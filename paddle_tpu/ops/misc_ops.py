"""Op-coverage tail: aliases, fused ops, pooling-with-index, and small
math ops that complete the reference's REGISTER_OPERATOR inventory
(SURVEY §2.1 operators row).

Reference kernels: fc_op.cc (mkldnn), flatten_op.cc, squeeze_op.cc,
unsqueeze_op.cc, fill_op.cc, minus_op.cc, is_empty_op.cc,
pad_constant_like_op.cc, mean_iou_op.cc, bilinear_tensor_product_op.cc,
conv_shift_op.cc, sampling_id_op.cc, pool_with_index_op.cc,
conv_transpose_op.cc (3d/depthwise variants), fused_elemwise_activation
_op.cc, fusion_lstm_op.cc, fusion_gru_op.cc,
fusion_seqexpand_concat_fc_op.cc, attention_lstm_op.cc.

The fusion_* family exists in the reference as hand-fused CPU kernels; on
TPU XLA performs that fusion, so these lowerings are *compositions* of the
same math with the fused op's exact interface.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .registry import (register_lowering, register_host_op, _LOWERINGS,
                       SEQLEN_SUFFIX, amp_cast_in, amp_cast_out,
                       amp_matmul, amp_harmonize)


# ---- aliases: same kernel, second registered name ----
_LOWERINGS['arg_max'] = _LOWERINGS['argmax']
_LOWERINGS['arg_min'] = _LOWERINGS['argmin']
_LOWERINGS['hierarchical_sigmoid'] = _LOWERINGS['hsigmoid']


@register_lowering('fc')
def _fc(ctx, op):
    """Direct fc op (reference operators/fc_op.cc — the mkldnn fused
    path; the Python fc layer normally decomposes into mul+add)."""
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'W')
    bias = ctx.get(op, 'Bias')
    num_col_dims = op.attrs.get('in_num_col_dims', 1)
    x2 = jnp.reshape(x, (int(np.prod(x.shape[:num_col_dims])), -1))
    out = amp_matmul(x2, w)
    if bias is not None:
        # the f32 bias must not re-widen a bf16 activation (AMP)
        out, b = amp_harmonize(out, jnp.reshape(bias, (1, -1)))
        out = out + b
    out = jnp.reshape(out, tuple(x.shape[:num_col_dims]) + (w.shape[1], ))
    ctx.set(op, 'Out', out)


def _flatten(x, axis):
    lead = int(np.prod(x.shape[:axis], dtype=np.int64)) if axis else 1
    return jnp.reshape(x, (lead, -1))


@register_lowering('flatten')
def _flatten_op(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', _flatten(x, op.attrs.get('axis', 1)))


@register_lowering('flatten2')
def _flatten2(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', _flatten(x, op.attrs.get('axis', 1)))
    ctx.set(op, 'XShape', jnp.zeros((0, ) + x.shape, x.dtype))


@register_lowering('squeeze2')
def _squeeze2(ctx, op):
    x = ctx.get(op, 'X')
    _LOWERINGS['squeeze'](ctx, op)
    ctx.set(op, 'XShape', jnp.zeros((0, ) + x.shape, x.dtype))


@register_lowering('unsqueeze2')
def _unsqueeze2(ctx, op):
    x = ctx.get(op, 'X')
    _LOWERINGS['unsqueeze'](ctx, op)
    ctx.set(op, 'XShape', jnp.zeros((0, ) + x.shape, x.dtype))


@register_lowering('fill')
def _fill(ctx, op):
    from ..fluid import core
    shape = op.attrs['shape']
    value = op.attrs['value']
    dtype = op.attrs.get('dtype')
    np_dtype = (core.convert_dtype_to_np(dtype)
                if dtype is not None else np.float32)
    arr = jnp.asarray(np.asarray(value, np_dtype).reshape(shape))
    ctx.set(op, 'Out', arr)


@register_lowering('minus')
def _minus(ctx, op):
    ctx.set(op, 'Out', ctx.get(op, 'X') - ctx.get(op, 'Y'))


@register_lowering('is_empty')
def _is_empty(ctx, op):
    x = ctx.get(op, 'X')
    ctx.set(op, 'Out', jnp.asarray([x.size == 0]))


@register_lowering('pad_constant_like')
def _pad_constant_like(ctx, op):
    """Pad Y up to X's shape with pad_value (reference
    pad_constant_like_op.cc)."""
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    pad_value = op.attrs.get('pad_value', 0.0)
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    ctx.set(op, 'Out', jnp.pad(y, pads, constant_values=pad_value))


@register_lowering('mean_iou')
def _mean_iou(ctx, op):
    """Mean intersection-over-union (reference mean_iou_op.h): per
    sample, a match increments correct[pred]; a mismatch increments
    wrong[label] AND wrong[pred].  IoU[c] = correct/(correct+wrong),
    averaged over classes with a nonzero denominator.  OutWrong and
    OutCorrect are PER-CLASS [num_classes] vectors; InMeanIou/InWrongs/
    InCorrects accumulate into the outputs (streaming evaluation)."""
    pred = jnp.reshape(ctx.get(op, 'Predictions'), (-1, )).astype(jnp.int32)
    label = jnp.reshape(ctx.get(op, 'Labels'), (-1, )).astype(jnp.int32)
    num_classes = int(op.attrs['num_classes'])
    cls = jnp.arange(num_classes)
    match = (pred == label)[:, None]
    pred_oh = pred[:, None] == cls[None, :]
    lbl_oh = label[:, None] == cls[None, :]
    correct = jnp.sum(pred_oh & match, axis=0).astype(jnp.int32)
    wrong = (jnp.sum(lbl_oh & ~match, axis=0) +
             jnp.sum(pred_oh & ~match, axis=0)).astype(jnp.int32)
    for w in ctx.get_list(op, 'InWrongs') or []:
        wrong = wrong + w.astype(jnp.int32)
    for c in ctx.get_list(op, 'InCorrects') or []:
        correct = correct + c.astype(jnp.int32)
    denom = wrong + correct
    present = denom > 0
    iou = jnp.where(present,
                    correct.astype(jnp.float32) /
                    jnp.maximum(denom, 1).astype(jnp.float32), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(
        jnp.sum(present.astype(jnp.float32)), 1.0)
    miou = jnp.reshape(miou, (1, ))
    for m in ctx.get_list(op, 'InMeanIou') or []:
        miou = miou + jnp.reshape(m, (1, )).astype(jnp.float32)
    ctx.set(op, 'OutMeanIou', miou)
    ctx.set(op, 'OutWrong', wrong)
    ctx.set(op, 'OutCorrect', correct)


@register_lowering('bilinear_tensor_product')
def _bilinear_tensor_product(ctx, op):
    """out[n, k] = x[n] @ W[k] @ y[n] + b[k] (reference
    bilinear_tensor_product_op.cc)."""
    x = ctx.get(op, 'X')  # (N, dx)
    y = ctx.get(op, 'Y')  # (N, dy)
    w = ctx.get(op, 'Weight')  # (K, dx, dy)
    bias = ctx.get(op, 'Bias')  # (1, K)
    out = jnp.einsum('nd,kde,ne->nk', x, w, y)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1))
    ctx.set(op, 'Out', out)


@register_lowering('conv_shift')
def _conv_shift(ctx, op):
    """Circular convolution (reference conv_shift_op.cc):
    out[b, i] = sum_j x[b, (i + j - N/2) mod M] * y[b, j]."""
    x = ctx.get(op, 'X')  # (B, M)
    y = ctx.get(op, 'Y')  # (B, N), N odd, N <= M
    m = x.shape[1]
    n = y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    gathered = x[:, idx]  # (B, M, N)
    ctx.set(op, 'Out', jnp.einsum('bmn,bn->bm', gathered, y))


@register_lowering('sampling_id')
def _sampling_id(ctx, op):
    """Sample one index per row from a probability matrix (reference
    sampling_id_op.cc) — RNG threaded through the executor's carried key."""
    x = ctx.get(op, 'X')  # (B, C) probabilities
    key = ctx.next_rng()
    logits = jnp.log(jnp.maximum(x, 1e-20))
    ids = jax.random.categorical(key, logits, axis=-1)
    ctx.set(op, 'Out', ids.astype(jnp.int64))


def _pool_with_index(ctx, op, ndim):
    """Max pool returning both values and flat spatial argmax indices
    (reference pool_with_index_op.cc) — the Mask pairs with unpool."""
    x = ctx.get(op, 'X')  # (N, C, *spatial)
    ksize = list(op.attrs['ksize'])
    strides = list(op.attrs.get('strides', [1] * ndim))
    paddings = list(op.attrs.get('paddings', [0] * ndim))
    if op.attrs.get('global_pooling', False):
        ksize = list(x.shape[2:])
        strides = [1] * ndim
        paddings = [0] * ndim
    neg = jnp.asarray(-jnp.inf, x.dtype)
    pads = [(0, 0), (0, 0)] + [(p, p) for p in paddings]
    xp = jnp.pad(x, pads, constant_values=neg)
    spatial = xp.shape[2:]
    out_dims = [
        (spatial[d] - ksize[d]) // strides[d] + 1 for d in range(ndim)
    ]
    # stack all kernel-offset shifted views, argmax over the window axis
    views = []
    flat_idx = []
    from itertools import product as _prod
    for offs in _prod(*[range(k) for k in ksize]):
        slices = [slice(None), slice(None)]
        for d in range(ndim):
            start = offs[d]
            end = start + (out_dims[d] - 1) * strides[d] + 1
            slices.append(slice(start, end, strides[d]))
        views.append(xp[tuple(slices)])
        # flat index into the UNPADDED input per output position
        pos = 0
        for d in range(ndim):
            coord = (jnp.arange(out_dims[d]) * strides[d] + offs[d] -
                     paddings[d])
            shape = [1] * ndim
            shape[d] = out_dims[d]
            coord = jnp.reshape(coord, shape)
            pos = pos * x.shape[2 + d] + coord
        flat_idx.append(jnp.broadcast_to(pos, out_dims))
    stacked = jnp.stack(views, axis=-1)  # (N, C, *out, K)
    kbest = jnp.argmax(stacked, axis=-1)
    out = jnp.take_along_axis(stacked, kbest[..., None], axis=-1)[..., 0]
    idx_stack = jnp.stack(flat_idx, axis=-1)  # (*out, K)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(idx_stack, out.shape + (idx_stack.shape[-1], )),
        kbest[..., None], axis=-1)[..., 0]
    ctx.set(op, 'Out', out)
    ctx.set(op, 'Mask', mask.astype(jnp.int32))


@register_lowering('max_pool2d_with_index')
def _max_pool2d_with_index(ctx, op):
    _pool_with_index(ctx, op, 2)


@register_lowering('max_pool3d_with_index')
def _max_pool3d_with_index(ctx, op):
    _pool_with_index(ctx, op, 3)


@register_lowering('conv3d_transpose')
def _conv3d_transpose(ctx, op):
    from .nn_ops import grouped_conv_transpose
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')  # (C_in, C_out/groups, kd, kh, kw)
    strides = list(op.attrs.get('strides', [1, 1, 1]))
    paddings = list(op.attrs.get('paddings', [0, 0, 0]))
    dilations = list(op.attrs.get('dilations', [1, 1, 1]))
    groups = op.attrs.get('groups', 1) or 1
    x, w = amp_cast_in(x, w)
    out = grouped_conv_transpose(x, w, strides, paddings, dilations, groups,
                                 ('NCDHW', 'IODHW', 'NCDHW'))
    ctx.set(op, 'Output', amp_cast_out(out))


@register_lowering('depthwise_conv2d_transpose')
def _depthwise_conv2d_transpose(ctx, op):
    """Per-channel transposed conv (reference conv_transpose_op.cc
    depthwise registration): grouped with groups == channels."""
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')  # (C, 1, kh, kw)
    strides = list(op.attrs.get('strides', [1, 1]))
    paddings = list(op.attrs.get('paddings', [0, 0]))
    dilations = list(op.attrs.get('dilations', [1, 1]))
    c = x.shape[1]
    # run C independent 1-channel transposed convs via vmap over channels
    xt = jnp.swapaxes(x, 0, 1)[:, :, None]  # (C, N, 1, H, W)
    wt = w[:, None]  # (C, 1, 1, kh, kw) -> per-channel (1,1,kh,kw)

    def one(chan_x, chan_w):
        return jax.lax.conv_transpose(
            chan_x, jnp.swapaxes(chan_w, 0, 1),
            strides=strides,
            padding=[(p, p) for p in paddings],
            rhs_dilation=dilations,
            dimension_numbers=('NCHW', 'IOHW', 'NCHW'),
            transpose_kernel=True)

    out = jax.vmap(one)(xt, wt)  # (C, N, 1, Ho, Wo)
    ctx.set(op, 'Output', jnp.swapaxes(out[:, :, 0], 0, 1))


_UNARY = {
    'scale': lambda x, a: x * a.get('scale', 1.0),
    'relu': lambda x, a: jax.nn.relu(x),
    'sigmoid': lambda x, a: jax.nn.sigmoid(x),
    'tanh': lambda x, a: jnp.tanh(x),
}
_BINARY = {
    'elementwise_add': lambda x, y: x + y,
    'elementwise_mul': lambda x, y: x * y,
}


@register_lowering('fused_elemwise_activation')
def _fused_elemwise_activation(ctx, op):
    """Binary elementwise + unary activation in one op (reference
    fused_elemwise_activation_op.cc; XLA would fuse these anyway).
    Reference composition rule: [unary, binary] -> Unary(Binary(X, Y));
    [binary, unary] -> Binary(X, Unary(Y))."""
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    f1, f2 = op.attrs['functor_list']
    attrs = op.attrs
    if y.ndim < x.ndim:
        axis = attrs.get('axis', -1)
        shape = [1] * x.ndim
        ax = axis if axis >= 0 else x.ndim - y.ndim
        for i, s in enumerate(y.shape):
            shape[ax + i] = s
        y = jnp.reshape(y, shape)
    if f1 in _BINARY:
        out = _BINARY[f1](x, _UNARY[f2](y, attrs))
    else:
        out = _UNARY[f1](_BINARY[f2](x, y), attrs)
    ctx.set(op, 'Out', out)


def _fusion_rnn_common(ctx, op, cell):
    """fusion_lstm / fusion_gru = X @ WeightX then the recurrent cell
    (reference fusion_lstm_op.cc, fusion_gru_op.cc)."""
    from ..fluid.framework import Operator
    x = ctx.get(op, 'X')  # (B, T, D)
    wx = ctx.get(op, 'WeightX')  # (D, G*H)
    xx = jnp.einsum('btd,dg->btg', x, wx)
    names = op.input('X')
    proxy_name = op.output('XX')[0] if op.output('XX') else (
        names[0] + '@fused_xx')
    ctx.store(proxy_name, xx)
    if names and (names[0] + SEQLEN_SUFFIX) in ctx.env:
        ctx.env[proxy_name + SEQLEN_SUFFIX] = ctx.env[
            names[0] + SEQLEN_SUFFIX]
    inner_inputs = {'Input': [proxy_name],
                    'Weight': op.input('WeightH'),
                    'Bias': op.input('Bias')}
    if op.input('H0'):
        inner_inputs['H0'] = op.input('H0')
    if op.input('C0'):
        inner_inputs['C0'] = op.input('C0')
    inner_outputs = {'Hidden': op.output('Hidden')}
    if cell == 'lstm':
        inner_outputs['Cell'] = op.output('Cell')
        inner_outputs['BatchGate'] = [proxy_name + '@bg']
        inner_outputs['BatchCellPreAct'] = [proxy_name + '@bc']
    else:
        inner_outputs = {'Hidden': op.output('Hidden'),
                         'BatchGate': [proxy_name + '@bg'],
                         'BatchResetHiddenPrev': [proxy_name + '@br'],
                         'BatchHidden': [proxy_name + '@bh']}
    inner = Operator(ctx.block, cell, inputs=inner_inputs,
                     outputs=inner_outputs, attrs=dict(op.attrs))
    _LOWERINGS[cell](ctx, inner)
    ctx.set(op, 'XX', xx)


@register_lowering('fusion_lstm')
def _fusion_lstm(ctx, op):
    _fusion_rnn_common(ctx, op, 'lstm')


@register_lowering('fusion_gru')
def _fusion_gru(ctx, op):
    _fusion_rnn_common(ctx, op, 'gru')


@register_lowering('fusion_seqexpand_concat_fc')
def _fusion_seqexpand_concat_fc(ctx, op):
    """concat(X0, expand(X1..Xn over X0's steps)) @ W (+bias, act)
    (reference fusion_seqexpand_concat_fc_op.cc)."""
    xs = ctx.get_list(op, 'X')
    w = ctx.get(op, 'FCWeight')
    bias = ctx.get(op, 'FCBias')
    ref = xs[0]  # (B, T, D0)
    t = ref.shape[1]
    parts = [ref]
    for other in xs[1:]:
        if other.ndim == 2:
            other = jnp.repeat(other[:, None], t, axis=1)
        parts.append(other)
    cat = jnp.concatenate(parts, axis=-1)
    out = jnp.einsum('btd,dm->btm', cat, w)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, 1, -1))
    act = op.attrs.get('fc_activation', 'identity')
    if act and act != 'identity':
        out = {'relu': jax.nn.relu, 'tanh': jnp.tanh,
               'sigmoid': jax.nn.sigmoid}[act](out)
    ctx.set(op, 'Out', out)


@register_lowering('attention_lstm')
def _attention_lstm(ctx, op):
    """Attention LSTM (reference attention_lstm_op.cc): each step attends
    over the whole input sequence conditioned on the previous cell state,
    pools an attended x, then runs one LSTM step on [x_pooled, h_prev]."""
    x = ctx.get(op, 'X')  # (B, T, M)
    c0 = ctx.get(op, 'C0')  # (B, D)
    h0 = ctx.get(op, 'H0')
    att_w = ctx.get(op, 'AttentionWeight')  # (M + D, 1)
    att_b = ctx.get(op, 'AttentionBias')  # (1, 1) optional
    att_scalar = ctx.get(op, 'AttentionScalar')  # (1, 1) optional
    att_scalar_b = ctx.get(op, 'AttentionScalarBias')
    lstm_w = ctx.get(op, 'LSTMWeight')  # (M + D, 4D)
    lstm_b = ctx.get(op, 'LSTMBias')  # (1, 4D)

    gate_act = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
                'relu': jax.nn.relu}[op.attrs.get('gate_activation',
                                                  'sigmoid')]
    cell_act = jnp.tanh
    cand_act = jnp.tanh

    b, t, m = x.shape
    d = c0.shape[1]
    names = op.input('X')
    lens = ctx.env.get(names[0] + SEQLEN_SUFFIX) if names else None
    if lens is None:
        mask = jnp.ones((b, t), x.dtype)
    else:
        mask = (jnp.arange(t)[None, :] < lens[:, None]).astype(x.dtype)

    h_prev = h0 if h0 is not None else jnp.zeros((b, d), x.dtype)
    c_prev = c0

    def step(carry, _):
        h, c = carry
        # attention score over every source position given cell state
        cexp = jnp.repeat(c[:, None, :], t, axis=1)  # (B, T, D)
        att_in = jnp.concatenate([x, cexp], axis=-1)  # (B, T, M+D)
        score = jnp.einsum('btk,ko->bto', att_in, att_w)[..., 0]
        if att_b is not None:
            score = score + jnp.reshape(att_b, (1, 1))
        if att_scalar is not None:
            score = score * jnp.reshape(att_scalar, (1, 1))
        if att_scalar_b is not None:
            score = score + jnp.reshape(att_scalar_b, (1, 1))
        score = jnp.where(mask > 0, score, -1e30)
        alpha = jax.nn.softmax(score, axis=1)  # (B, T)
        pooled = jnp.einsum('bt,btm->bm', alpha, x)  # LSTMX
        gates = jnp.concatenate([pooled, h], axis=-1) @ lstm_w
        if lstm_b is not None:
            gates = gates + jnp.reshape(lstm_b, (1, -1))
        gc, gi, gf, go = jnp.split(gates, 4, axis=1)
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c + i * cand_act(gc)
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(step, (h_prev, c_prev), None, length=t)
    hs = jnp.swapaxes(hs, 0, 1) * mask[..., None]
    cs = jnp.swapaxes(cs, 0, 1) * mask[..., None]
    ctx.set(op, 'Hidden', hs)
    ctx.set(op, 'Cell', cs)


# ---- host-side scope utilities ----


@register_host_op('delete_var')
def _delete_var(ctx, op, scope):
    """(reference delete_var_op.cc — frees vars mid-program)"""
    for name in op.input('X'):
        scope.erase([name])
        ctx.env.pop(name, None)


@register_host_op('extract_rows')
def _extract_rows(ctx, op, scope):
    """SelectedRows -> the dense row-id tensor (reference
    extract_rows_op.cc)."""
    from ..fluid import core
    name = op.input('X')[0]
    var = scope.find_var(name)
    val = var.value() if var is not None else ctx.get(op, 'X')
    if isinstance(val, core.SelectedRows):
        rows = np.asarray(val.rows(), np.int64).reshape(-1, 1)
    else:
        rows = np.arange(np.asarray(val).shape[0], dtype=np.int64)[:, None]
    out = op.output('Out')[0]
    scope.var(out).set_value(rows)
    ctx.store(out, rows)
