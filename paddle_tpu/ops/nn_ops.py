"""NN op lowerings: conv, pooling, normalization, dropout, embedding.

Reference kernels: paddle/fluid/operators/conv_op.cc (+conv_cudnn_op.cu),
pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc,
lookup_table_op.cc, lrn_op.cc.  Convs lower to lax.conv_general_dilated —
XLA tiles them onto the MXU; layouts are left to the compiler rather than
hand-picking NCHW/NHWC kernels like the cuDNN path does.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import (register_lowering, register_grad_lowering,
                       amp_upcast_f32,
                       fwd_structure, amp_cast_in, amp_cast_out,
                       amp_enabled)

_CONV_DN = ('NCHW', 'OIHW', 'NCHW')


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


@register_lowering('conv2d')
def _conv2d(ctx, op):
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')
    strides = _pair(op.attrs.get('strides', [1, 1]))
    paddings = _pair(op.attrs.get('paddings', [0, 0]))
    dilations = _pair(op.attrs.get('dilations', [1, 1]))
    groups = op.attrs.get('groups', 1) or 1
    x, w = amp_cast_in(x, w)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=_CONV_DN,
        feature_group_count=groups)
    # conv VJP rejects mixed operand dtypes, so AMP convs run fully in
    # bf16; outputs STAY bf16 (amp_cast_out policy) so activations cross
    # HBM at half width — BN recovers fp32 statistics internally
    ctx.set(op, 'Output', amp_cast_out(out))


@register_lowering('depthwise_conv2d')
def _depthwise_conv2d(ctx, op):
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')
    strides = _pair(op.attrs.get('strides', [1, 1]))
    paddings = _pair(op.attrs.get('paddings', [0, 0]))
    dilations = _pair(op.attrs.get('dilations', [1, 1]))
    x, w = amp_cast_in(x, w)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=_CONV_DN,
        feature_group_count=x.shape[1])
    ctx.set(op, 'Output', amp_cast_out(out))


def grouped_conv_transpose(x, w, strides, paddings, dilations, groups, dn):
    """Transpose conv as a fractionally-strided forward conv
    (conv_general_dilated with lhs_dilation=strides, kernel flipped;
    the reference col2im path computes the same map,
    conv_transpose_op.h).  Groups run as per-group slices, concatenated.
    w layout: (C_in, C_out/groups, *k); output spatial size is
    (in-1)*s - 2p + d*(k-1) + 1."""
    nd = len(strides)
    spatial = tuple(range(2, 2 + nd))
    k_eff = [d * (int(w.shape[2 + i]) - 1) + 1
             for i, d in enumerate(dilations)]
    pad = [(k_eff[i] - 1 - paddings[i], k_eff[i] - 1 - paddings[i])
           for i in range(nd)]

    def one(xi, wi):
        return jax.lax.conv_general_dilated(
            xi, jnp.flip(wi, spatial),
            window_strides=(1, ) * nd,
            padding=pad,
            lhs_dilation=list(strides),
            rhs_dilation=list(dilations),
            dimension_numbers=dn)

    if groups == 1:
        return one(x, w)
    xs = jnp.split(x, groups, axis=1)
    ws = jnp.split(w, groups, axis=0)
    return jnp.concatenate([one(xi, wi) for xi, wi in zip(xs, ws)], axis=1)


@register_lowering('conv2d_transpose')
def _conv2d_transpose(ctx, op):
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')  # (C_in, C_out/groups, kh, kw)
    strides = _pair(op.attrs.get('strides', [1, 1]))
    paddings = _pair(op.attrs.get('paddings', [0, 0]))
    dilations = _pair(op.attrs.get('dilations', [1, 1]))
    groups = op.attrs.get('groups', 1) or 1
    x, w = amp_cast_in(x, w)
    # gradient-of-conv formulation (matches the reference's col2im path)
    out = grouped_conv_transpose(x, w, strides, paddings, dilations, groups,
                                 ('NCHW', 'IOHW', 'NCHW'))
    ctx.set(op, 'Output', amp_cast_out(out))


@register_lowering('conv3d')
def _conv3d(ctx, op):
    x = ctx.get(op, 'Input')
    w = ctx.get(op, 'Filter')
    strides = op.attrs.get('strides', [1, 1, 1])
    paddings = op.attrs.get('paddings', [0, 0, 0])
    dilations = op.attrs.get('dilations', [1, 1, 1])
    groups = op.attrs.get('groups', 1) or 1
    x, w = amp_cast_in(x, w)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=list(strides),
        padding=[(p, p) for p in paddings],
        rhs_dilation=list(dilations),
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'),
        feature_group_count=groups)
    ctx.set(op, 'Output', amp_cast_out(out))


def _pool(x, op, ndim):
    ptype = op.attrs.get('pooling_type', 'max')
    ksize = list(op.attrs.get('ksize'))
    strides = list(op.attrs.get('strides', [1] * ndim))
    paddings = list(op.attrs.get('paddings', [0] * ndim))
    ceil_mode = op.attrs.get('ceil_mode', False)
    if op.attrs.get('global_pooling', False):
        ksize = list(x.shape[2:])
        paddings = [0] * ndim
        strides = [1] * ndim
        ceil_mode = False
    # ceil_mode (reference pool_op.cc): extra high-side padding so the last
    # partial window is kept
    pads_hl = []
    padded_extra = False
    for i, p in enumerate(paddings):
        if ceil_mode:
            size = x.shape[2 + i]
            out_ceil = -(-(size + 2 * p - ksize[i]) // strides[i]) + 1
            extra = (out_ceil - 1) * strides[i] + ksize[i] - (size + 2 * p)
            extra = max(extra, 0)
            padded_extra = padded_extra or extra > 0
            pads_hl.append((p, p + extra))
        else:
            pads_hl.append((p, p))
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pads = ((0, 0), (0, 0)) + tuple(pads_hl)
    if ptype == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window,
                                     strides_full, pads)
    # avg pool; exclusive=True counts only in-bounds elements
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_full,
                                   pads)
    if (op.attrs.get('exclusive', True) and
            any(p > 0 for p in paddings)) or padded_extra:
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       strides_full, pads)
        return summed / jnp.maximum(counts, 1.0)
    return summed / np.prod(ksize)


@register_lowering('pool2d')
def _pool2d(ctx, op):
    ctx.set(op, 'Out', _pool(ctx.get(op, 'X'), op, 2))


@register_lowering('pool3d')
def _pool3d(ctx, op):
    ctx.set(op, 'Out', _pool(ctx.get(op, 'X'), op, 3))


@register_lowering('batch_norm')
def _batch_norm(ctx, op):
    x = ctx.get(op, 'X')
    scale = ctx.get(op, 'Scale')
    bias = ctx.get(op, 'Bias')
    mean_in = ctx.get(op, 'Mean')
    var_in = ctx.get(op, 'Variance')
    eps = op.attrs.get('epsilon', 1e-5)
    momentum = op.attrs.get('momentum', 0.9)
    is_test = op.attrs.get('is_test', False)
    ugs = op.attrs.get('use_global_stats', None)
    # which statistics normalize: an EXPLICIT use_global_stats wins in
    # both directions (False = batch stats even at test time, True =
    # frozen running stats even in training); None follows is_test.
    # The running averages update only in actual training (not is_test)
    # AND only when batch statistics were computed — eval passes with
    # use_global_stats=False must not drift the checkpointed averages.
    use_running = bool(ugs) if ugs is not None else bool(is_test)
    update_running = (not use_running) and (not is_test)
    layout = op.attrs.get('data_layout', 'NCHW')
    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == 'NCHW' else x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[1 if layout == 'NCHW' else x.ndim - 1] = -1

    # bf16 activations (AMP) keep bf16 through BN, but the statistics
    # must accumulate in fp32 or large batches lose the mean entirely
    xs = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    if use_running:
        mean, var = mean_in, var_in
        saved_mean, saved_var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
    else:
        mean = jnp.mean(xs, axis=axes)
        var = jnp.mean(jnp.square(xs), axis=axes) - jnp.square(mean)
        saved_mean, saved_var = mean, var
        if update_running:
            # running stats do not take gradients
            m_s = jax.lax.stop_gradient(mean)
            v_s = jax.lax.stop_gradient(var)
            mean_out = momentum * mean_in + (1 - momentum) * m_s
            var_out = momentum * var_in + (1 - momentum) * v_s
        else:
            mean_out, var_out = mean_in, var_in
    inv_std = jax.lax.rsqrt(jnp.reshape(var, bshape) + eps)
    y = (xs - jnp.reshape(mean, bshape)) * inv_std * jnp.reshape(
        scale, bshape) + jnp.reshape(bias, bshape)
    ctx.set(op, 'Y', y.astype(x.dtype))
    ctx.set(op, 'MeanOut', mean_out)
    ctx.set(op, 'VarianceOut', var_out)
    ctx.set(op, 'SavedMean', saved_mean)
    ctx.set(op, 'SavedVariance', saved_var)


@register_lowering('layer_norm')
def _layer_norm(ctx, op):
    x = ctx.get(op, 'X')
    scale = ctx.get(op, 'Scale')
    bias = ctx.get(op, 'Bias')
    eps = op.attrs.get('epsilon', 1e-5)
    begin = op.attrs.get('begin_norm_axis', 1)
    axes = tuple(range(begin, x.ndim))
    # statistics accumulate in f32 even when bf16 activations flow in
    # (same policy as _batch_norm: bf16 mean/var reductions drift)
    xs = amp_upcast_f32(x)
    mean = jnp.mean(xs, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xs - mean), axis=axes, keepdims=True)
    y = ((xs - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    norm_shape = (1, ) * begin + x.shape[begin:]
    if scale is not None:
        y = y * jnp.reshape(scale, norm_shape).astype(x.dtype)
    if bias is not None:
        y = y + jnp.reshape(bias, norm_shape).astype(x.dtype)
    ctx.set(op, 'Y', y)
    ctx.set(op, 'Mean', jnp.reshape(mean, mean.shape[:begin]))
    ctx.set(op, 'Variance', jnp.reshape(var, var.shape[:begin]))


@register_lowering('dropout')
def _dropout(ctx, op):
    x = ctx.get(op, 'X')
    p = op.attrs.get('dropout_prob', 0.5)
    is_test = op.attrs.get('is_test', False) or ctx.is_test
    if is_test:
        # reference "downgrade_in_infer": scale activations at inference
        ctx.set(op, 'Out', x * (1.0 - p))
        ctx.set(op, 'Mask', jnp.ones_like(x))
        return
    key = ctx.next_rng()
    mask = (jax.random.uniform(key, x.shape) >= p).astype(x.dtype)
    ctx.set(op, 'Out', x * mask)
    ctx.set(op, 'Mask', mask)


@register_grad_lowering('dropout')
def _dropout_grad(ctx, op):
    """Explicit grad: must reuse the forward Mask, not fresh randomness
    (reference operators/dropout_op.h DropoutGradKernel)."""
    _, fwd_outputs, attrs = fwd_structure(op)
    out_name = fwd_outputs['Out'][0]
    dout = ctx.lookup(out_name + '@GRAD')
    gnames = op.output('X@GRAD')
    if not gnames:
        return
    if attrs.get('is_test', False) or ctx.is_test:
        ctx.store(gnames[0], dout * (1.0 - attrs.get('dropout_prob', 0.5)))
    else:
        mask = ctx.lookup(fwd_outputs['Mask'][0])
        ctx.store(gnames[0], dout * mask)


@register_lowering('lookup_table')
def _lookup_table(ctx, op):
    w = ctx.get(op, 'W')
    ids = ctx.get(op, 'Ids')
    padding_idx = op.attrs.get('padding_idx', -1)
    flat = jnp.reshape(ids, (-1, )).astype(jnp.int32)
    out = jnp.take(w, flat, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((flat == padding_idx)[:, None], jnp.zeros_like(out),
                        out)
    out_shape = tuple(ids.shape[:-1] if ids.shape and ids.shape[-1] == 1 else
                      ids.shape) + (w.shape[-1], )
    ctx.set(op, 'Out', jnp.reshape(out, out_shape))


@register_lowering('lrn')
def _lrn(ctx, op):
    x = ctx.get(op, 'X')  # NCHW
    n = op.attrs.get('n', 5)
    k = op.attrs.get('k', 2.0)
    alpha = op.attrs.get('alpha', 1e-4)
    beta = op.attrs.get('beta', 0.75)
    sq = jnp.square(x)
    half = n // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(padded[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    ctx.set(op, 'MidOut', mid)
    ctx.set(op, 'Out', x / jnp.power(mid, beta))


@register_lowering('im2sequence')
def _im2sequence(ctx, op):
    x = ctx.get(op, 'X')  # NCHW
    kernels = op.attrs['kernels']
    strides = op.attrs.get('strides', [1, 1])
    paddings = op.attrs.get('paddings', [0, 0, 0, 0])
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (paddings[0], paddings[2]),
                     (paddings[1], paddings[3])))
    oh = (xp.shape[2] - kernels[0]) // strides[0] + 1
    ow = (xp.shape[3] - kernels[1]) // strides[1] + 1
    patches = []
    for i in range(kernels[0]):
        for j in range(kernels[1]):
            patches.append(xp[:, :, i:i + oh * strides[0]:strides[0],
                              j:j + ow * strides[1]:strides[1]])
    # (N, C*kh*kw, OH, OW) -> (N*OH*OW, C*kh*kw)
    stacked = jnp.reshape(
        jnp.stack(patches, axis=2), (n, c * kernels[0] * kernels[1], oh, ow))
    out = jnp.reshape(jnp.transpose(stacked, (0, 2, 3, 1)),
                      (n * oh * ow, c * kernels[0] * kernels[1]))
    ctx.set(op, 'Out', out)


@register_lowering('lstm_unit')
def _lstm_unit(ctx, op):
    """One LSTM cell step on pre-computed gate activations
    (reference operators/lstm_unit_op.h:61-70; gate order i, f, o, g)."""
    x = ctx.get(op, 'X')  # (N, 4D)
    c_prev = ctx.get(op, 'C_prev')
    forget_bias = op.attrs.get('forget_bias', 0.0)
    i, f, o, g = jnp.split(x, 4, axis=1)
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    ctx.set(op, 'C', c)
    ctx.set(op, 'H', h)


@register_lowering('hsigmoid')
def _hsigmoid(ctx, op):
    """Hierarchical sigmoid via the reference's SimpleCode binary tree
    (operators/math/matrix_bit_code.h): code(c) = c + num_classes, walk the
    implicit-heap path.  Variable path lengths are masked for static shapes."""
    x = ctx.get(op, 'X')  # (N, D)
    w = ctx.get(op, 'W')  # (num_classes-1, D)
    bias = ctx.get(op, 'Bias')  # (1, num_classes-1) or None
    label = jnp.reshape(ctx.get(op, 'Label'), (-1, )).astype(jnp.int32)
    num_classes = op.attrs['num_classes']
    max_len = int(np.ceil(np.log2(num_classes)))
    code = label + num_classes
    length = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
    js = jnp.arange(max_len)
    valid = js[None, :] < length[:, None]  # (N, L)
    shift_idx = jnp.maximum(length[:, None] - js[None, :], 1)
    node = (code[:, None] >> shift_idx) - 1  # internal node ids
    node = jnp.clip(node, 0, num_classes - 2)
    bit = (code[:, None] >> jnp.maximum(shift_idx - 1, 0)) & 1
    w_sel = w[node]  # (N, L, D)
    pre = jnp.einsum('nld,nd->nl', w_sel, x)
    if bias is not None:
        pre = pre + jnp.reshape(bias, (-1, ))[node]
    ctx.set(op, 'PreOut', pre)
    # sigmoid cross entropy against the path bits, masked to path length
    loss = jnp.maximum(pre, 0) - pre * bit.astype(pre.dtype) + \
        jnp.log1p(jnp.exp(-jnp.abs(pre)))
    loss = jnp.where(valid, loss, jnp.zeros_like(loss))
    ctx.set(op, 'Out', jnp.sum(loss, axis=1, keepdims=True))


@register_lowering('nce')
def _nce(ctx, op):
    """Noise-contrastive estimation (reference operators/nce_op.h) with
    uniform negative sampling."""
    x = ctx.get(op, 'Input')  # (N, D)
    label = jnp.reshape(ctx.get(op, 'Label'), (-1, )).astype(jnp.int32)
    w = ctx.get(op, 'Weight')  # (C, D)
    b = ctx.get(op, 'Bias')  # (C, 1) or None
    num_total = op.attrs['num_total_classes']
    num_neg = op.attrs.get('num_neg_samples', 10)
    n = x.shape[0]
    key = ctx.next_rng()
    neg = jax.random.randint(key, (n, num_neg), 0, num_total)
    samples = jnp.concatenate([label[:, None], neg], axis=1)  # (N, 1+K)
    w_sel = w[samples]  # (N, 1+K, D)
    logits = jnp.einsum('nkd,nd->nk', w_sel, x)
    if b is not None:
        logits = logits + jnp.reshape(b, (-1, ))[samples]
    ctx.set(op, 'SampleLogits', logits)
    ctx.set(op, 'SampleLabels', samples.astype(jnp.int64))
    # uniform noise distribution q = K / C
    log_q = jnp.log(jnp.asarray(num_neg / num_total, logits.dtype))
    adj = logits - log_q
    pos_loss = jnp.maximum(adj[:, :1], 0) - adj[:, :1] + \
        jnp.log1p(jnp.exp(-jnp.abs(adj[:, :1])))
    neg_loss = jnp.maximum(adj[:, 1:], 0) + \
        jnp.log1p(jnp.exp(-jnp.abs(adj[:, 1:])))
    ctx.set(op, 'Cost', pos_loss + jnp.sum(neg_loss, axis=1, keepdims=True))


@register_grad_lowering('nce')
def _nce_grad(ctx, op):
    """NCE grad must reuse the forward's sampled labels, not resample."""
    fwd_inputs, fwd_outputs, attrs = fwd_structure(op)
    samples = ctx.lookup(fwd_outputs['SampleLabels'][0])
    x = ctx.lookup(fwd_inputs['Input'][0])
    w = ctx.lookup(fwd_inputs['Weight'][0])
    has_bias = bool(fwd_inputs.get('Bias'))
    b = ctx.lookup(fwd_inputs['Bias'][0]) if has_bias else None
    num_total = attrs['num_total_classes']
    num_neg = attrs.get('num_neg_samples', 10)
    cost_name = fwd_outputs['Cost'][0]
    dcost = ctx.lookup(cost_name + '@GRAD')

    def cost_fn(x, w, b):
        w_sel = w[samples]
        logits = jnp.einsum('nkd,nd->nk', w_sel, x)
        if b is not None:
            logits = logits + jnp.reshape(b, (-1, ))[samples]
        log_q = jnp.log(jnp.asarray(num_neg / num_total, logits.dtype))
        adj = logits - log_q
        pos = jnp.maximum(adj[:, :1], 0) - adj[:, :1] + \
            jnp.log1p(jnp.exp(-jnp.abs(adj[:, :1])))
        neg = jnp.maximum(adj[:, 1:], 0) + \
            jnp.log1p(jnp.exp(-jnp.abs(adj[:, 1:])))
        return pos + jnp.sum(neg, axis=1, keepdims=True)

    if has_bias:
        _, vjp = jax.vjp(cost_fn, x, w, b)
        dx, dw, db = vjp(dcost)
    else:
        _, vjp = jax.vjp(lambda x, w: cost_fn(x, w, None), x, w)
        dx, dw = vjp(dcost)
        db = None
    for slot, g in (('Input', dx), ('Weight', dw), ('Bias', db)):
        names = op.output(slot + '@GRAD')
        if names and names[0] and g is not None:
            ctx.store(names[0], g)


@register_lowering('bilinear_interp')
def _bilinear_interp(ctx, op):
    x = ctx.get(op, 'X')  # NCHW
    out_size = ctx.get(op, 'OutSize')
    oh = ow = None
    if out_size is not None:
        try:  # concrete OutSize only; traced values fall back to attrs
            oh, ow = int(np.asarray(out_size)[0]), int(np.asarray(out_size)[1])
        except Exception:
            oh = ow = None
    if oh is None:
        oh = op.attrs['out_h']
        ow = op.attrs['out_w']
    ctx.set(op, 'Out',
            jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), 'bilinear'))


@register_lowering('nearest_interp')
def _nearest_interp(ctx, op):
    x = ctx.get(op, 'X')
    oh = op.attrs['out_h']
    ow = op.attrs['out_w']
    ctx.set(op, 'Out',
            jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), 'nearest'))


@register_lowering('roi_pool')
def _roi_pool(ctx, op):
    """Max pooling over regions of interest (reference
    operators/roi_pool_op.{cc,h}): integer roi coords scaled by
    spatial_scale; bin [i,j] maxes over its sub-window, empty bins emit 0.
    ROIs arrive as an (R, 4) tensor (single image) or padded (B, R, 4) with
    an @SEQLEN side-band mapping rois to batch images."""
    x = ctx.get(op, 'X')  # (N, C, H, W)
    rois = ctx.get(op, 'ROIs')
    ph = int(op.attrs['pooled_height'])
    pw = int(op.attrs['pooled_width'])
    scale = float(op.attrs.get('spatial_scale', 1.0))
    n, c, h, w = x.shape

    from .sequence_ops import _seqlen
    lens = _seqlen(ctx, op, 'ROIs')
    if rois.ndim == 3:
        batch_of_roi = jnp.repeat(jnp.arange(rois.shape[0]), rois.shape[1])
        valid = (jnp.arange(rois.shape[1])[None, :] <
                 (lens[:, None] if lens is not None
                  else jnp.full((rois.shape[0], 1), rois.shape[1])))
        valid = valid.reshape(-1)
        rois = rois.reshape(-1, 4)
    else:
        if lens is not None and lens.shape[0] > 1:
            # a concatenated 2-D roi layout with a multi-image LoD cannot
            # be mapped to images under static shapes — feed rois as a
            # lod_level=1 input (padded 3-D) instead of failing silently
            # with every roi pooled from image 0
            raise NotImplementedError(
                'roi_pool: 2-D ROIs with a multi-image LoD side-band; '
                'feed ROIs as a lod_level=1 input (padded per image)')
        batch_of_roi = jnp.zeros((rois.shape[0], ), jnp.int32)
        valid = jnp.ones((rois.shape[0], ), bool)

    def pool_one(roi, img_idx):
        img = x[img_idx]  # (C, H, W)
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        i = jnp.arange(ph)
        j = jnp.arange(pw)
        hstart = jnp.clip((i * rh) // ph + y1, 0, h)
        hend = jnp.clip(-((-(i + 1) * rh) // ph) + y1, 0, h)
        wstart = jnp.clip((j * rw) // pw + x1, 0, w)
        wend = jnp.clip(-((-(j + 1) * rw) // pw) + x1, 0, w)
        ys = jnp.arange(h)
        xsr = jnp.arange(w)
        mask_h = (ys[None, :] >= hstart[:, None]) & (
            ys[None, :] < hend[:, None])  # (ph, H)
        mask_w = (xsr[None, :] >= wstart[:, None]) & (
            xsr[None, :] < wend[:, None])  # (pw, W)
        m = mask_h[:, None, :, None] & mask_w[None, :, None, :]  # ph pw H W
        vals = jnp.where(m[None], img[:, None, None, :, :], -jnp.inf)
        out = jnp.max(vals, axis=(3, 4))  # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(pool_one)(rois, batch_of_roi)  # (R, C, ph, pw)
    out = jnp.where(valid[:, None, None, None], out, 0.0)
    ctx.set(op, 'Out', out.astype(x.dtype))


@register_lowering('unpool')
def _unpool(ctx, op):
    """Max unpooling (reference operators/unpool_op.cc): scatter each input
    value to the flat spatial index recorded by the paired max-pool."""
    x = ctx.get(op, 'X')  # (N, C, H, W)
    idx = ctx.get(op, 'Indices')  # (N, C, H, W) flat indices into Ho*Wo
    ksize = op.attrs['ksize']
    strides = op.attrs.get('strides', [1, 1])
    paddings = op.attrs.get('paddings', [0, 0])
    n, c, h, w = x.shape
    ho = (h - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    wo = (w - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n, c, ho * wo), x.dtype)
    idx2 = idx.reshape(n, c, h * w).astype(jnp.int32)
    vals = x.reshape(n, c, h * w)
    ni = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    flat = flat.at[ni, ci, idx2].set(vals)
    ctx.set(op, 'Out', flat.reshape(n, c, ho, wo))


@register_lowering('spp')
def _spp(ctx, op):
    """Spatial pyramid pooling (reference operators/spp_op.cc): levels
    0..L-1 pool the feature map into (2^l x 2^l) adaptive bins, flattened
    and concatenated to a fixed-length vector regardless of input H, W."""
    x = ctx.get(op, 'X')  # (N, C, H, W)
    levels = int(op.attrs['pyramid_height'])
    ptype = op.attrs.get('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        i = jnp.arange(bins)
        hstart = (i * h) // bins
        hend = -((-(i + 1) * h) // bins)
        wstart = (i * w) // bins
        wend = -((-(i + 1) * w) // bins)
        ys = jnp.arange(h)
        xsr = jnp.arange(w)
        mask_h = (ys[None, :] >= hstart[:, None]) & (
            ys[None, :] < hend[:, None])  # (bins, H)
        mask_w = (xsr[None, :] >= wstart[:, None]) & (
            xsr[None, :] < wend[:, None])  # (bins, W)
        m = mask_h[:, None, :, None] & mask_w[None, :, None, :]
        mx = m[None, None]  # (1, 1, bins, bins, H, W)
        xv = x[:, :, None, None, :, :]
        if ptype == 'max':
            pooled = jnp.max(jnp.where(mx, xv, -jnp.inf), axis=(4, 5))
            # bins can be empty when 2^level exceeds H or W
            pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        else:
            cnt = jnp.sum(m, axis=(2, 3)).astype(x.dtype)  # (bins, bins)
            pooled = jnp.sum(jnp.where(mx, xv, 0.0),
                             axis=(4, 5)) / jnp.maximum(cnt[None, None], 1)
        outs.append(pooled.reshape(n, -1))
    ctx.set(op, 'Out', jnp.concatenate(outs, axis=1))


@register_lowering('scale_sub_region')
def _scale_sub_region(ctx, op):
    """Scale values inside per-sample [C,H,W] index boxes (reference
    legacy ScaleSubRegionLayer / operators/scale_sub_region via the v2
    builder): indices rows are 1-based inclusive
    [c0, c1, h0, h1, w0, w1]."""
    x = ctx.get(op, 'X')  # [B, C, H, W]
    idx = ctx.get(op, 'Indices').astype(jnp.int32)  # [B, 6]
    value = float(op.attrs.get('value', 1.0))
    b, c, h, w = x.shape
    cs = jnp.arange(c)[None, :, None, None]
    hs = jnp.arange(h)[None, None, :, None]
    ws = jnp.arange(w)[None, None, None, :]
    lo = lambda col: (idx[:, col] - 1)[:, None, None, None]
    hi = lambda col: idx[:, col][:, None, None, None]
    mask = ((cs >= lo(0)) & (cs < hi(1)) &
            (hs >= lo(2)) & (hs < hi(3)) &
            (ws >= lo(4)) & (ws < hi(5)))
    ctx.set(op, 'Out', jnp.where(mask, x * value, x))


@register_lowering('dynamic_conv2d')
def _dynamic_conv2d(ctx, op):
    """Per-sample dynamic-filter convolution (the legacy ConvOperator
    inside mixed_layer: the FILTER is another layer's output, not a
    parameter).  X [B, C, H, W], Filter [B, O*C*kh*kw] -> [B, O, H', W']
    via a vmapped conv."""
    x = ctx.get(op, 'X')
    f = ctx.get(op, 'Filter')
    o = int(op.attrs['num_filters'])
    kh, kw = op.attrs['filter_size']
    stride = op.attrs.get('strides', [1, 1])
    pad = op.attrs.get('paddings', [0, 0])
    b, c = x.shape[0], x.shape[1]
    filt = jnp.reshape(f, (b, o, c, int(kh), int(kw)))

    def one(xi, fi):
        return jax.lax.conv_general_dilated(
            xi[None], fi, tuple(int(s) for s in stride),
            [(int(pad[0]), int(pad[0])), (int(pad[1]), int(pad[1]))],
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))[0]

    ctx.set(op, 'Out', jax.vmap(one)(x, filt))
