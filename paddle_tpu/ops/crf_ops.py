"""Linear-chain CRF ops, TPU-native.

The reference computes the CRF forward algorithm sequence-by-sequence on
CPU only (operators/linear_chain_crf_op.cc, crf_decoding_op.cc — both
CPU-kernel-only, with explicit Alpha/EmissionExps caches for the
hand-written gradient).  Here both the forward algorithm and Viterbi run
as batched ``lax.scan`` over the padded time axis in log space; the
gradient comes from ``jax.vjp`` of the (differentiable) logsumexp
recursion, so no Alpha caching is needed.

Transition layout matches the reference (linear_chain_crf_op.cc comments):
row 0 = start weights, row 1 = end weights, rows 2.. = [D, D] transition
matrix w[i, j] = score of moving from tag i to tag j.
"""

import jax
import jax.numpy as jnp

from .registry import register_lowering, SEQLEN_SUFFIX


def _emission_label_lengths(ctx, op, em_slot, label_slot):
    emission = ctx.get(op, em_slot)  # [B, T, D]
    label = ctx.get(op, label_slot, default=None)
    if label is not None and label.ndim == 3:
        label = label[..., 0]  # [B, T]
    lengths = ctx.env.get(op.input(em_slot)[0] + SEQLEN_SUFFIX)
    b, t = emission.shape[0], emission.shape[1]
    if lengths is None:
        lengths = jnp.full((b, ), t, jnp.int32)
    return emission, label, lengths


@register_lowering('linear_chain_crf')
def _linear_chain_crf(ctx, op):
    """Negative log-likelihood of the gold path per sequence [B, 1].

    (The reference's LogLikelihood output is also the negated
    log-likelihood — see linear_chain_crf_op.h ForwardOneSequence.)
    """
    emission, label, lengths = _emission_label_lengths(
        ctx, op, 'Emission', 'Label')
    transition = ctx.get(op, 'Transition')  # [D+2, D]
    b, t, d = emission.shape
    w_start, w_end, w = transition[0], transition[1], transition[2:]
    steps = jnp.arange(t)

    # ---- partition function: alpha recursion in log space ----
    def alpha_step(alpha, x):
        e_t, t_idx = x  # e_t: [B, D]
        # logsumexp_i(alpha[i] + w[i, j]) + e_t[j]
        scores = alpha[:, :, None] + w[None, :, :]  # [B, D, D]
        new = jax.nn.logsumexp(scores, axis=1) + e_t
        alive = (t_idx < lengths)[:, None]
        return jnp.where(alive, new, alpha), None

    alpha0 = w_start[None, :] + emission[:, 0]  # [B, D]
    alpha, _ = jax.lax.scan(
        alpha_step, alpha0,
        (jnp.swapaxes(emission, 0, 1)[1:], steps[1:]))
    log_z = jax.nn.logsumexp(alpha + w_end[None, :], axis=1)  # [B]

    # ---- gold path score ----
    valid = steps[None, :] < lengths[:, None]  # [B, T]
    lab = jnp.where(valid, label, 0).astype(jnp.int32)
    em_scores = jnp.take_along_axis(emission, lab[:, :, None],
                                    axis=2)[..., 0]  # [B, T]
    em_sum = jnp.sum(jnp.where(valid, em_scores, 0.0), axis=1)
    trans_scores = w[lab[:, :-1], lab[:, 1:]]  # [B, T-1]
    trans_valid = valid[:, 1:]
    trans_sum = jnp.sum(jnp.where(trans_valid, trans_scores, 0.0), axis=1)
    last_lab = jnp.take_along_axis(
        lab, jnp.maximum(lengths - 1, 0)[:, None].astype(jnp.int32),
        axis=1)[:, 0]
    score = (em_sum + trans_sum + w_start[lab[:, 0]] + w_end[last_lab])

    ctx.set(op, 'LogLikelihood', (log_z - score)[:, None])


@register_lowering('crf_decoding')
def _crf_decoding(ctx, op):
    """Viterbi decode (reference crf_decoding_op.h Decode): forward max
    scan storing argmax pointers, then a reverse scan backtracks.  With a
    Label input the output is the per-token correctness indicator, like
    the reference."""
    emission, label, lengths = _emission_label_lengths(
        ctx, op, 'Emission', 'Label')
    transition = ctx.get(op, 'Transition')
    b, t, d = emission.shape
    w_start, w_end, w = transition[0], transition[1], transition[2:]
    steps = jnp.arange(t)

    def viterbi_step(v, x):
        e_t, t_idx = x
        scores = v[:, :, None] + w[None, :, :]  # [B, D(from), D(to)]
        best = jnp.max(scores, axis=1) + e_t
        ptr = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B, D]
        alive = (t_idx < lengths)[:, None]
        v_new = jnp.where(alive, best, v)
        return v_new, (ptr, v_new)

    v0 = w_start[None, :] + emission[:, 0]
    v_last, (ptrs, _) = jax.lax.scan(
        viterbi_step, v0, (jnp.swapaxes(emission, 0, 1)[1:], steps[1:]))
    # ptrs[k] holds the back-pointer for timestep k+1; v_last is v at L-1
    # because dead steps carry v through unchanged.
    last_state = jnp.argmax(v_last + w_end[None, :], axis=1) \
        .astype(jnp.int32)  # [B]

    # pad pointers so index t reads the back-pointer INTO step t
    ptrs_full = jnp.concatenate(
        [jnp.zeros((1, b, d), jnp.int32), ptrs], axis=0)  # [T, B, D]

    def back_step(state, x):
        ptr_next, t_idx = x  # ptr_next = ptrs_full[t+1]
        prev = jnp.take_along_axis(ptr_next, state[:, None],
                                   axis=1)[:, 0]  # state at t from t+1
        s_t = jnp.where(t_idx == lengths - 1, last_state,
                        jnp.where(t_idx < lengths - 1, prev, 0))
        # carry must hold the state at t for the next (earlier) step
        carry = jnp.where(t_idx <= lengths - 1, s_t, last_state)
        return carry, s_t

    ptr_shift = jnp.concatenate(
        [ptrs_full[1:], jnp.zeros((1, b, d), jnp.int32)], axis=0)
    _, path_rev = jax.lax.scan(
        back_step, last_state, (ptr_shift[::-1], steps[::-1]))
    path = jnp.swapaxes(path_rev[::-1], 0, 1)  # [B, T]
    valid = steps[None, :] < lengths[:, None]
    path = jnp.where(valid, path, 0).astype(jnp.int64)

    if label is not None:
        out = (path == label.astype(path.dtype)) & valid
        out = out.astype(jnp.int64)
    else:
        out = path
    name = op.output('ViterbiPath')[0]
    ctx.store(name, out[:, :, None])
    ctx.env[name + SEQLEN_SUFFIX] = lengths
