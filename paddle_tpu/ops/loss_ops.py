"""Loss op lowerings (reference: paddle/fluid/operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, and the *_loss_op.cc family)."""

import functools

import jax
import jax.numpy as jnp

from .registry import register_lowering, amp_upcast_f32

_EPS = 1e-12


def _index_label(label):
    """(N,1) or (N,) int labels -> (N,) int32."""
    if label.ndim > 1 and label.shape[-1] == 1:
        label = jnp.reshape(label, label.shape[:-1])
    return label.astype(jnp.int32)


@register_lowering('cross_entropy')
def _cross_entropy(ctx, op):
    # log() of bf16 probabilities loses digits — compute f32
    x = amp_upcast_f32(ctx.get(op, 'X'))  # probabilities (N, C)
    label = ctx.get(op, 'Label')
    if op.attrs.get('soft_label', False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, _EPS)), axis=-1,
                        keepdims=True)
    else:
        idx = _index_label(label)
        picked = jnp.take_along_axis(x, idx[..., None], axis=-1)
        loss = -jnp.log(jnp.maximum(picked, _EPS))
        ignore = op.attrs.get('ignore_index', -100)
        loss = jnp.where(idx[..., None] == ignore, jnp.zeros_like(loss),
                         loss)
    ctx.set(op, 'Y', loss)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, ))
def _fused_ce_bf16(logits, idx, ignore):
    return _fused_ce_fwd_math(logits, idx, ignore)[:2]


def _fused_ce_fwd_math(logits, idx, ignore):
    # reductions in f32 (exp/sum over a large vocab drifts in bf16); the
    # upcast fuses into the reduction so no f32 [N, V] tensor crosses HBM
    lf = logits.astype(jnp.float32)
    z = jax.scipy.special.logsumexp(lf, axis=-1, keepdims=True)
    valid = (idx != ignore)
    safe = jnp.where(valid, idx, 0)
    picked = jnp.take_along_axis(lf, safe[..., None], axis=-1)
    loss = jnp.where(valid[..., None], z - picked, 0.0)
    p = jnp.exp(lf - z).astype(logits.dtype)    # residual stays bf16
    return loss, p, (p, safe, valid)


def _fused_ce_fwd(logits, idx, ignore):
    loss, p, res = _fused_ce_fwd_math(logits, idx, ignore)
    return (loss, p), res


def _fused_ce_bwd(ignore, res, gs):
    g_loss, _g_p = gs       # the Softmax output is not differentiated
    p, safe, valid = res
    onehot = jax.nn.one_hot(safe, p.shape[-1], dtype=jnp.float32)
    scale = jnp.where(valid[..., None], g_loss.astype(jnp.float32), 0.0)
    # dlogits lands bf16 DIRECTLY: its consumer is the bf16 vocab-matmul
    # backward, and emitting f32 here cost a [N, V] f32 round-trip plus
    # a convert (13% of the transformer step, round-4 xplane profile)
    d = ((p.astype(jnp.float32) - onehot) * scale).astype(p.dtype)
    return (d, jnp.zeros(safe.shape, jax.dtypes.float0))


_fused_ce_bf16.defvjp(_fused_ce_fwd, _fused_ce_bwd)


@register_lowering('softmax_with_cross_entropy')
def _softmax_with_cross_entropy(ctx, op):
    raw = ctx.get(op, 'Logits')
    label = ctx.get(op, 'Label')
    if not op.attrs.get('soft_label', False) and raw.dtype == jnp.bfloat16:
        # AMP hard-label fast path: custom VJP keeps every [N, V]
        # HBM-crossing tensor (softmax residual, dlogits) in bf16
        idx = _index_label(label)
        loss, softmax = _fused_ce_bf16(
            raw, idx, op.attrs.get('ignore_index', -100))
        ctx.set(op, 'Softmax', softmax)
        ctx.set(op, 'Loss', loss)
        return
    # f32 path (and soft labels): plain composition, f32 throughout.
    # Softmax is an Intermediate output in the reference op (its grad
    # kernel never consumes a Softmax cotangent) and the bf16 fast path
    # above can't see one either — stop_gradient keeps the two paths'
    # autodiff semantics identical (ADVICE r4 #1)
    logits = amp_upcast_f32(raw)
    log_p = jax.nn.log_softmax(logits, axis=-1)
    softmax = jax.lax.stop_gradient(jnp.exp(log_p))
    if op.attrs.get('soft_label', False):
        loss = -jnp.sum(label * log_p, axis=-1, keepdims=True)
    else:
        idx = _index_label(label)
        loss = -jnp.take_along_axis(log_p, idx[..., None], axis=-1)
        ignore = op.attrs.get('ignore_index', -100)
        loss = jnp.where(idx[..., None] == ignore, jnp.zeros_like(loss),
                         loss)
    ctx.set(op, 'Softmax', softmax)
    ctx.set(op, 'Loss', loss)


@register_lowering('sigmoid_cross_entropy_with_logits')
def _sigmoid_ce(ctx, op):
    x = amp_upcast_f32(ctx.get(op, 'X'))
    label = ctx.get(op, 'Label')
    # max(x,0) - x*z + log(1+exp(-|x|)), numerically stable
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set(op, 'Out', loss)


@register_lowering('huber_loss')
def _huber_loss(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    delta = op.attrs['delta']
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    ctx.set(op, 'Residual', r)
    ctx.set(op, 'Out', loss)


@register_lowering('smooth_l1_loss')
def _smooth_l1(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    sigma = op.attrs.get('sigma', 1.0)
    in_w = ctx.get(op, 'InsideWeight')
    out_w = ctx.get(op, 'OutsideWeight')
    s2 = sigma * sigma
    d = x - y
    if in_w is not None:
        d = d * in_w
    ad = jnp.abs(d)
    l = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    ctx.set(op, 'Diff', d)
    if out_w is not None:
        l = l * out_w
    ctx.set(op, 'Out', jnp.sum(l, axis=tuple(range(1, l.ndim)),
                               keepdims=False)[:, None])


@register_lowering('log_loss')
def _log_loss(ctx, op):
    p = amp_upcast_f32(ctx.get(op, 'Predicted'))
    label = ctx.get(op, 'Labels')
    eps = op.attrs.get('epsilon', 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    ctx.set(op, 'Loss', loss)


@register_lowering('hinge_loss')
def _hinge_loss(ctx, op):
    logits = ctx.get(op, 'Logits')
    labels = ctx.get(op, 'Labels')
    ctx.set(op, 'Loss',
            jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_lowering('rank_loss')
def _rank_loss(ctx, op):
    label = ctx.get(op, 'Label')
    left = amp_upcast_f32(ctx.get(op, 'Left'))
    right = amp_upcast_f32(ctx.get(op, 'Right'))
    d = left - right
    ctx.set(op, 'Out', jnp.log1p(jnp.exp(d)) - label * d)


@register_lowering('margin_rank_loss')
def _margin_rank_loss(ctx, op):
    label = ctx.get(op, 'Label')
    x1 = ctx.get(op, 'X1')
    x2 = ctx.get(op, 'X2')
    margin = op.attrs.get('margin', 0.0)
    out = jnp.maximum(-label * (x1 - x2) + margin, 0.0)
    ctx.set(op, 'Activated', (out > 0).astype(x1.dtype))
    ctx.set(op, 'Out', out)


@register_lowering('modified_huber_loss')
def _modified_huber_loss(ctx, op):
    x = ctx.get(op, 'X')
    y = ctx.get(op, 'Y')
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z),
                               jnp.zeros_like(z)))
    ctx.set(op, 'IntermediateVal', z)
    ctx.set(op, 'Out', loss)


@register_lowering('kldiv_loss')
def _kldiv_loss(ctx, op):
    x = ctx.get(op, 'X')  # log-probabilities
    target = ctx.get(op, 'Target')
    loss = target * (jnp.log(jnp.maximum(target, _EPS)) - x)
    reduction = op.attrs.get('reduction', 'mean')
    if reduction == 'mean':
        loss = jnp.mean(loss)
    elif reduction == 'sum':
        loss = jnp.sum(loss)
    elif reduction == 'batchmean':
        loss = jnp.sum(loss) / x.shape[0]
    ctx.set(op, 'Loss', loss)
