"""Optimizer op lowerings (reference: paddle/fluid/operators/sgd_op.cc,
momentum_op.cc, adam_op.cc, adagrad_op.cc, rmsprop_op.cc, adadelta_op.cc,
adamax_op.cc, decayed_adagrad_op.cc, ftrl_op.cc).

Each op functionally returns the updated slots (ParamOut etc.); the executor
threads persistable state so updates land back in the scope — the pure
analog of the reference's in-place param update kernels.
"""

import jax.numpy as jnp

from .registry import register_lowering


def _lr(ctx, op):
    lr = ctx.get(op, 'LearningRate')
    return jnp.reshape(lr, ())


@register_lowering('sgd')
def _sgd(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    lr = _lr(ctx, op)
    ctx.set(op, 'ParamOut', p - lr * g)


@register_lowering('momentum')
def _momentum(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    v = ctx.get(op, 'Velocity')
    lr = _lr(ctx, op)
    mu = op.attrs['mu']
    v_out = mu * v + g
    if op.attrs.get('use_nesterov', False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    ctx.set(op, 'ParamOut', p_out)
    ctx.set(op, 'VelocityOut', v_out)


@register_lowering('adam')
def _adam(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    m1 = ctx.get(op, 'Moment1')
    m2 = ctx.get(op, 'Moment2')
    b1p = jnp.reshape(ctx.get(op, 'Beta1Pow'), ())
    b2p = jnp.reshape(ctx.get(op, 'Beta2Pow'), ())
    lr = _lr(ctx, op)
    b1 = op.attrs.get('beta1', 0.9)
    b2 = op.attrs.get('beta2', 0.999)
    eps = op.attrs.get('epsilon', 1e-8)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    ctx.set(op, 'ParamOut', p_out)
    ctx.set(op, 'Moment1Out', m1_out)
    ctx.set(op, 'Moment2Out', m2_out)


@register_lowering('adagrad')
def _adagrad(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    mom = ctx.get(op, 'Moment')
    lr = _lr(ctx, op)
    eps = op.attrs.get('epsilon', 1e-6)
    mom_out = mom + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    ctx.set(op, 'ParamOut', p_out)
    ctx.set(op, 'MomentOut', mom_out)


@register_lowering('decayed_adagrad')
def _decayed_adagrad(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    mom = ctx.get(op, 'Moment')
    lr = _lr(ctx, op)
    decay = op.attrs.get('decay', 0.95)
    eps = op.attrs.get('epsilon', 1e-6)
    mom_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    ctx.set(op, 'ParamOut', p_out)
    ctx.set(op, 'MomentOut', mom_out)


@register_lowering('adadelta')
def _adadelta(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    avg_sq_grad = ctx.get(op, 'AvgSquaredGrad')
    avg_sq_upd = ctx.get(op, 'AvgSquaredUpdate')
    rho = op.attrs.get('rho', 0.95)
    eps = op.attrs.get('epsilon', 1e-6)
    asg_out = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg_out + eps)) * g
    asu_out = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    ctx.set(op, 'ParamOut', p + update)
    ctx.set(op, 'AvgSquaredGradOut', asg_out)
    ctx.set(op, 'AvgSquaredUpdateOut', asu_out)


@register_lowering('adamax')
def _adamax(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    m = ctx.get(op, 'Moment')
    inf_norm = ctx.get(op, 'InfNorm')
    b1p = jnp.reshape(ctx.get(op, 'Beta1Pow'), ())
    lr = _lr(ctx, op)
    b1 = op.attrs.get('beta1', 0.9)
    b2 = op.attrs.get('beta2', 0.999)
    eps = op.attrs.get('epsilon', 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1 - b1p)
    ctx.set(op, 'ParamOut', p - lr_t * m_out / inf_out)
    ctx.set(op, 'MomentOut', m_out)
    ctx.set(op, 'InfNormOut', inf_out)


@register_lowering('rmsprop')
def _rmsprop(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    ms = ctx.get(op, 'MeanSquare')
    mom = ctx.get(op, 'Moment')
    lr = _lr(ctx, op)
    eps = op.attrs.get('epsilon', 1e-10)
    decay = op.attrs.get('decay', 0.9)
    momentum = op.attrs.get('momentum', 0.0)
    ms_out = decay * ms + (1 - decay) * jnp.square(g)
    mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out + eps)
    ctx.set(op, 'ParamOut', p - mom_out)
    ctx.set(op, 'MomentOut', mom_out)
    ctx.set(op, 'MeanSquareOut', ms_out)


@register_lowering('ftrl')
def _ftrl(ctx, op):
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    sq_accum = ctx.get(op, 'SquaredAccumulator')
    lin_accum = ctx.get(op, 'LinearAccumulator')
    lr = _lr(ctx, op)
    l1 = op.attrs.get('l1', 0.0)
    l2 = op.attrs.get('l2', 0.0)
    lr_power = op.attrs.get('lr_power', -0.5)
    new_accum = sq_accum + jnp.square(g)
    pow_new = jnp.power(new_accum, -lr_power)
    pow_old = jnp.power(sq_accum, -lr_power)
    lin_out = lin_accum + g - (pow_new - pow_old) / lr * p
    x = l1 * jnp.sign(lin_out) - lin_out
    y = pow_new / lr + 2 * l2
    p_out = jnp.where(jnp.abs(lin_out) > l1, x / y, jnp.zeros_like(p))
    ctx.set(op, 'ParamOut', p_out)
    ctx.set(op, 'SquaredAccumOut', new_accum)
    ctx.set(op, 'LinearAccumOut', lin_out)


@register_lowering('proximal_gd')
def _proximal_gd(ctx, op):
    """(reference operators/proximal_gd_op.cc): prox step with L1/L2:
    prox = param - lr * grad; out = sign(prox) * max(|prox| - lr*l1, 0)
    / (1 + lr*l2)."""
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    lr = _lr(ctx, op)
    l1 = op.attrs.get('l1', 0.0)
    l2 = op.attrs.get('l2', 0.0)
    prox = p - lr * g
    out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
           / (1.0 + lr * l2))
    ctx.set(op, 'ParamOut', out)


@register_lowering('proximal_adagrad')
def _proximal_adagrad(ctx, op):
    """(reference operators/proximal_adagrad_op.cc): adagrad moment then
    the same prox-l1/l2 shrinkage with per-element effective lr."""
    p = ctx.get(op, 'Param')
    g = ctx.get(op, 'Grad')
    m = ctx.get(op, 'Moment')
    lr = _lr(ctx, op)
    l1 = op.attrs.get('l1', 0.0)
    l2 = op.attrs.get('l2', 0.0)
    m_out = m + g * g
    # elements with zero accumulated moment (never any gradient) must not
    # update: 1/sqrt(0) would blow up eff_lr and the l1 shrink would zero
    # the parameter (the reference kernel NaNs here)
    eff_lr = lr / (jnp.sqrt(m_out) + 1e-10)
    prox = p - eff_lr * g
    out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0.0)
           / (1.0 + eff_lr * l2))
    ctx.set(op, 'ParamOut', jnp.where(m_out > 0, out, p))
    ctx.set(op, 'MomentOut', m_out)


@register_lowering('average_accumulates')
def _average_accumulates(ctx, op):
    """Accumulate parameter sums for ModelAverage (reference
    operators/average_accumulates_op.{cc,h}): sum_1 collects every step,
    rolls into sum_2 every kMaxNumAccumulates steps, and the whole window
    rolls into sum_3 when the average window closes."""
    p = ctx.get(op, 'param')
    sum_1 = ctx.get(op, 'in_sum_1')
    sum_2 = ctx.get(op, 'in_sum_2')
    sum_3 = ctx.get(op, 'in_sum_3')
    num_acc = jnp.reshape(ctx.get(op, 'in_num_accumulates'), ())
    old_num_acc = jnp.reshape(ctx.get(op, 'in_old_num_accumulates'), ())
    num_upd = jnp.reshape(ctx.get(op, 'in_num_updates'), ())
    avg_window = op.attrs.get('average_window', 0.0)
    min_avg = op.attrs.get('min_average_window', 10000)
    max_avg = op.attrs.get('max_average_window', 10000)
    k_max_acc = 16384  # kMaxNumAccumulates (average_accumulates_op.h)

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    sum_1 = sum_1 + p
    roll2 = (num_upd % k_max_acc) == 0
    sum_2 = jnp.where(roll2, sum_2 + sum_1, sum_2)
    sum_1 = jnp.where(roll2, jnp.zeros_like(sum_1), sum_1)
    window = jnp.minimum(
        jnp.asarray(max_avg, jnp.float32),
        num_upd.astype(jnp.float32) * avg_window)
    close = (num_acc >= min_avg) & (num_acc.astype(jnp.float32) >= window)
    sum_3 = jnp.where(close, sum_1 + sum_2, sum_3)
    sum_1 = jnp.where(close, jnp.zeros_like(sum_1), sum_1)
    sum_2 = jnp.where(close, jnp.zeros_like(sum_2), sum_2)
    old_num_acc = jnp.where(close, num_acc, old_num_acc)
    num_acc = jnp.where(close, jnp.zeros_like(num_acc), num_acc)

    ctx.set(op, 'out_sum_1', sum_1)
    ctx.set(op, 'out_sum_2', sum_2)
    ctx.set(op, 'out_sum_3', sum_3)
    ctx.set(op, 'out_num_accumulates', jnp.reshape(num_acc, (1, )))
    ctx.set(op, 'out_old_num_accumulates', jnp.reshape(old_num_acc, (1, )))
    ctx.set(op, 'out_num_updates', jnp.reshape(num_upd, (1, )))
