"""Beam-search decoding ops, TPU-native.

The reference implements beam search over nested LoD tensors whose beam
dimension *grows* per step (operators/beam_search_op.cc selects items per
source sentence from candidate LoD level 0, beam_search_decode_op.cc walks
the sentence->candidate LoD levels to backtrack).  LoD growth is dynamic
shape — poison for XLA — so here the beam dimension is STATIC: every beam
tensor has leading dim ``B*K`` (batch x beam, row-major by sentence) and
dead beams are represented by masked -1e9 scores instead of absent rows.
Backtracking is one reverse ``lax.scan`` over explicit parent pointers
(the dense analog of the reference's LoD parent encoding).
"""

import jax
import jax.numpy as jnp

from .registry import register_lowering, SEQLEN_SUFFIX

NEG_INF = -1e9


@register_lowering('beam_expand')
def _beam_expand(ctx, op):
    """Tile a per-sentence tensor to per-beam rows: [B, ...] -> [B*K, ...]
    (dense analog of the LoD expansion the reference decoder does with
    sequence_expand over the beam LoD level)."""
    x = ctx.get(op, 'X')
    k = int(op.attrs['beam_size'])
    out = jnp.repeat(x, k, axis=0)
    name = op.output('Out')[0]
    ctx.store(name, out)
    xname = op.input('X')[0]
    seq = ctx.env.get(xname + SEQLEN_SUFFIX)
    if seq is not None:
        ctx.env[name + SEQLEN_SUFFIX] = jnp.repeat(seq, k, axis=0)


@register_lowering('beam_init_scores')
def _beam_init_scores(ctx, op):
    """Initial accumulated log-probs: 0 for beam 0 of each sentence, -1e9
    for the rest, so step 1 top-k picks K *distinct* continuations of the
    single start token (the job LoD growth does in the reference: it
    starts with one beam per sentence and only widens after step 1)."""
    x = ctx.get(op, 'X')  # [B, ...]: batch-size reference
    k = int(op.attrs['beam_size'])
    b = x.shape[0]
    row = jnp.full((k, ), NEG_INF, jnp.float32).at[0].set(0.0)
    ctx.set(op, 'Out', jnp.tile(row, (b, ))[:, None])


@register_lowering('beam_search')
def _beam_search(ctx, op):
    """One beam-search selection step (reference beam_search_op.cc).

    Inputs (all leading dim B*K, sentence-major):
      pre_ids    [B*K, 1] int   previous chosen token per beam
      pre_scores [B*K, 1] float accumulated log-prob per beam
      ids        [B*K, C] int   candidate token ids (top-C of the softmax)
      scores     [B*K, C] float accumulated log-prob of each candidate
    Outputs:
      selected_ids    [B*K, 1], selected_scores [B*K, 1]
      parent_idx      [B*K] int32 global row index of each survivor's parent
    A finished beam (pre_id == end_id) contributes exactly one candidate —
    itself, score unchanged — mirroring the reference's handling where
    finished hypotheses are carried through.
    """
    pre_ids = ctx.get(op, 'pre_ids')
    pre_scores = ctx.get(op, 'pre_scores')
    ids = ctx.get(op, 'ids')
    scores = ctx.get(op, 'scores')
    k = int(op.attrs['beam_size'])
    end_id = int(op.attrs['end_id'])

    offsets = op.attrs.get('row_offsets')
    level = int(op.attrs.get('level', 0))
    bk, c = scores.shape
    if level != 0 and offsets is None:
        # level selects the grouping LoD level (beam_search_op.cc:31
        # abs_lod[level]); on the static layout level 1 is the
        # candidate level — every row its own selection pool
        offsets = list(range(bk + 1))
    if offsets is not None:
        _beam_search_pooled(ctx, op, pre_ids, pre_scores, ids, scores,
                            [int(o) for o in offsets], k, end_id)
        return
    b = bk // k
    finished = (pre_ids.reshape(bk) == end_id)  # [B*K]

    # finished beams: candidate 0 = (end_id, pre_score), rest masked out
    keep0 = jnp.zeros((bk, c), bool).at[:, 0].set(True)
    cand_scores = jnp.where(finished[:, None],
                            jnp.where(keep0, pre_scores.reshape(bk, 1),
                                      NEG_INF), scores)
    cand_ids = jnp.where(finished[:, None], end_id, ids)

    flat_scores = cand_scores.reshape(b, k * c)
    top_scores, top_idx = jax.lax.top_k(flat_scores, k)  # [B, K]
    parent_local = top_idx // c  # beam index within sentence
    parent_idx = (jnp.arange(b, dtype=jnp.int32)[:, None] * k +
                  parent_local.astype(jnp.int32))  # global rows
    sel_ids = jnp.take_along_axis(
        cand_ids.reshape(b, k * c), top_idx, axis=1)

    ctx.set(op, 'selected_ids', sel_ids.reshape(bk, 1))
    ctx.set(op, 'selected_scores', top_scores.reshape(bk, 1))
    ctx.set(op, 'parent_idx', parent_idx.reshape(bk))


def _beam_search_pooled(ctx, op, pre_ids, pre_scores, ids, scores,
                        offsets, k, end_id):
    """Nested-LoD selection pools on the static layout (reference
    beam_search_op.cc with a 2-level sentence->candidate LoD): ``offsets``
    are the absolute row offsets of the pools at the chosen ``level`` —
    exactly the reference's ``ToAbsOffset(lod)[level]``.  Pools may be
    ragged.  Per pool: every live row contributes its C candidates, a
    finished row (pre_id == end_id) contributes itself once with all its
    probability mass (beam_search_op.cc:177-191), and a pool whose rows
    are ALL finished keeps emitting end_id carries — the static stand-in
    for PruneEndBeams' row removal (the decode backtrack drops them).
    Output is [num_pools * k, 1], each pool's survivors ordered by
    (parent row, score desc) to match the reference's per-parent
    grouping.
    """
    bk, c = scores.shape
    n_pools = len(offsets) - 1
    finished = (pre_ids.reshape(bk) == end_id)

    keep0 = jnp.zeros((bk, c), bool).at[:, 0].set(True)
    cand_scores = jnp.where(finished[:, None],
                            jnp.where(keep0, pre_scores.reshape(bk, 1),
                                      NEG_INF), scores)
    cand_ids = jnp.where(finished[:, None], end_id, ids)

    # row -> pool id from the static offsets
    import numpy as _np
    row_pool = _np.searchsorted(_np.asarray(offsets[1:]),
                                _np.arange(bk), side='right')
    row_pool = jnp.asarray(row_pool, jnp.int32)  # [bk]

    flat_scores = cand_scores.reshape(bk * c)
    flat_ids = cand_ids.reshape(bk * c)
    flat_row = jnp.repeat(jnp.arange(bk, dtype=jnp.int32), c)
    flat_pool = jnp.repeat(row_pool, c)

    sel_rows, sel_ids, sel_scores = [], [], []
    # out-of-pool entries are masked strictly BELOW the in-pool padding
    # (-1e9) so a pool with fewer than k finite candidates never ties
    # into a foreign pool's entries; any selection at the foreign level
    # is rewritten to an end_id carry on the pool's first row
    FOREIGN = NEG_INF * 2
    for s in range(n_pools):
        pool_scores = jnp.where(flat_pool == s, flat_scores, FOREIGN)
        top_scores, top_idx = jax.lax.top_k(pool_scores, k)
        rows = jnp.take(flat_row, top_idx)
        toks = jnp.take(flat_ids, top_idx)
        foreign = top_scores <= (NEG_INF * 1.5)
        rows = jnp.where(foreign, offsets[s], rows)
        toks = jnp.where(foreign, end_id, toks)
        top_scores = jnp.where(foreign, NEG_INF, top_scores)
        # reference ToMap groups survivors by parent row; break score
        # ties (and order) by (row, -score)
        order = jnp.argsort(rows * jnp.float32(1e6) - top_scores,
                            stable=True)
        sel_rows.append(jnp.take(rows, order))
        sel_ids.append(jnp.take(toks, order))
        sel_scores.append(jnp.take(top_scores, order))

    parent = jnp.concatenate(sel_rows).astype(jnp.int32)
    out_ids = jnp.concatenate(sel_ids).reshape(n_pools * k, 1)
    out_scores = jnp.concatenate(sel_scores).reshape(n_pools * k, 1)
    ctx.set(op, 'selected_ids', out_ids)
    ctx.set(op, 'selected_scores', out_scores)
    ctx.set(op, 'parent_idx', parent)


@register_lowering('beam_search_decode')
def _beam_search_decode(ctx, op):
    """Backtrack beams into sentences (reference beam_search_decode_op.cc).

    Inputs: Ids [T, B*K, 1], ParentIdx [T, B*K], Scores [T, B*K, 1] — the
    stacked per-step outputs of beam_search (a lowered TensorArray).
    Outputs: SentenceIds [B, K, T] (end_id padded), SentenceScores [B, K].
    The reference walks two LoD levels; here it is one reverse scan over
    parent pointers.
    """
    ids = ctx.get(op, 'Ids')
    parents = ctx.get(op, 'ParentIdx')
    scores = ctx.get(op, 'Scores')
    if isinstance(ids, list):
        ids = jnp.stack(ids)
    if isinstance(parents, list):
        parents = jnp.stack(parents)
    if isinstance(scores, list):
        scores = jnp.stack(scores)
    k = int(op.attrs['beam_size'])
    t, bk = ids.shape[0], ids.shape[1]
    b = bk // k
    ids2 = ids.reshape(t, bk)
    parents2 = parents.reshape(t, bk).astype(jnp.int32)

    def back(rows, step):
        step_ids, step_parents = step
        tok = step_ids[rows]
        return step_parents[rows], tok

    rows0 = jnp.arange(bk, dtype=jnp.int32)
    _, toks_rev = jax.lax.scan(back, rows0, (ids2[::-1], parents2[::-1]))
    sent = toks_rev[::-1].T.reshape(b, k, t)  # [B, K, T]
    final_scores = scores.reshape(t, bk)[-1].reshape(b, k)
    ctx.set(op, 'SentenceIds', sent)
    ctx.set(op, 'SentenceScores', final_scores)




@register_lowering('cross_entropy_over_beam')
def _cross_entropy_over_beam(ctx, op):
    """Learning-to-search cost over multi-step beam expansions
    (reference trainer_config_helpers/layers.py:6465 cross_entropy_over_beam;
    kernel: legacy/gserver/layers/CrossEntropyOverBeam.cpp).

    Per expansion e the op takes Scores_e (padded [R_e, C_e] candidate
    scores, rows grouped by sequence), Ids_e ([R_e, K] selected candidate
    ids, -1-padded) and Gold_e ([B] gold candidate id).  Every complete
    path through the selected candidates is scored by summing its
    per-expansion candidate scores; the cost is softmax cross entropy
    over all paths with the gold path as the label.  If gold falls off
    the beam at step t, the paths are those of the beam at step t and
    the gold path is appended as an extra candidate (the reference's
    goldAsExtraPath).

    TPU-native split: the integer path construction (data-dependent,
    CPU-only in the reference too) runs on host via jax.pure_callback on
    the NON-differentiated ids/gold; the score gather + softmax-CE stays
    in XLA so d(cost)/d(scores) flows through the normal vjp (scatter-add
    through the gathers).

    Documented delta: expansion rows are mapped to the r-th VALID
    (non -1) selected entry of the previous expansion, consistently with
    the reference's calValidExpandStep counting; the reference's own
    constructTotalExpansion indexes parents by flat slot, which disagrees
    with its counting whenever a -1 hole precedes the parent inside a
    row — we keep the self-consistent semantics."""
    import numpy as np

    score_names = op.input('Scores')
    id_names = op.input('Ids')
    gold_names = op.input('Gold')
    n_exp = len(score_names)
    assert len(id_names) == n_exp and len(gold_names) == n_exp, \
        'cross_entropy_over_beam: Scores/Ids/Gold must align per expansion'

    scores = [ctx.lookup(n) for n in score_names]
    ids = [ctx.lookup(n) for n in id_names]
    golds = [ctx.lookup(n) for n in gold_names]
    scores = [s[..., 0] if s.ndim == 3 and s.shape[-1] == 1 else s
              for s in scores]
    ids = [i[..., 0] if i.ndim == 3 and i.shape[-1] == 1 else i
           for i in ids]
    golds = [g.reshape(-1) for g in golds]

    b = int(golds[0].shape[0])
    ks = [int(i.shape[1]) for i in ids]  # per-expansion beam width
    # static path bound: every candidate slot of the widest expansion
    # could be a surviving path, +1 for the gold-as-extra path
    p_max = max(int(i.shape[0]) * int(i.shape[1]) for i in ids) + 1

    def build_paths(*args):
        ids_np = [np.asarray(a, np.int64) for a in args[:n_exp]]
        golds_np = [np.asarray(a, np.int64) for a in args[n_exp:]]
        path_row = np.zeros((b, n_exp, p_max), np.int32)
        path_col = np.zeros((b, n_exp, p_max), np.int32)
        exp_valid = np.zeros((b, n_exp), np.float32)
        path_mask = np.zeros((b, p_max), np.bool_)
        gold_idx = np.zeros((b, ), np.int32)

        # per-expansion row offsets per sequence: expansion 0 has one
        # row per sequence; expansion e+1 has one row per valid entry
        starts = [np.zeros(b + 1, np.int64) for _ in range(n_exp)]
        starts[0] = np.arange(b + 1, dtype=np.int64)
        for e in range(n_exp - 1):
            counts = [int((ids_np[e][starts[e][s]:starts[e][s + 1]] >= 0)
                          .sum()) for s in range(b)]
            starts[e + 1] = np.concatenate(
                [[0], np.cumsum(counts)]).astype(np.int64)

        for s in range(b):
            # --- valid-expansion scan + gold tracking ---
            gold_row = [0] * (n_exp + 1)   # row of the gold path, per-seq
            gold_col = [-1] * n_exp
            valid_cnt = 0
            gold_off_beam = True
            for e in range(n_exp):
                seq_ids = ids_np[e][starts[e][s]:starts[e][s + 1]]
                row = seq_ids[gold_row[e]] if gold_row[e] < len(seq_ids) \
                    else np.full((ks[e], ), -1, np.int64)
                hits = np.nonzero(row == golds_np[e][s])[0]
                valid_cnt = e + 1
                if hits.size == 0:
                    break
                gold_col[e] = int(hits[0])
                flat_pos = gold_row[e] * ks[e] + gold_col[e]
                gold_row[e + 1] = int(
                    (seq_ids.reshape(-1)[:flat_pos] >= 0).sum())
            else:
                gold_off_beam = False
            last = valid_cnt - 1
            exp_valid[s, :valid_cnt] = 1.0

            # --- every valid entry of the last expansion is a path ---
            seq_last = ids_np[last][starts[last][s]:starts[last][s + 1]]
            entries = [(r, c, int(v))
                       for r, rowv in enumerate(seq_last)
                       for c, v in enumerate(rowv) if v >= 0]
            n_path = len(entries)
            for p, (r, c, v) in enumerate(entries):
                path_row[s, last, p] = starts[last][s] + r
                path_col[s, last, p] = v
                parent = r
                for e in range(last - 1, -1, -1):
                    seq_e = ids_np[e][starts[e][s]:starts[e][s + 1]]
                    vr, vc = np.nonzero(seq_e >= 0)
                    pr, pc = int(vr[parent]), int(vc[parent])
                    path_row[s, e, p] = starts[e][s] + pr
                    path_col[s, e, p] = int(seq_e[pr, pc])
                    parent = pr
            if gold_off_beam:
                for e in range(valid_cnt):
                    path_row[s, e, n_path] = starts[e][s] + gold_row[e]
                    path_col[s, e, n_path] = int(golds_np[e][s])
                gold_idx[s] = n_path
                n_path += 1
            else:
                flat_pos = gold_row[last] * ks[last] + gold_col[last]
                gold_idx[s] = int(
                    (seq_last.reshape(-1)[:flat_pos] >= 0).sum())
            path_mask[s, :n_path] = True
        return path_row, path_col, exp_valid, path_mask, gold_idx

    out_spec = (
        jax.ShapeDtypeStruct((b, n_exp, p_max), jnp.int32),
        jax.ShapeDtypeStruct((b, n_exp, p_max), jnp.int32),
        jax.ShapeDtypeStruct((b, n_exp), jnp.float32),
        jax.ShapeDtypeStruct((b, p_max), jnp.bool_),
        jax.ShapeDtypeStruct((b, ), jnp.int32),
    )
    path_row, path_col, exp_valid, path_mask, gold_idx = jax.pure_callback(
        build_paths, out_spec,
        *[i.astype(jnp.int32) for i in ids],
        *[g.astype(jnp.int32) for g in golds])

    # --- differentiable half: gather + masked softmax CE over paths ---
    total = jnp.zeros((b, p_max), jnp.float32)
    for e in range(n_exp):
        s_e = scores[e].astype(jnp.float32)
        rows = jnp.clip(path_row[:, e, :], 0, s_e.shape[0] - 1)
        cols = jnp.clip(path_col[:, e, :], 0, s_e.shape[1] - 1)
        total = total + s_e[rows, cols] * exp_valid[:, e][:, None]
    total = jnp.where(path_mask, total, NEG_INF)
    lse = jax.nn.logsumexp(total, axis=1)
    gold_score = jnp.take_along_axis(total, gold_idx[:, None].astype(
        jnp.int32), axis=1)[:, 0]
    loss = (lse - gold_score)[:, None]
    ctx.set(op, 'Out', loss)
