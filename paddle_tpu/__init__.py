"""paddle_tpu — a TPU-native deep learning framework with the capability
surface of PaddlePaddle Fluid (reference: /root/reference, Fluid 0.14).

Programs are built with the fluid API (``paddle_tpu.fluid``), compiled
whole-block to XLA, and executed on TPU.  See SURVEY.md for the layer map.
"""

__version__ = '0.1.0'

import os as _os
import sys as _sys

if 'jax' in _sys.modules and _os.environ.get('JAX_PLATFORMS'):
    # An ambient site config (which is what imports jax this early) may
    # have force-set jax.config.jax_platforms over the JAX_PLATFORMS
    # env var; re-assert the env contract now, before importing any
    # submodule (they may run jax computations at import).  Inlined
    # rather than imported from fluid.core to keep that ordering; when
    # jax is not yet loaded, fluid.core.lazy_jax() applies the same
    # reconciliation (see reconcile_platforms there for the full why).
    _jax = _sys.modules['jax']
    _want = _os.environ['JAX_PLATFORMS']
    try:
        if (_jax.config.jax_platforms or '').split(',')[0] != \
                _want.split(',')[0]:
            _jax.config.update('jax_platforms', _want)
    except Exception:
        pass  # backends already initialized: leave the live config alone

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import parallel  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401  (after fluid: it builds on it)


def batch(reader_creator, batch_size, drop_last=False):
    """Group a sample reader into a batched reader
    (reference: python/paddle/batch.py)."""

    def batch_reader():
        r = reader_creator()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


# imported after `batch` exists: v2 re-exports it
from . import v2  # noqa: F401,E402
from . import distributed  # noqa: F401,E402

__all__ = ['fluid', 'reader', 'dataset', 'parallel', 'inference',
           'serving', 'batch', 'v2', 'distributed']
