"""paddle_tpu — a TPU-native deep learning framework with the capability
surface of PaddlePaddle Fluid (reference: /root/reference, Fluid 0.14).

Programs are built with the fluid API (``paddle_tpu.fluid``), compiled
whole-block to XLA, and executed on TPU.  See SURVEY.md for the layer map.
"""

__version__ = '0.1.0'

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import parallel  # noqa: F401
from . import inference  # noqa: F401


def batch(reader_creator, batch_size, drop_last=False):
    """Group a sample reader into a batched reader
    (reference: python/paddle/batch.py)."""

    def batch_reader():
        r = reader_creator()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


# imported after `batch` exists: v2 re-exports it
from . import v2  # noqa: F401,E402
from . import distributed  # noqa: F401,E402

__all__ = ['fluid', 'reader', 'dataset', 'parallel', 'inference', 'batch',
           'v2', 'distributed']
