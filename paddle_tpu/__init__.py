"""paddle_tpu — a TPU-native deep learning framework with the capability
surface of PaddlePaddle Fluid (reference: /root/reference, Fluid 0.14).

Programs are built with the fluid API (``paddle_tpu.fluid``), compiled
whole-block to XLA, and executed on TPU.  See SURVEY.md for the layer map.
"""

__version__ = '0.1.0'

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401

__all__ = ['fluid', 'reader', 'dataset']
