"""Network service for the Master task queue (reference: go/master's RPC
service `Service.GetTask/TaskFinished/TaskFailed` registered over Go
net/rpc, go/master/service.go:89, consumed by the C-shim client
python/paddle/v2/master/client.py).

Transport: newline-delimited JSON over TCP — the control plane carries a
few small messages per task (payloads are record RANGES, not records),
so the Go version's codec buys nothing here.  The wire shell (daemon
server thread, tracked connections, fault-injection sites, malformed
lines answered typed, rid-routed dedup) is the shared
``transport.ServiceServer`` (ISSUE 17); this module owns only the
master's dispatch table.  One request per line:

    {"method": "get_task"}                     -> {"tid": N, "task": {...}}
    {"method": "task_finished", "tid": N}      -> {"ok": true}
    {"method": "task_failed", "tid": N}        -> {"discarded": 0|1}
    {"method": "counts"}                       -> {"counts": [t,p,d,x]}
    {"method": "new_pass", "expected": p|null} -> {"ok": true, "advanced": bool}
    {"method": "pass_num"}                     -> {"pass_num": p}

Error responses carry the server-side exception TYPE next to the
message — ``{"error": msg, "etype": "ValueError"}`` — so the client
can classify transient vs permanent instead of flattening everything
into RuntimeError (``transport.error_from_response``).

Exactly-once mutations (ISSUE 15): a request may carry ``client`` +
``rid`` (the resilient client mints one per LOGICAL mutating call and
reuses it across retries).  The server routes such requests through
the master's bounded per-client dedup window
(``Master.dedup_execute``): a retried request whose first response was
lost REPLAYS the recorded response instead of re-executing — a
replayed ``task_failed`` does not advance the failure count, a
replayed ``get_task`` returns the same claim instead of leaking the
first one until its lease expires.  The window rides the versioned
snapshot envelope, so dedup survives failover to a promoted standby.

The server owns the Master instance; trainers hold a MasterClient (or
the retrying ``transport.ResilientMasterClient``).  Fault tolerance
semantics live in the queue itself (timeouts requeue a dead trainer's
pending task; failure_max caps retries) — the server is a thin door
onto them.  ``fault_injector`` wires a ``faults.FaultInjector`` into
the handler's ``server_recv``/``server_send`` sites for the chaos
suite.
"""

import json
import socket
import threading

from .transport import MasterUnavailableError, ServiceServer, \
    error_from_response

__all__ = ['MasterServer', 'MasterClient']


def _dispatch_master(master, method, req):
    """One request -> one response dict (errors included — the
    recorded-response dedup window must replay refusals too; the
    ServiceServer wraps raised exceptions the same way)."""
    try:
        if method == 'get_task':
            tid, task = master.get_task()
            return {'tid': tid, 'task': task}
        elif method == 'task_finished':
            master.task_finished(int(req['tid']))
            return {'ok': True}
        elif method == 'task_failed':
            return {'discarded': master.task_failed(int(req['tid']))}
        elif method == 'counts':
            return {'counts': list(master.counts())}
        elif method == 'new_pass':
            advanced = master.new_pass(expected=req.get('expected'))
            return {'ok': True, 'advanced': advanced}
        elif method == 'pass_num':
            return {'pass_num': master.current_pass()}
        elif method in ('register_worker', 'heartbeat',
                        'deregister_worker'):
            # membership door (the etcd registration dir): a
            # worker's TTL lease lives in the master; a crashed
            # worker just stops calling and its lease expires
            epoch, workers = getattr(master, method)(
                str(req['worker_id']))
            return {'epoch': epoch, 'workers': workers}
        elif method == 'members':
            epoch, workers = master.members()
            return {'epoch': epoch, 'workers': workers}
        elif method == 'snapshot':
            # replication door (go/master etcd_client.go analog):
            # a standby on ANOTHER filesystem mirrors the queue
            # state so master-host loss doesn't lose the pass.
            # Read _seq BEFORE serializing: a mutator landing
            # between the two would otherwise pair an OLD blob
            # with a NEWER seq, and the replica would durably
            # skip re-pulling the state that seq promised (e.g.
            # a force-snapshotted poison-task discard).  The
            # stale-seq direction is safe — the next pull sees
            # seq advance and re-mirrors.
            import base64
            seq = getattr(master, '_seq', 0)
            blob = master.snapshot()  # versioned envelope
            return {'blob': base64.b64encode(blob).decode(),
                    'seq': seq}
        return {'error': 'unknown method %r' % method,
                'etype': 'ValueError'}
    except Exception as e:  # surface to the client, keep serving
        return {'error': str(e), 'etype': type(e).__name__}


class MasterServer(object):
    """Serve a Master over TCP from a daemon thread (the shared
    ``ServiceServer`` shell with the master dispatch table and the
    master's own snapshot-riding dedup window)."""

    def __init__(self, master, host='127.0.0.1', port=0,
                 fault_injector=None):
        self.master = master
        self.fault_injector = fault_injector
        self._srv = ServiceServer(
            lambda method, req: _dispatch_master(master, method, req),
            host=host, port=port, fault_injector=fault_injector,
            dedup_execute=(master.dedup_execute
                           if hasattr(master, 'dedup_execute')
                           else None))
        self.host, self.port = self._srv.host, self._srv.port

    @property
    def endpoint(self):
        return self._srv.endpoint

    def close(self):
        self._srv.close()


class MasterClient(object):
    """Trainer-side connection (reference v2/master/client.py ctypes
    shim -> go client).  Blocking request/response on one socket; one
    hiccup is fatal — use ``transport.ResilientMasterClient`` for the
    retrying/failing-over lane.  Errors are typed: connection-level
    failures raise ``MasterUnavailableError`` (a ConnectionError),
    in-band server refusals raise ``MasterProtocolError`` (a
    RuntimeError) carrying the wire ``etype`` in the message."""

    def __init__(self, endpoint, timeout=30.0):
        host, port = endpoint.rsplit(':', 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._rfile = self._sock.makefile('rb')
        # one socket, strict request/response framing: concurrent
        # callers (an elastic job's claim/ack/heartbeat threads) must
        # not interleave their lines
        self._lock = threading.Lock()

    def _call(self, **req):
        with self._lock:
            try:
                self._sock.sendall((json.dumps(req) + '\n').encode())
                line = self._rfile.readline()
            except OSError as e:
                raise MasterUnavailableError(
                    'master connection failed: %s' % e) from e
        if not line:
            raise MasterUnavailableError(
                'master closed the connection')
        try:
            resp = json.loads(line.decode())
        except ValueError as e:
            raise MasterUnavailableError(
                'corrupt master response line: %s' % e) from e
        if 'error' in resp:
            raise error_from_response(resp)
        return resp

    def get_task(self):
        r = self._call(method='get_task')
        return r['tid'], r['task']

    def task_finished(self, tid):
        self._call(method='task_finished', tid=tid)

    def task_failed(self, tid):
        return self._call(method='task_failed', tid=tid)['discarded']

    def counts(self):
        return tuple(self._call(method='counts')['counts'])

    def new_pass(self, expected=None):
        return self._call(method='new_pass',
                          expected=expected)['advanced']

    def current_pass(self):
        return self._call(method='pass_num')['pass_num']

    def register_worker(self, worker_id):
        r = self._call(method='register_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def heartbeat(self, worker_id):
        r = self._call(method='heartbeat', worker_id=worker_id)
        return r['epoch'], r['workers']

    def deregister_worker(self, worker_id):
        r = self._call(method='deregister_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def members(self):
        r = self._call(method='members')
        return r['epoch'], r['workers']

    def fetch_snapshot(self):
        """(blob_bytes, seq) of the master's current queue state."""
        import base64
        r = self._call(method='snapshot')
        return base64.b64decode(r['blob']), r.get('seq', 0)

    def close(self):
        # the buffered reader wraps its own dup of the socket fd:
        # closing only the socket leaked it (ISSUE 15 satellite)
        for closer in (self._rfile, self._sock):
            try:
                closer.close()
            except OSError:
                pass
