"""Network service for the Master task queue (reference: go/master's RPC
service `Service.GetTask/TaskFinished/TaskFailed` registered over Go
net/rpc, go/master/service.go:89, consumed by the C-shim client
python/paddle/v2/master/client.py).

Transport: newline-delimited JSON over TCP — the control plane carries a
few small messages per task (payloads are record RANGES, not records),
so the Go version's codec buys nothing here.  One request per line:

    {"method": "get_task"}                     -> {"tid": N, "task": {...}}
    {"method": "task_finished", "tid": N}      -> {"ok": true}
    {"method": "task_failed", "tid": N}        -> {"discarded": 0|1}
    {"method": "counts"}                       -> {"counts": [t,p,d,x]}
    {"method": "new_pass", "expected": p|null} -> {"ok": true, "advanced": bool}
    {"method": "pass_num"}                     -> {"pass_num": p}

Error responses carry the server-side exception TYPE next to the
message — ``{"error": msg, "etype": "ValueError"}`` — so the client
can classify transient vs permanent instead of flattening everything
into RuntimeError (``transport.error_from_response``).

Exactly-once mutations (ISSUE 15): a request may carry ``client`` +
``rid`` (the resilient client mints one per LOGICAL mutating call and
reuses it across retries).  The server routes such requests through
the master's bounded per-client dedup window
(``Master.dedup_execute``): a retried request whose first response was
lost REPLAYS the recorded response instead of re-executing — a
replayed ``task_failed`` does not advance the failure count, a
replayed ``get_task`` returns the same claim instead of leaking the
first one until its lease expires.  The window rides the versioned
snapshot envelope, so dedup survives failover to a promoted standby.

The server owns the Master instance; trainers hold a MasterClient (or
the retrying ``transport.ResilientMasterClient``).  Fault tolerance
semantics live in the queue itself (timeouts requeue a dead trainer's
pending task; failure_max caps retries) — the server is a thin door
onto them.  ``fault_injector`` wires a ``faults.FaultInjector`` into
the handler's ``server_recv``/``server_send`` sites for the chaos
suite.
"""

import json
import socket
import socketserver
import threading
import time

from .transport import MasterUnavailableError, error_from_response

__all__ = ['MasterServer', 'MasterClient']


class _Handler(socketserver.StreamRequestHandler):
    def setup(self):
        socketserver.StreamRequestHandler.setup(self)
        # tracked so MasterServer.close() can force-close live
        # conversations: a client blocked on readline gets EOF (a
        # typed error), never a hang on a half-shut-down server
        self.server.track(self.connection)

    def finish(self):
        self.server.untrack(self.connection)
        socketserver.StreamRequestHandler.finish(self)

    def _dispatch(self, master, method, req):
        """One request -> one response dict (errors included — the
        recorded-response dedup window must replay refusals too)."""
        try:
            if method == 'get_task':
                tid, task = master.get_task()
                return {'tid': tid, 'task': task}
            elif method == 'task_finished':
                master.task_finished(int(req['tid']))
                return {'ok': True}
            elif method == 'task_failed':
                return {'discarded': master.task_failed(int(req['tid']))}
            elif method == 'counts':
                return {'counts': list(master.counts())}
            elif method == 'new_pass':
                advanced = master.new_pass(expected=req.get('expected'))
                return {'ok': True, 'advanced': advanced}
            elif method == 'pass_num':
                return {'pass_num': master.current_pass()}
            elif method in ('register_worker', 'heartbeat',
                            'deregister_worker'):
                # membership door (the etcd registration dir): a
                # worker's TTL lease lives in the master; a crashed
                # worker just stops calling and its lease expires
                epoch, workers = getattr(master, method)(
                    str(req['worker_id']))
                return {'epoch': epoch, 'workers': workers}
            elif method == 'members':
                epoch, workers = master.members()
                return {'epoch': epoch, 'workers': workers}
            elif method == 'snapshot':
                # replication door (go/master etcd_client.go analog):
                # a standby on ANOTHER filesystem mirrors the queue
                # state so master-host loss doesn't lose the pass.
                # Read _seq BEFORE serializing: a mutator landing
                # between the two would otherwise pair an OLD blob
                # with a NEWER seq, and the replica would durably
                # skip re-pulling the state that seq promised (e.g.
                # a force-snapshotted poison-task discard).  The
                # stale-seq direction is safe — the next pull sees
                # seq advance and re-mirrors.
                import base64
                seq = getattr(master, '_seq', 0)
                blob = master.snapshot()  # versioned envelope
                return {'blob': base64.b64encode(blob).decode(),
                        'seq': seq}
            return {'error': 'unknown method %r' % method,
                    'etype': 'ValueError'}
        except Exception as e:  # surface to the client, keep serving
            return {'error': str(e), 'etype': type(e).__name__}

    def handle(self):
        # connection teardown (a dying client, or close() force-
        # shutting the socket under us) ends the conversation, never
        # an unhandled-exception traceback in the handler thread
        try:
            self._serve_lines()
        except OSError:
            return

    def _serve_lines(self):
        master = self.server.master
        fi = self.server.fault_injector
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line.decode())
                method = req.get('method')
            except (ValueError, UnicodeDecodeError) as e:
                # a half-written or corrupted line must not wedge the
                # handler: answer typed, keep reading
                self._write({'error': 'malformed request line: %s' % e,
                             'etype': type(e).__name__})
                continue
            if fi is not None:
                rule = fi.check('server_recv', method)
                if rule is not None:
                    act = rule['action']
                    if act == 'delay':
                        time.sleep(rule['delay_s'])
                    elif act in ('drop_request', 'drop_response'):
                        continue  # the request never "arrived"
                    elif act == 'close':
                        return
            rid, client = req.get('rid'), req.get('client')
            if rid is not None and hasattr(master, 'dedup_execute'):
                resp = master.dedup_execute(
                    str(client), str(rid),
                    lambda: self._dispatch(master, method, req))
            else:
                resp = self._dispatch(master, method, req)
            if fi is not None:
                rule = fi.check('server_send', method)
                if rule is not None:
                    act = rule['action']
                    if act == 'delay':
                        time.sleep(rule['delay_s'])
                    elif act == 'drop_response':
                        continue  # processed, response lost on the wire
                    elif act == 'close':
                        return
                    elif act == 'garbage':
                        try:
                            self.wfile.write(b'\x00!garbage!\n')
                            self.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError,
                                OSError):
                            return
                        continue
            if not self._write(resp):
                return

    def _write(self, resp):
        try:
            self.wfile.write((json.dumps(resp) + '\n').encode())
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler):
        socketserver.ThreadingTCPServer.__init__(self, addr, handler)
        self._conns = set()
        self._conns_lock = threading.Lock()

    def track(self, conn):
        with self._conns_lock:
            self._conns.add(conn)

    def untrack(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)

    def live_connections(self):
        with self._conns_lock:
            return list(self._conns)


class MasterServer(object):
    """Serve a Master over TCP from a daemon thread."""

    def __init__(self, master, host='127.0.0.1', port=0,
                 fault_injector=None):
        self.master = master
        self.fault_injector = fault_injector
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.master = master
        self._srv.fault_injector = fault_injector
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    @property
    def endpoint(self):
        return '%s:%d' % (self.host, self.port)

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()
        # force-close live conversations: a handler thread blocked in
        # readline (its client is quiet) or a client blocked waiting
        # for a response must both observe EOF now — racing callers
        # get the typed connection error, never a hang on a server
        # that stopped accepting but kept old sockets open
        for conn in self._srv.live_connections():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class MasterClient(object):
    """Trainer-side connection (reference v2/master/client.py ctypes
    shim -> go client).  Blocking request/response on one socket; one
    hiccup is fatal — use ``transport.ResilientMasterClient`` for the
    retrying/failing-over lane.  Errors are typed: connection-level
    failures raise ``MasterUnavailableError`` (a ConnectionError),
    in-band server refusals raise ``MasterProtocolError`` (a
    RuntimeError) carrying the wire ``etype`` in the message."""

    def __init__(self, endpoint, timeout=30.0):
        host, port = endpoint.rsplit(':', 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._rfile = self._sock.makefile('rb')
        # one socket, strict request/response framing: concurrent
        # callers (an elastic job's claim/ack/heartbeat threads) must
        # not interleave their lines
        self._lock = threading.Lock()

    def _call(self, **req):
        with self._lock:
            try:
                self._sock.sendall((json.dumps(req) + '\n').encode())
                line = self._rfile.readline()
            except OSError as e:
                raise MasterUnavailableError(
                    'master connection failed: %s' % e) from e
        if not line:
            raise MasterUnavailableError(
                'master closed the connection')
        try:
            resp = json.loads(line.decode())
        except ValueError as e:
            raise MasterUnavailableError(
                'corrupt master response line: %s' % e) from e
        if 'error' in resp:
            raise error_from_response(resp)
        return resp

    def get_task(self):
        r = self._call(method='get_task')
        return r['tid'], r['task']

    def task_finished(self, tid):
        self._call(method='task_finished', tid=tid)

    def task_failed(self, tid):
        return self._call(method='task_failed', tid=tid)['discarded']

    def counts(self):
        return tuple(self._call(method='counts')['counts'])

    def new_pass(self, expected=None):
        return self._call(method='new_pass',
                          expected=expected)['advanced']

    def current_pass(self):
        return self._call(method='pass_num')['pass_num']

    def register_worker(self, worker_id):
        r = self._call(method='register_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def heartbeat(self, worker_id):
        r = self._call(method='heartbeat', worker_id=worker_id)
        return r['epoch'], r['workers']

    def deregister_worker(self, worker_id):
        r = self._call(method='deregister_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def members(self):
        r = self._call(method='members')
        return r['epoch'], r['workers']

    def fetch_snapshot(self):
        """(blob_bytes, seq) of the master's current queue state."""
        import base64
        r = self._call(method='snapshot')
        return base64.b64decode(r['blob']), r.get('seq', 0)

    def close(self):
        # the buffered reader wraps its own dup of the socket fd:
        # closing only the socket leaked it (ISSUE 15 satellite)
        for closer in (self._rfile, self._sock):
            try:
                closer.close()
            except OSError:
                pass
