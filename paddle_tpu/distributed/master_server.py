"""Network service for the Master task queue (reference: go/master's RPC
service `Service.GetTask/TaskFinished/TaskFailed` registered over Go
net/rpc, go/master/service.go:89, consumed by the C-shim client
python/paddle/v2/master/client.py).

Transport: newline-delimited JSON over TCP — the control plane carries a
few small messages per task (payloads are record RANGES, not records),
so the Go version's codec buys nothing here.  One request per line:

    {"method": "get_task"}                     -> {"tid": N, "task": {...}}
    {"method": "task_finished", "tid": N}      -> {"ok": true}
    {"method": "task_failed", "tid": N}        -> {"discarded": 0|1}
    {"method": "counts"}                       -> {"counts": [t,p,d,x]}
    {"method": "new_pass", "expected": p|null} -> {"ok": true, "advanced": bool}
    {"method": "pass_num"}                     -> {"pass_num": p}

The server owns the Master instance; trainers hold a MasterClient.
Fault tolerance semantics live in the queue itself (timeouts requeue a
dead trainer's pending task; failure_max caps retries) — the server is
a thin door onto them.
"""

import json
import socket
import socketserver
import threading

__all__ = ['MasterServer', 'MasterClient']


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        master = self.server.master
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line.decode())
                method = req.get('method')
                if method == 'get_task':
                    tid, task = master.get_task()
                    resp = {'tid': tid, 'task': task}
                elif method == 'task_finished':
                    master.task_finished(int(req['tid']))
                    resp = {'ok': True}
                elif method == 'task_failed':
                    r = master.task_failed(int(req['tid']))
                    resp = {'discarded': r}
                elif method == 'counts':
                    resp = {'counts': list(master.counts())}
                elif method == 'new_pass':
                    advanced = master.new_pass(
                        expected=req.get('expected'))
                    resp = {'ok': True, 'advanced': advanced}
                elif method == 'pass_num':
                    resp = {'pass_num': master.current_pass()}
                elif method in ('register_worker', 'heartbeat',
                                'deregister_worker'):
                    # membership door (the etcd registration dir): a
                    # worker's TTL lease lives in the master; a crashed
                    # worker just stops calling and its lease expires
                    epoch, workers = getattr(master, method)(
                        str(req['worker_id']))
                    resp = {'epoch': epoch, 'workers': workers}
                elif method == 'members':
                    epoch, workers = master.members()
                    resp = {'epoch': epoch, 'workers': workers}
                elif method == 'snapshot':
                    # replication door (go/master etcd_client.go analog):
                    # a standby on ANOTHER filesystem mirrors the queue
                    # state so master-host loss doesn't lose the pass.
                    # Read _seq BEFORE serializing: a mutator landing
                    # between the two would otherwise pair an OLD blob
                    # with a NEWER seq, and the replica would durably
                    # skip re-pulling the state that seq promised (e.g.
                    # a force-snapshotted poison-task discard).  The
                    # stale-seq direction is safe — the next pull sees
                    # seq advance and re-mirrors.
                    import base64
                    seq = getattr(master, '_seq', 0)
                    blob = master.snapshot()  # versioned envelope
                    resp = {'blob': base64.b64encode(blob).decode(),
                            'seq': seq}
                else:
                    resp = {'error': 'unknown method %r' % method}
            except Exception as e:  # surface to the client, keep serving
                resp = {'error': str(e)}
            try:
                self.wfile.write((json.dumps(resp) + '\n').encode())
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MasterServer(object):
    """Serve a Master over TCP from a daemon thread."""

    def __init__(self, master, host='127.0.0.1', port=0):
        self.master = master
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.master = master
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    @property
    def endpoint(self):
        return '%s:%d' % (self.host, self.port)

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient(object):
    """Trainer-side connection (reference v2/master/client.py ctypes
    shim -> go client).  Blocking request/response on one socket."""

    def __init__(self, endpoint, timeout=30.0):
        host, port = endpoint.rsplit(':', 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._rfile = self._sock.makefile('rb')
        # one socket, strict request/response framing: concurrent
        # callers (an elastic job's claim/ack/heartbeat threads) must
        # not interleave their lines
        self._lock = threading.Lock()

    def _call(self, **req):
        with self._lock:
            self._sock.sendall((json.dumps(req) + '\n').encode())
            line = self._rfile.readline()
        if not line:
            raise ConnectionError('master closed the connection')
        resp = json.loads(line.decode())
        if 'error' in resp:
            raise RuntimeError('master error: %s' % resp['error'])
        return resp

    def get_task(self):
        r = self._call(method='get_task')
        return r['tid'], r['task']

    def task_finished(self, tid):
        self._call(method='task_finished', tid=tid)

    def task_failed(self, tid):
        return self._call(method='task_failed', tid=tid)['discarded']

    def counts(self):
        return tuple(self._call(method='counts')['counts'])

    def new_pass(self, expected=None):
        return self._call(method='new_pass',
                          expected=expected)['advanced']

    def current_pass(self):
        return self._call(method='pass_num')['pass_num']

    def register_worker(self, worker_id):
        r = self._call(method='register_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def heartbeat(self, worker_id):
        r = self._call(method='heartbeat', worker_id=worker_id)
        return r['epoch'], r['workers']

    def deregister_worker(self, worker_id):
        r = self._call(method='deregister_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def members(self):
        r = self._call(method='members')
        return r['epoch'], r['workers']

    def fetch_snapshot(self):
        """(blob_bytes, seq) of the master's current queue state."""
        import base64
        r = self._call(method='snapshot')
        return base64.b64decode(r['blob']), r.get('seq', 0)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
