"""Deterministic fault injection for the control-plane transport
(ISSUE 15).

The reference stack earns its fault-tolerance claims the hard way —
etcd re-election, RPC retries — but nothing in EITHER tree can *test*
those paths on demand: you wait for a flaky network.  ``FaultInjector``
is a seeded, scriptable seam at the newline-JSON transport boundary,
checked by ``ResilientMasterClient`` (sites ``client_send`` /
``client_recv``) and the ``MasterServer`` handler (sites
``server_recv`` / ``server_send``):

    fi = FaultInjector(seed=0)
    # drop the SECOND get_task response on the wire (processed
    # server-side, never delivered): the client must retry with the
    # same request id and the dedup window must replay the claim
    fi.script('server_send', 'get_task', 'drop_response', nth=2)
    # stretch every heartbeat by 0.8s (just under a 1s lease)
    fi.script('client_send', 'heartbeat', 'delay', nth=1,
              times=1000, delay_s=0.8)
    server = MasterServer(master, fault_injector=fi)

Actions (the classic network-fault menu):

    ``drop_request``   the request is swallowed before processing
                       (client_send / server_recv)
    ``drop_response``  processed, but the response never goes out
                       (server_send / client_recv)
    ``delay``          the call proceeds after ``delay_s`` (any site)
    ``garbage``        a non-JSON line goes out instead of the
                       response (server_send)
    ``close``          the connection is torn down mid-conversation
                       (client_send / server_recv / server_send)

``script()`` rejects an (site, action) pair its call sites do not
implement — a scheduled fault either fires or is a typed error, never
a silently-counted no-op.

Rules match on (site, method, per-(site,method) call ordinal) — an
``nth``/``times`` window — or probabilistically via ``prob`` drawn
from the injector's own seeded rng, so a chaos schedule is REPLAYABLE:
same seed + same call sequence = same faults.  Every applied fault is
appended to ``log`` and counted in ``applied``.
"""

import random
import threading

__all__ = ['FaultInjector', 'InjectedFault']

_SITES = ('client_send', 'client_recv', 'server_recv', 'server_send')
_ACTIONS = ('drop_request', 'drop_response', 'delay', 'close',
            'garbage')
# which actions each injection site actually implements — a rule the
# call sites would ignore must be a typed error at script() time, or
# the schedule counts a "fault" that never happened
_SITE_ACTIONS = {
    'client_send': ('drop_request', 'delay', 'close'),
    'client_recv': ('drop_response', 'delay'),
    'server_recv': ('drop_request', 'delay', 'close'),
    'server_send': ('drop_response', 'delay', 'close', 'garbage'),
}


class InjectedFault(ConnectionError):
    """Raised at a client-side injection point to simulate the wire
    failing (a ConnectionError, so the resilient client's transient
    path retries it like any real socket death)."""


class FaultInjector(object):
    """Seeded, scriptable transport-fault schedule (see module doc)."""

    def __init__(self, seed=0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._rules = []
        self._counts = {}  # (site, method) -> calls observed
        self._lock = threading.Lock()
        self.log = []      # applied faults, in order
        self.applied = 0

    def script(self, site, method, action, nth=1, times=1,
               delay_s=0.0, prob=None):
        """Schedule ``action`` at ``site`` for ``method`` (or ``'*'``).

        Deterministic window: fires on the ``nth``-th through
        ``nth+times-1``-th call of (site, method) through this
        injector (1-based).  ``prob`` switches the rule to seeded
        coin-flip mode instead (fires with probability ``prob`` on
        every call in the window — window defaults stay 1/1, so pass
        a wide ``times`` for an open-ended probabilistic rule)."""
        if site not in _SITES:
            raise ValueError('FaultInjector: unknown site %r (one of '
                             '%s)' % (site, ', '.join(_SITES)))
        if action not in _ACTIONS:
            raise ValueError('FaultInjector: unknown action %r (one '
                             'of %s)' % (action, ', '.join(_ACTIONS)))
        if action not in _SITE_ACTIONS[site]:
            raise ValueError(
                'FaultInjector: action %r is not implemented at site '
                '%r (supported there: %s)'
                % (action, site, ', '.join(_SITE_ACTIONS[site])))
        if int(nth) < 1 or int(times) < 1:
            raise ValueError('FaultInjector: nth/times are 1-based '
                             'positive counts')
        self._rules.append({
            'site': site, 'method': method, 'action': action,
            'nth': int(nth), 'times': int(times),
            'delay_s': float(delay_s),
            'prob': None if prob is None else float(prob),
        })
        return self

    def check(self, site, method):
        """One transport event: returns the first matching rule (a
        dict with ``action``/``delay_s``) or None.  The CALLER
        interprets the action — the injector only decides and
        records."""
        with self._lock:
            key = (site, method)
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            for rule in self._rules:
                if rule['site'] != site:
                    continue
                if rule['method'] not in ('*', method):
                    continue
                if not (rule['nth'] <= n < rule['nth'] + rule['times']):
                    continue
                if rule['prob'] is not None and \
                        self._rng.random() >= rule['prob']:
                    continue
                self.applied += 1
                self.log.append((site, method, n, rule['action']))
                return rule
        return None

    def counts(self):
        """{(site, method): calls observed} — schedule-writing aid."""
        with self._lock:
            return dict(self._counts)
