"""Host-side asynchronous sparse-embedding service — the TPU-native
shape of the reference's surviving async training mode (VERDICT r2
next-#9).

Reference architecture (2018 CTR production): the giant embedding lives
on parameter servers; trainers `prefetch` only the rows a batch touches
(operators/prefetch_op.cc -> AsyncPrefetchVar, distributed/rpc_client.h:46),
compute the dense step, and push sparse grads back WITHOUT barriers —
the pserver's `RunAsyncLoop` applies updates as they arrive
(operators/listen_and_serv_op.cc:179; design
doc/fluid/design/dist_train/async_update.md).

Here the dense step is synchronous SPMD on the chip (BASELINE north
star), and THIS service carries the async half: the table is
host-resident (it is too large for HBM — that is the whole reason the
reference sharded it off-device), `prefetch()` gathers the batch's rows
to feed the compiled step, `push_grad()` enqueues the row-gradients, and
a daemon thread applies them to the table while the next step's compute
runs.  Reads may observe a bounded staleness of the in-flight updates —
exactly the async-SGD semantics the reference shipped.
"""

import queue
import threading

import numpy as np

__all__ = ['AsyncSparseEmbedding', 'AsyncSparseClosedError']


class AsyncSparseClosedError(RuntimeError):
    """Typed reject for a gradient pushed after ``close()``: the apply
    daemon is gone, so a silent enqueue would drop the update forever
    (the reference's analog is an RPC send to a shut-down pserver)."""

    def __init__(self, what='push_grad'):
        super(AsyncSparseClosedError, self).__init__(
            '%s on a closed AsyncSparseEmbedding — the apply daemon has '
            'shut down; create a new service (or call close() last)'
            % what)


class AsyncSparseEmbedding(object):
    """One host-side embedding table with asynchronous SGD updates.

    vocab, dim : table shape
    lr         : SGD learning rate applied to pushed row-gradients
    capacity   : max queued (ids, grad) batches before push blocks
                 (bounds staleness the way the reference bounded it by
                 RPC in-flight windows)
    """

    def __init__(self, vocab, dim, lr=0.01, capacity=64, seed=0,
                 init_scale=0.01, table=None):
        if table is not None:
            # adopt an existing master table (the two-tier embedding
            # cache seeds the host tier from the startup-initialized
            # value instead of re-drawing it)
            # copy=True: the source may be a read-only view of a live
            # jax array — the master must stay writable
            self._table = np.array(table, dtype='float32', copy=True)
            if self._table.shape != (int(vocab), int(dim)):
                raise ValueError(
                    'AsyncSparseEmbedding: table= has shape %s, expected '
                    '(%d, %d)' % (self._table.shape, vocab, dim))
        else:
            rng = np.random.RandomState(seed)
            self._table = (init_scale * rng.standard_normal(
                (vocab, dim))).astype('float32')
        self._lr = float(lr)
        self._q = queue.Queue(maxsize=capacity)
        self._lock = threading.Lock()  # table row read/write atomicity
        self._applied = 0
        self._pushed = 0
        self._error = None
        self._closed = False
        self._join_timeouts = 0
        # serializes close() against racing pushers: a push that won
        # entry before close() set the flag still lands in the queue
        # close() is about to drain; one that lost raises typed instead
        # of enqueueing to a dead daemon
        self._close_lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- trainer side (reference prefetch_op / send sparse grad) --
    def prefetch(self, ids):
        """Gather current row values for a batch of ids -> [len(ids), D]
        (reference AsyncPrefetchVar; reads see the table as of now,
        minus whatever updates are still queued — async semantics)."""
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            return self._table[ids].copy()

    def push_grad(self, ids, grad):
        """Enqueue d(loss)/d(rows) for asynchronous application; returns
        immediately (the reference's barrier-free send).  Raises the
        typed ``AsyncSparseClosedError`` after ``close()`` — the daemon
        is gone, so enqueueing would silently drop the update."""
        if self._error is not None:
            raise self._error
        ids = np.asarray(ids).reshape(-1).copy()
        grad = np.asarray(grad, dtype='float32').reshape(
            len(ids), -1).copy()
        with self._close_lock:
            if self._closed:
                raise AsyncSparseClosedError()
            self._pushed += 1
            self._q.put((ids, grad))

    # -- batched row exchange (ISSUE 12: the two-tier embedding cache's
    # host-overflow API — the cache fetches a miss set's rows ahead of
    # the dispatch that needs them and writes dirty evicted rows back) --
    def fetch_rows(self, ids):
        """Batched row gather for the hot-row cache's miss set: current
        values of ``ids`` -> [len(ids), D].  Unlike ``prefetch`` this is
        the cache-exchange read: callers that need read-your-writes
        ordering against ``write_rows`` serialize on their own exchange
        pipeline (the cache's writeback events), not on the grad
        queue."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            return self._table[ids].copy()

    def write_rows(self, ids, rows):
        """Batched row SET (not a gradient): the cache's dirty-eviction
        writeback — the evicted rows' latest trained values replace the
        host master's.  Ids must be distinct (they are: one slab slot
        per id).  Raises the typed closed error after ``close()``."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, dtype='float32').reshape(len(ids), -1)
        with self._close_lock:
            if self._closed:
                raise AsyncSparseClosedError('write_rows')
            with self._lock:
                self._table[ids] = rows

    @property
    def shape(self):
        return self._table.shape

    @property
    def nbytes(self):
        return int(self._table.nbytes)

    # -- server side (reference listen_and_serv RunAsyncLoop) --
    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                # account for the shutdown sentinel too: a drain()
                # (or table()) issued AFTER close must not hang on
                # Queue.join()'s unfinished-task count
                self._q.task_done()
                return
            ids, grad = item
            try:
                with self._lock:
                    # duplicate ids in one batch must accumulate
                    np.subtract.at(self._table, ids, self._lr * grad)
                self._applied += 1
            except Exception as e:  # pragma: no cover - surfaced on push
                self._error = e
            finally:
                self._q.task_done()

    def drain(self):
        """Block until every pushed update is applied (checkpoint /
        end-of-pass barrier — the one sync point async training keeps,
        mirroring the reference's checkpoint_notify)."""
        self._q.join()
        if self._error is not None:
            raise self._error

    @property
    def stats(self):
        return {'pushed': self._pushed, 'applied': self._applied,
                'queued': self._q.qsize(),
                'close_join_timeouts': self._join_timeouts}

    def table(self):
        """A consistent snapshot of the table (drains first)."""
        self.drain()
        with self._lock:
            return self._table.copy()

    def close(self):
        """Shut the service down: every update pushed BEFORE close is
        applied (the pending queue drains fully before this returns),
        then the daemon exits.  Idempotent; a push that races close
        either lands in the drained queue or raises the typed
        ``AsyncSparseClosedError`` — never a silent drop."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self.drain()
        self._q.put(None)
        self._worker.join(timeout=self.JOIN_TIMEOUT_S)
        if self._worker.is_alive():
            # a wedged apply daemon must not masquerade as a clean
            # close: count it and say so — the table snapshot above
            # already drained, but the thread is still out there
            self._join_timeouts += 1
            import logging
            logging.getLogger(__name__).warning(
                'AsyncSparseEmbedding.close(): apply daemon did not '
                'join within %.1fs — thread left running (stats: %r)',
                self.JOIN_TIMEOUT_S, self.stats)

    # close()'s bound on waiting for the apply daemon to exit; a
    # timeout is counted in stats['close_join_timeouts'] and logged
    JOIN_TIMEOUT_S = 10.0

    @property
    def closed(self):
        return self._closed
