"""Elastic fault-tolerant training jobs (ISSUE 13).

PAPER.md's cloud story (§Go runtime): an EDL master dispatches RecordIO
chunk tasks via etcd to STATELESS trainers, and a checkpointing pserver
makes the job durable — a dead trainer's claimed task times out and is
re-dispatched, a restarted trainer resumes from the checkpoint, and the
trainer fleet can shrink or grow while the job runs.  This module is
that story end to end on the TPU-native stack:

``ElasticTrainJob`` owns the WHOLE job state:

  * **membership** — the worker registers with the master under a TTL
    lease (``Master.register_worker``/``heartbeat`` — the etcd
    registration dir) and a background heartbeat keeps it alive; when
    the live set changes (a peer's lease expires on host loss, or a
    new peer joins), the job re-forms its mesh at the surviving extent
    at the next dispatch boundary and re-shards live state through the
    existing GSPMD machinery (the sharded-checkpoint contract, in
    memory);
  * **data** — master-dispatched record-range tasks drain through a
    ``FeedPipeline`` source generator, so the task pull + record read
    + batch build OVERLAP device compute on the staging thread;
    ``task_finished`` is acked only AFTER the covering dispatch has
    synced (the pipeline's ``on_delivered`` hook) AND — when
    checkpointing is on — the manifest covering that step has durably
    COMMITTED (the store's ``on_commit`` callback), so acked work is
    always in the durable params: a worker killed mid-dispatch OR
    mid-commit leaves its claims to lease-timeout and re-dispatch,
    exactly go/master/service.go's recovery (the checkpoint's master
    cursor counts commit-gated acks as done, so a whole-job restore
    agrees with the params);
  * **durability** — periodic ASYNC sharded checkpoints
    (``AsyncShardedCheckpoint``): params + optimizer accumulators +
    the master task cursor + reader position + RNG, captured as host
    copies at the delivered-dispatch boundary (donated-safe: the next
    dispatch may donate the device buffers) and WRITTEN on a
    background thread so the step loop never blocks, with atomic
    manifest commit (tmp + rename) and bounded retention.  A restarted
    or replacement worker resumes from the newest manifest and replays
    nothing: acked work is in the params, unacked claims re-dispatch.

Job-level gauges (tasks done/failed/requeued, checkpoint age/bytes/
stall, membership epoch) ride the PR 6 metrics-source registry and the
trace watchdog; ``tools/perf_gate.py elastic`` gates the async
checkpoint overhead and the kill-resume goodput.

The checkpoint cursor is only consistent when no dispatch runs ahead of
delivery, so a checkpointing job pins ``pipeline_depth=1`` (staging
still overlaps compute — the input-pipeline win the elastic lane
actually needs; the deeper in-flight window is a serving-lane
optimization).
"""

import base64
import json
import os
import shutil
import threading
import time

import numpy as np

__all__ = ['ElasticTrainJob', 'AsyncShardedCheckpoint',
           'CheckpointWriteError', 'ElasticJobError']

MANIFEST_FMT = 'paddle-tpu-elastic-manifest'
MANIFEST_VERSION = 1
_MANIFEST_PREFIX = 'MANIFEST-'
_SHARDS_DIR = 'shards'
# liveness marker (ISSUE 17 satellite): written at store open, removed
# at close — AsyncShardedCheckpoint.gc() never touches a dir carrying
# one, so cross-job retention cannot eat a running job's manifests
_ACTIVE_MARKER = 'ACTIVE'


class CheckpointWriteError(RuntimeError):
    """The background checkpoint writer failed; raised (once) from
    ``wait()``/``close()`` so a silent writer death cannot masquerade
    as durability."""


class ElasticJobError(RuntimeError):
    """An ElasticTrainJob configuration/state error."""


def _save_shard(path, arr):
    from ..fluid import io as fluid_io
    fluid_io._save_one(path, arr)


def _load_shard(path):
    from ..fluid import io as fluid_io
    return fluid_io._load_one(path)


class AsyncShardedCheckpoint(object):
    """Sharded checkpoint store with async writes, atomic manifest
    commit and bounded retention.

    Layout under ``directory``::

        MANIFEST-<step>.json        # commit point (tmp + os.replace)
        shards/<step>/<var_name>    # one LoDTensor-format file per var

    ``save(step, arrays, extras)`` enqueues HOST arrays for a
    background writer (latest-wins: a save landing while the previous
    one is still writing REPLACES it and counts a ``stall`` — the step
    loop never blocks on checkpoint IO).  The manifest is written only
    after every shard landed, via tmp + rename, so a crash mid-write
    leaves a ``.tmp`` shard dir and no manifest — swept (with every
    other orphan) on open and after each retention prune: no manifest
    ever references a missing shard, and no shard file outlives its
    manifest.

    ``sync=True`` writes inline on the caller thread (the measured
    comparator lane for perf_gate ``elastic``)."""

    def __init__(self, directory, keep=3, sync=False):
        self.directory = directory
        self.keep = max(int(keep), 1)
        self.sync = bool(sync)
        os.makedirs(os.path.join(directory, _SHARDS_DIR), exist_ok=True)
        self._cond = threading.Condition()
        self._pending = None
        self._busy_since = None
        self._thread = None
        self._closed = False
        self._error = None
        self._m = {'saves': 0, 'stalls': 0, 'errors': 0,
                   'bytes_written': 0, 'last_step': None,
                   'last_commit_t': None}
        with open(os.path.join(directory, _ACTIVE_MARKER), 'w') as f:
            json.dump({'pid': os.getpid(), 'opened_t': time.time()}, f)
        self._sweep()  # crashed-write hygiene from a previous life

    # ---- paths ---------------------------------------------------------

    def _manifest_path(self, step):
        return os.path.join(self.directory,
                            '%s%012d.json' % (_MANIFEST_PREFIX, step))

    def _shard_dir(self, step):
        return os.path.join(self.directory, _SHARDS_DIR, '%012d' % step)

    def _manifest_steps(self):
        out = []
        for f in os.listdir(self.directory):
            if f.startswith(_MANIFEST_PREFIX) and f.endswith('.json'):
                try:
                    out.append(int(f[len(_MANIFEST_PREFIX):-5]))
                except ValueError:
                    continue
        return sorted(out)

    # ---- write side ----------------------------------------------------

    def save(self, step, arrays, extras=None, wait=False,
             on_commit=None):
        """Checkpoint ``arrays`` (name -> array) at ``step``.  Host
        copies are taken HERE, synchronously — after ``save`` returns
        the caller may donate/mutate the device buffers freely; only
        the serialization + disk write is deferred to the writer
        thread.  ``extras`` must be JSON-serializable (the master
        cursor blob rides base64-encoded).  ``on_commit(step)`` runs
        right after the manifest commit (on the writer thread; inline
        for a sync store) — the elastic job's ack-release point: work
        is reported finished only once its covering state is durable.
        A latest-wins-replaced save's callback is NOT invoked; the
        newer save's commit covers it."""
        if self._closed:
            raise CheckpointWriteError('checkpoint store is closed')
        item = (int(step),
                {n: np.asarray(a) for n, a in arrays.items()},
                dict(extras or {}), on_commit)
        if self.sync:
            self._write(item)
            if on_commit is not None:
                on_commit(int(step))
            return
        with self._cond:
            if self._closed:
                raise CheckpointWriteError('checkpoint store is closed')
            if self._pending is not None:
                # latest-wins: never block the step loop, never queue
                # unboundedly — the dropped save is a counted stall
                self._m['stalls'] += 1
            self._pending = item
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer_loop,
                    name='ckpt-writer-%s' % os.path.basename(
                        self.directory.rstrip(os.sep)),
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        if wait:
            self.wait()

    # an idle writer retires after this long; the next save() simply
    # starts a fresh one — so N short-lived checkpointing objects (e.g.
    # Trainers in a sweep) never accumulate N parked threads
    IDLE_EXIT_S = 5.0

    def _writer_loop(self):
        idle_deadline = time.time() + self.IDLE_EXIT_S
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    if time.time() >= idle_deadline:
                        self._thread = None  # save() restarts us
                        return
                    self._cond.wait(0.1)
                if self._pending is None and self._closed:
                    return
                item, self._pending = self._pending, None
                self._busy_since = time.time()
            try:
                self._write(item)
                if item[3] is not None:
                    # the commit callback runs BEFORE the busy flag
                    # clears, so wait() returning implies callbacks ran
                    item[3](item[0])
            except BaseException as e:  # surfaced by wait()/close()
                self._error = e
                self._m['errors'] += 1
            finally:
                with self._cond:
                    self._busy_since = None
                    self._cond.notify_all()
            idle_deadline = time.time() + self.IDLE_EXIT_S

    def _write(self, item):
        step, arrays, extras = item[0], item[1], item[2]
        sdir = self._shard_dir(step)
        tmp = sdir + '.tmp'
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        shards, nbytes = {}, 0
        for name, arr in arrays.items():
            # var names may contain '/'-unsafe chars only in exotic
            # programs; keep the flat name (the manifest records it)
            _save_shard(os.path.join(tmp, name), arr)
            shards[name] = '%s/%012d/%s' % (_SHARDS_DIR, step, name)
            nbytes += int(arr.nbytes)
        if os.path.isdir(sdir):
            # re-commit of the same step (e.g. the final checkpoint at
            # a step a periodic save already committed): retract the
            # MANIFEST FIRST so a crash inside this window leaves "no
            # manifest for this step" (resume falls back to the
            # previous retained manifest) — never a committed manifest
            # pointing at deleted shards
            mpath = self._manifest_path(step)
            if os.path.exists(mpath):
                os.remove(mpath)
            shutil.rmtree(sdir)
        os.replace(tmp, sdir)
        manifest = {
            'fmt': MANIFEST_FMT, 'version': MANIFEST_VERSION,
            'step': step, 'shards': shards, 'bytes': nbytes,
            'time': time.time(), 'extras': extras,
        }
        mpath = self._manifest_path(step)
        mtmp = mpath + '.tmp'
        with open(mtmp, 'w') as f:
            json.dump(manifest, f)
        os.replace(mtmp, mpath)  # the atomic commit point
        self._m['saves'] += 1
        self._m['bytes_written'] += nbytes
        self._m['last_step'] = step
        self._m['last_commit_t'] = time.time()
        self._sweep()

    def _sweep(self):
        """Retention + hygiene: keep the newest ``keep`` manifests;
        remove pruned manifests FIRST, then their shard dirs; then
        sweep every orphan — shard dirs without a live manifest
        (crashed prune), ``.tmp`` shard dirs and manifest tmps
        (crashed write)."""
        steps = self._manifest_steps()
        for step in steps[:-self.keep]:
            try:
                os.remove(self._manifest_path(step))
            except OSError:
                pass
        live = set(steps[-self.keep:])
        shards_root = os.path.join(self.directory, _SHARDS_DIR)
        for d in os.listdir(shards_root):
            base = d[:-4] if d.endswith('.tmp') else d
            try:
                step = int(base)
            except ValueError:
                step = None
            if d.endswith('.tmp') or step is None or step not in live:
                shutil.rmtree(os.path.join(shards_root, d),
                              ignore_errors=True)
        for f in os.listdir(self.directory):
            if f.startswith(_MANIFEST_PREFIX) and f.endswith('.json.tmp'):
                try:
                    os.remove(os.path.join(self.directory, f))
                except OSError:
                    pass

    # ---- read side -----------------------------------------------------

    def latest(self):
        """The newest committed manifest dict, or None."""
        steps = self._manifest_steps()
        if not steps:
            return None
        with open(self._manifest_path(steps[-1])) as f:
            return json.load(f)

    def load(self, manifest=None):
        """(step, {name: array}, extras) for ``manifest`` (default:
        newest)."""
        manifest = manifest if manifest is not None else self.latest()
        if manifest is None:
            raise CheckpointWriteError(
                'no committed checkpoint manifest under %s'
                % self.directory)
        arrays = {
            name: _load_shard(os.path.join(self.directory,
                                           *rel.split('/')))
            for name, rel in manifest['shards'].items()
        }
        return int(manifest['step']), arrays, dict(
            manifest.get('extras') or {})

    # ---- lifecycle / observability -------------------------------------

    def pending_age(self):
        """Seconds the writer has been busy on the CURRENT write (None
        when idle) — the watchdog's checkpoint-stall probe."""
        since = self._busy_since
        return (time.time() - since) if since is not None else None

    def wait(self, timeout=30.0):
        """Block until the writer drained (pending save committed);
        raises CheckpointWriteError if the writer failed."""
        deadline = time.time() + timeout
        with self._cond:
            while (self._pending is not None or
                   self._busy_since is not None):
                left = deadline - time.time()
                if left <= 0:
                    raise CheckpointWriteError(
                        'checkpoint writer did not drain in %.1fs'
                        % timeout)
                self._cond.wait(min(left, 0.1))
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointWriteError(
                'checkpoint write failed: %r' % (err, )) from err

    def metrics(self):
        m = dict(self._m)
        m['pending'] = self._pending is not None
        m['writing'] = self._busy_since is not None
        last = m['last_commit_t']
        m['age_s'] = (time.time() - last) if last else None
        return m

    @classmethod
    def gc(cls, root, keep_jobs=2, keep_hours=None):
        """Cross-job retention (ISSUE 17 satellite): ``root`` holds one
        checkpoint directory per job (the per-job stores already bound
        their own step retention with ``keep=``; what grows without
        bound is the number of FINISHED jobs).  Removes dead job dirs —
        committed manifests, shards and all — keeping the newest
        ``keep_jobs`` of them by last-manifest mtime.  ``keep_hours``
        (ISSUE 19 satellite) adds an age-based sweep on top of the
        count-based one: a dead store whose newest manifest is older
        than ``keep_hours`` hours is removed even when the
        ``keep_jobs`` count would have retained it.  Never touched:
        dirs carrying the ``ACTIVE`` marker (a live store; a crashed
        job's stale marker is the operator's to clear) and dirs that
        don't look like checkpoint stores at all (no manifests, no
        shards/).  Returns the removed paths."""
        if int(keep_jobs) < 0:
            raise ValueError('gc: keep_jobs must be >= 0')
        if keep_hours is not None and float(keep_hours) < 0:
            raise ValueError('gc: keep_hours must be >= 0')
        dead = []
        for name in sorted(os.listdir(root)):
            d = os.path.join(root, name)
            if not os.path.isdir(d):
                continue
            try:
                entries = os.listdir(d)
            except OSError:
                continue
            manifests = [f for f in entries
                         if f.startswith(_MANIFEST_PREFIX)
                         and f.endswith('.json')]
            if not manifests and _SHARDS_DIR not in entries:
                continue  # not a checkpoint store: never touch
            if _ACTIVE_MARKER in entries:
                continue  # live job: never touch
            newest = max([os.path.getmtime(os.path.join(d, f))
                          for f in manifests] or
                         [os.path.getmtime(d)])
            dead.append((newest, d))
        dead.sort()
        doomed = set(
            d for _, d in dead[:max(0, len(dead) - int(keep_jobs))])
        if keep_hours is not None:
            cutoff = time.time() - float(keep_hours) * 3600.0
            doomed.update(d for newest, d in dead if newest < cutoff)
        removed = []
        for _, d in dead:
            if d not in doomed:
                continue
            shutil.rmtree(d, ignore_errors=True)
            removed.append(d)
        return removed

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        try:
            os.remove(os.path.join(self.directory, _ACTIVE_MARKER))
        except OSError:
            pass
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointWriteError(
                'checkpoint write failed: %r' % (err, )) from err


class ElasticTrainJob(object):
    """One fault-tolerant elastic training job: master-fed data,
    heartbeat membership, async sharded checkpoints, dp shrink/grow.

    build_fn: rebuilds the model from scratch (a restarted worker must
        recreate identical var names, so it runs under
        ``unique_name.guard``); returns ``(main_program,
        startup_program, loss_var)``.
    master: an in-process ``distributed.Master`` or a ``MasterClient``
        dialing the job's ``MasterServer`` — the job only uses the
        shared get_task/task_finished/task_failed/new_pass/heartbeat/
        snapshot surface.  Pass ``endpoints=`` instead (master=None)
        to have the job own a ``ResilientMasterClient`` over that
        endpoint list (ISSUE 15): master RPCs then retry through
        transient faults, reconnect across a master restart and fail
        over in order to promoted standbys — the job rides a master
        restart mid-pass (reconnect -> the heartbeat re-registers ->
        epoch bump -> the existing mesh re-form path) instead of
        crashing on the first broken socket.  A task the failed-over
        master re-dispatches after THIS job already trained it (its
        ack died with the primary) is recognized by its record range
        and acked WITHOUT retraining — zero double-processed records
        across failover.
    ckpt_dir: the ``AsyncShardedCheckpoint`` directory; a newest
        manifest there is resumed from (params + optimizer
        accumulators + RNG restored; the master cursor rides the
        manifest for whole-job restarts via ``restore_master=True``).
    batch_fn: ``batch_fn(records) -> feed dict`` — one claimed task's
        raw record bytes become one training step's batch.
    mesh_for: ``mesh_for(n_live_workers) -> axes dict`` (e.g.
        ``lambda n: {'dp': 2 * n}``) — the job forms its mesh over the
        first ``prod(axes)`` devices and RE-FORMS it when membership
        changes; None runs the single-device ``Executor`` lane.
    steps_per_dispatch: tasks trained per device dispatch (the scan K).
    checkpoint_every: checkpoint every N delivered dispatches (0/None
        disables periodic checkpoints; the final state still commits).
    task_hook: ``task_hook(tid, task, ordinal)`` called on the staging
        thread right after a claim — test crash site (an exception here
        is a worker crash: claims are left to lease-timeout).
    """

    def __init__(self, build_fn, master, ckpt_dir, batch_fn,
                 worker_id='worker-0', steps_per_dispatch=1,
                 pipeline_depth=1, checkpoint_every=1,
                 keep_checkpoints=3, sync_checkpoints=False,
                 mesh_for=None, pass_num=1, poll_interval=0.05,
                 heartbeat_interval=1.0, task_hook=None, name=None,
                 watchdog_stall_s=None, restore_master=False,
                 fetch_list=None, endpoints=None, retry_policy=None):
        self._owns_master = False
        if endpoints is not None:
            if master is not None:
                raise ElasticJobError(
                    'pass master= OR endpoints=, not both')
            from .transport import ResilientMasterClient
            master = ResilientMasterClient(endpoints,
                                           retry=retry_policy)
            self._owns_master = True
        elif retry_policy is not None:
            raise ElasticJobError(
                'retry_policy= only applies to the endpoints= lane '
                '(an explicit master= owns its own fault handling)')
        if int(pipeline_depth) > 1 and checkpoint_every:
            # the checkpoint cursor reads the scope at delivery time;
            # a dispatch issued AHEAD of the delivered one would already
            # have advanced it past the acked tasks
            raise ElasticJobError(
                'a checkpointing ElasticTrainJob needs pipeline_depth=1 '
                '(the cursor must not run ahead of acked tasks); got '
                'depth %d' % int(pipeline_depth))
        if master is None:
            raise ElasticJobError(
                'ElasticTrainJob needs master= or endpoints=')
        self.build_fn = build_fn
        self.master = master
        self.ckpt_dir = ckpt_dir
        self.batch_fn = batch_fn
        self.worker_id = worker_id
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.pipeline_depth = int(pipeline_depth)
        self.checkpoint_every = int(checkpoint_every or 0)
        self.keep_checkpoints = int(keep_checkpoints)
        self.sync_checkpoints = bool(sync_checkpoints)
        self.mesh_for = mesh_for
        self.pass_num = int(pass_num)
        self.poll_interval = float(poll_interval)
        self.heartbeat_interval = float(heartbeat_interval)
        self.task_hook = task_hook
        self.watchdog_stall_s = watchdog_stall_s
        self.restore_master = bool(restore_master)
        self._extra_fetches = list(fetch_list or [])
        self.name = name or ('elastic-%s' % worker_id)

        self.resumed = False
        self.start_step = 0
        self.step = 0
        self.tasks_done = []
        self.losses = []
        self.ckpt = None
        self._exe = None
        self._scope = None
        self._main = self._startup = self._loss = None
        self._scanners = {}
        self._claims = {}  # ordinal -> (tid, task key)
        self._claims_lock = threading.Lock()
        # record ranges THIS job has delivered, mapped to the step
        # whose dispatch delivered them (their updates are in the live
        # params as of that step): a failed-over master re-dispatching
        # one — the ack died with the primary — is acked without
        # retraining.  The step gates that ack on durability when
        # checkpointing is on: ack-after-durability holds for dedup
        # acks exactly like trained acks.
        self._processed = {}
        self._dedup_pending = []  # staged dedup acks: (step, tid)
        # delivered-but-unacked tasks, each tagged with the step whose
        # manifest must COMMIT before the ack may go out (the
        # ack-after-durability contract; flushed by the store's
        # on_commit callback).  With checkpointing disabled there is
        # no durability to wait for and acks go out at delivery.
        self._pending_acks = []
        self._acks_lock = threading.Lock()
        self._ordinal = 0
        self._window_base = 0
        self._delivered_dispatches = 0
        self._cur_pass = 0
        self._pass_done = False
        self._stop = False
        self._resize_pending = False
        self._live = []
        self._formed_live = None  # the live set the executor is FOR
        self._epoch = 0
        self._members_lock = threading.Lock()
        self._hb_stop = None
        self._hb_thread = None
        self._m = {'tasks_done': 0, 'tasks_failed': 0,
                   'tasks_requeued': 0, 'tasks_deduped': 0,
                   'membership_epoch': 0,
                   'resizes': 0, 'dispatches': 0, 'heartbeats': 0,
                   'heartbeat_errors': 0, 'dp_extent': 0}
        self._metrics_key = None
        self._watchdog_probe = None

    # ---- membership ----------------------------------------------------

    def _note_members(self, epoch, workers):
        with self._members_lock:
            self._epoch = int(epoch)
            self._m['membership_epoch'] = self._epoch
            self._live = list(workers)
            # a resize is pending iff the live set differs from the set
            # the CURRENT executor was formed for — comparing against
            # _formed_live (not the previous observation) means a
            # change landing while the executor is still being built is
            # caught by _make_executor's own post-build check instead
            # of silently swallowed
            if self.mesh_for is not None and \
                    self._formed_live is not None and \
                    self._live != self._formed_live:
                self._resize_pending = True

    def _heartbeat_loop(self):
        while not self._hb_stop.wait(self.heartbeat_interval):
            try:
                epoch, workers = self.master.heartbeat(self.worker_id)
                self._m['heartbeats'] += 1
                self._note_members(epoch, workers)
            except Exception:
                # a dead master door: keep trying — the job itself will
                # fail on its next claim if the master is truly gone
                self._m['heartbeat_errors'] += 1

    def members(self):
        """(epoch, live worker ids) as last seen by the heartbeat."""
        with self._members_lock:
            return self._epoch, list(self._live)

    # ---- build / resume ------------------------------------------------

    def _build(self):
        import paddle_tpu.fluid as fluid
        self.ckpt = AsyncShardedCheckpoint(
            self.ckpt_dir, keep=self.keep_checkpoints,
            sync=self.sync_checkpoints)
        with fluid.unique_name.guard():
            self._main, self._startup, self._loss = self.build_fn()
        self._scope = fluid.core.Scope()
        exe0 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(self._scope):
            exe0.run(self._startup)
        self._rng_state = None
        manifest = self.ckpt.latest()
        if manifest is not None:
            step, arrays, extras = self.ckpt.load(manifest)
            with fluid.scope_guard(self._scope):
                for name, arr in arrays.items():
                    self._scope.var(name).set_value(arr)
            self.resumed = True
            self.start_step = self.step = step
            self._rng_state = extras.get('rng')
            self._cur_pass = int(extras.get('pass', 0))
            if self.restore_master and extras.get('master'):
                # whole-job restart: the manifest's cursor blob brings
                # the task queue back to the acked frontier (claimed
                # tasks return to todo — nothing replays, nothing is
                # lost)
                if not hasattr(self.master, 'restore'):
                    raise ElasticJobError(
                        'restore_master=True needs an in-process '
                        'Master (a MasterClient cannot rewrite the '
                        'remote queue); got %r' % type(self.master))
                self.master.restore(
                    base64.b64decode(extras['master']))

    def _persistable_names(self):
        from ..fluid import io as fluid_io
        return [v.name for v in self._main.list_vars()
                if fluid_io.is_persistable(v)]

    def _state_arrays(self):
        """Host copies of every persistable (params + optimizer
        accumulators), donated-safe: taken NOW, before the next
        dispatch can donate the device buffers."""
        from ..fluid import core
        out = {}
        for name in self._persistable_names():
            var = self._scope.find_var(name)
            if var is None or var.value() is None:
                continue
            val = var.value()
            if isinstance(val, core.LoDTensor):
                out[name] = val.numpy()
            else:
                out[name] = np.asarray(val)
        return out

    def _rng_snapshot(self):
        exe = self._exe
        if exe is None:
            return None
        if hasattr(exe, '_mesh'):
            key = exe._rng
            return None if key is None else \
                ['pe'] + [int(v) for v in np.asarray(key).ravel()]
        if exe._rng is None:
            return None
        return ['exe', int(exe._rng_seed), int(exe._rng)]

    def _rng_restore(self, state):
        if not state:
            return
        exe = self._exe
        if state[0] == 'pe' and hasattr(exe, '_mesh'):
            import jax.numpy as jnp
            exe._rng = jnp.asarray(np.array(state[1:], np.uint32))
        elif state[0] == 'exe' and not hasattr(exe, '_mesh'):
            exe._rng_seed, exe._rng = int(state[1]), int(state[2])

    def _make_executor(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu import parallel
        rng = self._rng_snapshot() or self._rng_state
        with self._members_lock:
            formed_for = list(self._live)
        if self.mesh_for is None:
            from ..fluid import core
            place = fluid.TPUPlace() if core.is_compiled_with_tpu() \
                else fluid.CPUPlace()
            self._exe = fluid.Executor(place)
            self._m['dp_extent'] = 1
        else:
            import jax
            n_live = max(1, len(formed_for))
            axes = dict(self.mesh_for(n_live))
            total = int(np.prod([s for s in axes.values()]))
            devices = jax.devices()[:total]
            if len(devices) < total:
                raise ElasticJobError(
                    'mesh_for(%d) wants %d devices, only %d exist'
                    % (n_live, total, len(devices)))
            mesh = parallel.make_mesh(axes, devices=devices)
            self._exe = fluid.ParallelExecutor(
                loss_name=self._loss.name, main_program=self._main,
                scope=self._scope, mesh=mesh)
            self._m['dp_extent'] = self._exe._dp_extent()
        self._rng_restore(rng)
        self._rng_state = None
        with self._members_lock:
            # the executor is now FOR formed_for; a membership change
            # that landed DURING the (slow) build re-arms the resize
            # instead of being lost
            self._formed_live = formed_for
            self._resize_pending = (self.mesh_for is not None and
                                    self._live != formed_for)

    def _gather_state_to_host(self):
        """Pull every persistable back to a host array in the scope so
        the NEXT executor re-shards it onto the new mesh (the in-memory
        form of the sharded-checkpoint save/load round trip)."""
        import paddle_tpu.fluid as fluid
        with fluid.scope_guard(self._scope):
            for name, arr in self._state_arrays().items():
                self._scope.var(name).set_value(arr)

    # ---- data ----------------------------------------------------------

    def _read_range(self, task):
        from ..runtime import native
        path = task['path']
        entry = self._scanners.get(path)
        if entry is None or entry[1] > task['start']:
            if entry is not None:
                entry[0].close()
            entry = [native.RecordIOScanner(path), 0]
            self._scanners[path] = entry
        scanner, pos = entry
        records = []
        try:
            while pos < task['start'] + task['count']:
                rec = next(scanner)
                if pos >= task['start']:
                    records.append(rec)
                pos += 1
        finally:
            entry[1] = pos
        return records

    def _task_source(self):
        """The FeedPipeline source: claim -> read -> batch, one yield
        per task, run on the STAGING thread so the whole pull overlaps
        device compute.  Stops at pass end or a pending resize.  Pass
        advancement is SHARED-safe (ISSUE 14): several workers drain
        one master and each reports pass end, so the advance is
        ``new_pass(expected=)`` on the pass this source observed — a
        peer's earlier advance makes ours a no-op instead of a double
        cursor bump (or a mid-pass recycle of the next pass's done
        tasks)."""
        master_pass = self.master.current_pass()
        while not self._stop and not self._resize_pending:
            tid, task = self.master.get_task()
            if tid == -1:
                self._cur_pass += 1
                # the dedup set is PER PASS: the next pass's re-
                # dispatch of every range is legitimate new work — a
                # stale entry would silently skip training the whole
                # pass (it also bounds the set's growth)
                self._processed.clear()
                if self._cur_pass >= self.pass_num:
                    self._pass_done = True
                    return
                if self.master.new_pass(expected=master_pass):
                    master_pass += 1
                else:
                    # a peer worker advanced first: resync to the
                    # master's cursor instead of double-advancing
                    master_pass = self.master.current_pass()
                continue
            if task is None:
                # nothing claimable RIGHT NOW: either a peer holds
                # claims, or OUR delivered-but-unacked tasks keep the
                # master's pending set nonempty (acks gate on a
                # manifest commit) — a frontier checkpoint releases
                # them, or the pass could never reach -1
                self._maybe_flush_frontier()
                time.sleep(self.poll_interval)
                continue
            key = (task['path'], int(task['start']),
                   int(task['count']))
            done_step = self._processed.get(key)
            if done_step is not None:
                # a failed-over (or restarted) master re-dispatched a
                # range this job already trained — the ack died with
                # the primary.  The update is in our params: ack it,
                # never retrain it (double-processing would skew the
                # final params vs a fault-free run).  Under
                # checkpointing the ack gates on durability like any
                # other: immediate only once a manifest covering the
                # delivering step committed, else staged for the
                # store's on_commit release.
                durable = True
                if self.checkpoint_every and self.ckpt is not None:
                    last = self.ckpt.metrics()['last_step']
                    durable = last is not None and last >= done_step
                if durable:
                    self.master.task_finished(tid)
                    self._m['tasks_deduped'] += 1
                else:
                    with self._acks_lock:
                        self._dedup_pending.append((done_step, tid))
                continue
            ordinal = self._ordinal
            with self._claims_lock:
                self._claims[ordinal] = (tid, key)
            if self.task_hook is not None:
                # crash site for the fault tests: an exception here is
                # a worker death — the claim above lease-times-out and
                # re-dispatches
                self.task_hook(tid, task, ordinal)
            try:
                records = self._read_range(task)
                feed = self.batch_fn(records)
            except Exception:
                # a bad chunk read fails the task back for another
                # trainer (or retry) — cloud_reader's contract
                with self._claims_lock:
                    self._claims.pop(ordinal, None)
                entry = self._scanners.pop(task['path'], None)
                if entry is not None:
                    entry[0].close()
                self.master.task_failed(tid)
                self._m['tasks_failed'] += 1
                continue
            self._ordinal += 1
            yield feed

    def _on_delivered(self, ordinals, fetches):
        """The pipeline's post-sync hook: the dispatch covering these
        source ordinals has completed on device — the step cursor
        advances and a checkpoint boundary may capture a consistent
        (params, cursor) pair.  The tasks' ACKS are only STAGED here:
        ``task_finished`` goes out when a manifest covering this step
        COMMITS (the store's on_commit callback), so a crash between
        delivery and durability re-dispatches the tasks and the
        replacement retrains them from a manifest that excludes them —
        acked work is ALWAYS in the durable params.  (The residual
        window — manifest committed, ack still in flight when the
        worker dies — re-trains a task whose update was already saved,
        the same at-least-once boundary as the reference's in-flight
        TaskFinished RPC.)  With checkpointing disabled acks go out
        immediately."""
        # pipeline ordinals are window-local (a re-formed mesh gets a
        # fresh pipeline counting from 0); the job's claim keys are
        # global, offset by the window's first ordinal
        ordinals = [self._window_base + o for o in ordinals]
        delivered = []
        with self._claims_lock:
            for o in ordinals:
                ent = self._claims.pop(o, None)
                if ent is not None:
                    delivered.append(ent[0])
                    self._processed[ent[1]] = self.step + len(ordinals)
        self.step += len(ordinals)
        self._m['dispatches'] += 1
        self._delivered_dispatches += 1
        if self.checkpoint_every:
            with self._acks_lock:
                self._pending_acks.extend(
                    (self.step, tid) for tid in delivered)
        else:
            self._send_acks(delivered)
        if fetches:
            try:
                self.losses.append(float(np.asarray(fetches[0]).ravel()[0]))
            except (TypeError, ValueError, IndexError):
                pass
        if self.checkpoint_every and \
                self._delivered_dispatches % self.checkpoint_every == 0:
            self.checkpoint()

    def _send_acks(self, tids):
        for tid in tids:
            self.master.task_finished(tid)
        self.tasks_done.extend(tids)
        self._m['tasks_done'] += len(tids)

    def _flush_acks_up_to(self, committed_step):
        """The store's on_commit callback: release every staged ack
        whose covering step is now durable — trained acks and staged
        DEDUP acks (re-dispatched ranges whose delivering step had
        not committed yet) alike."""
        with self._acks_lock:
            ready = [tid for s, tid in self._pending_acks
                     if s <= committed_step]
            self._pending_acks = [(s, tid) for s, tid in
                                  self._pending_acks
                                  if s > committed_step]
            dedup_ready = [tid for s, tid in self._dedup_pending
                           if s <= committed_step]
            self._dedup_pending = [(s, tid) for s, tid in
                                   self._dedup_pending
                                   if s > committed_step]
        self._send_acks(ready)
        for tid in dedup_ready:
            self.master.task_finished(tid)
            self._m['tasks_deduped'] += 1

    def _maybe_flush_frontier(self):
        """Ack-after-durability's liveness guard: when every claim is
        delivered, staged acks are waiting, and no save is in flight,
        take a frontier checkpoint — its commit releases the acks.
        Safe from the staging thread: all claims delivered plus the
        depth-1 pipeline means no dispatch is mutating the scope (the
        run thread is blocked on the staging queue)."""
        if not self.checkpoint_every or self.ckpt is None:
            return
        with self._acks_lock:
            if not self._pending_acks and not self._dedup_pending:
                return
        with self._claims_lock:
            if self._claims:
                return  # a dispatch may still be in flight
        m = self.ckpt.metrics()
        if m['pending'] or m['writing']:
            return  # that save's commit will flush the acks
        self.checkpoint()

    # ---- durability ----------------------------------------------------

    def _master_cursor(self):
        """The master queue state as an envelope blob (b64 str), via
        whichever surface this job's master exposes — rewritten so
        tasks whose updates are IN the params being checkpointed (acks
        staged, waiting on this very manifest's commit) count as done:
        a whole-job restore must not re-dispatch work the params
        already hold.  Staged acks are read BEFORE the snapshot, so an
        ack flushing in between is completed twice — a no-op."""
        with self._acks_lock:
            # staged DEDUP acks are in the params too (their update
            # landed at their original delivery): the cursor rewrite
            # completes both kinds
            staged = [tid for _s, tid in self._pending_acks] + \
                [tid for _s, tid in self._dedup_pending]
        try:
            if hasattr(self.master, 'snapshot'):
                blob = self.master.snapshot()
            elif hasattr(self.master, 'fetch_snapshot'):
                blob, _seq = self.master.fetch_snapshot()
            else:
                return None
            if staged:
                from .master import complete_tasks_in_blob
                blob = complete_tasks_in_blob(blob, staged)
        except Exception:
            return None  # a cursor-less checkpoint still resumes params
        return base64.b64encode(blob).decode()

    def checkpoint(self, wait=False):
        """Capture (params + accumulators, master cursor, reader
        position, RNG) at the current delivered frontier and hand it to
        the async writer."""
        extras = {
            'step': self.step,
            'pass': self._cur_pass,
            'rng': self._rng_snapshot(),
            'worker': self.worker_id,
            'epoch': self._epoch,
            'master': self._master_cursor(),
        }
        self.ckpt.save(self.step, self._state_arrays(), extras,
                       wait=wait, on_commit=self._flush_acks_up_to)

    # ---- the run loop --------------------------------------------------

    def _run_window(self):
        """One FeedPipeline lifetime: runs until pass end, a pending
        resize, or a source crash (which propagates — crash
        semantics)."""
        from ..fluid.dataflow import FeedPipeline
        import paddle_tpu.fluid as fluid
        self._window_base = self._ordinal
        fetch_list = [self._loss] + self._extra_fetches
        kwargs = {}
        if not hasattr(self._exe, '_mesh'):
            kwargs = {'program': self._main, 'scope': self._scope}
        pipe = FeedPipeline(
            self._exe, fetch_list=fetch_list,
            source=self._task_source(),
            steps=self.steps_per_dispatch,
            pipeline_depth=self.pipeline_depth,
            name='%s-pipe' % self.name,
            watchdog_stall_s=self.watchdog_stall_s,
            on_delivered=self._on_delivered, **kwargs)
        try:
            with fluid.scope_guard(self._scope):
                for _ in pipe:
                    pass  # acks/steps/checkpoints ride _on_delivered
        finally:
            self._last_pipe_metrics = pipe.metrics()
            # a crash-path close never re-raises here: the iteration
            # above already delivered the typed error once
            pipe.close()

    def _requeue_unacked(self):
        """Safety sweep at a clean window boundary: fail back any
        claim that never reached a delivered dispatch so the re-formed
        job (or a peer) gets it immediately instead of waiting out the
        lease."""
        with self._claims_lock:
            pending = list(self._claims.items())
            self._claims.clear()
        for _ordinal, (tid, _key) in pending:
            try:
                self.master.task_failed(tid)
                self._m['tasks_requeued'] += 1
            except Exception:
                pass  # the lease will expire on its own

    def _resize(self):
        """Re-form the mesh at the surviving extent: host-ify live
        state, rebuild the executor over the new mesh (GSPMD re-shards
        on the next dispatch), resume draining."""
        self._requeue_unacked()
        self._gather_state_to_host()
        self._make_executor()  # owns re-arming/clearing _resize_pending
        self._m['resizes'] += 1

    def _register_observability(self):
        from ..fluid import profiler as _profiler
        from ..fluid import trace as _trace
        import weakref
        ref = weakref.ref(self)
        self._metrics_fn = lambda: (ref().metrics() if ref() else None)
        self._metrics_key = _profiler.register_metrics_source(
            self.name, self._metrics_fn)
        weakref.finalize(self, _profiler.unregister_metrics_source,
                         self._metrics_key, self._metrics_fn)
        if self.watchdog_stall_s is not None:
            def age(ref=ref):
                job = ref()
                return job.ckpt.pending_age() if job and job.ckpt \
                    else None
            self._watchdog_probe = _trace.watchdog.register(
                'elastic/%s/checkpoint_stall' % self.name, age,
                float(self.watchdog_stall_s))
            self._watchdog_age_fn = age
            weakref.finalize(self, _trace.watchdog.unregister,
                             self._watchdog_probe, age)
            if hasattr(self.master, 'unreachable_age'):
                # master-unreachable probe (ISSUE 15): the resilient
                # client tracks how long the control plane has been
                # continuously failing — a dead master past the stall
                # threshold dumps the flight recorder once per episode
                def m_age(ref=ref):
                    job = ref()
                    return job.master.unreachable_age() if job \
                        else None
                self._master_probe = _trace.watchdog.register(
                    'elastic/%s/master_unreachable' % self.name,
                    m_age, float(self.watchdog_stall_s))
                self._master_age_fn = m_age
                weakref.finalize(self, _trace.watchdog.unregister,
                                 self._master_probe, m_age)

    def run(self):
        """Drive the job to the end of its pass budget.  Crash
        semantics on error: heartbeats stop, claims are left to
        lease-timeout, the exception propagates (a replacement job over
        the same ckpt_dir resumes from the newest manifest)."""
        epoch, workers = self.master.register_worker(self.worker_id)
        self._note_members(epoch, workers)
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name='%s-hb' % self.name,
            daemon=True)
        self._hb_thread.start()
        self._register_observability()
        try:
            self._build()
            self._make_executor()
            while not self._pass_done and not self._stop:
                self._run_window()
                if self._resize_pending and not self._pass_done:
                    self._resize()
            # final durable state: commit and WAIT (the job is done —
            # there is no step loop left to overlap with)
            if self.ckpt is not None:
                self.checkpoint(wait=not self.sync_checkpoints)
            # stop heartbeats BEFORE deregistering: a racing renewal
            # after the deregister would re-register this finished
            # worker as a ghost member (and spuriously resize peers)
            self._stop_heartbeat()
            self._deregister()
            return self
        except BaseException:
            self._abort()
            raise
        finally:
            self._stop_heartbeat()
            for entry in self._scanners.values():
                entry[0].close()
            self._scanners.clear()

    def stop(self):
        """Graceful stop request (takes effect at the next claim)."""
        self._stop = True

    def _deregister(self):
        try:
            self.master.deregister_worker(self.worker_id)
        except Exception:
            pass

    def _abort(self):
        """Crash semantics: claims stay (their leases will expire and
        re-dispatch), no deregistration — the master sees exactly what
        it would see of a dead host.  The checkpoint writer is drained
        (best effort) so the in-process crash SIMULATION quiesces to
        one of the two real post-mortem states — manifest committed
        AND its acks flushed, or neither — never a half-state where a
        later background commit races the replacement's resume."""
        self._stop = True
        if self.ckpt is not None:
            try:
                self.ckpt.wait(timeout=30)
            except Exception:
                pass

    def _stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    def metrics(self):
        m = dict(self._m)
        m['step'] = self.step
        m['start_step'] = self.start_step
        m['resumed'] = self.resumed
        if self.ckpt is not None:
            ck = self.ckpt.metrics()
            m['checkpoint_age_s'] = ck.pop('age_s')
            m['checkpoint_bytes'] = ck['bytes_written']
            m['checkpoint_stalls'] = ck['stalls']
            m['checkpoint'] = ck
        if hasattr(self.master, 'metrics'):
            # the resilient-lane gauges (ISSUE 15): how hard the
            # control plane is working to stay connected
            mc = self.master.metrics()
            m['master_retries'] = mc.get('retries', 0)
            m['master_reconnects'] = mc.get('reconnects', 0)
            m['master_failovers'] = mc.get('failovers', 0)
            m['master_unreachable_s'] = mc.get('unreachable_s')
            m['master_client'] = mc
        return m

    def close(self):
        """Release the checkpoint writer (idempotent)."""
        self._stop_heartbeat()
        if self.ckpt is not None:
            self.ckpt.close()
        if self._owns_master:
            try:
                self.master.close()
            except Exception:
                pass
