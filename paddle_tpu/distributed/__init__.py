"""Distributed/EDL runtime pieces outside the SPMD compute path
(reference: go/ — master task queue, pserver; SURVEY §2.2)."""

from .master import Master, TaskQueuePyFallback, cloud_reader  # noqa: F401
