"""Distributed/EDL runtime pieces outside the SPMD compute path
(reference: go/ — master task queue, pserver; SURVEY §2.2)."""

from .master import Master, TaskQueuePyFallback, cloud_reader, \
    SnapshotReplica  # noqa: F401
from .master_server import MasterServer, MasterClient  # noqa: F401
from .transport import ResilientMasterClient, ResilientServiceClient, \
    RetryPolicy, ServiceServer, DedupWindow, \
    MasterUnavailableError, MasterProtocolError, \
    ServiceUnavailableError, ServiceProtocolError  # noqa: F401
from .faults import FaultInjector, InjectedFault  # noqa: F401
from .async_sparse import AsyncSparseEmbedding, \
    AsyncSparseClosedError  # noqa: F401
from .embed_cache import CachedEmbeddingTable, EmbedCacheCapacityError, \
    optimizer_accumulator_vars  # noqa: F401
from .elastic import ElasticTrainJob, AsyncShardedCheckpoint, \
    CheckpointWriteError, ElasticJobError  # noqa: F401
from .pserver import PServerShard, ShardedEmbeddingClient, \
    shard_row_ranges, sharded_cache_from_scope  # noqa: F401
