"""Fault-tolerant dataset task-queue master + trainer client.

Reference: the Go master (go/master/service.go — task partition,
GetTask/TaskFinished/TaskFailed, timeouts, failureMax, snapshot/recover;
go/master/etcd_client.go — etcd lock + state store; consumed from Python by
python/paddle/v2/master/client.py and v2/reader/creator.py cloud_reader).

TPU-native deployment: the queue engine is native C++ (csrc/master.cc via
ctypes); coordination runs over a shared filesystem — a pidfile lock
replaces the etcd distributed master lock, and the snapshot blob persists
to a file instead of an etcd key.  Trainers remain stateless: a dead
trainer's claimed task times out and is re-dispatched; a restarted master
recovers the queue from the last snapshot with claimed tasks returned to
the todo queue.
"""

import base64
import ctypes
import json
import os
import struct
import threading
import time
from collections import OrderedDict

from ..runtime import native

# versioned snapshot envelope (ISSUE 13): the engine blob wrapped with
# the pass/cursor fields an elastic job checkpoint needs — pass number,
# todo/doing/done/discarded counts and per-task failure counts all
# survive a master restart.  Old raw blobs (either engine's) still
# restore; bump the version when the envelope grows NEW fields so old
# masters can refuse blobs they cannot represent.  v3 (ISSUE 15) added
# the per-client RPC dedup window, so exactly-once across retries
# survives failover to a standby restored from a replicated snapshot.
SNAPSHOT_FMT = 'paddle-tpu-master-snapshot'
SNAPSHOT_VERSION = 3

_NATIVE_MAGIC = 0x301076736d  # csrc/master.cc kSnapshotMagic


def _parse_engine_blob(blob, payloads=False):
    """Decode either engine's snapshot blob into
    {'todo': [(tid, failures)], 'done': [...], 'next_id', 'discarded'}
    — the cursor view the envelope mirrors.  With ``payloads`` each
    task triple carries its payload bytes too (the rewrite path needs
    them; the plain cursor view drops them — the blob itself stays the
    restore authority)."""
    blob = bytes(blob)
    if len(blob) >= 8 and struct.unpack('<q', blob[:8])[0] == _NATIVE_MAGIC:
        pos = [8]

        def i64():
            v, = struct.unpack_from('<q', blob, pos[0])
            pos[0] += 8
            return v

        def tasks():
            out = []
            for _ in range(i64()):
                tid, failures, n = i64(), i64(), i64()
                payload = blob[pos[0]:pos[0] + n]
                pos[0] += n
                out.append((tid, failures, payload) if payloads
                           else (tid, failures))
            return out

        todo = tasks()
        done = tasks()
        return {'todo': todo, 'done': done, 'next_id': i64(),
                'discarded': i64()}
    state = json.loads(blob.decode())

    def conv(items):
        return [(t, f, p.encode('latin-1')) if payloads else (t, f)
                for t, f, p in items]

    return {'todo': conv(state['todo']), 'done': conv(state['done']),
            'next_id': state['next_id'],
            'discarded': state['discarded']}


def complete_tasks_in_blob(blob, tids):
    """Rewrite a snapshot (envelope or raw engine blob) so ``tids``
    count as DONE.  The elastic job's checkpoint stores the master
    cursor AS OF ITS PARAMS: a task whose update is already in the
    checkpointed params but whose ack is still gated on the manifest
    commit must not be re-dispatched by a whole-job restore.  Returns
    a versioned envelope whose engine blob is the portable fallback-
    JSON format (both engines restore it)."""
    env = _parse_envelope(blob)
    pass_num = 0
    engine = bytes(blob)
    if env is not None:
        pass_num = int(env.get('pass_num', 0))
        engine = base64.b64decode(env['engine'])
    state = _parse_engine_blob(engine, payloads=True)
    tids = set(int(t) for t in tids)
    moved = [t for t in state['todo'] if t[0] in tids]
    todo = [t for t in state['todo'] if t[0] not in tids]
    done = state['done'] + moved
    engine_json = json.dumps({
        'todo': [(t, f, p.decode('latin-1')) for t, f, p in todo],
        'done': [(t, f, p.decode('latin-1')) for t, f, p in done],
        'next_id': state['next_id'],
        'discarded': state['discarded'],
    }).encode()
    return json.dumps({
        'fmt': SNAPSHOT_FMT,
        'version': SNAPSHOT_VERSION,
        'pass_num': pass_num,
        'counts': [len(todo), 0, len(done), state['discarded']],
        'failures': {str(t): f for t, f, _ in todo + done if f},
        # the dedup window rides the rewrite untouched: a restored
        # master must still replay recorded responses for retries in
        # flight across the restore
        'dedup': (env.get('dedup') or {}) if env is not None else {},
        'engine': base64.b64encode(engine_json).decode(),
    }).encode()


def _parse_envelope(blob):
    """The decoded envelope dict, or None when ``blob`` is any legacy
    format (raw engine binary, fallback JSON, garbage — the caller's
    legacy path decides what to do with those)."""
    head = bytes(blob).lstrip()[:1]
    if head != b'{':
        return None
    try:
        env = json.loads(bytes(blob).decode())
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(env, dict) or env.get('fmt') != SNAPSHOT_FMT:
        return None
    return env


class TaskQueuePyFallback(object):
    """Pure-Python queue engine with the semantics of csrc/master.cc, used
    when the native lib is unavailable.  Lock-guarded like the native
    engine's std::mutex: an in-process elastic job drives the queue
    from several threads at once (staging-thread claims, writer-thread
    acks, run-thread snapshots)."""

    def __init__(self, timeout_secs, failure_max):
        self.timeout_secs = timeout_secs
        self.failure_max = failure_max
        self.todo = []  # (id, failures, payload)
        self.pending = {}  # id -> (failures, payload, deadline)
        self.done = []
        self.discarded = 0
        self.next_id = 1
        self._mu = threading.Lock()

    def _requeue(self):
        now = time.monotonic()
        for tid in list(self.pending):
            failures, payload, deadline = self.pending[tid]
            if deadline <= now:
                del self.pending[tid]
                failures += 1
                if failures >= self.failure_max:
                    self.discarded += 1
                else:
                    self.todo.append((tid, failures, payload))

    def add_task(self, payload):
        with self._mu:
            tid = self.next_id
            self.next_id += 1
            self.todo.append((tid, 0, payload))
            return tid

    def get_task(self):
        with self._mu:
            self._requeue()
            if not self.todo:
                return (None, None) if self.pending else (-1, None)
            tid, failures, payload = self.todo.pop(0)
            self.pending[tid] = (failures, payload,
                                 time.monotonic() + self.timeout_secs)
            return tid, payload

    def task_finished(self, tid):
        with self._mu:
            if tid in self.pending:
                failures, payload, _ = self.pending.pop(tid)
                self.done.append((tid, failures, payload))

    def task_failed(self, tid):
        with self._mu:
            if tid not in self.pending:
                return -1
            failures, payload, _ = self.pending.pop(tid)
            failures += 1
            if failures >= self.failure_max:
                self.discarded += 1
                return 1
            self.todo.append((tid, failures, payload))
            return 0

    def new_pass(self):
        with self._mu:
            self.todo.extend((tid, 0, payload)
                             for tid, _, payload in self.done)
            self.done = []

    def counts(self):
        with self._mu:
            self._requeue()
            return (len(self.todo), len(self.pending), len(self.done),
                    self.discarded)

    def snapshot(self):
        with self._mu:
            self._requeue()
            state = {
                'todo': [(t, f, p.decode('latin-1'))
                         for t, f, p in self.todo] +
                        [(t, f, p.decode('latin-1'))
                         for t, (f, p, _) in self.pending.items()],
                'done': [(t, f, p.decode('latin-1'))
                         for t, f, p in self.done],
                'next_id': self.next_id,
                'discarded': self.discarded,
            }
            return json.dumps(state).encode()

    def restore(self, blob):
        state = json.loads(bytes(blob).decode())
        with self._mu:
            self.todo = [(t, f, p.encode('latin-1'))
                         for t, f, p in state['todo']]
            self.pending = {}
            self.done = [(t, f, p.encode('latin-1'))
                         for t, f, p in state['done']]
            self.next_id = state['next_id']
            self.discarded = state['discarded']


class _NativeQueue(object):
    """ctypes façade over csrc/master.cc with the fallback's interface."""

    def __init__(self, lib, timeout_secs, failure_max):
        self._lib = lib
        self._h = lib.ms_create(float(timeout_secs), int(failure_max))
        self._cap = 1 << 12

    def add_task(self, payload):
        return int(self._lib.ms_add_task(self._h, bytes(payload),
                                         len(payload)))

    def get_task(self):
        cap = self._cap
        while True:
            buf = ctypes.create_string_buffer(cap)
            tid = ctypes.c_int64()
            n = self._lib.ms_get_task(self._h, buf, cap,
                                      ctypes.byref(tid))
            if n == -1:
                return -1, None  # pass finished
            if n == -2:
                return None, None  # wait: tasks claimed elsewhere
            if n <= -3:
                cap = -(n + 3)
                self._cap = max(self._cap, cap)
                continue
            return int(tid.value), buf.raw[:n]

    def task_finished(self, tid):
        self._lib.ms_task_finished(self._h, tid)

    def task_failed(self, tid):
        return int(self._lib.ms_task_failed(self._h, tid))

    def new_pass(self):
        self._lib.ms_new_pass(self._h)

    def counts(self):
        arr = (ctypes.c_int64 * 4)()
        self._lib.ms_counts(self._h, arr)
        return tuple(int(v) for v in arr)

    def snapshot(self):
        cap = self._cap
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.ms_snapshot(self._h, buf, cap)
            if n <= -3:
                cap = -(n + 3)
                self._cap = max(self._cap, cap)
                continue
            return buf.raw[:n]

    def restore(self, blob):
        if self._lib.ms_restore(self._h, bytes(blob), len(blob)) != 0:
            raise IOError('corrupt master snapshot blob')

    def __del__(self):
        try:
            self._lib.ms_destroy(self._h)
        except Exception:
            pass


class Master(object):
    """The master service: dataset partition + claimable task queue +
    snapshot persistence + single-active-master pidfile lock.

    store_path: directory for the snapshot + lock (the etcd stand-in).
    A restarted master recovers the queue from the last snapshot there.
    """

    def __init__(self, store_path=None, chunk_timeout_secs=60,
                 failure_max=3, worker_lease_secs=10.0):
        lib = native._load()
        if lib is not None:
            self._q = _NativeQueue(lib, chunk_timeout_secs, failure_max)
        else:
            self._q = TaskQueuePyFallback(chunk_timeout_secs, failure_max)
        self.store_path = store_path
        self._lock_fd = None
        self._events = 0
        # pass cursor (ISSUE 13): which dataset pass the queue is on —
        # rides the versioned snapshot envelope so a restarted master
        # (or a job resuming from a checkpointed cursor) knows where the
        # run was, not just which tasks remain
        self.pass_num = 0
        # worker membership (the etcd-registration shape, PAPER.md's EDL
        # master): worker id -> lease deadline; every join/leave/expiry
        # bumps the epoch an elastic job re-forms its mesh on
        self.worker_lease_secs = float(worker_lease_secs)
        self._members = {}
        self._membership_epoch = 0
        self._members_lock = threading.Lock()
        # guards the new_pass check-then-advance (ISSUE 14): several
        # workers share one master and each reports pass end — the
        # compare must be atomic with the advance or two observers of
        # the same -1 double-advance the cursor
        self._pass_lock = threading.Lock()
        # monotone mutation counter: EVERY queue-state change bumps it
        # (set_dataset, claims, finish/fail, new_pass, restore) — the
        # replication door keys snapshot freshness on this, and keying
        # on _events alone let set_dataset-only state slip past pull()
        self._seq = 0
        # per-client RPC dedup window (ISSUE 15): client -> OrderedDict
        # of request id -> recorded response.  A retried mutation whose
        # first response was lost replays the record instead of
        # re-executing (exactly-once across retries); rides the
        # snapshot envelope so it survives failover.  RLock: recording
        # a forced snapshot (task_failed discard) re-enters through
        # snapshot()'s own dedup read.
        self._dedup = OrderedDict()
        self._dedup_lock = threading.RLock()
        if store_path:
            os.makedirs(store_path, exist_ok=True)
            self._acquire_lock()
            snap = os.path.join(store_path, 'master_snapshot.bin')
            if os.path.exists(snap):
                with open(snap, 'rb') as f:
                    self.restore(f.read())

    # bounds for the RPC dedup window: retries always carry the
    # client's LATEST request id (calls are serialized client-side),
    # so a short per-client history suffices; the client LRU keeps a
    # worker churn from growing the envelope without bound
    DEDUP_WINDOW = 64
    DEDUP_CLIENTS = 64

    def dedup_execute(self, client, rid, fn):
        """Run ``fn()`` (one RPC dispatch returning a response dict)
        exactly once per (client, rid): a repeat — a client retrying
        after a lost response — REPLAYS the recorded response.  Error
        responses are recorded too (a refusal must replay as the same
        refusal).  The window is bounded per client and across
        clients (LRU)."""
        with self._dedup_lock:
            win = self._dedup.get(client)
            if win is not None and rid in win:
                self._dedup.move_to_end(client)
                return win[rid]
            resp = fn()
            if win is None:
                win = self._dedup[client] = OrderedDict()
                while len(self._dedup) > self.DEDUP_CLIENTS:
                    self._dedup.popitem(last=False)
            self._dedup.move_to_end(client)
            win[rid] = resp
            while len(win) > self.DEDUP_WINDOW:
                win.popitem(last=False)
            # deliberately NO _seq bump for the record itself: any
            # call that MUTATED queue state already bumped it (so the
            # replica re-pulls and its window replays too), while a
            # no-op's record (an idle get_task poll, a task_failed
            # miss) is safe to lose — re-executing it on a standby
            # returns the identical response.  Bumping here would
            # make every idle poll re-mirror the whole snapshot.
            return resp

    def snapshot(self):
        """The versioned snapshot envelope: the engine blob plus the
        pass/cursor fields a job checkpoint introspects (pass_num,
        todo/doing/done/discarded counts, per-task failure counts)
        and the RPC dedup window.  ``restore()`` round-trips it; raw
        engine blobs (old snapshots) still restore."""
        blob = self._q.snapshot()
        cursor = _parse_engine_blob(blob)
        with self._dedup_lock:
            dedup = {c: [[r, resp] for r, resp in win.items()]
                     for c, win in self._dedup.items()}
        env = {
            'fmt': SNAPSHOT_FMT,
            'version': SNAPSHOT_VERSION,
            'pass_num': self.pass_num,
            # the engine snapshot folds pending into todo (claimants
            # presumed dead on recovery), so counts here are the
            # RESTORED view: (todo+doing, 0, done, discarded)
            'counts': [len(cursor['todo']), 0, len(cursor['done']),
                       cursor['discarded']],
            'failures': {str(t): f for t, f in
                         cursor['todo'] + cursor['done'] if f},
            'dedup': dedup,
            'engine': base64.b64encode(blob).decode(),
        }
        return json.dumps(env).encode()

    def restore(self, blob):
        """Restore from a versioned envelope OR any legacy blob (raw
        native binary / fallback JSON / cross-engine)."""
        env = _parse_envelope(blob)
        if env is not None:
            if env['version'] > SNAPSHOT_VERSION:
                raise IOError(
                    'master snapshot envelope version %d is newer than '
                    'this master (%d)' % (env['version'],
                                          SNAPSHOT_VERSION))
            self._restore_blob(base64.b64decode(env['engine']))
            self.pass_num = int(env.get('pass_num', 0))
            dedup = env.get('dedup') or {}
        else:
            self._restore_blob(blob)
            dedup = {}
        with self._dedup_lock:
            self._dedup = OrderedDict(
                (c, OrderedDict((r, resp) for r, resp in win))
                for c, win in dedup.items())
        self._seq += 1

    def _restore_blob(self, blob):
        """Restore from either engine's snapshot format: the native engine
        writes a magic-tagged binary blob, the fallback writes JSON.  A
        snapshot from the *other* engine (e.g. a host without the native
        lib wrote JSON, then a native master restarts) is translated by
        re-enqueueing its tasks."""
        try:
            self._q.restore(blob)
            return
        except (IOError, ValueError, KeyError, UnicodeDecodeError):
            pass
        if not blob.lstrip()[:1] == b'{':
            raise IOError(
                'master snapshot is neither this engine\'s format nor '
                'JSON — refusing to guess (delete %s to start fresh)' %
                os.path.join(self.store_path or '', 'master_snapshot.bin'))
        state = json.loads(bytes(blob).decode())
        # done tasks first: claim+finish each so pass accounting survives
        for _, _, payload in state.get('done', []):
            tid = self._q.add_task(payload.encode('latin-1'))
            got, _ = self._q.get_task()
            self._q.task_finished(got if got is not None else tid)
        for _, _, payload in state.get('todo', []):
            self._q.add_task(payload.encode('latin-1'))

    # -- etcd-lock analog: flock on a stable lockfile.  flock acquisition
    # is atomic in the kernel and the lock dies with the holder, so there
    # is no stale-pid read/steal window for two masters to race through --
    def _acquire_lock(self):
        import fcntl
        path = os.path.join(self.store_path, 'master.lock')
        fd = os.open(path, os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            try:
                with open(path) as f:
                    owner = f.read().strip()
            except IOError:
                owner = '?'
            raise RuntimeError(
                'another master (pid %s) holds the lock at %s' %
                (owner or '?', path))
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._lock_fd = fd
        self._lock_path = path

    def close(self):
        self.snapshot_to_store()  # final flush before releasing the lock
        if self._lock_fd is not None:
            os.close(self._lock_fd)  # releases the flock
            self._lock_fd = None

    # -- dataset partitioning (go/master service.go partition()) --
    def set_dataset(self, paths, records_per_task=64):
        """Partition recordio files into record-range tasks.  No-op when a
        recovered snapshot already holds tasks."""
        if sum(self._q.counts()[:3]) > 0:
            return
        self._seq += 1
        for path in paths:
            n = 0
            scanner = native.RecordIOScanner(path)
            for _ in scanner:
                n += 1
            scanner.close()
            for start in range(0, n, records_per_task):
                payload = json.dumps({
                    'path': path,
                    'start': start,
                    'count': min(records_per_task, n - start),
                }).encode()
                self._q.add_task(payload)
        self.snapshot_to_store()

    # -- queue API (service.go GetTask/TaskFinished/TaskFailed) --
    def get_task(self):
        """(task_id, task_dict); (-1, None) = pass finished; (None, None)
        = nothing available right now (claimed elsewhere)."""
        tid, payload = self._q.get_task()
        if payload is None:
            return tid, None
        self._seq += 1
        return tid, json.loads(payload.decode())

    # snapshot throttling: timeout-redispatch already tolerates a stale
    # snapshot (a recovered pending task just re-runs), so rewriting the
    # whole blob on every completion would be O(tasks^2) disk traffic
    SNAPSHOT_EVERY = 16

    def task_finished(self, tid):
        self._q.task_finished(tid)
        self._seq += 1
        self._maybe_snapshot()

    def task_failed(self, tid):
        r = self._q.task_failed(tid)
        self._seq += 1
        # a discard decision (failure cap reached) must be durable, or a
        # restarted master re-dispatches the poisoned task forever
        self._maybe_snapshot(force=(r == 1))
        return r

    def _maybe_snapshot(self, force=False):
        self._events += 1
        if force or self._events % self.SNAPSHOT_EVERY == 0:
            self.snapshot_to_store()

    def current_pass(self):
        """The pass cursor — what a worker passes back as
        ``new_pass(expected=)`` so pass advancement is shared safely."""
        return self.pass_num

    def new_pass(self, expected=None):
        """Recycle done tasks into todo and advance the pass cursor.

        ``expected`` is the multi-worker protocol (ISSUE 14, the PR 12
        listed-untested gap): several workers drain ONE master, and
        EACH reports pass end when it observes get_task() == -1 — so
        the advance must be compare-and-set on the pass the worker was
        draining.  A stale duplicate (a faster peer already advanced)
        no-ops instead of double-advancing the cursor — or worse,
        recycling the NEXT pass's freshly-done tasks back into todo
        mid-pass, which would serve records twice per pass and skew
        the ack accounting.  ``expected=None`` (a single-owner caller)
        advances unconditionally, the pre-ISSUE-14 semantics.
        Returns True when the pass actually advanced."""
        with self._pass_lock:
            if expected is not None and int(expected) != self.pass_num:
                return False
            self._q.new_pass()
            self.pass_num += 1
            self._seq += 1
            return True

    def counts(self):
        """(todo, pending, done, discarded)"""
        return self._q.counts()

    def snapshot_to_store(self):
        if not self.store_path:
            return
        snap = os.path.join(self.store_path, 'master_snapshot.bin')
        tmp = snap + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(self.snapshot())
        os.replace(tmp, snap)  # atomic like the etcd transactional put

    # -- worker membership (the etcd registration dir, PAPER.md §EDL:
    # trainers register under a TTL lease; the master's view of the
    # live set is what an elastic job re-forms its dp extent on) --
    def _sweep_members(self, now=None):
        now = time.monotonic() if now is None else now
        dead = [w for w, dl in self._members.items() if dl <= now]
        for w in dead:
            del self._members[w]
        if dead:
            self._membership_epoch += 1

    def register_worker(self, worker_id):
        """Join (or rejoin) the membership set under a fresh lease;
        returns (epoch, sorted live worker ids)."""
        with self._members_lock:
            now = time.monotonic()
            self._sweep_members(now)
            if worker_id not in self._members:
                self._membership_epoch += 1
            self._members[worker_id] = now + self.worker_lease_secs
            return self._membership_epoch, sorted(self._members)

    def heartbeat(self, worker_id):
        """Renew ``worker_id``'s lease (registering it if its old lease
        already expired); returns (epoch, sorted live worker ids)."""
        return self.register_worker(worker_id)

    def deregister_worker(self, worker_id):
        """Graceful leave (a crashed worker just stops heartbeating and
        its lease expires); returns (epoch, sorted live worker ids)."""
        with self._members_lock:
            self._sweep_members()
            if worker_id in self._members:
                del self._members[worker_id]
                self._membership_epoch += 1
            return self._membership_epoch, sorted(self._members)

    def members(self):
        """(epoch, sorted live worker ids) after sweeping expired
        leases."""
        with self._members_lock:
            self._sweep_members()
            return self._membership_epoch, sorted(self._members)


class SnapshotReplica(object):
    """Cross-host snapshot replication through the TCP door (the
    reference master survives host loss via etcd,
    go/master/etcd_client.go:1; the flock+file store alone assumes a
    shared filesystem).  A replica on ANOTHER base_dir mirrors the
    primary's queue snapshots; after the primary host dies, a new
    ``Master(store_path=replica_dir)`` restores from the last pulled
    blob — same recovery path as a local restart.

        rep = SnapshotReplica('host:port', '/other/fs/master_store')
        rep.pull()            # one mirror now, or
        rep.start(interval)   # background mirror thread
    """

    def __init__(self, endpoint, store_path):
        self.endpoint = endpoint
        self.store_path = store_path
        os.makedirs(store_path, exist_ok=True)
        self._seq = None
        self._thread = None
        self._stop = None
        self.last_error = None
        self.consecutive_failures = 0

    def pull(self):
        """Mirror the primary's current snapshot; returns True if a new
        blob was written (seq advanced or first pull)."""
        from .master_server import MasterClient
        cli = MasterClient(self.endpoint)
        try:
            blob, seq = cli.fetch_snapshot()
        finally:
            cli.close()
        if self._seq is not None and seq == self._seq:
            return False
        snap = os.path.join(self.store_path, 'master_snapshot.bin')
        tmp = snap + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(blob)
        os.replace(tmp, snap)  # atomic, like the primary's own store
        self._seq = seq
        return True

    def start(self, interval=1.0):
        import threading
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.pull()
                    self.last_error = None
                    self.consecutive_failures = 0
                except (ConnectionError, OSError, RuntimeError) as e:
                    # transient blips (dropped TCP, one bad response)
                    # must not kill mirroring for the rest of the run —
                    # keep retrying until stop(); the caller can watch
                    # consecutive_failures to alarm on a dead primary
                    self.last_error = e
                    self.consecutive_failures += 1

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def cloud_reader(master, pass_num=1, poll_interval=0.05,
                 base_pass=None):
    """Record iterator over the master's task queue (reference
    python/paddle/v2/reader/creator.py:91 cloud_reader): claims a task,
    streams its record range, reports completion; failures (reader
    exceptions) report task_failed so another trainer retries the chunk.

    ``base_pass`` (ISSUE 14): the JOB's starting pass cursor, for
    fleets of readers sharing one master — every worker of one job
    passes the same base (usually 0, or the checkpointed cursor), so
    ``pass_num`` bounds the MASTER's passes rather than each worker's
    attach-relative count (a worker attaching after a peer already
    advanced the cursor must not extend the run by its own pass_num).
    None anchors at this reader's attach point — exact legacy
    semantics for a lone reader."""

    def reader():
        passes = 0
        # per-file scanner cache with the current record position: tasks
        # claimed in file order stream sequentially instead of rescanning
        # from record 0 per task (only an out-of-order claim reopens)
        open_scanners = {}  # path -> [scanner, next_record_index]

        def read_range(path, start, count):
            entry = open_scanners.get(path)
            if entry is None or entry[1] > start:
                if entry is not None:
                    entry[0].close()
                entry = [native.RecordIOScanner(path), 0]
                open_scanners[path] = entry
            scanner, pos = entry
            records = []
            try:
                while pos < start + count:
                    rec = next(scanner)
                    if pos >= start:
                        records.append(rec)
                    pos += 1
            finally:
                entry[1] = pos
            return records

        # the shared-master pass protocol (ISSUE 14): progress is the
        # MASTER's pass cursor, not this reader's count of -1
        # sightings — N readers all observe every pass end, so the
        # advance is new_pass(expected=<the pass being drained>): one
        # reader wins, the others' duplicates no-op and resync.  A
        # master without current_pass (a minimal stand-in) keeps the
        # legacy local counting.
        if hasattr(master, 'current_pass'):
            cur = master.current_pass()
            base = int(base_pass) if base_pass is not None else cur
        else:
            cur = base = None
        try:
            while passes < pass_num:
                tid, task = master.get_task()
                if tid == -1:
                    if cur is None:
                        passes += 1
                        if passes < pass_num:
                            master.new_pass()
                        continue
                    passes = cur - base + 1
                    if passes >= pass_num:
                        continue  # final pass drained: loop exits
                    if master.new_pass(expected=cur):
                        cur += 1
                    else:
                        # a peer advanced first (maybe further than
                        # one pass while we were mid-claim): resync
                        cur = master.current_pass()
                    continue
                if task is None:
                    time.sleep(poll_interval)
                    continue
                try:
                    records = read_range(task['path'], task['start'],
                                         task['count'])
                except Exception:
                    # drop the (possibly corrupt) cached scanner before
                    # another trainer retries the chunk
                    entry = open_scanners.pop(task['path'], None)
                    if entry is not None:
                        entry[0].close()
                    master.task_failed(tid)
                    continue
                for rec in records:
                    yield rec
                master.task_finished(tid)
        finally:
            for scanner, _ in open_scanners.values():
                scanner.close()

    return reader
