"""Two-tier embedding store (ISSUE 12): an HBM hot-row cache slab in
front of a host-resident master table.

PAPER.md's sparse remote updaters keep giant embedding tables OFF the
trainer chip: the reference's distributed lookup table prefetches only
the rows a batch touches (operators/prefetch_op.cc) and pushes sparse
grads back.  PR 10 made tables row-shard over the mesh, but a table
bigger than the WHOLE mesh still cannot load.  Under the zipfian id
traffic the CTR workload generates, a small HBM-resident hot-row
working set absorbs almost every lookup — this module turns that into
the measured fast path:

  * the MASTER table is host-resident, held by
    ``AsyncSparseEmbedding`` (``fetch_rows``/``write_rows`` are the
    batched exchange API); optimizer accumulators (velocity / adagrad
    accumulator / adam moments) keep host masters alongside;
  * a fixed ``[C, D]`` device SLAB per table (weight + each
    accumulator) lives in the scope under the table's own var name —
    the train scan's gather/scatter and the PR 10 row-subset
    optimizers run on the slab unchanged, against ids REMAPPED to
    slots on host (``stage_block``);
  * between scan dispatches an EXCHANGE swaps rows: dirty evicted
    rows gather out of the slab (one ``ops.sparse.slab_gather_rows``)
    and write back to the host master on a background writeback
    worker, host-fetched miss rows scatter in (one
    ``slab_scatter_rows``) — slot vectors pad to power-of-two widths
    so executables stay bounded;
  * the host half of block N+1's exchange (miss-set computation + the
    master-table fetch, on a background fetch worker) OVERLAPS scan
    N's device compute when driven by the ``FeedPipeline`` staging
    thread; an exchange whose fetch has not landed when its dispatch
    needs it is a counted ``prefetch_stall`` — the dispatch waits, so
    a late fetch is never a correctness hazard;
  * parity is provable: the slab rows are bitwise the rows a
    full-table run would hold (SGD's one-scatter-add path is EXACT;
    merged-duplicate adaptive optimizers agree allclose), and
    ``flush()`` writes every dirty resident row back so
    ``table()`` == the full-table lane's final table.

Thread contract: ``stage_block`` is called by ONE staging thread (or
the synchronous caller) in block order; ``apply`` by the dispatch
thread in the same order; ``flush``/``close`` by anyone (they
serialize on the apply lock).  The id->slot directory is lock-guarded.
"""

import collections
import queue as _queue
import threading
import time

import numpy as np

from .async_sparse import AsyncSparseEmbedding

__all__ = ['CachedEmbeddingTable', 'EmbedCacheCapacityError',
           'optimizer_accumulator_vars']

# optimizer-op input slots holding row-shaped accumulators that must
# ride the cache (one host master + one slab each); scalar slots
# (Beta1Pow, LearningRate) update densely and stay plain scope vars
_ACCUMULATOR_SLOTS = ('Velocity', 'Moment', 'Moment1', 'Moment2',
                      'MeanSquare', 'SquaredAccumulator',
                      'LinearAccumulator', 'AvgSquaredGrad',
                      'AvgSquaredUpdate')


def _host_like(obj):
    """True for a host-TIER aux master (the sharded pserver client's
    per-table view — anything speaking fetch_rows/write_rows) as
    opposed to a plain in-process ndarray."""
    return hasattr(obj, 'fetch_rows') and hasattr(obj, 'write_rows')


class EmbedCacheCapacityError(RuntimeError):
    """Typed reject: one scan block touches more unique rows than the
    cache has slots — the exchange cannot make them all resident at
    once.  Raise capacity (or shrink the block)."""

    def __init__(self, var, uniq, capacity):
        self.var = var
        self.unique_rows = int(uniq)
        self.capacity = int(capacity)
        super(EmbedCacheCapacityError, self).__init__(
            'embed cache %r: one block touches %d unique rows but the '
            'slab has %d slots — raise capacity above the per-block '
            'working set (or lower steps per dispatch)'
            % (var, uniq, capacity))


def optimizer_accumulator_vars(program, var_name):
    """Row-shaped optimizer accumulator var names of ``var_name``'s
    optimizer op in ``program`` (the vars that must cache alongside the
    table: momentum velocity, adagrad accumulator, adam moments).

    Raises a typed ValueError when the table's optimizer has no
    row-subset kernel (``ops.sparse._ROW_SUBSET_APPLY``): such an
    optimizer would fall back to ``lazy_apply``'s dense [V, D]
    materialization against the [C, D] slab — an opaque shape crash
    deep inside the jit — so the unsupported combination must reject
    at cache construction instead."""
    from ..ops.sparse import _ROW_SUBSET_APPLY
    out = []
    for op in program.global_block().ops:
        if 'Param' not in op.inputs or op.input('Param') != [var_name]:
            continue
        if op.type in _ROW_SUBSET_APPLY:
            for slot in _ACCUMULATOR_SLOTS:
                if slot in op.inputs:
                    out.extend(op.input(slot))
            continue
        raise ValueError(
            'embed cache: optimizer %r updating table %r has no '
            'row-subset kernel — the two-tier cache supports %s '
            '(the lazy-dense fallback would materialize the [V, D] '
            'gradient the slab exists to avoid)'
            % (op.type, var_name, sorted(_ROW_SUBSET_APPLY)))
    return out


def register_stall_probe(owner, name, cache, threshold):
    """Arm a trace-watchdog probe over ``cache``'s current
    prefetch-stall age, unregistered when ``owner`` (the engine or
    pipeline that started it) is GC'd.  ONE implementation of the
    weak-closure + finalize-unregister pattern, shared by
    InferenceEngine.start and FeedPipeline.start — the subtle parts
    (the probe must not pin a dropped cache, the unregister must pair
    the exact fn) live here once."""
    import weakref
    from ..fluid import trace as _trace
    cref = weakref.ref(cache)

    def age(cref=cref):
        c = cref()
        return c.stall_age() if c is not None else None

    probe = _trace.watchdog.register(name, age, threshold)
    weakref.finalize(owner, _trace.watchdog.unregister, probe, age)
    return probe


class _Exchange(object):
    """One block's staged row swap: dirty victims out, misses in."""

    __slots__ = ('seq', 'miss_ids', 'miss_slots', 'victim_ids',
                 'victim_slots', 'wait_events', 'fetch_done', 'fetched',
                 'wb_done', 'gathered', 'applied')

    def __init__(self, seq, miss_ids, miss_slots, victim_ids,
                 victim_slots, wait_events):
        self.seq = seq
        self.miss_ids = miss_ids          # np int64 [M]
        self.miss_slots = miss_slots      # np int32 [M]
        self.victim_ids = victim_ids      # np int64 [E] (dirty only)
        self.victim_slots = victim_slots  # np int32 [E]
        self.wait_events = wait_events    # writebacks this fetch needs
        self.fetch_done = threading.Event()
        self.fetched = None               # {table_name: [M, D] np}
        self.wb_done = threading.Event()
        self.gathered = None              # {table_name: device [W, D]}
        self.applied = False


class CachedEmbeddingTable(object):
    """One cached table: host master tier + ``[C, D]`` device slab tier.

    var        : the table's scope/program var name (the slab lives
                 there; lookups/optimizers touch it unchanged).
    id_feeds   : feed names carrying this table's lookup ids — the
                 block staging remaps them to slot indices.
    capacity   : slot count C of the slab (must cover every block's
                 unique-row working set; rounds up to ``multiple``).
    host       : the master-tier ``AsyncSparseEmbedding`` (built by
                 ``from_scope`` from the startup-initialized value).
    aux        : {var_name: host ndarray} — optimizer accumulators
                 co-cached with the weight (same slots, own slabs).
    scope      : the fluid scope holding the slab vars.
    """

    def __init__(self, var, id_feeds, capacity, host, scope, aux=None,
                 multiple=1):
        self.var = str(var)
        self.id_feeds = [str(f) for f in id_feeds]
        if not self.id_feeds:
            raise ValueError('CachedEmbeddingTable: id_feeds is required '
                             '(which feeds carry the lookup ids?)')
        multiple = max(int(multiple), 1)
        self.capacity = -(-int(capacity) // multiple) * multiple
        if self.capacity < 1:
            raise ValueError('CachedEmbeddingTable: capacity must be >= 1')
        self._host = host
        self.vocab, self.dim = host.shape
        if self.capacity > self.vocab:
            raise ValueError(
                'CachedEmbeddingTable: capacity %d exceeds the vocab %d '
                '— a slab covering the whole table needs no overflow '
                'tier' % (self.capacity, self.vocab))
        self._scope = scope
        # an aux master is either a plain ndarray (copy=True: sources
        # may be read-only views of live jax arrays) or a host-tier
        # object speaking fetch_rows/write_rows (a ShardedEmbeddingClient
        # table view — ISSUE 19), adopted as-is
        self._aux_host = {
            str(n): a if _host_like(a)
            else np.array(a, dtype='float32', copy=True)
            for n, a in (aux or {}).items()}
        for n, a in self._aux_host.items():
            if tuple(a.shape) != (self.vocab, self.dim):
                raise ValueError(
                    'CachedEmbeddingTable: accumulator %r has shape %s, '
                    'expected %s' % (n, tuple(a.shape),
                                     (self.vocab, self.dim)))
        # ---- the id->slot directory (host mirror of the slab) --------
        self._lock = threading.RLock()       # directory state
        self._apply_lock = threading.RLock()  # exchange FIFO / flush
        self._slot_ids = np.full((self.capacity, ), -1, np.int64)
        self._id2slot = {}
        self._dirty = np.zeros((self.capacity, ), bool)
        self._lru = collections.OrderedDict()  # id -> None, LRU order
        self._free = list(range(self.capacity))
        self._wb_pending = {}  # id -> _Exchange whose writeback covers it
        self._exchanges = collections.deque()  # staged, unapplied
        self._seq = 0
        # ---- workers -------------------------------------------------
        self._fetch_q = _queue.Queue()
        self._wb_q = _queue.Queue()
        self._closed = False
        self._stall_since = None
        self._fetch_worker = threading.Thread(
            target=self._fetch_loop, daemon=True,
            name='embed-cache-fetch-%s' % self.var)
        self._wb_worker = threading.Thread(
            target=self._wb_loop, daemon=True,
            name='embed-cache-wb-%s' % self.var)
        self._fetch_worker.start()
        self._wb_worker.start()
        # ---- metrics -------------------------------------------------
        self._m = {'lookups': 0, 'hits': 0, 'misses': 0, 'blocks': 0,
                   'steps': 0, 'exchanges': 0, 'prefetch_stalls': 0,
                   'prefetch_overlapped': 0, 'host_fetch_bytes': 0,
                   'host_writeback_bytes': 0, 'writeback_rows': 0,
                   'flushes': 0}

    # ---- construction ----------------------------------------------------

    @classmethod
    def from_scope(cls, scope, program, var, capacity, id_feeds,
                   multiple=1):
        """Build the two-tier store over an EXISTING scope: the
        startup-initialized ``[V, D]`` value (and its optimizer
        accumulators, discovered from ``program``'s optimizer ops)
        demote to host masters, and fresh ``[C, D]`` zero slabs take
        their places in the scope — from here on the program trains/
        serves against the slab."""
        v = scope.find_var(var)
        if v is None or v.value() is None:
            raise ValueError(
                'CachedEmbeddingTable.from_scope: %r is not initialized '
                'in the scope — run the startup program first' % var)
        master = np.asarray(v.value())
        if master.ndim != 2:
            raise ValueError(
                'CachedEmbeddingTable.from_scope: %r has shape %s — '
                'only 2-D embedding tables cache' % (var, master.shape))
        vocab, dim = master.shape
        host = AsyncSparseEmbedding(vocab, dim, table=master)
        aux = {}
        for name in optimizer_accumulator_vars(program, var):
            av = scope.find_var(name)
            if av is None or av.value() is None:
                continue
            arr = np.asarray(av.value())
            if arr.shape == (vocab, dim):
                aux[name] = arr
        cache = cls(var, id_feeds, capacity, host, scope, aux=aux,
                    multiple=multiple)
        # install the slabs: the scope vars now hold [C, D]
        zeros = np.zeros((cache.capacity, dim), master.dtype)
        v.set_value(zeros.copy())
        for name in cache._aux_host:
            scope.find_var(name).set_value(zeros.copy())
        return cache

    # ---- accounting ------------------------------------------------------

    @property
    def tables(self):
        """Every slab var name (weight first, then accumulators)."""
        return [self.var] + sorted(self._aux_host)

    def slab_nbytes(self):
        """Device bytes of every slab at capacity — the
        ``:embed-cache`` arbiter account's size."""
        return len(self.tables) * self.capacity * self.dim * 4

    def master_nbytes(self):
        """Host bytes of the WEIGHT master (what the program declares
        as the [V, D] var — the bytes that never go on device)."""
        return self.vocab * self.dim * 4

    def metrics(self):
        m = dict(self._m)
        m['capacity'] = self.capacity
        m['vocab'] = self.vocab
        m['resident'] = self.capacity - len(self._free)
        m['hit_rate'] = (m['hits'] / m['lookups']) if m['lookups'] else None
        ex = m['exchanges']
        m['prefetch_overlap_ratio'] = (
            m['prefetch_overlapped'] / ex) if ex else None
        host_bytes = m['host_fetch_bytes'] + m['host_writeback_bytes']
        m['host_bytes'] = host_bytes
        m['host_bytes_per_step'] = (
            host_bytes / m['steps']) if m['steps'] else None
        m['pending_exchanges'] = len(self._exchanges)
        return m

    def stall_age(self):
        """Seconds the dispatch thread has CURRENTLY been waiting on a
        late host fetch (None when not stalled) — the watchdog's
        prefetch-stall probe."""
        since = self._stall_since
        return (time.time() - since) if since is not None else None

    def check_scope(self, scope, who):
        """The ONE scope-binding invariant (slabs live in exactly one
        scope), shared by every executor/pipeline/engine integration
        point: raise a typed error BEFORE any staging mutates the
        directory when the run's scope is not the cache's."""
        if self._scope is not scope:
            raise ValueError(
                '%s: embed cache %r is bound to a different scope than '
                'this run — build the cache from the scope that holds '
                'its slabs' % (who, self.var))

    # ---- staging (the host half; FeedPipeline staging thread) -----------

    def _remap(self, arr, id2slot_sorted):
        uniq, slots = id2slot_sorted
        flat = np.asarray(arr, np.int64)
        idx = np.searchsorted(uniq, flat)
        return slots[idx].astype(np.int64)

    def stage_block(self, id_arrays, train=True, steps=None):
        """Stage one scan block's exchange AHEAD of its dispatch: given
        the block's id feeds (a list over steps of {feed: ndarray}),
        compute the miss set against the directory, pick victims (LRU
        among rows the block does not touch), start the host fetch, and
        return ``(remapped, exchange)`` — the same structure with every
        id replaced by its slab slot, plus the exchange handle the
        dispatch applies (None when the block is fully resident).
        ``train=False`` (the serving lot path) skips dirty-marking:
        inference never modifies the slab, so its evictions are free."""
        if self._closed:
            raise RuntimeError('CachedEmbeddingTable %r is closed'
                               % self.var)
        per_step = [{f: np.asarray(d[f], np.int64) for f in self.id_feeds
                     if f in d} for d in id_arrays]
        flat = [a.reshape(-1) for d in per_step for a in d.values()]
        if not flat or not sum(a.size for a in flat):
            return id_arrays, None
        all_ids = np.concatenate(flat)
        if all_ids.min() < 0 or all_ids.max() >= self.vocab:
            raise ValueError(
                'embed cache %r: block ids out of range [0, %d)'
                % (self.var, self.vocab))
        uniq = np.unique(all_ids)
        if len(uniq) > self.capacity:
            raise EmbedCacheCapacityError(self.var, len(uniq),
                                          self.capacity)
        with self._lock:
            block_set = set(uniq.tolist())
            miss_ids = [i for i in uniq.tolist() if i not in self._id2slot]
            n_miss = len(miss_ids)
            miss_slots, victim_ids, victim_slots = [], [], []
            wait_events = []
            for mid in miss_ids:
                if self._free:
                    slot = self._free.pop()
                else:
                    vid = next(i for i in self._lru if i not in block_set)
                    slot = self._id2slot.pop(vid)
                    del self._lru[vid]
                    if self._dirty[slot]:
                        victim_ids.append(vid)
                        victim_slots.append(slot)
                        self._dirty[slot] = False
                self._id2slot[mid] = slot
                self._slot_ids[slot] = mid
                miss_slots.append(slot)
            for i in uniq.tolist():
                self._lru[i] = None
                self._lru.move_to_end(i)
            if train:
                for i in uniq.tolist():
                    self._dirty[self._id2slot[i]] = True
            # a miss whose latest value is still in flight to the host
            # (a dirty eviction whose writeback has not landed) must
            # wait for that exchange's writeback before fetching
            seen = set()
            for mid in miss_ids:
                prior = self._wb_pending.get(mid)
                if prior is not None and id(prior) not in seen:
                    seen.add(id(prior))
                    wait_events.append(prior.wb_done)
            ex = None
            if n_miss or victim_ids:
                self._seq += 1
                ex = _Exchange(
                    self._seq, np.asarray(miss_ids, np.int64),
                    np.asarray(miss_slots, np.int32),
                    np.asarray(victim_ids, np.int64),
                    np.asarray(victim_slots, np.int32), wait_events)
                for vid in victim_ids:
                    self._wb_pending[vid] = ex
                self._exchanges.append(ex)
            # accounting + the remap table
            lookups = int(sum(a.size for a in flat))
            self._m['lookups'] += lookups
            self._m['misses'] += n_miss
            self._m['hits'] += lookups - n_miss
            self._m['blocks'] += 1
            self._m['steps'] += int(steps if steps is not None
                                    else len(per_step) or 1)
            slots_for = np.asarray([self._id2slot[i] for i in
                                    uniq.tolist()], np.int64)
        if ex is not None:
            self._m['exchanges'] += 1
            self._fetch_q.put(ex)
        remap = (uniq, slots_for)
        out = []
        for src, ids in zip(id_arrays, per_step):
            d = dict(src)
            for f, a in ids.items():
                d[f] = self._remap(a, remap)
            out.append(d)
        return out, ex

    def stage_feed_list(self, feed_list, train=True, steps=None):
        """``stage_block`` over run_multi-shaped prepared feed dicts:
        remaps the id feeds IN PLACE of each dict and returns the
        exchange handle."""
        remapped, ex = self.stage_block(feed_list, train=train,
                                        steps=steps)
        for dst, src in zip(feed_list, remapped):
            for f in self.id_feeds:
                if f in src:
                    dst[f] = src[f]
        return ex

    # ---- workers ---------------------------------------------------------

    def _aux_write(self, name, ids, rows):
        aux = self._aux_host[name]
        if _host_like(aux):
            aux.write_rows(ids, rows)
        else:
            aux[ids] = rows

    def _fetch_loop(self):
        while True:
            ex = self._fetch_q.get()
            if ex is None:
                self._fetch_q.task_done()
                return
            try:
                for ev in ex.wait_events:
                    ev.wait()
                fetched = {}
                if len(ex.miss_ids):
                    fetched[self.var] = self._host.fetch_rows(ex.miss_ids)
                    for name, arr in self._aux_host.items():
                        fetched[name] = (arr.fetch_rows(ex.miss_ids)
                                         if _host_like(arr)
                                         else arr[ex.miss_ids].copy())
                    self._m['host_fetch_bytes'] += (
                        len(ex.miss_ids) * self.dim * 4 *
                        len(self.tables))
                ex.fetched = fetched
            finally:
                ex.fetch_done.set()
                self._fetch_q.task_done()

    def _wb_loop(self):
        while True:
            ex = self._wb_q.get()
            if ex is None:
                self._wb_q.task_done()
                return
            try:
                n = len(ex.victim_ids)
                if n and ex.gathered is not None:
                    for name, dev in ex.gathered.items():
                        rows = np.asarray(dev)[:n]
                        if name == self.var:
                            self._host.write_rows(ex.victim_ids, rows)
                        else:
                            self._aux_write(name, ex.victim_ids, rows)
                    self._m['host_writeback_bytes'] += (
                        n * self.dim * 4 * len(self.tables))
                    self._m['writeback_rows'] += n
            finally:
                with self._lock:
                    for vid in ex.victim_ids.tolist():
                        if self._wb_pending.get(vid) is ex:
                            del self._wb_pending[vid]
                ex.wb_done.set()
                self._wb_q.task_done()

    # ---- the device half (dispatch thread) -------------------------------

    def _slab_value(self, name):
        var = self._scope.find_var(name)
        if var is None or var.value() is None:
            raise RuntimeError(
                'embed cache %r: slab var %r is not in the scope'
                % (self.var, name))
        return var.value()

    def _apply_one(self, ex):
        from ..ops.sparse import (exchange_width, pad_exchange,
                                  slab_gather_rows, slab_scatter_rows)
        if not ex.fetch_done.is_set():
            # the prefetch did not finish ahead of the dispatch: a
            # counted stall, never a correctness hazard — wait it out
            self._m['prefetch_stalls'] += 1
            self._stall_since = time.time()
            try:
                ex.fetch_done.wait()
            finally:
                self._stall_since = None
        else:
            self._m['prefetch_overlapped'] += 1
        n_evict = len(ex.victim_ids)
        if n_evict:
            # gather the dirty evicted rows BEFORE the scatter below
            # overwrites their slots; the writeback worker syncs them
            # off the dispatch thread
            w = exchange_width(n_evict)
            slots = pad_exchange(ex.victim_slots, w, self.capacity)
            ex.gathered = {
                name: slab_gather_rows(self._slab_value(name), slots)
                for name in self.tables
            }
        self._wb_q.put(ex)
        n_miss = len(ex.miss_ids)
        if n_miss:
            w = exchange_width(n_miss)
            slots = pad_exchange(ex.miss_slots, w, self.capacity)
            for name in self.tables:
                rows = ex.fetched[name]
                padded = np.zeros((w, ) + rows.shape[1:], rows.dtype)
                padded[:n_miss] = rows
                new = slab_scatter_rows(self._slab_value(name), slots,
                                        padded)
                self._scope.find_var(name).set_value(new)
        ex.applied = True

    def apply(self, exchange):
        """Apply one staged exchange (and, defensively, any staged
        BEFORE it — FIFO order is the correctness contract) right
        before its block's dispatch.  Idempotent: a flush that already
        applied it makes this a no-op."""
        if exchange is None:
            return
        with self._apply_lock:
            while not exchange.applied and self._exchanges:
                self._apply_one(self._exchanges.popleft())

    # ---- flush / lifecycle ----------------------------------------------

    def flush(self):
        """The paused-window barrier: apply every staged exchange (a
        block staged but not yet dispatched just has its rows moved
        early — value-neutral), drain the writeback queue, then write
        every DIRTY resident row back to the host masters.  After
        flush the host tier is the full truth; the slab stays valid
        (bitwise) so training/serving resumes exactly.

        Caller contract: quiesce staging first (close the FeedPipeline
        / pause the engine worker) — flush serializes against APPLY,
        not against a concurrent ``stage_block``."""
        from ..ops.sparse import exchange_width, pad_exchange, \
            slab_gather_rows
        with self._apply_lock:
            while self._exchanges:
                self._apply_one(self._exchanges.popleft())
            self._fetch_q.join()
            self._wb_q.join()
            with self._lock:
                dirty_slots = np.nonzero(self._dirty)[0]
                dirty_ids = self._slot_ids[dirty_slots]
                self._dirty[dirty_slots] = False
            n = len(dirty_slots)
            if n:
                w = exchange_width(n)
                slots = pad_exchange(dirty_slots, w, self.capacity)
                for name in self.tables:
                    rows = np.asarray(
                        slab_gather_rows(self._slab_value(name),
                                         slots))[:n]
                    if name == self.var:
                        self._host.write_rows(dirty_ids, rows)
                    else:
                        self._aux_write(name, dirty_ids, rows)
                self._m['host_writeback_bytes'] += (
                    n * self.dim * 4 * len(self.tables))
                self._m['writeback_rows'] += n
            self._host.drain()
            self._m['flushes'] += 1

    def invalidate(self):
        """Flush, then forget every residency: the next block misses
        everything (the every-step-exchange comparator lane, and the
        big hammer for external master-table edits)."""
        with self._apply_lock:
            self.flush()
            with self._lock:
                self._id2slot.clear()
                self._lru.clear()
                self._slot_ids[:] = -1
                self._free = list(range(self.capacity))

    def table(self, name=None):
        """The full ``[V, D]`` host truth of the weight table (or an
        accumulator) after a flush — the parity check's view."""
        self.flush()
        if name is None or name == self.var:
            return self._host.table()
        aux = self._aux_host[name]
        return aux.table() if _host_like(aux) else aux.copy()

    def evict_to_host(self):
        """Demote every slab to a host ndarray after a flush (bitwise
        values — the next dispatch re-stages them through the normal
        cache_back path).  Returns bytes moved — the ``:embed-cache``
        arbiter account's eviction unit."""
        import jax
        self.flush()
        moved = 0
        for name in self.tables:
            var = self._scope.find_var(name)
            v = var.value() if var is not None else None
            if isinstance(v, jax.Array):
                arr = np.asarray(v)
                var.set_value(arr)
                moved += int(arr.nbytes)
        return moved

    def close(self):
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._closed = True
            self._fetch_q.put(None)
            self._wb_q.put(None)
            self._fetch_worker.join(timeout=10)
            self._wb_worker.join(timeout=10)
            self._host.close()

    @property
    def closed(self):
        return self._closed

    def __repr__(self):
        return ('CachedEmbeddingTable(%r, vocab=%d, dim=%d, capacity=%d, '
                'tables=%d)' % (self.var, self.vocab, self.dim,
                                self.capacity, len(self.tables)))
