"""Distributed parameter-server embedding tier (ISSUE 19): sharded
pserver processes behind the resilient transport.

The reference Fluid stack's signature scale capability is the sparse
remote updater backed by a FLEET of parameter servers (the
DistributeTranspiler pserver path, and the Go pserver with etcd
registration and checkpointing — PAPER.md SFluid-distributed); our
repro so far held every embedding master in ONE host process
(``AsyncSparseEmbedding``), capping the table at one host's DRAM.
This module reborns that tier TPU-natively on the PR 15/17 RPC
substrate:

``PServerShard``
    serves a CONTIGUOUS row-range of one or more ``[V, D]`` tables
    (the weight plus every optimizer accumulator — the same table set
    ``CachedEmbeddingTable`` discovers) over ``transport.py``'s
    ``ServiceServer``.  Batched ``fetch_rows``/``write_rows``/
    ``apply_rows`` RPCs ride the ndarray wire codec from
    ``serving/fleet.py``; mutations carry client-minted rids through
    the reusable ``DedupWindow``, so a retried write applies exactly
    once.  Durability rides ``AsyncShardedCheckpoint``: the shard
    checkpoints its row-range AND its dedup window atomically with the
    covered mutation, so a killed-and-restarted shard resumes from its
    last commit and an in-flight retry REPLAYS instead of
    double-applying.

``ShardedEmbeddingClient``
    presents the existing ``AsyncSparseEmbedding`` surface
    (``fetch_rows``/``write_rows``/``prefetch``/``push_grad``/
    ``shape``/``nbytes``/``drain``/``table``/``close`` + the
    background push queue) over N shards: each batch is row-range
    routed (one ``searchsorted`` over the shard starts), the partial
    results merge back in id order, so results are BITWISE identical
    to the single-process master.  Each shard lane is a
    ``ResilientServiceClient`` — reconnect, seeded backoff, in-order
    standby failover — so a shard restart is a retry, not an error.

``CachedEmbeddingTable`` composes transparently: pass the client as
the cache's host tier (``sharded_cache_from_scope`` wires the whole
stack) and the HBM hot-row slab, staging-thread prefetch overlap and
read-your-writes writeback ordering all ride the sharded master
unchanged.
"""

import queue
import threading

import numpy as np

from .async_sparse import AsyncSparseClosedError
from .elastic import AsyncShardedCheckpoint
from .transport import DedupWindow, ResilientServiceClient, RetryPolicy, \
    ServiceServer

__all__ = ['PServerShard', 'ShardedEmbeddingClient', 'shard_row_ranges',
           'sharded_cache_from_scope']

# shard methods whose server-side effect is NOT idempotent across a
# lost response: they carry a request id and ride the dedup window
# (write_rows is a set — but its RESPONSE must still replay, and a
# checkpointed window must cover it so a post-restart retry cannot
# interleave with newer writes to the same rows)
_PSERVER_MUTATING = frozenset(['write_rows', 'apply_rows'])


def _wire_encode(v):
    from ..serving.fleet import _wire_encode as enc
    return enc(v)


def _wire_decode(v):
    from ..serving.fleet import _wire_decode as dec
    return dec(v)


def shard_row_ranges(vocab, shards):
    """Contiguous ``[start, stop)`` row-ranges covering ``[0, vocab)``
    across ``shards`` shards — the first ``vocab % shards`` shards get
    one extra row.  The canonical partition used by every launcher
    here (tests, perf_gate, load_gen), so client-side routing can
    always be a single searchsorted."""
    vocab, shards = int(vocab), int(shards)
    if shards < 1:
        raise ValueError('shard_row_ranges: shards must be >= 1')
    if vocab < shards:
        raise ValueError(
            'shard_row_ranges: vocab %d < shards %d would leave empty '
            'shards' % (vocab, shards))
    base, extra = divmod(vocab, shards)
    ranges, lo = [], 0
    for i in range(shards):
        hi = lo + base + (1 if i < extra else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


class PServerShard(object):
    """One parameter-server shard: a contiguous row-range of one or
    more ``[rows, D]`` tables served over the resilient transport.

    tables  : {name: [rows, D] array} — the weight table plus its
              optimizer accumulators, all the SAME shape (copies are
              taken; the shard owns its state).
    row_start: global row index of local row 0 — ids on the wire are
              GLOBAL; the shard translates.
    weight  : name of the weight table (``apply_rows``'s target);
              defaults to the sole table when only one is given.
    lr      : SGD rate for ``apply_rows`` pushed row-gradients (the
              async-SGD lane; the cached-training lane uses
              ``write_rows`` and never touches this).
    checkpoint_dir: when set, the shard checkpoints every
              ``checkpoint_every`` mutations through an
              ``AsyncShardedCheckpoint`` — tables AND dedup window in
              one atomic commit — and ``restore()`` can rebuild the
              shard from the last commit after a kill.
    """

    def __init__(self, tables, row_start, weight=None, lr=0.01,
                 host='127.0.0.1', port=0, fault_injector=None,
                 checkpoint_dir=None, checkpoint_every=1, keep=3,
                 dedup_window=256, dedup_clients=64,
                 _dedup_state=None, _step=0):
        if not tables:
            raise ValueError('PServerShard: tables is empty')
        self._tables = {str(n): np.array(a, dtype='float32', copy=True)
                        for n, a in tables.items()}
        shapes = {a.shape for a in self._tables.values()}
        if len(shapes) != 1 or any(len(s) != 2 for s in shapes):
            raise ValueError(
                'PServerShard: tables must share one 2-D shape, got %s'
                % sorted((n, a.shape) for n, a in self._tables.items()))
        self.rows, self.dim = next(iter(shapes))
        self.row_start = int(row_start)
        if weight is None:
            if len(self._tables) != 1:
                raise ValueError(
                    'PServerShard: weight= is required with multiple '
                    'tables %s' % sorted(self._tables))
            weight = next(iter(self._tables))
        self.weight = str(weight)
        if self.weight not in self._tables:
            raise ValueError('PServerShard: weight %r not in tables %s'
                             % (self.weight, sorted(self._tables)))
        self._lr = float(lr)
        self._lock = threading.Lock()      # table row read/write atomicity
        # serializes mutations AGAINST the checkpoint snapshot: the
        # (tables, dedup window) pair committed to disk must be
        # mutually consistent — a record without its table effect
        # loses the write on restore, a table effect without its
        # record double-applies on retry.  Holding this across
        # dedup.execute + checkpoint closes both windows.
        self._mut_lock = threading.Lock()
        self._dedup = DedupWindow(window=dedup_window,
                                  clients=dedup_clients)
        if _dedup_state:
            self._dedup.restore_state(_dedup_state)
        self._mutations = int(_step)
        self._saved_at = int(_step)
        self._checkpoint_every = max(int(checkpoint_every), 1)
        self._store = (AsyncShardedCheckpoint(checkpoint_dir, keep=keep)
                       if checkpoint_dir else None)
        self._closed = False
        self._server = ServiceServer(
            self._dispatch, host=host, port=port,
            fault_injector=fault_injector,
            dedup_execute=self._dedup_execute)

    # ---- construction ---------------------------------------------------

    @classmethod
    def restore(cls, checkpoint_dir, host='127.0.0.1', port=0,
                fault_injector=None, checkpoint_every=1, keep=3,
                dedup_window=256, dedup_clients=64):
        """Rebuild a killed shard from its last committed checkpoint —
        tables, mutation counter AND dedup window — typically at the
        SAME port, so clients' reconnect/retry lanes find it again.
        An in-flight mutation retried against the restored shard
        replays its recorded response instead of double-applying."""
        store = AsyncShardedCheckpoint(checkpoint_dir, keep=keep)
        try:
            step, arrays, extras = store.load()
        finally:
            store.close()
        return cls(tables=arrays, row_start=extras['row_start'],
                   weight=extras['weight'], lr=extras['lr'],
                   host=host, port=port, fault_injector=fault_injector,
                   checkpoint_dir=checkpoint_dir,
                   checkpoint_every=checkpoint_every, keep=keep,
                   dedup_window=dedup_window,
                   dedup_clients=dedup_clients,
                   _dedup_state=extras.get('dedup') or {}, _step=step)

    # ---- the RPC surface ------------------------------------------------

    def _dedup_execute(self, client, rid, fn):
        with self._mut_lock:
            resp = self._dedup.execute(client, rid, fn)
            # the response (fresh or replay) is recorded in the window
            # NOW and no other mutation can interleave: a checkpoint
            # taken here commits a consistent (tables, window) pair
            self._maybe_checkpoint()
            return resp

    def _local(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = ids - self.row_start
        if len(local) and (local.min() < 0 or local.max() >= self.rows):
            raise ValueError(
                'pserver shard rows [%d, %d): ids out of range (got '
                '[%d, %d])' % (self.row_start, self.row_start + self.rows,
                               int(ids.min()), int(ids.max())))
        return local

    def _table(self, name):
        name = self.weight if name is None else str(name)
        if name not in self._tables:
            raise ValueError('pserver shard: unknown table %r (have %s)'
                             % (name, sorted(self._tables)))
        return self._tables[name]

    def _dispatch(self, method, req):
        if method == 'meta':
            return {'row_start': self.row_start, 'rows': self.rows,
                    'dim': self.dim, 'tables': sorted(self._tables),
                    'weight': self.weight, 'lr': self._lr}
        if method == 'fetch_rows':
            arr = self._table(req.get('table'))
            local = self._local(req['ids'])
            with self._lock:
                return {'rows': _wire_encode(arr[local].copy())}
        if method == 'write_rows':
            arr = self._table(req.get('table'))
            local = self._local(req['ids'])
            rows = np.asarray(_wire_decode(req['rows']),
                              dtype='float32').reshape(len(local), -1)
            if rows.shape[1] != self.dim:
                raise ValueError(
                    'pserver shard: write_rows dim %d != %d'
                    % (rows.shape[1], self.dim))
            with self._lock:
                arr[local] = rows
                self._mutations += 1
            return {'written': int(len(local))}
        if method == 'apply_rows':
            local = self._local(req['ids'])
            grad = np.asarray(_wire_decode(req['grad']),
                              dtype='float32').reshape(len(local), -1)
            with self._lock:
                # duplicate ids in one batch must accumulate — the
                # same np.subtract.at async-SGD the single-process
                # master applies
                np.subtract.at(self._tables[self.weight], local,
                               self._lr * grad)
                self._mutations += 1
            return {'applied': int(len(local))}
        if method == 'stats':
            return self.metrics()
        raise ValueError('pserver shard: unknown method %r' % method)

    # ---- durability -----------------------------------------------------

    def _snapshot(self):
        """(step, arrays, extras) under the table lock — explicit
        copies: the store's writer thread serializes later and must
        not see concurrent row writes."""
        with self._lock:
            step = self._mutations
            arrays = {n: a.copy() for n, a in self._tables.items()}
        extras = {'row_start': self.row_start, 'weight': self.weight,
                  'lr': self._lr, 'dedup': self._dedup.export_state()}
        return step, arrays, extras

    def _maybe_checkpoint(self):
        if self._store is None:
            return
        if self._mutations - self._saved_at < self._checkpoint_every:
            return
        step, arrays, extras = self._snapshot()
        self._store.save(step, arrays, extras=extras)
        self._saved_at = step

    def checkpoint(self, wait=False):
        """Force a checkpoint of the current state (no-op without a
        checkpoint_dir); ``wait=True`` blocks until it committed —
        the pre-kill barrier of the chaos suite."""
        if self._store is None:
            return
        with self._mut_lock:
            step, arrays, extras = self._snapshot()
            self._store.save(step, arrays, extras=extras)
            self._saved_at = step
        if wait:
            self._store.wait()

    # ---- lifecycle / observability --------------------------------------

    @property
    def endpoint(self):
        return self._server.endpoint

    @property
    def port(self):
        return self._server.port

    @property
    def dedup_replays(self):
        return self._dedup.replays

    def metrics(self):
        m = {'row_start': self.row_start, 'rows': self.rows,
             'dim': self.dim, 'mutations': self._mutations,
             'dedup_replays': self._dedup.replays,
             'endpoint': self.endpoint}
        if self._store is not None:
            m['checkpoint'] = self._store.metrics()
        return m

    def table(self, name=None):
        """A copy of one full local table — the in-process view for
        tests and launchers (RPC callers use fetch_rows)."""
        arr = self._table(name)
        with self._lock:
            return arr.copy()

    def kill(self):
        """Crash simulation (the chaos lane): tear the server down
        mid-conversation and stop checkpointing WITHOUT the final
        commit ``close()`` would take.  Whatever the store committed
        stays on disk for ``restore()``."""
        if self._closed:
            return
        self._closed = True
        self._server.close()
        if self._store is not None:
            self._store.close()

    def close(self):
        """Graceful shutdown: commit a final checkpoint (when
        durable), then stop serving."""
        if self._closed:
            return
        self._closed = True
        try:
            if self._store is not None:
                with self._mut_lock:
                    step, arrays, extras = self._snapshot()
                    self._store.save(step, arrays, extras=extras,
                                     wait=True)
        finally:
            self._server.close()
            if self._store is not None:
                self._store.close()

    @property
    def closed(self):
        return self._closed

    def __repr__(self):
        return ('PServerShard(rows=[%d, %d), dim=%d, tables=%d, %s)'
                % (self.row_start, self.row_start + self.rows, self.dim,
                   len(self._tables), self.endpoint))


class _ShardedTableView(object):
    """One table's host-tier view over the sharded client — what
    ``CachedEmbeddingTable`` adopts as an aux master (fetch_rows/
    write_rows/shape/nbytes/table), routing through the owner's
    per-shard lanes."""

    def __init__(self, owner, name):
        self._owner = owner
        self.name = str(name)

    @property
    def shape(self):
        return self._owner.shape

    @property
    def nbytes(self):
        return self._owner.nbytes

    def fetch_rows(self, ids):
        return self._owner._fetch(self.name, ids)

    def write_rows(self, ids, rows):
        self._owner._check_open('write_rows')
        self._owner._write(self.name, ids, rows)

    def table(self):
        return self._owner.table(self.name)


class ShardedEmbeddingClient(object):
    """The ``AsyncSparseEmbedding`` surface over N pserver shards.

    endpoints: one entry per shard — a ``'host:port'`` string or a
        list of them (primary first, standbys after: the in-order
        failover contract of ``ResilientServiceClient``).  Shards are
        sorted by their advertised row_start; together they must cover
        ``[0, vocab)`` contiguously.
    retry    : base ``RetryPolicy``; each shard lane derives its own
        with a decorrelated seed (``seed + 1009 * shard``), the fleet
        idiom.
    capacity : push-queue bound, as on ``AsyncSparseEmbedding``.

    Reads gather per-shard partials and merge them back in id order;
    pushed gradients partition per shard and apply via exactly-once
    ``apply_rows`` — both BITWISE what the single-process master
    computes, which is the tier's parity bar.
    """

    def __init__(self, endpoints, capacity=64, timeout=5.0, retry=None,
                 fault_injector=None, service='pserver'):
        if not endpoints:
            raise ValueError('ShardedEmbeddingClient: endpoints is empty')
        base = retry if retry is not None else RetryPolicy()
        self._clients = []
        for idx, eps in enumerate(endpoints):
            self._clients.append(ResilientServiceClient(
                eps, timeout=timeout, fault_injector=fault_injector,
                mutating=_PSERVER_MUTATING,
                service='%s[%d]' % (service, idx),
                retry=RetryPolicy(max_attempts=base.max_attempts,
                                  base_backoff_s=base.base_backoff_s,
                                  max_backoff_s=base.max_backoff_s,
                                  deadline_s=base.deadline_s,
                                  jitter=base.jitter,
                                  seed=base.seed + 1009 * idx)))
        metas = [c.call('meta') for c in self._clients]
        order = sorted(range(len(metas)),
                       key=lambda i: int(metas[i]['row_start']))
        self._clients = [self._clients[i] for i in order]
        metas = [metas[i] for i in order]
        dims = {int(m['dim']) for m in metas}
        weights = {m['weight'] for m in metas}
        tabsets = {tuple(m['tables']) for m in metas}
        if len(dims) != 1 or len(weights) != 1 or len(tabsets) != 1:
            raise ValueError(
                'ShardedEmbeddingClient: shards disagree on dim/weight/'
                'tables: %s' % metas)
        self.dim = dims.pop()
        self._weight = weights.pop()
        self.tables = list(tabsets.pop())
        self._starts = np.array([int(m['row_start']) for m in metas],
                                np.int64)
        stops = self._starts + np.array([int(m['rows']) for m in metas],
                                        np.int64)
        if self._starts[0] != 0 or \
                (len(metas) > 1 and
                 (self._starts[1:] != stops[:-1]).any()):
            raise ValueError(
                'ShardedEmbeddingClient: shard row-ranges do not tile '
                '[0, vocab) contiguously: %s'
                % [(int(a), int(b)) for a, b in zip(self._starts, stops)])
        self.vocab = int(stops[-1])
        # ---- the push queue (AsyncSparseEmbedding surface) -----------
        self._q = queue.Queue(maxsize=capacity)
        self._applied = 0
        self._pushed = 0
        self._error = None
        self._closed = False
        self._join_timeouts = 0
        self._close_lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ---- routing --------------------------------------------------------

    def _partition(self, ids):
        """Yield (shard_index, positions) covering ``ids`` — positions
        index into the flat id batch, so partial results merge back in
        id order."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if len(ids) and (ids.min() < 0 or ids.max() >= self.vocab):
            raise ValueError(
                'ShardedEmbeddingClient: ids out of range [0, %d) '
                '(got [%d, %d])' % (self.vocab, int(ids.min()),
                                    int(ids.max())))
        shard_of = np.searchsorted(self._starts, ids, side='right') - 1
        for s in np.unique(shard_of):
            yield int(s), np.nonzero(shard_of == s)[0]

    def _fetch(self, name, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), self.dim), 'float32')
        for s, pos in self._partition(ids):
            resp = self._clients[s].call(
                'fetch_rows', table=name, ids=ids[pos].tolist())
            out[pos] = _wire_decode(resp['rows'])
        return out

    def _write(self, name, ids, rows):
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, dtype='float32').reshape(len(ids), -1)
        for s, pos in self._partition(ids):
            self._clients[s].call(
                'write_rows', table=name, ids=ids[pos].tolist(),
                rows=_wire_encode(rows[pos]))

    def _check_open(self, what):
        if self._error is not None:
            raise self._error
        if self._closed:
            raise AsyncSparseClosedError(what)

    # ---- the AsyncSparseEmbedding surface -------------------------------

    def prefetch(self, ids):
        """Gather current row values for a batch of ids -> [N, D]
        (reads see the shards as of now, minus whatever pushed updates
        are still queued — async semantics, as on the single-process
        master)."""
        return self._fetch(self._weight, ids)

    def fetch_rows(self, ids):
        """Batched row gather across shards, merged in id order."""
        return self._fetch(self._weight, ids)

    def write_rows(self, ids, rows):
        """Batched row SET, row-range routed; exactly-once per shard.
        Raises the typed closed error after ``close()``."""
        with self._close_lock:
            self._check_open('write_rows')
        self._write(self._weight, ids, rows)

    def push_grad(self, ids, grad):
        """Enqueue d(loss)/d(rows) for asynchronous application across
        the shards; returns immediately (the reference's barrier-free
        send).  Raises the typed ``AsyncSparseClosedError`` after
        ``close()``."""
        if self._error is not None:
            raise self._error
        ids = np.asarray(ids).reshape(-1).copy()
        grad = np.asarray(grad, dtype='float32').reshape(
            len(ids), -1).copy()
        with self._close_lock:
            if self._closed:
                raise AsyncSparseClosedError()
            self._pushed += 1
            self._q.put((ids, grad))

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            ids, grad = item
            try:
                # ascending-shard application: each row's updates land
                # in push order (partitioning preserves per-row update
                # order), so the result is bitwise the single-process
                # np.subtract.at
                for s, pos in self._partition(ids):
                    self._clients[s].call(
                        'apply_rows', ids=ids[pos].tolist(),
                        grad=_wire_encode(grad[pos]))
                self._applied += 1
            except Exception as e:  # surfaced on push/drain
                self._error = e
            finally:
                self._q.task_done()

    def drain(self):
        """Block until every pushed update is applied on its shard."""
        self._q.join()
        if self._error is not None:
            raise self._error

    @property
    def shape(self):
        return (self.vocab, self.dim)

    @property
    def nbytes(self):
        return int(self.vocab) * int(self.dim) * 4

    @property
    def stats(self):
        return {'pushed': self._pushed, 'applied': self._applied,
                'queued': self._q.qsize(),
                'close_join_timeouts': self._join_timeouts}

    def metrics(self):
        """Per-shard RPC lane metrics (calls/retries/reconnects/
        failovers/injected_faults/endpoint) + the push stats."""
        m = dict(self.stats)
        m['shards'] = [c.metrics() for c in self._clients]
        return m

    # table() chunk: bounds one fetch_rows message (JSON-framed rows)
    TABLE_CHUNK_ROWS = 8192

    def table(self, name=None):
        """A consistent [V, D] snapshot assembled from every shard
        (drains the push queue first)."""
        self.drain()
        name = self._weight if name is None else str(name)
        out = np.empty((self.vocab, self.dim), 'float32')
        for lo in range(0, self.vocab, self.TABLE_CHUNK_ROWS):
            hi = min(lo + self.TABLE_CHUNK_ROWS, self.vocab)
            out[lo:hi] = self._fetch(name, np.arange(lo, hi, dtype=np.int64))
        return out

    def aux(self, name):
        """The host-tier view of one accumulator table — what
        ``CachedEmbeddingTable`` adopts as an aux master."""
        name = str(name)
        if name not in self.tables:
            raise ValueError(
                'ShardedEmbeddingClient: unknown table %r (have %s)'
                % (name, self.tables))
        return _ShardedTableView(self, name)

    JOIN_TIMEOUT_S = 10.0

    def close(self):
        """Shut the client down: every update pushed BEFORE close is
        applied (the queue drains fully), then the push daemon exits
        and the shard lanes close.  Idempotent; a racing push either
        lands in the drained queue or raises typed."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        try:
            self.drain()
        finally:
            self._q.put(None)
            self._worker.join(timeout=self.JOIN_TIMEOUT_S)
            if self._worker.is_alive():
                self._join_timeouts += 1
                import logging
                logging.getLogger(__name__).warning(
                    'ShardedEmbeddingClient.close(): push daemon did '
                    'not join within %.1fs (stats: %r)',
                    self.JOIN_TIMEOUT_S, self.stats)
            for c in self._clients:
                c.close()

    @property
    def closed(self):
        return self._closed

    def __repr__(self):
        return ('ShardedEmbeddingClient(vocab=%d, dim=%d, shards=%d, '
                'tables=%d)' % (self.vocab, self.dim,
                                len(self._clients), len(self.tables)))


def sharded_cache_from_scope(scope, program, var, capacity, id_feeds,
                             shards=4, multiple=1, lr=0.01,
                             checkpoint_root=None, checkpoint_every=1,
                             keep=3, fault_injector=None, retry=None,
                             timeout=5.0, host='127.0.0.1',
                             standby_ports=None):
    """``CachedEmbeddingTable.from_scope``, parameter-server edition:
    demote the startup-initialized ``[V, D]`` table (and its optimizer
    accumulators, discovered from ``program``) to a FLEET of
    ``shards`` row-range ``PServerShard`` processes, wire a
    ``ShardedEmbeddingClient`` over them, and hand it to the cache as
    the host tier (aux masters ride the client's per-table views).
    Fresh ``[C, D]`` zero slabs replace the scope vars, exactly as the
    single-process path does — the program trains against the slab,
    the masters live behind RPC.

    checkpoint_root: when set, each shard checkpoints under
        ``<root>/shard-<idx>`` (the chaos lane's kill-and-restore
        substrate).
    standby_ports: optional per-shard list of extra ports to list as
        failover endpoints (the chaos lane pre-binds a standby there).

    Returns ``(cache, client, shard_list)`` — closing the cache closes
    the client (its host tier); the shards are the caller's to close.
    """
    import os
    from .embed_cache import CachedEmbeddingTable, \
        optimizer_accumulator_vars
    v = scope.find_var(var)
    if v is None or v.value() is None:
        raise ValueError(
            'sharded_cache_from_scope: %r is not initialized in the '
            'scope — run the startup program first' % var)
    master = np.asarray(v.value())
    if master.ndim != 2:
        raise ValueError(
            'sharded_cache_from_scope: %r has shape %s — only 2-D '
            'embedding tables cache' % (var, master.shape))
    vocab, dim = master.shape
    aux = {}
    for name in optimizer_accumulator_vars(program, var):
        av = scope.find_var(name)
        if av is None or av.value() is None:
            continue
        arr = np.asarray(av.value())
        if arr.shape == (vocab, dim):
            aux[name] = arr
    shard_list, endpoints = [], []
    for idx, (lo, hi) in enumerate(shard_row_ranges(vocab, shards)):
        tables = {str(var): master[lo:hi]}
        for name, arr in aux.items():
            tables[name] = arr[lo:hi]
        ckpt = (os.path.join(checkpoint_root, 'shard-%05d' % idx)
                if checkpoint_root else None)
        shard = PServerShard(tables, row_start=lo, weight=str(var),
                             lr=lr, host=host,
                             fault_injector=fault_injector,
                             checkpoint_dir=ckpt,
                             checkpoint_every=checkpoint_every,
                             keep=keep)
        shard_list.append(shard)
        eps = [shard.endpoint]
        if standby_ports is not None:
            eps += ['%s:%d' % (host, p) for p in
                    np.atleast_1d(standby_ports[idx]).tolist()]
        endpoints.append(eps)
    client = ShardedEmbeddingClient(endpoints, timeout=timeout,
                                    retry=retry,
                                    fault_injector=fault_injector)
    cache = CachedEmbeddingTable(
        var, id_feeds, capacity, host=client, scope=scope,
        aux={n: client.aux(n) for n in aux}, multiple=multiple)
    zeros = np.zeros((cache.capacity, dim), master.dtype)
    v.set_value(zeros.copy())
    for name in cache._aux_host:
        scope.find_var(name).set_value(zeros.copy())
    return cache, client, shard_list
