"""Resilient control-plane RPC lane (ISSUE 15).

The reference survives control-plane faults by construction: the
go/master registers through etcd so a crashed master is re-elected and
clients transparently re-resolve it (go/master/etcd_client.go), and
the Fluid send/recv ops retry RPCs against a restarted pserver
(operators/send_op.cc's grpc retry loop).  The bare ``MasterClient``
is ONE blocking socket that dies on the first hiccup; this module is
the lane that makes master RPCs survivable:

* a typed error taxonomy — ``MasterUnavailableError`` (transient: the
  socket broke, the host is gone, the response never came; a retry or
  failover may succeed) vs ``MasterProtocolError`` (permanent: the
  server ANSWERED and said no; a rid-carrying mutation's outcome is
  recorded in the dedup window, so retrying the identical call could
  only replay the identical refusal — in-band errors are final).
  The server carries the exception TYPE name over the wire
  (``{'error': ..., 'etype': ...}``) for diagnosis, so the client
  stops flattening everything into one RuntimeError;

* ``RetryPolicy`` — per-call deadline, exponential backoff with
  SEEDED jitter (deterministic chaos runs), max attempts;

* ``ResilientMasterClient`` — the ``MasterClient`` surface over a
  LIST of endpoints (primary + promoted standbys, tried in order),
  owning reconnect-on-broken-socket and failover.  Mutating methods
  (``get_task``/``task_finished``/``task_failed``/``new_pass``) carry
  a client-minted request id; the ``MasterServer`` keeps a bounded
  per-client dedup window replaying the recorded response, so a retry
  after a LOST RESPONSE is exactly-once: a replayed ``task_failed``
  does not advance the failure count toward ``failure_max``, and a
  replayed ``get_task`` returns the SAME claimed task instead of
  leaking the first claim until its lease expires.  The window rides
  the versioned snapshot envelope, so dedup survives failover to a
  standby restored from a replicated snapshot.
"""

import json
import random
import socket
import threading
import time
import uuid

from .faults import InjectedFault

__all__ = ['RetryPolicy', 'ResilientMasterClient',
           'MasterUnavailableError', 'MasterProtocolError']


class MasterUnavailableError(ConnectionError):
    """Transient: the master could not be reached (connect refused,
    socket broke mid-call, response never arrived, all endpoints
    down).  A retry — possibly against a promoted standby — may
    succeed.  Subclasses ConnectionError so pre-taxonomy callers
    (``except ConnectionError``) keep working."""


class MasterProtocolError(RuntimeError):
    """Permanent: the master answered and refused (unknown method, a
    server-side exception, a snapshot-version refusal).  Retrying the
    identical call cannot help.  Subclasses RuntimeError so
    pre-taxonomy callers (``except RuntimeError``) keep working."""


def error_from_response(resp):
    """The typed exception for an IN-BAND error response.  The server
    ANSWERED — the conversation works and (for a rid-carrying
    mutation) the outcome is recorded in the dedup window, so a retry
    of the identical call can only replay the identical refusal:
    every in-band error is FINAL for its logical call
    (MasterProtocolError).  Only transport-level failures (no answer
    at all) are transient.  ``etype`` (the server-side exception
    class name) rides the message for diagnosis."""
    etype = resp.get('etype')
    msg = 'master error: %s' % resp.get('error')
    if etype:
        msg += ' [server %s]' % etype
    return MasterProtocolError(msg)


class RetryPolicy(object):
    """Backoff/deadline contract for one logical master call.

    max_attempts: total attempts (first try included).
    base_backoff_s / max_backoff_s: exponential schedule —
        ``base * 2**(attempt-1)`` capped at ``max_backoff_s``.
    deadline_s: wall bound for the WHOLE call across retries and
        failovers; exhausting it raises MasterUnavailableError.
    jitter: each backoff is scaled by ``1 + U(0, jitter)`` drawn from
        a SEEDED rng — deterministic schedules for the chaos suite,
        decorrelated retries in a fleet (each worker seeds with its
        own id).
    """

    def __init__(self, max_attempts=6, base_backoff_s=0.05,
                 max_backoff_s=2.0, deadline_s=30.0, jitter=0.5,
                 seed=0):
        if int(max_attempts) < 1:
            raise ValueError('RetryPolicy: max_attempts must be >= 1')
        self.max_attempts = int(max_attempts)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.deadline_s = float(deadline_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)

    def backoff(self, attempt):
        """Sleep before attempt ``attempt+1`` (1-based failed
        attempt)."""
        base = min(self.base_backoff_s * (2.0 ** (attempt - 1)),
                   self.max_backoff_s)
        return base * (1.0 + self._rng.random() * self.jitter)


# methods whose server-side effect is NOT idempotent across a lost
# response: these carry a request id and ride the dedup window
_MUTATING = frozenset(['get_task', 'task_finished', 'task_failed',
                       'new_pass'])


class ResilientMasterClient(object):
    """The ``MasterClient`` surface with reconnect, retry, failover
    and exactly-once mutations (see module doc).

    endpoints: ``'host:port'`` list tried IN ORDER — the primary
        first, promoted standbys after; a working endpoint sticks
        until it breaks.
    retry: a ``RetryPolicy`` (default constructed when None).
    timeout: per-attempt socket timeout — a dropped response turns
        into a retry after this long, so keep it a small multiple of
        the expected RPC latency, well under ``retry.deadline_s``.
    fault_injector: optional ``FaultInjector`` checked at the
        ``client_send``/``client_recv`` sites.
    client_id: the dedup-window identity; defaults to a fresh uuid —
        pass a stable id only if YOU guarantee request ids never
        repeat under it.
    """

    def __init__(self, endpoints, retry=None, timeout=5.0,
                 fault_injector=None, client_id=None):
        if isinstance(endpoints, str):
            endpoints = [endpoints]
        self.endpoints = [str(e) for e in endpoints]
        if not self.endpoints:
            raise ValueError('ResilientMasterClient: endpoints is '
                             'empty')
        self.retry = retry if retry is not None else RetryPolicy()
        self.timeout = float(timeout)
        self.fault_injector = fault_injector
        self._client_id = client_id or uuid.uuid4().hex[:16]
        self._rid = 0
        self._sock = None
        self._rfile = None
        self._ep_idx = 0
        self._ever_connected = False
        self._closed = False
        # one socket, strict request/response framing: concurrent
        # callers (heartbeat + staging threads) serialize here — the
        # same contract as the bare MasterClient
        self._lock = threading.RLock()
        self._unreachable_since = None
        self._m = {'calls': 0, 'retries': 0, 'reconnects': 0,
                   'failovers': 0, 'injected_faults': 0}

    # ---- connection ----------------------------------------------------

    def _drop_conn(self):
        for closer in (self._rfile, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:
                    pass
        self._rfile = self._sock = None

    def _ensure_conn(self, deadline):
        if self._sock is not None:
            return
        last = None
        n = len(self.endpoints)
        for off in range(n):
            idx = (self._ep_idx + off) % n
            host, port = self.endpoints[idx].rsplit(':', 1)
            budget = max(min(self.timeout,
                             deadline - time.monotonic()), 0.05)
            try:
                sock = socket.create_connection((host, int(port)),
                                                timeout=budget)
            except OSError as e:
                last = e
                continue
            sock.settimeout(self.timeout)
            self._sock = sock
            self._rfile = sock.makefile('rb')
            if self._ever_connected:
                self._m['reconnects'] += 1
            self._ever_connected = True
            if idx != self._ep_idx:
                # the lane moved to a standby (or back): failover
                self._m['failovers'] += 1
                self._ep_idx = idx
            return
        raise MasterUnavailableError(
            'no master endpoint reachable (%s): %s'
            % (', '.join(self.endpoints), last))

    # ---- the call loop -------------------------------------------------

    def _attempt(self, req, deadline):
        self._ensure_conn(deadline)
        fi = self.fault_injector
        method = req['method']
        if fi is not None:
            rule = fi.check('client_send', method)
            if rule is not None:
                self._m['injected_faults'] += 1
                act = rule['action']
                if act == 'delay':
                    time.sleep(rule['delay_s'])
                elif act == 'close':
                    self._drop_conn()
                    raise InjectedFault('client_send close (%s)'
                                        % method)
                elif act == 'drop_request':
                    raise InjectedFault('client_send drop_request '
                                        '(%s)' % method)
        self._sock.sendall((json.dumps(req) + '\n').encode())
        line = self._rfile.readline()
        if fi is not None:
            rule = fi.check('client_recv', method)
            if rule is not None:
                self._m['injected_faults'] += 1
                act = rule['action']
                if act == 'delay':
                    time.sleep(rule['delay_s'])
                else:
                    raise InjectedFault('client_recv %s (%s)'
                                        % (act, method))
        if not line:
            raise MasterUnavailableError(
                'master closed the connection')
        resp = json.loads(line.decode())  # ValueError -> transient
        if 'error' in resp:
            raise error_from_response(resp)
        return resp

    def _call(self, method, **kw):
        req = dict(kw)
        req['method'] = method
        with self._lock:
            if self._closed:
                raise MasterUnavailableError(
                    'ResilientMasterClient is closed')
            self._m['calls'] += 1
            if method in _MUTATING:
                # the exactly-once identity: RETRIES of this logical
                # call reuse the id, so the server's dedup window
                # replays the recorded response instead of
                # re-executing the mutation
                self._rid += 1
                req['client'] = self._client_id
                req['rid'] = str(self._rid)
            deadline = time.monotonic() + self.retry.deadline_s
            attempt = 0
            while True:
                attempt += 1
                try:
                    resp = self._attempt(req, deadline)
                except MasterProtocolError:
                    # the transport WORKED; the refusal is permanent
                    self._unreachable_since = None
                    raise
                except (OSError, ValueError) as e:
                    # OSError covers socket death, timeouts, refused
                    # connects, InjectedFault and the typed
                    # MasterUnavailableError; ValueError is a
                    # corrupted (non-JSON) line
                    self._drop_conn()
                    if self._unreachable_since is None:
                        self._unreachable_since = time.monotonic()
                    out_of_time = (time.monotonic() >= deadline)
                    if attempt >= self.retry.max_attempts or \
                            out_of_time:
                        raise MasterUnavailableError(
                            'master call %r failed after %d attempt'
                            '(s) over %r: %s'
                            % (method, attempt, self.endpoints,
                               e)) from e
                    self._m['retries'] += 1
                    time.sleep(max(min(self.retry.backoff(attempt),
                                       deadline - time.monotonic()),
                                   0.0))
                else:
                    self._unreachable_since = None
                    return resp

    # ---- observability -------------------------------------------------

    def unreachable_age(self):
        """Seconds the master has been continuously unreachable (None
        when the last call succeeded) — the watchdog's
        master-unreachable probe."""
        since = self._unreachable_since
        return (time.monotonic() - since) if since is not None \
            else None

    def metrics(self):
        m = dict(self._m)
        m['endpoint'] = self.endpoints[self._ep_idx]
        m['endpoints'] = list(self.endpoints)
        m['unreachable_s'] = self.unreachable_age()
        return m

    # ---- the MasterClient surface --------------------------------------

    def get_task(self):
        r = self._call('get_task')
        return r['tid'], r['task']

    def task_finished(self, tid):
        self._call('task_finished', tid=tid)

    def task_failed(self, tid):
        return self._call('task_failed', tid=tid)['discarded']

    def counts(self):
        return tuple(self._call('counts')['counts'])

    def new_pass(self, expected=None):
        return self._call('new_pass', expected=expected)['advanced']

    def current_pass(self):
        return self._call('pass_num')['pass_num']

    def register_worker(self, worker_id):
        r = self._call('register_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def heartbeat(self, worker_id):
        r = self._call('heartbeat', worker_id=worker_id)
        return r['epoch'], r['workers']

    def deregister_worker(self, worker_id):
        r = self._call('deregister_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def members(self):
        r = self._call('members')
        return r['epoch'], r['workers']

    def fetch_snapshot(self):
        """(blob_bytes, seq) of the master's current queue state."""
        import base64
        r = self._call('snapshot')
        return base64.b64decode(r['blob']), r.get('seq', 0)

    def close(self):
        with self._lock:
            self._closed = True
            self._drop_conn()
