"""Shared resilient RPC substrate (ISSUE 15, generalized in ISSUE 17).

The reference survives control-plane faults by construction: the
go/master registers through etcd so a crashed master is re-elected and
clients transparently re-resolve it (go/master/etcd_client.go), and
the Fluid send/recv ops retry RPCs against a restarted pserver
(operators/send_op.cc's grpc retry loop).  This module is that lane,
SERVICE-AGNOSTIC — the master control plane rides it (master_server.py
/ elastic.py) and so does the serving fleet tier (serving/fleet.py):

* a typed error taxonomy — ``ServiceUnavailableError`` (transient: the
  socket broke, the host is gone, the response never came; a retry or
  failover may succeed) vs ``ServiceProtocolError`` (permanent: the
  server ANSWERED and said no; a rid-carrying mutation's outcome is
  recorded in the dedup window, so retrying the identical call could
  only replay the identical refusal — in-band errors are final).
  ``MasterUnavailableError`` / ``MasterProtocolError`` are back-compat
  ALIASES of the same classes, so every pre-generalization
  ``except``/``isinstance`` site keeps working.  The server carries
  the exception TYPE name over the wire (``{'error': ..., 'etype':
  ...}``) for diagnosis and typed re-raising, so the client stops
  flattening everything into one RuntimeError;

* ``RetryPolicy`` — per-call deadline, exponential backoff with
  SEEDED jitter (deterministic chaos runs), max attempts;

* ``ResilientServiceClient`` — a blocking request/response client over
  a LIST of endpoints (primary + promoted standbys, tried in order),
  owning reconnect-on-broken-socket and failover.  Methods named in
  its ``mutating`` set carry a client-minted request id reused across
  retries of the same LOGICAL call; the server's bounded per-client
  dedup window replays the recorded response, so a retry after a LOST
  RESPONSE is exactly-once.  ``ResilientMasterClient`` is this client
  with the master's method surface and mutating set
  (``get_task``/``task_finished``/``task_failed``/``new_pass``);

* ``DedupWindow`` — the bounded per-client exactly-once window as a
  standalone piece (OrderedDict LRU over clients and rids, refusals
  recorded too) for services whose state object does not carry its
  own (the ``Master`` keeps its internal window: it rides the
  versioned snapshot envelope so dedup survives failover to a
  promoted standby);

* ``ServiceServer`` — the newline-delimited-JSON-over-TCP server
  shell (daemon thread, tracked connections force-closed on
  ``close()``, ``server_recv``/``server_send`` fault-injection sites,
  malformed lines answered typed, rid-carrying requests routed
  through a ``dedup_execute`` hook) factored out of the master server
  so any dispatch table can stand behind the same wire behavior.
"""

import json
import random
import socket
import socketserver
import threading
import time
import uuid
from collections import OrderedDict

from .faults import InjectedFault

__all__ = ['RetryPolicy', 'ResilientServiceClient',
           'ResilientMasterClient', 'ServiceServer', 'DedupWindow',
           'ServiceUnavailableError', 'ServiceProtocolError',
           'MasterUnavailableError', 'MasterProtocolError']


class ServiceUnavailableError(ConnectionError):
    """Transient: the service could not be reached (connect refused,
    socket broke mid-call, response never arrived, all endpoints
    down).  A retry — possibly against another endpoint — may
    succeed.  Subclasses ConnectionError so pre-taxonomy callers
    (``except ConnectionError``) keep working."""


class ServiceProtocolError(RuntimeError):
    """Permanent: the service answered and refused (unknown method, a
    server-side exception, a snapshot-version refusal).  Retrying the
    identical call cannot help.  Subclasses RuntimeError so
    pre-taxonomy callers (``except RuntimeError``) keep working.
    ``resp`` carries the raw wire response so a caller can re-raise
    the server-side type (``etype``) as a richer typed error (the
    fleet router re-mints ``OverloadedError`` from it)."""

    def __init__(self, msg, resp=None):
        RuntimeError.__init__(self, msg)
        self.resp = resp or {}


# back-compat aliases (ISSUE 15 names): same classes, so existing
# ``except MasterUnavailableError`` sites and isinstance checks keep
# working against errors raised by the generic substrate
MasterUnavailableError = ServiceUnavailableError
MasterProtocolError = ServiceProtocolError


def error_from_response(resp, service='master'):
    """The typed exception for an IN-BAND error response.  The server
    ANSWERED — the conversation works and (for a rid-carrying
    mutation) the outcome is recorded in the dedup window, so a retry
    of the identical call can only replay the identical refusal:
    every in-band error is FINAL for its logical call
    (ServiceProtocolError).  Only transport-level failures (no answer
    at all) are transient.  ``etype`` (the server-side exception
    class name) rides the message for diagnosis and the raw response
    rides ``.resp`` for typed re-raising."""
    etype = resp.get('etype')
    msg = '%s error: %s' % (service, resp.get('error'))
    if etype:
        msg += ' [server %s]' % etype
    return ServiceProtocolError(msg, resp=resp)


class RetryPolicy(object):
    """Backoff/deadline contract for one logical service call.

    max_attempts: total attempts (first try included).
    base_backoff_s / max_backoff_s: exponential schedule —
        ``base * 2**(attempt-1)`` capped at ``max_backoff_s``.
    deadline_s: wall bound for the WHOLE call across retries and
        failovers; exhausting it raises ServiceUnavailableError.
    jitter: each backoff is scaled by ``1 + U(0, jitter)`` drawn from
        a SEEDED rng — deterministic schedules for the chaos suite,
        decorrelated retries in a fleet (each worker seeds with its
        own id).
    """

    def __init__(self, max_attempts=6, base_backoff_s=0.05,
                 max_backoff_s=2.0, deadline_s=30.0, jitter=0.5,
                 seed=0):
        if int(max_attempts) < 1:
            raise ValueError('RetryPolicy: max_attempts must be >= 1')
        self.max_attempts = int(max_attempts)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.deadline_s = float(deadline_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)

    def backoff(self, attempt):
        """Sleep before attempt ``attempt+1`` (1-based failed
        attempt)."""
        base = min(self.base_backoff_s * (2.0 ** (attempt - 1)),
                   self.max_backoff_s)
        return base * (1.0 + self._rng.random() * self.jitter)


# master methods whose server-side effect is NOT idempotent across a
# lost response: these carry a request id and ride the dedup window
_MUTATING = frozenset(['get_task', 'task_finished', 'task_failed',
                       'new_pass'])


class ResilientServiceClient(object):
    """Blocking request/response client with reconnect, retry,
    failover and exactly-once mutations (see module doc).

    endpoints: ``'host:port'`` list tried IN ORDER — the primary
        first, standbys after; a working endpoint sticks until it
        breaks.
    mutating: method names that carry a client-minted request id
        (reused across retries of one logical call) so the server's
        dedup window can replay a lost response instead of
        re-executing.
    retry: a ``RetryPolicy`` (default constructed when None).
    timeout: per-attempt socket timeout — a dropped response turns
        into a retry after this long, so keep it a small multiple of
        the expected RPC latency, well under ``retry.deadline_s``.
    fault_injector: optional ``FaultInjector`` checked at the
        ``client_send``/``client_recv`` sites.
    client_id: the dedup-window identity; defaults to a fresh uuid —
        pass a stable id only if YOU guarantee request ids never
        repeat under it.
    service: the label used in error messages ('master', 'replica',
        ...) so a stack trace names the lane that failed.
    """

    def __init__(self, endpoints, retry=None, timeout=5.0,
                 fault_injector=None, client_id=None, mutating=(),
                 service='service'):
        if isinstance(endpoints, str):
            endpoints = [endpoints]
        self.endpoints = [str(e) for e in endpoints]
        if not self.endpoints:
            raise ValueError('%s: endpoints is empty'
                             % type(self).__name__)
        self.retry = retry if retry is not None else RetryPolicy()
        self.timeout = float(timeout)
        self.fault_injector = fault_injector
        self.mutating = frozenset(mutating)
        self.service = str(service)
        self._client_id = client_id or uuid.uuid4().hex[:16]
        self._rid = 0
        self._sock = None
        self._rfile = None
        self._ep_idx = 0
        self._ever_connected = False
        self._closed = False
        # one socket, strict request/response framing: concurrent
        # callers (heartbeat + staging threads) serialize here — the
        # same contract as the bare MasterClient
        self._lock = threading.RLock()
        self._unreachable_since = None
        self._m = {'calls': 0, 'retries': 0, 'reconnects': 0,
                   'failovers': 0, 'injected_faults': 0}

    # ---- connection ----------------------------------------------------

    def _drop_conn(self):
        for closer in (self._rfile, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:
                    pass
        self._rfile = self._sock = None

    def _ensure_conn(self, deadline):
        if self._sock is not None:
            return
        last = None
        n = len(self.endpoints)
        for off in range(n):
            idx = (self._ep_idx + off) % n
            host, port = self.endpoints[idx].rsplit(':', 1)
            budget = max(min(self.timeout,
                             deadline - time.monotonic()), 0.05)
            try:
                sock = socket.create_connection((host, int(port)),
                                                timeout=budget)
            except OSError as e:
                last = e
                continue
            sock.settimeout(self.timeout)
            self._sock = sock
            self._rfile = sock.makefile('rb')
            if self._ever_connected:
                self._m['reconnects'] += 1
            self._ever_connected = True
            if idx != self._ep_idx:
                # the lane moved to a standby (or back): failover
                self._m['failovers'] += 1
                self._ep_idx = idx
            return
        raise ServiceUnavailableError(
            'no %s endpoint reachable (%s): %s'
            % (self.service, ', '.join(self.endpoints), last))

    # ---- the call loop -------------------------------------------------

    def _attempt(self, req, deadline):
        self._ensure_conn(deadline)
        fi = self.fault_injector
        method = req['method']
        if fi is not None:
            rule = fi.check('client_send', method)
            if rule is not None:
                self._m['injected_faults'] += 1
                act = rule['action']
                if act == 'delay':
                    time.sleep(rule['delay_s'])
                elif act == 'close':
                    self._drop_conn()
                    raise InjectedFault('client_send close (%s)'
                                        % method)
                elif act == 'drop_request':
                    raise InjectedFault('client_send drop_request '
                                        '(%s)' % method)
        self._sock.sendall((json.dumps(req) + '\n').encode())
        line = self._rfile.readline()
        if fi is not None:
            rule = fi.check('client_recv', method)
            if rule is not None:
                self._m['injected_faults'] += 1
                act = rule['action']
                if act == 'delay':
                    time.sleep(rule['delay_s'])
                else:
                    raise InjectedFault('client_recv %s (%s)'
                                        % (act, method))
        if not line:
            raise ServiceUnavailableError(
                '%s closed the connection' % self.service)
        resp = json.loads(line.decode())  # ValueError -> transient
        if 'error' in resp:
            raise error_from_response(resp, service=self.service)
        return resp

    def call(self, method, **kw):
        """One logical call: retries/failovers inside, exactly-once
        when ``method`` is in the mutating set."""
        req = dict(kw)
        req['method'] = method
        with self._lock:
            if self._closed:
                raise ServiceUnavailableError(
                    '%s is closed' % type(self).__name__)
            self._m['calls'] += 1
            if method in self.mutating:
                # the exactly-once identity: RETRIES of this logical
                # call reuse the id, so the server's dedup window
                # replays the recorded response instead of
                # re-executing the mutation
                self._rid += 1
                req['client'] = self._client_id
                req['rid'] = str(self._rid)
            deadline = time.monotonic() + self.retry.deadline_s
            attempt = 0
            while True:
                attempt += 1
                try:
                    resp = self._attempt(req, deadline)
                except ServiceProtocolError:
                    # the transport WORKED; the refusal is permanent
                    self._unreachable_since = None
                    raise
                except (OSError, ValueError) as e:
                    # OSError covers socket death, timeouts, refused
                    # connects, InjectedFault and the typed
                    # ServiceUnavailableError; ValueError is a
                    # corrupted (non-JSON) line
                    self._drop_conn()
                    if self._unreachable_since is None:
                        self._unreachable_since = time.monotonic()
                    out_of_time = (time.monotonic() >= deadline)
                    if attempt >= self.retry.max_attempts or \
                            out_of_time:
                        raise ServiceUnavailableError(
                            '%s call %r failed after %d attempt'
                            '(s) over %r: %s'
                            % (self.service, method, attempt,
                               self.endpoints, e)) from e
                    self._m['retries'] += 1
                    time.sleep(max(min(self.retry.backoff(attempt),
                                       deadline - time.monotonic()),
                                   0.0))
                else:
                    self._unreachable_since = None
                    return resp

    # internal spelling kept for the pre-generalization subclasses
    _call = call

    # ---- observability -------------------------------------------------

    def unreachable_age(self):
        """Seconds the service has been continuously unreachable (None
        when the last call succeeded) — the watchdog's
        unreachable probe."""
        since = self._unreachable_since
        return (time.monotonic() - since) if since is not None \
            else None

    def metrics(self):
        m = dict(self._m)
        m['endpoint'] = self.endpoints[self._ep_idx]
        m['endpoints'] = list(self.endpoints)
        m['unreachable_s'] = self.unreachable_age()
        return m

    def close(self):
        with self._lock:
            self._closed = True
            self._drop_conn()

    @property
    def closed(self):
        return self._closed


class ResilientMasterClient(ResilientServiceClient):
    """The ``MasterClient`` surface over the shared substrate:
    reconnect, retry, failover and exactly-once mutations
    (``get_task``/``task_finished``/``task_failed``/``new_pass``
    carry the dedup rid) — see the module doc and ISSUE 15."""

    def __init__(self, endpoints, retry=None, timeout=5.0,
                 fault_injector=None, client_id=None):
        ResilientServiceClient.__init__(
            self, endpoints, retry=retry, timeout=timeout,
            fault_injector=fault_injector, client_id=client_id,
            mutating=_MUTATING, service='master')

    # ---- the MasterClient surface --------------------------------------

    def get_task(self):
        r = self._call('get_task')
        return r['tid'], r['task']

    def task_finished(self, tid):
        self._call('task_finished', tid=tid)

    def task_failed(self, tid):
        return self._call('task_failed', tid=tid)['discarded']

    def counts(self):
        return tuple(self._call('counts')['counts'])

    def new_pass(self, expected=None):
        return self._call('new_pass', expected=expected)['advanced']

    def current_pass(self):
        return self._call('pass_num')['pass_num']

    def register_worker(self, worker_id):
        r = self._call('register_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def heartbeat(self, worker_id):
        r = self._call('heartbeat', worker_id=worker_id)
        return r['epoch'], r['workers']

    def deregister_worker(self, worker_id):
        r = self._call('deregister_worker', worker_id=worker_id)
        return r['epoch'], r['workers']

    def members(self):
        r = self._call('members')
        return r['epoch'], r['workers']

    def fetch_snapshot(self):
        """(blob_bytes, seq) of the master's current queue state."""
        import base64
        r = self._call('snapshot')
        return base64.b64decode(r['blob']), r.get('seq', 0)


class _InProgress(object):
    """Placeholder for a (client, rid) whose first execution is still
    running: a RETRY of the same logical call (the client timed out
    waiting, the response is merely slow) parks on the event and
    replays the eventual record instead of re-executing."""

    __slots__ = ('event', 'resp')

    def __init__(self):
        self.event = threading.Event()
        self.resp = None


class DedupWindow(object):
    """Bounded per-client exactly-once window, standalone (the
    ``Master`` keeps its own so it can ride the snapshot envelope —
    same semantics, same bounds).  ``execute(client, rid, fn)`` runs
    ``fn()`` (one RPC dispatch returning a response dict) exactly once
    per (client, rid): a repeat — a client retrying after a lost OR
    SLOW response — REPLAYS the recorded response (waiting for the
    in-flight first execution when it hasn't finished yet).  Error
    responses are recorded too (a refusal must replay as the same
    refusal).  The window is bounded per client and across clients
    (LRU).  Unlike the master's window, ``fn()`` runs OUTSIDE the
    window lock: a replica's long-running generate dispatch must not
    serialize every other request behind it."""

    def __init__(self, window=64, clients=64):
        if int(window) < 1 or int(clients) < 1:
            raise ValueError('DedupWindow: window and clients must '
                             'be >= 1')
        self.window = int(window)
        self.clients = int(clients)
        self.replays = 0
        self._win = OrderedDict()
        self._lock = threading.Lock()

    def execute(self, client, rid, fn):
        marker = None
        with self._lock:
            win = self._win.get(client)
            rec = win.get(rid) if win is not None else None
            if rec is not None:
                self._win.move_to_end(client)
                self.replays += 1
                if not isinstance(rec, _InProgress):
                    return rec
                marker = rec  # first execution still running: wait
            else:
                if win is None:
                    win = self._win[client] = OrderedDict()
                    while len(self._win) > self.clients:
                        self._win.popitem(last=False)
                self._win.move_to_end(client)
                win[rid] = _InProgress()
        if marker is not None:
            marker.event.wait()
            resp = marker.resp
            if resp is None:  # the first execution died mid-call
                resp = {'error': 'deduplicated call failed before a '
                                 'response was recorded',
                        'etype': 'RuntimeError'}
            return resp
        try:
            resp = fn()
        except BaseException:
            # clear the marker so a retry re-executes instead of
            # replaying a phantom; wake any parked waiters
            with self._lock:
                win = self._win.get(client)
                rec = win.pop(rid, None) if win is not None else None
            if isinstance(rec, _InProgress):
                rec.event.set()
            raise
        with self._lock:
            win = self._win.get(client)
            rec = None
            if win is not None:
                rec = win.get(rid)
                win[rid] = resp
                while len(win) > self.window:
                    win.popitem(last=False)
        if isinstance(rec, _InProgress):
            rec.resp = resp
            rec.event.set()
        return resp

    # ---- durability (ISSUE 19: pserver shard restart) ------------------

    def export_state(self):
        """JSON-serializable snapshot of the recorded responses —
        ``{client: {rid: response}}`` in LRU order.  In-flight
        executions (``_InProgress`` markers) are skipped: their
        response is not recorded yet, so a restore-then-retry
        re-executes them — exactly the at-least-once a lost response
        already implies.  A service that checkpoints its STATE must
        checkpoint this window alongside, or a retry arriving after a
        restart re-applies a mutation the state already holds."""
        with self._lock:
            return {
                client: {rid: resp for rid, resp in win.items()
                         if not isinstance(resp, _InProgress)}
                for client, win in self._win.items()
            }

    def restore_state(self, state):
        """Adopt an ``export_state()`` snapshot (replacing the current
        window) — the restarted-shard half of exactly-once: a client
        retrying a mutation the pre-restart process already applied
        replays the recorded response instead of double-applying.
        Bounds are re-enforced, newest entries win."""
        with self._lock:
            self._win = OrderedDict()
            for client, win in (state or {}).items():
                w = self._win[str(client)] = OrderedDict(
                    (str(rid), dict(resp)) for rid, resp in win.items())
                while len(w) > self.window:
                    w.popitem(last=False)
            while len(self._win) > self.clients:
                self._win.popitem(last=False)


class _ServiceHandler(socketserver.StreamRequestHandler):
    def setup(self):
        socketserver.StreamRequestHandler.setup(self)
        # tracked so ServiceServer.close() can force-close live
        # conversations: a client blocked on readline gets EOF (a
        # typed error), never a hang on a half-shut-down server
        self.server.track(self.connection)

    def finish(self):
        self.server.untrack(self.connection)
        socketserver.StreamRequestHandler.finish(self)

    def handle(self):
        # connection teardown (a dying client, or close() force-
        # shutting the socket under us) ends the conversation, never
        # an unhandled-exception traceback in the handler thread
        try:
            self._serve_lines()
        except OSError:
            return

    def _safe_dispatch(self, method, req):
        """One request -> one response dict.  Errors become in-band
        responses INSIDE this call so a dedup window records refusals
        too (a replayed refusal must replay identically)."""
        try:
            return self.server.dispatch(method, req)
        except Exception as e:  # surface to the client, keep serving
            return {'error': str(e), 'etype': type(e).__name__}

    def _serve_lines(self):
        fi = self.server.fault_injector
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line.decode())
                method = req.get('method')
            except (ValueError, UnicodeDecodeError) as e:
                # a half-written or corrupted line must not wedge the
                # handler: answer typed, keep reading
                self._write({'error': 'malformed request line: %s' % e,
                             'etype': type(e).__name__})
                continue
            if fi is not None:
                rule = fi.check('server_recv', method)
                if rule is not None:
                    act = rule['action']
                    if act == 'delay':
                        time.sleep(rule['delay_s'])
                    elif act in ('drop_request', 'drop_response'):
                        continue  # the request never "arrived"
                    elif act == 'close':
                        return
            rid, client = req.get('rid'), req.get('client')
            dedup = self.server.dedup_execute
            if rid is not None and dedup is not None:
                resp = dedup(str(client), str(rid),
                             lambda: self._safe_dispatch(method, req))
            else:
                resp = self._safe_dispatch(method, req)
            if fi is not None:
                rule = fi.check('server_send', method)
                if rule is not None:
                    act = rule['action']
                    if act == 'delay':
                        time.sleep(rule['delay_s'])
                    elif act == 'drop_response':
                        continue  # processed, response lost on the wire
                    elif act == 'close':
                        return
                    elif act == 'garbage':
                        try:
                            self.wfile.write(b'\x00!garbage!\n')
                            self.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError,
                                OSError):
                            return
                        continue
            if not self._write(resp):
                return

    def _write(self, resp):
        try:
            self.wfile.write((json.dumps(resp) + '\n').encode())
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False


class _TrackedTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler):
        socketserver.ThreadingTCPServer.__init__(self, addr, handler)
        self._conns = set()
        self._conns_lock = threading.Lock()

    def track(self, conn):
        with self._conns_lock:
            self._conns.add(conn)

    def untrack(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)

    def live_connections(self):
        with self._conns_lock:
            return list(self._conns)


class ServiceServer(object):
    """Serve a dispatch table over newline-delimited JSON TCP from a
    daemon thread.

    dispatch: ``fn(method, req) -> response dict`` — exceptions become
        typed in-band error responses (``{'error', 'etype'}``);
        unknown methods should return one too.
    dedup_execute: optional ``fn(client, rid, dispatch_thunk)`` — a
        rid-carrying request routes through it so retried mutations
        replay their recorded response (pass ``Master.dedup_execute``
        or a ``DedupWindow().execute``).
    fault_injector: optional ``FaultInjector`` wired into the
        ``server_recv``/``server_send`` handler sites.
    """

    def __init__(self, dispatch, host='127.0.0.1', port=0,
                 fault_injector=None, dedup_execute=None):
        self.dispatch = dispatch
        self.fault_injector = fault_injector
        self.dedup_execute = dedup_execute
        self._srv = _TrackedTCPServer((host, port), _ServiceHandler)
        self._srv.dispatch = dispatch
        self._srv.fault_injector = fault_injector
        self._srv.dedup_execute = dedup_execute
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    @property
    def endpoint(self):
        return '%s:%d' % (self.host, self.port)

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()
        # force-close live conversations: a handler thread blocked in
        # readline (its client is quiet) or a client blocked waiting
        # for a response must both observe EOF now — racing callers
        # get the typed connection error, never a hang on a server
        # that stopped accepting but kept old sockets open
        for conn in self._srv.live_connections():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
