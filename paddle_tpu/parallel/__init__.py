"""SPMD parallelism over jax.sharding meshes.

This package replaces the reference's entire multi-device machinery —
ParallelExecutor's per-GPU SSA graphs with NCCL AllReduce op-handles
(paddle/fluid/framework/details/multi_devices_graph_pass.cc:529,
all_reduce_op_handle.cc:48) and the gRPC parameter-server transpile
(transpiler/distribute_transpiler.py:180) — with XLA GSPMD: ONE program,
sharding annotations, compiler-inserted collectives riding ICI/DCN.

Axes convention: 'dp' data parallel, 'tp' tensor/model parallel, 'pp'
pipeline stages, 'sp' sequence/context parallel, 'ep' expert parallel.
"""

import numpy as np

from .mesh import make_mesh, mesh_axes, DeviceMesh
from .api import shard, sharding_of, scanned_spec, PartitionSpec
from .context_parallel import (ring_attention, ulysses_attention,
                               dense_attention)
from .multihost import init_distributed_env, parse_distributed_env
from .pipeline import pipeline_spmd, pipeline_apply, stack_stage_params
from .moe import moe_ffn, moe_ffn_spmd, init_moe_params

__all__ = [
    'make_mesh', 'mesh_axes', 'DeviceMesh', 'shard', 'sharding_of',
    'scanned_spec', 'PartitionSpec', 'ring_attention', 'ulysses_attention',
    'dense_attention', 'init_distributed_env', 'parse_distributed_env',
    'pipeline_spmd', 'pipeline_apply', 'stack_stage_params',
    'moe_ffn', 'moe_ffn_spmd', 'init_moe_params',
]
