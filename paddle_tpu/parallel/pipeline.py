"""Pipeline parallelism over a 'pp' mesh axis (GPipe-style).

The reference (2018) has no pipeline engine — its model-parallel story
is per-layer device placement inside ParallelDo / pserver shards
(SURVEY §2.5).  On TPU the natural pipeline is SPMD: every device runs
the SAME program, holds ONE stage's parameters (the stage-stacked
pytree is sharded over 'pp' on its leading axis), and activations hop
to the next stage over the ICI neighbor link via `lax.ppermute` — the
cheapest collective on the chip, same pattern ring attention uses for
K/V blocks.

Schedule: classic GPipe fill-drain.  With S stages and M microbatches
the loop runs M + S - 1 ticks; stage 0 injects microbatch t at tick t,
stage s computes on the activation it received at tick end t-1, and
the last stage emits microbatch t - (S-1) at tick t.  Bubble fraction
is (S-1)/(M+S-1) — callers pick M >= 4*S to amortise (the classic
GPipe guidance).

Everything is pure JAX and differentiable: reverse-mode AD transposes
the ppermutes (activations flow backward stage-to-stage exactly like a
hand-written 1F1B backward), so `jax.grad` of a pipelined loss IS
pipeline-parallel backprop.

Layout contract: microbatches [M, mb, ...] (leading microbatch axis),
stage parameters stacked on a leading [S, ...] axis and sharded
P('pp') so shard_map hands each device its own stage's slice.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['pipeline_apply', 'pipeline_spmd', 'stack_stage_params']


def stack_stage_params(per_stage):
    """[pytree_of_stage0, pytree_of_stage1, ...] -> one pytree whose
    leaves carry a leading stage axis (shard it over 'pp')."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_stage)


def pipeline_apply(stage_fn, stage_params, x_mb, axis_name='pp'):
    """GPipe loop body — runs INSIDE shard_map.

    stage_fn: (params_one_stage, h) -> h_next, same output/input shape
              (inter-stage activations must agree; project inside the
              stage if widths differ).
    stage_params: THIS device's stage slice (leading stage axis already
              consumed by the shard_map in_spec).
    x_mb:     [M, mb, ...] microbatches, replicated over 'pp'.
    Returns [M, mb, ...] pipeline outputs, replicated over 'pp'.
    """
    s = jax.lax.psum(1, axis_name)          # number of stages (static)
    stage = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    fwd = [(i, i + 1) for i in range(s - 1)]  # no wraparound: stage 0
    # receives zeros, which it ignores (it reads the feed instead)

    def tick(carry, t):
        buf, outs = carry
        # stage 0 reads the microbatch feed; others read the activation
        # that arrived from the previous stage at the end of last tick
        inp = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, m - 1)], buf)
        out = stage_fn(stage_params, inp)
        nxt = jax.lax.ppermute(out, axis_name, fwd)
        # the LAST stage's tick-t output is microbatch t-(s-1)
        idx = t - (s - 1)
        valid = jnp.logical_and(stage == s - 1,
                                jnp.logical_and(idx >= 0, idx < m))
        upd = jax.lax.dynamic_update_slice(
            outs, out[None].astype(outs.dtype),
            (jnp.clip(idx, 0, m - 1),) + (0,) * out.ndim)
        outs = jnp.where(valid, upd, outs)
        return (nxt, outs), None

    zero_buf = jnp.zeros_like(x_mb[0])
    zero_out = jnp.zeros((m,) + x_mb.shape[1:], x_mb.dtype)
    (_, outs), _ = jax.lax.scan(tick, (zero_buf, zero_out),
                                jnp.arange(m + s - 1))
    # only the last stage holds real outputs; broadcast to every stage
    # so the loss is computable anywhere (others contribute zeros)
    return jax.lax.psum(
        jnp.where(stage == s - 1, outs, jnp.zeros_like(outs)), axis_name)


def pipeline_spmd(stage_fn, mesh, axis_name='pp', batch_axis=None):
    """Wrap pipeline_apply in a shard_map over `mesh`.

    Returns fn(stacked_params, x_mb) -> [M, mb, ...]:
      stacked_params  leaves [S, ...], sharded P('pp') on the stage axis
      x_mb            [M, mb, ...] microbatches, replicated over 'pp';
                      pass batch_axis='dp' to also shard the mb dim over
                      a data-parallel mesh axis (the pipeline is
                      orthogonal to data parallelism — each dp slice
                      runs its own fill-drain over the same stages).
    """
    param_spec = P(axis_name)
    data_spec = P(None, batch_axis) if batch_axis else P()
    n_stage = mesh.shape[axis_name]

    def check_stages(stacked):
        for leaf in jax.tree_util.tree_leaves(stacked):
            if leaf.shape[0] != n_stage:
                raise ValueError(
                    'pipeline_spmd: stacked stage axis is %d but the '
                    "'%s' mesh axis has %d devices — a mismatched "
                    'stack would silently run the wrong stages'
                    % (leaf.shape[0], axis_name, n_stage))

    def body(stacked_local, x_mb):
        # shard_map hands each device a length-1 slice of the stage
        # axis (validated against the mesh in the caller wrapper);
        # squeeze it so stage_fn sees one stage's parameters
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        return pipeline_apply(stage_fn, local, x_mb,
                              axis_name=axis_name)

    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_spec, data_spec),
        out_specs=data_spec,
        check_vma=False)

    def fn(stacked_params, x_mb):
        check_stages(stacked_params)
        return mapped(stacked_params, x_mb)

    return fn
