"""Multi-host bootstrap: the TPU-native replacement for the reference's
NCCL-id rendezvous.

Reference mechanism: rank 0 creates an ``ncclUniqueId`` and RPCs it to
peers under the ``NCCLID`` var (operators/gen_nccl_id_op.cc:31,
platform/nccl_helper.h:81), with roles/endpoints wired through
``PADDLE_*`` environment variables (trainer.py:324,
benchmark/fluid/fluid_benchmark.py:62-101).

TPU-native: the JAX distributed runtime owns rendezvous —
``jax.distributed.initialize(coordinator, num_processes, process_id)``;
after it, ``jax.devices()`` spans every host and one SPMD program over a
global mesh scales across DCN with zero program changes.  This module
keeps the reference's env-var contract:

    PADDLE_TRAINER_ID        -> process_id
    PADDLE_TRAINERS_NUM      -> num_processes
    PADDLE_TRAINER_ENDPOINTS -> first endpoint = coordinator address
    (or PADDLE_COORDINATOR   -> coordinator address directly)
"""

import os

__all__ = ['init_distributed_env', 'parse_distributed_env',
           'parse_elastic_env']


def parse_distributed_env(environ=None, require_id=True):
    """Resolve (coordinator_address, num_processes, process_id) from the
    PADDLE_* env contract; (None, 1, 0) when not configured.  With
    require_id, a multi-host env missing PADDLE_TRAINER_ID raises (the
    caller has no other id source)."""
    env = environ if environ is not None else os.environ
    num = int(env.get('PADDLE_TRAINERS_NUM', env.get('PADDLE_TRAINERS',
                                                     1)))
    pid_raw = env.get('PADDLE_TRAINER_ID')
    if require_id and num > 1 and pid_raw is None:
        # defaulting to 0 would make every host claim process 0 and hang
        # the coordinator waiting for the others — fail loudly instead
        raise ValueError(
            'PADDLE_TRAINERS_NUM=%d but PADDLE_TRAINER_ID is not set; '
            'every host must export its unique trainer id' % num)
    pid = int(pid_raw or 0)
    coordinator = env.get('PADDLE_COORDINATOR')
    if coordinator is None:
        endpoints = env.get('PADDLE_TRAINER_ENDPOINTS', '')
        first = endpoints.split(',')[0].strip()
        coordinator = first or None
    return coordinator, num, pid


def parse_elastic_env(environ=None):
    """(worker_id, master_endpoint) for an elastic trainer
    (``distributed.ElasticTrainJob``) from the same PADDLE_* contract:

        PADDLE_TRAINER_ID       -> worker id ('trainer-<id>')
        WORKER_TAG              -> overrides the worker id
        PADDLE_MASTER_ENDPOINT  -> the MasterServer door
        (or MASTER_ENDPOINT     -> same, the test-harness spelling)

    master_endpoint is None when no master door is configured (an
    in-process Master job)."""
    env = environ if environ is not None else os.environ
    _, _, pid = parse_distributed_env(env, require_id=False)
    worker_id = env.get('WORKER_TAG') or ('trainer-%d' % pid)
    endpoint = env.get('PADDLE_MASTER_ENDPOINT') or \
        env.get('MASTER_ENDPOINT')
    return worker_id, endpoint


def init_distributed_env(coordinator_address=None, num_processes=None,
                         process_id=None):
    """Initialize the multi-host JAX runtime (no-op single-host).

    Explicit args override the PADDLE_* env contract.  Returns
    (num_processes, process_id)."""
    # explicit args override the env: only require an env trainer id
    # when the caller did not pass one
    env_coord, env_num, env_pid = parse_distributed_env(
        require_id=(process_id is None))
    coordinator_address = coordinator_address or env_coord
    num_processes = num_processes if num_processes is not None else env_num
    process_id = process_id if process_id is not None else env_pid
    if num_processes <= 1:
        return 1, 0
    if coordinator_address is None:
        raise ValueError(
            'multi-host run (%d processes) needs a coordinator: set '
            'PADDLE_COORDINATOR or PADDLE_TRAINER_ENDPOINTS' %
            num_processes)
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return num_processes, process_id
