"""Device-mesh construction (the analog of the reference's
NCCLContextMap world setup, platform/nccl_helper.h:81-123 — but rendezvous
and topology are owned by the TPU runtime, not an id-exchange op)."""

import numpy as np

__all__ = ['make_mesh', 'mesh_axes', 'DeviceMesh']


def _accel_devices():
    import jax
    devs = [d for d in jax.devices() if d.platform != 'cpu']
    return devs if devs else jax.devices()


def make_mesh(axes=None, devices=None):
    """Build a jax.sharding.Mesh.

    axes: dict axis_name -> size (sizes must multiply to len(devices));
          an axis size of -1 is inferred.  Default: {'dp': n_devices}.
    """
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = _accel_devices()
    n = len(devices)
    if axes is None:
        axes = {'dp': n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError('mesh axes %s do not cover %d devices' %
                         (dict(zip(names, sizes)), n))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def mesh_axes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


class DeviceMesh(object):
    """Thin named wrapper kept for API symmetry with places."""

    def __init__(self, axes=None, devices=None):
        self.mesh = make_mesh(axes, devices)

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *a):
        return self._ctx.__exit__(*a)
