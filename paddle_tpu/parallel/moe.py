"""Expert parallelism: switch-style Mixture-of-Experts over an 'ep' axis.

The reference (2018) predates MoE; this is a TPU-native capability in
the same spirit as ring attention (context_parallel.py).  Design:

- **Routing**: top-1 (Switch Transformer) gating with a fixed per-expert
  token capacity C = ceil(tokens/experts * capacity_factor).  Static
  shapes throughout — XLA cannot compile data-dependent token counts,
  so routing is the classic GShard dense-dispatch formulation: a
  [tokens, E, C] one-hot dispatch tensor built from a capacity-limited
  cumulative count, einsummed against the token activations.  Tokens
  over capacity are dropped (output zero, the documented Switch
  behavior); the combine weight carries the gate probability so
  gradients flow into the router.
- **Expert parallelism**: experts are sharded over the 'ep' mesh axis
  (leading axis of every expert weight).  Tokens are sharded over 'ep'
  too (data-parallel in, expert-parallel compute): after local dispatch
  the [E, C_local, D] buckets cross devices with ONE `lax.all_to_all`
  (each device keeps its own experts' buckets from every peer), the
  local experts run as one batched einsum — E_local big MXU matmuls —
  and a second all_to_all routes results home.  This is exactly the
  GShard/Switch dataflow, with XLA inserting nothing else.

Differentiable end-to-end (all_to_all transposes to all_to_all), and
composable with 'dp' outside.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ['moe_ffn', 'moe_ffn_spmd', 'init_moe_params']


def init_moe_params(rng, d_model, d_ff, n_expert, dtype=np.float32):
    """Expert weights with a leading [E, ...] axis (shard over 'ep')."""
    k = 1.0 / np.sqrt(d_model)
    r = np.random.RandomState(rng)
    return {
        'gate_w': (r.standard_normal((d_model, n_expert)) * k).astype(dtype),
        'w1': (r.standard_normal((n_expert, d_model, d_ff)) * k).astype(dtype),
        'b1': np.zeros((n_expert, d_ff), dtype),
        'w2': (r.standard_normal((n_expert, d_ff, d_model)) *
               (1.0 / np.sqrt(d_ff))).astype(dtype),
        'b2': np.zeros((n_expert, d_model), dtype),
    }


def _route_top1(x, gate_w, n_expert, capacity):
    """Switch top-1 routing with capacity.  x: [N, D].
    Returns (dispatch [N, E, C] one-hot, combine [N, E, C] weighted)."""
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)          # [N, E]
    expert = jnp.argmax(probs, axis=-1)              # [N]
    gate = jnp.max(probs, axis=-1)                   # [N]
    onehot = jax.nn.one_hot(expert, n_expert, dtype=jnp.float32)  # [N, E]
    # position of each token within its expert's queue (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [N, E], -1 elsewhere
    keep = (pos < capacity) & (onehot > 0)           # capacity drop
    # each row has exactly one selected expert -> its slot index (max
    # over E skips the -1 sentinels; -1 rows one_hot to all-zero = drop)
    slot = jnp.max(jnp.where(keep, pos, -1.0), axis=-1).astype(jnp.int32)
    pos_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # [N, C]
    dispatch = keep.astype(jnp.float32)[..., None] * pos_oh[:, None, :]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def _expert_ffn(w1, b1, w2, b2, h):
    """Batched expert FFN: h [E, C, D] -> [E, C, D], relu inner."""
    a = jnp.maximum(jnp.einsum('ecd,edf->ecf', h, w1) + b1[:, None, :], 0.0)
    return jnp.einsum('ecf,efd->ecd', a, w2) + b2[:, None, :]


def moe_ffn(params, x, capacity_factor=1.25):
    """Single-device reference semantics — the test oracle path AND the
    body of the fluid lowering (ops/moe_ops.py), so the routing math
    has one source of truth.  x: [N, D] tokens.  Returns [N, D]."""
    n_expert = params['gate_w'].shape[-1]
    n = x.shape[0]
    capacity = max(int(np.ceil(n / n_expert * capacity_factor)), 1)
    dispatch, combine = _route_top1(x, params['gate_w'], n_expert,
                                    capacity)
    # [N,E,C] x [N,D] -> buckets [E,C,D]
    buckets = jnp.einsum('nec,nd->ecd', dispatch, x.astype(jnp.float32))
    out = _expert_ffn(params['w1'].astype(jnp.float32),
                      params['b1'].astype(jnp.float32),
                      params['w2'].astype(jnp.float32),
                      params['b2'].astype(jnp.float32), buckets)
    return jnp.einsum('nec,ecd->nd', combine, out).astype(x.dtype)


def _moe_local(params_local, x_local, n_expert, capacity, axis_name):
    """Per-shard body (runs under shard_map).  x_local: [N_local, D]
    (tokens sharded over 'ep'); params_local: this device's experts
    (leading E_local axis).  Dispatch is computed against ALL experts,
    buckets cross shards via all_to_all, local experts compute, results
    all_to_all home."""
    ep = jax.lax.psum(1, axis_name)
    e_local = n_expert // ep
    gate_w = params_local['gate_w']          # replicated [D, E]
    dispatch, combine = _route_top1(x_local, gate_w, n_expert, capacity)
    # local buckets for every expert: [E, C, D]
    buckets = jnp.einsum('nec,nd->ecd', dispatch,
                         x_local.astype(jnp.float32))
    # regroup to [ep, E_local, C, D] and trade: device k keeps group k
    # from every peer -> [ep(origin), E_local, C, D]
    b = buckets.reshape(ep, e_local, capacity, -1)
    b = jax.lax.all_to_all(b, axis_name, split_axis=0, concat_axis=0,
                           tiled=False)
    # run MY experts over the tokens of all origins: fold origins into
    # the capacity axis for one batched einsum
    h = jnp.transpose(b, (1, 0, 2, 3)).reshape(e_local, ep * capacity, -1)
    out = _expert_ffn(params_local['w1'].astype(jnp.float32),
                      params_local['b1'].astype(jnp.float32),
                      params_local['w2'].astype(jnp.float32),
                      params_local['b2'].astype(jnp.float32), h)
    # unfold and send each origin's results back home
    out = jnp.transpose(
        out.reshape(e_local, ep, capacity, -1), (1, 0, 2, 3))
    out = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    out = out.reshape(n_expert, capacity, -1)    # [E, C, D] back home
    return jnp.einsum('nec,ecd->nd', combine, out).astype(x_local.dtype)


def moe_ffn_spmd(mesh, n_expert, axis_name='ep', capacity_factor=1.25,
                 batch_axis=None):
    """shard_map-wrapped expert-parallel MoE FFN.

    Returns fn(params, x) -> [N, D]:
      params  init_moe_params pytree; expert leaves [E, ...] sharded
              P('ep'), gate replicated
      x       [N, D] tokens, sharded over 'ep' (and 'dp' via batch_axis
              composes outside)
    Capacity is per LOCAL shard (each shard routes its own tokens), so
    the dispatch tensors stay shard-local sized.
    """
    expert_spec = {'gate_w': P(), 'w1': P(axis_name), 'b1': P(axis_name),
                   'w2': P(axis_name), 'b2': P(axis_name)}
    tok_axes = (batch_axis, axis_name) if batch_axis else (axis_name,)
    tok_spec = P(tok_axes if len(tok_axes) > 1 else axis_name)

    def body(params_local, x_local):
        n_local = x_local.shape[0]
        capacity = int(np.ceil(n_local / n_expert * capacity_factor))
        return _moe_local(params_local, x_local, n_expert,
                          max(capacity, 1), axis_name)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(expert_spec, tok_spec),
        out_specs=tok_spec, check_vma=False)
