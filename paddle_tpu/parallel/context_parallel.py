"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference (2018) has no sequence parallelism — long sequences are
handled by LoD + DynamicRNN (SURVEY §5.7).  A TPU-native framework must
scale attention past one chip's HBM, so context parallelism is first-class
here:

- **Ring attention** (`ring_attention`): Q stays put, K/V blocks rotate
  around the 'sp' mesh axis via `lax.ppermute` while each step folds its
  block into a blockwise online softmax (running max / running sum), so no
  device ever materialises the full [L, L] score matrix or the full K/V.
  Collectives ride ICI neighbor links — the cheapest possible pattern.
- **Ulysses** (`ulysses_attention`): two `lax.all_to_all`s reshard
  [B, L/n, H, D] -> [B, L, H/n, D] and back, computing full-sequence
  attention per head shard.  Cheaper compute bookkeeping than the ring when
  H is divisible by the axis size and L fits per-device after the gather of
  scores is avoided per-head; costlier bandwidth (all-to-all vs neighbor).

Both are pure-JAX, differentiable (reverse-mode AD transposes the
ppermutes/all_to_alls), and compose with 'dp' batch sharding in the same
`shard_map`.  Tensor layout: [batch, seq, heads, head_dim].
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ulysses_attention', 'dense_attention']

_NEG_INF = -1e30


def _attend_block(q, k, v, scale, mask):
    """Scores for one (Q local, KV block) pair + masked blockwise softmax
    pieces.  q: [B,Lq,H,D], k/v: [B,Lk,H,D], mask: [B,1,Lq,Lk] or None.
    Returns (m, l, acc): running max [B,H,Lq], sum [B,H,Lq],
    numerator [B,Lq,H,D]."""
    # scores and softmax statistics in f32 regardless of input dtype:
    # bf16 inputs (AMP) keep the MXU fast paths, but a bf16 running
    # sum/max across thousands of columns drifts (8-bit mantissa)
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    # guard all-masked rows: exp(-inf - (-inf)) = nan
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum('bhqk,bkhd->bqhd', p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m_safe, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    """Fold two blockwise-softmax partials into one (online softmax)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    # acc is [B,Lq,H,D]; alphas are [B,H,Lq] -> [B,Lq,H,1]
    t1 = jnp.transpose(a1, (0, 2, 1))[..., None]
    t2 = jnp.transpose(a2, (0, 2, 1))[..., None]
    return m, l, acc1 * t1 + acc2 * t2


def _block_mask(q_pos, k_pos, causal, batch_lens):
    """[B,1,Lq,Lk] boolean mask (True = attend) from global positions.
    batch_lens: [B] valid K lengths (global) or None."""
    mask = None
    if causal:
        mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
    if batch_lens is not None:
        valid = k_pos[None, :] < batch_lens[:, None]  # [B, Lk]
        valid = valid[:, None, None, :]  # [B,1,1,Lk]
        mask = valid if mask is None else jnp.logical_and(mask, valid)
    if mask is not None:
        mask = jnp.broadcast_to(
            mask, (mask.shape[0], 1, q_pos.shape[0], k_pos.shape[0]))
    return mask


def dense_attention(q, k, v, causal=False, scale=None, seq_lengths=None):
    """Single-device reference: softmax(QK^T * scale [+mask]) V.
    q,k,v: [B,L,H,D]; seq_lengths: [B] optional valid K/V lengths.

    One-shot softmax, NOT the blockwise m/l/merge form the ring path
    uses: on a single device the online-softmax machinery costs real
    HBM traffic (f32 [B,L,H,D] numerator + l transposes + the final
    divide measured ~4ms/step of layout copies on the r5 transformer
    A/B trace) and buys nothing — there are no blocks to merge."""
    scale = scale if scale is not None else q.shape[-1]**-0.5
    lq, lk = q.shape[1], k.shape[1]
    mask = _block_mask(
        jnp.arange(lq), jnp.arange(lk), causal,
        None if seq_lengths is None else jnp.asarray(seq_lengths))
    # scores/softmax in f32 (bf16 exp/sum across thousands of columns
    # drifts); the probability matrix re-narrows to v's dtype so the
    # PV matmul and its [B,L,H,D] output stay half-width under AMP
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)  # all-masked rows: 0, not 1/Lk
    return jnp.einsum('bhqk,bkhd->bqhd', p.astype(v.dtype), v)


def _ring_local(q, k, v, lens, axis_name, n_steps, causal, scale):
    """Per-shard ring attention body (runs under shard_map).

    q,k,v: local [B, Lc, H, D] chunks of the 'sp'-sharded sequence;
    lens: [B] global valid lengths or None."""
    idx = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lkv = k.shape[1]  # cross-attention: K/V chunk length may differ from Q's
    q_pos = idx * lq + jnp.arange(lq)

    # running statistics live in f32 (see _attend_block)
    m0 = jnp.full((b, h, lq), _NEG_INF / 2, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    acc0 = jnp.zeros((b, lq, h, v.shape[-1]), jnp.float32)

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        # block held at step t originated on device (idx - t) mod n
        src = (idx - t) % n_steps
        k_pos = src * lkv + jnp.arange(lkv)
        mask = _block_mask(q_pos, k_pos, causal, lens)
        bm, bl, bacc = _attend_block(q, k_blk, v_blk, scale, mask)
        m, l, acc = _merge(m, l, acc, bm, bl, bacc)
        perm = [(i, (i + 1) % n_steps) for i in range(n_steps)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, acc), None

    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n_steps))
    l = jnp.transpose(l, (0, 2, 1))[..., None]  # [B,Lc,H,1]
    return acc / jnp.maximum(l, 1e-20)


def ring_attention(q, k, v, mesh, axis='sp', causal=False, scale=None,
                   seq_lengths=None, batch_axis=None):
    """Ring attention over the ``axis`` mesh dimension.

    q,k,v: [B, L, H, D] with L divisible by the axis size (global view —
    under jit the arrays may already be sharded; shard_map just binds the
    per-device view).  batch_axis: mesh axis B is sharded over ('dp') or
    None.  Returns [B, L, H, D].
    """
    scale = scale if scale is not None else q.shape[-1]**-0.5
    n = mesh.shape[axis]
    body = functools.partial(_ring_local, axis_name=axis, n_steps=n,
                             causal=causal, scale=scale)
    return _sharded_call(body, q, k, v, seq_lengths, mesh, axis, batch_axis)


def _ulysses_local(q, k, v, lens, axis_name, n, causal, scale):
    """Per-shard Ulysses body: all_to_all seq->head reshard, dense local
    attention over the FULL sequence for H/n heads, reshard back.

    tiled all_to_all: [B, L/n, H, D] -(split H, concat L)-> [B, L, H/n, D];
    device j keeps head group j, receives every device's sequence chunk in
    ring order so the concatenated L axis is the global sequence."""

    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = dense_attention(q, k, v, causal=causal, scale=scale,
                          seq_lengths=lens)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis='sp', causal=False, scale=None,
                      seq_lengths=None, batch_axis=None):
    """DeepSpeed-Ulysses-style attention: two all-to-alls swap the sharded
    dimension from sequence to heads so each device runs full-sequence
    attention on H/n heads.  Requires H % axis_size == 0."""
    scale = scale if scale is not None else q.shape[-1]**-0.5
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError('ulysses needs heads (%d) divisible by %s=%d' %
                         (q.shape[2], axis, n))
    body = functools.partial(_ulysses_local, axis_name=axis, n=n,
                             causal=causal, scale=scale)
    return _sharded_call(body, q, k, v, seq_lengths, mesh, axis, batch_axis)


def _sharded_call(body, q, k, v, seq_lengths, mesh, axis, batch_axis):
    """shard_map a local attention body over (sp [, dp]) with optional
    replicated-over-sp per-batch lengths."""
    qkv_spec = P(batch_axis, axis, None, None)
    if seq_lengths is None:
        return jax.shard_map(
            lambda a, b, c: body(a, b, c, None),
            mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec, check_vma=False)(q, k, v)
    return jax.shard_map(
        lambda a, b, c, sl: body(a, b, c, sl),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(batch_axis)),
        out_specs=qkv_spec, check_vma=False)(
            q, k, v, jnp.asarray(seq_lengths, jnp.int32))
