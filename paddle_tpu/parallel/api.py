"""Sharding annotations on program variables.

The reference expresses placement by *rewriting programs* (transpilers) or
building per-device graphs; TPU-natively, placement is a property: annotate
a Variable with a PartitionSpec and the SPMD executor lays it out, letting
GSPMD insert collectives.
"""

from jax.sharding import PartitionSpec

__all__ = ['shard', 'sharding_of', 'scanned_spec', 'PartitionSpec']

_ATTR = '_sharding_spec'


def shard(var, *spec):
    """Annotate a program Variable (or Parameter) with a PartitionSpec.

    Example: shard(w, None, 'tp') — shard w's dim1 over the 'tp' mesh axis.
    """
    if len(spec) == 1 and isinstance(spec[0], PartitionSpec):
        setattr(var, _ATTR, spec[0])
    else:
        setattr(var, _ATTR, PartitionSpec(*spec))
    return var


def sharding_of(var, default=None):
    return getattr(var, _ATTR, default)


def scanned_spec(spec):
    """The PartitionSpec for a K-steps-stacked value: the per-step spec
    shifted right of an UNsharded leading steps axis (run_multi's
    scanned feeds: [K, B, ...] with B over 'dp', K over nothing)."""
    return PartitionSpec(*((None, ) + tuple(spec)))
