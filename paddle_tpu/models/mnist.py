"""MNIST models (reference: benchmark/fluid/models/mnist.py and
tests/book/test_recognize_digits.py)."""

import paddle_tpu.fluid as fluid

__all__ = ['mlp', 'conv_net', 'build']


def mlp(img, label):
    hidden = fluid.layers.fc(input=img, size=200, act='tanh')
    hidden = fluid.layers.fc(input=hidden, size=200, act='tanh')
    prediction = fluid.layers.fc(input=hidden, size=10, act='softmax')
    loss = fluid.layers.cross_entropy(input=prediction, label=label)
    return prediction, fluid.layers.mean(loss)


def conv_net(img, label):
    """LeNet-style conv net (reference test_recognize_digits.py conv path)."""
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=img,
        filter_size=5,
        num_filters=20,
        pool_size=2,
        pool_stride=2,
        act='relu')
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1,
        filter_size=5,
        num_filters=50,
        pool_size=2,
        pool_stride=2,
        act='relu')
    prediction = fluid.layers.fc(input=conv_pool_2, size=10, act='softmax')
    loss = fluid.layers.cross_entropy(input=prediction, label=label)
    return prediction, fluid.layers.mean(loss)


def build(nn_type='mlp', img_shape=(784, ), lr=0.01):
    """Build (main, startup, feeds, prediction, loss, acc)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(
            name='img', shape=list(img_shape), dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        net = mlp if nn_type == 'mlp' else conv_net
        prediction, loss = net(img, label)
        acc = fluid.layers.accuracy(input=prediction, label=label)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['img', 'label'],
        prediction=prediction,
        loss=loss,
        acc=acc)
