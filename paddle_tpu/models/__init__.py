"""Benchmark/book model zoo (reference: benchmark/fluid/models/ and
python/paddle/fluid/tests/book/)."""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401
from . import seq2seq  # noqa: F401
from . import stacked_lstm  # noqa: F401
from . import fit_a_line  # noqa: F401
from . import word2vec  # noqa: F401
from . import recommender  # noqa: F401
from . import label_semantic_roles  # noqa: F401

__all__ = [
    'mnist', 'resnet', 'vgg', 'seq2seq', 'stacked_lstm', 'fit_a_line',
    'word2vec', 'recommender', 'label_semantic_roles'
]
