"""Benchmark/book model zoo (reference: benchmark/fluid/models/ and
python/paddle/fluid/tests/book/)."""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401

__all__ = ['mnist', 'resnet', 'vgg']
