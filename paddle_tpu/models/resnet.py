"""ResNet for ImageNet/cifar (reference: benchmark/fluid/models/resnet.py).

The bench flagship: conv2d + batch_norm hot path, bottleneck blocks.  All
convs stay NCHW at the API level; XLA lays them out for the MXU.
"""

import paddle_tpu.fluid as fluid

__all__ = ['resnet_imagenet', 'resnet_cifar10', 'build']


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu'):
    conv1 = fluid.layers.conv2d(
        input=input,
        filter_size=filter_size,
        num_filters=ch_out,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=False)
    return fluid.layers.batch_norm(input=conv1, act=act)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None)
    return input


def basicblock(input, ch_out, stride):
    short = shortcut(input, ch_out, stride)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None)
    return fluid.layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_out, stride):
    short = shortcut(input, ch_out * 4, stride)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None)
    return fluid.layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_out, count, stride):
    res_out = block_func(input, ch_out, stride)
    for i in range(1, count):
        res_out = block_func(res_out, ch_out, 1)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, logits_only=False):
    """ResNet-50/101/152 (reference resnet.py:47).  ``logits_only`` skips
    the softmax so the caller can use the fused
    softmax_with_cross_entropy loss (one kernel, better numerics than
    softmax + cross_entropy — reference softmax_with_cross_entropy_op.cc
    motivates the same fusion)."""
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck)
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3)
    pool1 = fluid.layers.pool2d(
        input=conv1, pool_type='max', pool_size=3, pool_stride=2,
        pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2)
    pool2 = fluid.layers.pool2d(
        input=res4, pool_size=7, pool_type='avg', pool_stride=1,
        global_pooling=True)
    out = fluid.layers.fc(input=pool2, size=class_dim,
                          act=None if logits_only else 'softmax')
    return out


def resnet_cifar10(input, class_dim, depth=32):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(
        input=input, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 64, n, 2)
    pool = fluid.layers.pool2d(
        input=res3, pool_size=8, pool_type='avg', pool_stride=1,
        global_pooling=True)
    out = fluid.layers.fc(input=pool, size=class_dim, act='softmax')
    return out


def build(depth=50,
          class_dim=1000,
          image_shape=(3, 224, 224),
          lr=0.01,
          use_momentum=True,
          variant='imagenet',
          fused_ce=True):
    """Build the train/test programs (reference benchmark fluid_benchmark).

    ``fused_ce`` (imagenet variant) trains on the fused
    softmax_with_cross_entropy head — one kernel, log-sum-exp stable —
    and leaves a softmax prediction output for inference/accuracy."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(
            name='img', shape=list(image_shape), dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        if variant == 'imagenet' and fused_ce:
            logits = resnet_imagenet(img, class_dim, depth=depth,
                                     logits_only=True)
            prediction = fluid.layers.softmax(logits)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits=logits, label=label))
        else:
            if variant == 'imagenet':
                prediction = resnet_imagenet(img, class_dim, depth=depth)
            else:
                prediction = resnet_cifar10(img, class_dim, depth=depth)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=prediction, label=label))
        acc = fluid.layers.accuracy(input=prediction, label=label)
        test_program = main.clone(for_test=True)
        if use_momentum:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        else:
            opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(loss)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['img', 'label'],
        prediction=prediction,
        loss=loss,
        acc=acc)
