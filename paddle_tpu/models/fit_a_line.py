"""Linear regression on uci_housing
(reference: tests/book/test_fit_a_line.py)."""

import paddle_tpu.fluid as fluid

__all__ = ['build']


def build(feature_dim=13, lr=0.01):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name='x', shape=[feature_dim],
                              dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(cost)
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['x', 'y'],
        prediction=y_predict,
        loss=avg_cost)
