"""Stacked dynamic-LSTM sentiment model
(reference: benchmark/fluid/models/stacked_dynamic_lstm.py)."""

import paddle_tpu.fluid as fluid

__all__ = ['stacked_lstm_net', 'build']


def stacked_lstm_net(data, label, dict_dim, emb_dim=128, hid_dim=128,
                     stacked_num=3, class_dim=2):
    emb = fluid.layers.embedding(
        input=data, size=[dict_dim, emb_dim], is_sparse=False)

    fc1 = fluid.layers.fc(input=emb, size=hid_dim * 4)
    lstm1, cell1 = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim * 4)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim * 4)
        lstm, cell = fluid.layers.dynamic_lstm(
            input=fc, size=hid_dim * 4, is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]

    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type='max')
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type='max')

    prediction = fluid.layers.fc(
        input=[fc_last, lstm_last], size=class_dim, act='softmax')
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    return prediction, fluid.layers.mean(cost)


def build(dict_dim=5149, class_dim=2, emb_dim=128, hid_dim=128,
          stacked_num=3, lr=0.002):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(
            name='words', shape=[1], dtype='int64', lod_level=1)
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        prediction, loss = stacked_lstm_net(
            data, label, dict_dim, emb_dim, hid_dim, stacked_num, class_dim)
        acc = fluid.layers.accuracy(input=prediction, label=label)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['words', 'label'],
        prediction=prediction,
        loss=loss,
        acc=acc)
