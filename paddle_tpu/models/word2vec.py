"""N-gram word2vec (reference: tests/book/test_word2vec.py).

4 context-word embeddings sharing one table -> concat -> hidden ->
softmax over the vocabulary.
"""

import paddle_tpu.fluid as fluid

__all__ = ['build']


def build(dict_size=200, embed_size=32, hidden_size=256, lr=0.001,
          is_sparse=False):
    feed_names = ['firstw', 'secondw', 'thirdw', 'forthw', 'nextw']
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        words = [
            fluid.layers.data(name=n, shape=[1], dtype='int64')
            for n in feed_names
        ]
        embeds = [
            fluid.layers.embedding(
                input=w,
                size=[dict_size, embed_size],
                dtype='float32',
                is_sparse=is_sparse,
                param_attr=fluid.ParamAttr(name='shared_w'))
            for w in words[:4]
        ]
        concat = fluid.layers.concat(input=embeds, axis=1)
        hidden = fluid.layers.fc(input=concat, size=hidden_size,
                                 act='sigmoid')
        predict = fluid.layers.fc(input=hidden, size=dict_size,
                                  act='softmax')
        cost = fluid.layers.cross_entropy(input=predict, label=words[4])
        avg_cost = fluid.layers.mean(cost)
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=feed_names,
        prediction=predict,
        loss=avg_cost)
