"""CTR model: sparse-embedding DNN (wide & deep flavored).

Reference capability: the distributed-lookup-table CTR config
(SURVEY §2.5 "Model parallelism (sparse / large embedding)",
doc/fluid/design/dist_train/distributed_lookup_table_design.md).  The
embedding table is looked up with ``is_sparse=True`` so its gradient is a
SelectedRows/SparseRows row-subset — never a dense [V, D] tensor — and,
under the SPMD executor, the table itself can be row-sharded over the mesh
with ``paddle_tpu.parallel.shard(embed_param, 'mp', None)``.
"""

import paddle_tpu.fluid as fluid
from paddle_tpu.dataset import ctr as ctr_data

__all__ = ['build']


def build(sparse_dim=None, embed_size=16, hidden_sizes=(64, 32),
          lr=0.01, is_sparse=True, is_distributed=False, optimizer=None):
    sparse_dim = sparse_dim or ctr_data.SPARSE_DIM
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.layers.data(
            name='dense', shape=[ctr_data.DENSE_DIM], dtype='float32')
        sparse_ids = fluid.layers.data(
            name='sparse_ids', shape=[ctr_data.SPARSE_SLOTS], dtype='int64')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')

        # one shared table for all 26 slots: ids [B, 26] -> [B, 26, E]
        embed = fluid.layers.embedding(
            input=sparse_ids,
            size=[sparse_dim, embed_size],
            is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr=fluid.ParamAttr(name='ctr_embedding'),
            dtype='float32')
        embed_flat = fluid.layers.reshape(
            embed, shape=[-1, ctr_data.SPARSE_SLOTS * embed_size])

        deep = fluid.layers.concat([dense, embed_flat], axis=1)
        for h in hidden_sizes:
            deep = fluid.layers.fc(input=deep, size=h, act='relu')
        # wide part: linear on dense features
        wide = fluid.layers.fc(input=dense, size=1, act=None)
        deep_out = fluid.layers.fc(input=deep, size=1, act=None)
        logit = fluid.layers.elementwise_add(deep_out, wide)
        predict = fluid.layers.sigmoid(logit)
        loss = fluid.layers.sigmoid_cross_entropy_with_logits(
            logit, fluid.layers.cast(label, 'float32'))
        avg_loss = fluid.layers.mean(loss)
        test_program = main.clone(for_test=True)
        opt = optimizer or fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(avg_loss)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['dense', 'sparse_ids', 'label'],
        prediction=predict,
        loss=avg_loss)
