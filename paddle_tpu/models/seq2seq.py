"""Seq2seq NMT with attention
(reference: benchmark/fluid/machine_translation.py and
tests/book/test_machine_translation.py).

Encoder: embedding -> per-token fc -> dynamic LSTM.
Decoder: DynamicRNN over target tokens with Bahdanau-style attention over
the encoder states — the attention is plain sequence ops (expand, softmax,
pool) inside the RNN block, lowered to one masked lax.scan whose inner ops
are batched matmuls on the MXU.
"""

import paddle_tpu.fluid as fluid

__all__ = ['build', 'build_decode', 'build_step_decode']


def encoder(src_word_id, src_dict_dim, embedding_dim, encoder_size):
    src_embedding = fluid.layers.embedding(
        input=src_word_id, size=[src_dict_dim, embedding_dim])
    fc1 = fluid.layers.fc(input=src_embedding, size=encoder_size * 4,
                          act='tanh')
    lstm_hidden, lstm_cell = fluid.layers.dynamic_lstm(
        input=fc1, size=encoder_size * 4)
    return lstm_hidden


def simple_attention(encoder_vec, encoder_proj, decoder_state,
                     decoder_size):
    """(reference machine_translation.py simple_attention)"""
    decoder_state_proj = fluid.layers.fc(
        input=decoder_state, size=decoder_size, bias_attr=False)
    decoder_state_expand = fluid.layers.sequence_expand(
        x=decoder_state_proj, y=encoder_proj)
    concated = fluid.layers.elementwise_add(encoder_proj,
                                            decoder_state_expand)
    concated = fluid.layers.tanh(concated)
    attention_weights = fluid.layers.fc(
        input=concated, size=1, act=None, bias_attr=False)
    attention_weights = fluid.layers.sequence_softmax(
        input=attention_weights)
    scaled = fluid.layers.elementwise_mul(
        x=encoder_vec, y=attention_weights, axis=0)
    context = fluid.layers.sequence_pool(input=scaled, pool_type='sum')
    return context


def train_decoder(context_boot, encoder_vec, encoder_proj, trg_word_id,
                  trg_dict_dim, embedding_dim, decoder_size):
    trg_embedding = fluid.layers.embedding(
        input=trg_word_id, size=[trg_dict_dim, embedding_dim])

    rnn = fluid.layers.DynamicRNN()
    with rnn.block():
        current_word = rnn.step_input(trg_embedding)
        vec = rnn.static_input(encoder_vec)
        proj = rnn.static_input(encoder_proj)
        hidden_mem = rnn.memory(init=context_boot)
        context = simple_attention(vec, proj, hidden_mem, decoder_size)
        decoder_inputs = fluid.layers.fc(
            input=[context, current_word],
            size=decoder_size * 3,
            bias_attr=False)
        h, _, _ = fluid.layers.gru_unit(
            input=decoder_inputs, hidden=hidden_mem, size=decoder_size * 3)
        rnn.update_memory(hidden_mem, h)
        # the scan zeroes outputs past each row's true length, so a
        # constant-1 output doubles as the [B, T, 1] padding mask
        valid = fluid.layers.fill_constant_batch_size_like(
            input=current_word, shape=[-1, 1], value=1.0, dtype='float32')
        rnn.output(h, valid)
    # The reference model computes fc(h, act='softmax') INSIDE the rnn
    # block (machine_translation.py lstm_decoder_with_attention) — one
    # [B, D]x[D, V] matmul per scan step.  The projection is pointwise in
    # time, so hoisting it after the scan is mathematically identical but
    # runs as a single [B*T, D]x[D, V] matmul — the model's dominant
    # FLOPs land on the MXU in one tile-friendly call instead of T
    # sequential slivers.
    hidden_seq, valid_mask = rnn()
    logits = fluid.layers.fc(input=hidden_seq, size=trg_dict_dim)
    return logits, valid_mask


def build(src_dict_dim=1000,
          trg_dict_dim=1000,
          embedding_dim=64,
          encoder_size=64,
          decoder_size=64,
          lr=0.001):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(
            name='src_word_id', shape=[1], dtype='int64', lod_level=1)
        trg = fluid.layers.data(
            name='target_language_word', shape=[1], dtype='int64',
            lod_level=1)
        label = fluid.layers.data(
            name='target_language_next_word', shape=[1], dtype='int64',
            lod_level=1)

        encoder_out = encoder(src, src_dict_dim, embedding_dim,
                              encoder_size)
        encoder_proj = fluid.layers.fc(
            input=encoder_out, size=decoder_size, bias_attr=False)
        encoder_last = fluid.layers.sequence_last_step(input=encoder_out)
        decoder_boot = fluid.layers.fc(
            input=encoder_last, size=decoder_size, act='tanh')

        logits, valid_mask = train_decoder(decoder_boot, encoder_out,
                                           encoder_proj, trg, trg_dict_dim,
                                           embedding_dim, decoder_size)
        # zero the padded rows like the in-scan softmax did (the scan
        # masks its outputs; the hoisted softmax must re-apply that mask)
        prediction = fluid.layers.elementwise_mul(
            fluid.layers.softmax(logits), valid_mask)
        # fused log-softmax + NLL: one kernel, no materialized [B,T,V]
        # probability tensor on the backward path (reference
        # softmax_with_cross_entropy_op.cc is the same fusion)
        cost = fluid.layers.softmax_with_cross_entropy(logits, label)
        # per-sentence sum over true length, then batch mean (padding is
        # masked by the carried lengths)
        sent_cost = fluid.layers.sequence_pool(input=cost, pool_type='sum')
        avg_cost = fluid.layers.mean(sent_cost)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['src_word_id', 'target_language_word',
               'target_language_next_word'],
        prediction=prediction,
        loss=avg_cost)


def build_decode(src_dict_dim=1000,
                 trg_dict_dim=1000,
                 embedding_dim=64,
                 encoder_size=64,
                 decoder_size=64,
                 beam_size=4,
                 max_length=16,
                 start_id=0,
                 end_id=1):
    """Beam-search inference program (reference:
    tests/book/test_machine_translation.py decode()).

    The reference drives a while-op whose beams grow through nested LoD;
    here the beam dim is static [B*K] and the loop is a StaticRNN (one
    lax.scan of max_length steps) carrying (ids, scores, hidden) with the
    beam_search op doing per-step selection and beam_search_decode
    backtracking parent pointers at the end.
    """
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(
            name='src_word_id', shape=[1], dtype='int64', lod_level=1)
        encoder_out = encoder(src, src_dict_dim, embedding_dim,
                              encoder_size)
        encoder_proj = fluid.layers.fc(
            input=encoder_out, size=decoder_size, bias_attr=False)
        encoder_last = fluid.layers.sequence_last_step(input=encoder_out)
        decoder_boot = fluid.layers.fc(
            input=encoder_last, size=decoder_size, act='tanh')

        # tile per-sentence state to per-beam rows [B*K, ...]
        vec = fluid.layers.beam_expand(encoder_out, beam_size)
        proj = fluid.layers.beam_expand(encoder_proj, beam_size)
        boot = fluid.layers.beam_expand(decoder_boot, beam_size)
        init_ids = fluid.layers.fill_constant_batch_size_like(
            input=boot, shape=[-1, 1], value=float(start_id), dtype='int64')
        init_scores = fluid.layers.beam_init_scores(decoder_boot, beam_size)
        # dummy step input just drives the scan for max_length steps
        ticker = fluid.layers.fill_constant_batch_size_like(
            input=boot, shape=[max_length, -1, 1], value=0.0,
            dtype='float32', input_dim_idx=0, output_dim_idx=1)

        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            rnn.step_input(ticker)
            pre_ids = rnn.memory(init=init_ids)
            pre_scores = rnn.memory(init=init_scores)
            hidden_mem = rnn.memory(init=boot)
            context = simple_attention(vec, proj, hidden_mem, decoder_size)
            pre_word = fluid.layers.embedding(
                input=pre_ids, size=[trg_dict_dim, embedding_dim])
            decoder_inputs = fluid.layers.fc(
                input=[context, pre_word],
                size=decoder_size * 3,
                bias_attr=False)
            h, _, _ = fluid.layers.gru_unit(
                input=decoder_inputs, hidden=hidden_mem,
                size=decoder_size * 3)
            prob = fluid.layers.fc(
                input=h, size=trg_dict_dim, act='softmax')
            topk_scores, topk_indices = fluid.layers.topk(prob, beam_size)
            accu_scores = fluid.layers.elementwise_add(
                fluid.layers.log(topk_scores), pre_scores)
            sel_ids, sel_scores, parent_idx = fluid.layers.beam_search(
                pre_ids, pre_scores, topk_indices, accu_scores,
                beam_size, end_id)
            new_h = fluid.layers.gather(h, parent_idx)
            rnn.update_memory(pre_ids, sel_ids)
            rnn.update_memory(pre_scores, sel_scores)
            rnn.update_memory(hidden_mem, new_h)
            rnn.output(sel_ids, sel_scores, parent_idx)

        ids_arr, scores_arr, parents_arr = rnn()
        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            ids_arr, scores_arr, parents_arr, beam_size, end_id)
    return dict(
        main=main,
        startup=startup,
        feeds=['src_word_id'],
        sentence_ids=sent_ids,
        sentence_scores=sent_scores)


def build_step_decode(src_dict_dim=1000,
                      trg_dict_dim=1000,
                      embedding_dim=64,
                      encoder_size=64,
                      decoder_size=64,
                      start_id=0,
                      end_id=1,
                      max_len=16,
                      chunk=None):
    """STEPWISE greedy NMT decode for the generation serving lane
    (ISSUE 7), CHUNKABLE since ISSUE 14: the prompt encoder is a
    masked GRU recurrence (``dynamic_gru``) whose hidden state IS the
    decode state, so a prompt can prefill either in ONE pass (the
    monolithic ``prefill`` program) or as a chain of C-token blocks
    (the ``chunk`` program) with BITWISE-identical final state — the
    same masked scan, the same shared weights, merely split across
    dispatches at token boundaries.

      prefill: src LoD -> embedding -> fc -> dynamic_gru (h0 = zeros,
          steps past each row's length frozen by the @SEQLEN mask) ->
          sequence_last_step: ONE [B, decoder_size] state fetch (the
          hidden after the prompt's last real token);
      chunk (``chunk=C`` builds it): (gen_ctok [B, C, 1] token block,
          gen_hidden) -> the SAME embedding/fc/dynamic_gru (ParamAttr-
          pinned shared names) seeded with ``h_0=gen_hidden`` and
          masked by the block's per-row real length (the engine feeds
          the @SEQLEN companion) -> the advanced hidden.  Chaining
          ceil(L/C) chunks over a prompt == the monolithic prefill
          bitwise: a masked lax.scan applies, for every j < L,
          ``h = gru(x_j, h)`` and freezes the rest — partitioning j
          over chunk dispatches changes no float op.
      step: (token, hidden) -> (vocab logits, hidden') — embedding +
          fc + one gru_unit SHARING the prefill GRU's weight (one
          recurrence consumes the prompt and generates), greedy beam 1.

    Every step-program op is row-independent, so the slot-batched
    decode scan is token-identical to per-request decode.  The
    prefill/chunk pair shares ONE gru bias (``dynamic_gru`` always
    creates one — both adding the same zero-initialized param keeps
    chaining bitwise), while the step recurrence ``gru_unit`` is
    bias-free: the two coincide numerically only while that bias
    stays zero (it is never trained here), so prompt consumption and
    decode share the [D, 3D] recurrence WEIGHT, not strictly every
    term.  ``encoder_size`` is retained for call-site compatibility
    (the GRU prompt encoder is sized by ``decoder_size``)."""
    del encoder_size  # the GRU prompt encoder is decoder_size-wide
    shared = {
        'emb': fluid.ParamAttr(name='gen_nmt_src_emb'),
        'proj': fluid.ParamAttr(name='gen_nmt_src_proj'),
        'gru': fluid.ParamAttr(name='gen_nmt_gru_w'),
        # dynamic_gru always carries a bias; prefill and chunk must add
        # the SAME one or chaining would not be bitwise
        'gru_b': fluid.ParamAttr(name='gen_nmt_gru_b'),
    }

    def _encode(tokens, h_0=None, flatten=1):
        emb = fluid.layers.embedding(
            input=tokens, size=[src_dict_dim, embedding_dim],
            param_attr=shared['emb'])
        proj = fluid.layers.fc(input=emb, size=decoder_size * 3,
                               bias_attr=False, num_flatten_dims=flatten,
                               param_attr=shared['proj'])
        hidden_seq = fluid.layers.dynamic_gru(
            proj, decoder_size, param_attr=shared['gru'],
            bias_attr=shared['gru_b'], h_0=h_0)
        return fluid.layers.sequence_last_step(input=hidden_seq)

    prefill, prefill_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prefill, prefill_startup):
        src = fluid.layers.data(
            name='src_word_id', shape=[1], dtype='int64', lod_level=1)
        boot = _encode(src)
    chunk_prog = chunk_startup = chunk_h = None
    if chunk is not None:
        from ..fluid.shape_policy import bucketed_len
        chunk = bucketed_len(int(chunk))
        chunk_prog, chunk_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(chunk_prog, chunk_startup):
            ctok = fluid.layers.data(name='gen_ctok', shape=[chunk, 1],
                                     dtype='int64')
            hidden_in = fluid.layers.data(
                name='gen_hidden', shape=[decoder_size], dtype='float32')
            chunk_h = _encode(ctok, h_0=hidden_in, flatten=2)
    step, step_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(step, step_startup):
        token = fluid.layers.data(name='gen_token', shape=[1],
                                  dtype='int64')
        hidden = fluid.layers.data(name='gen_hidden',
                                   shape=[decoder_size], dtype='float32')
        pre_word = fluid.layers.embedding(
            input=token, size=[trg_dict_dim, embedding_dim])
        decoder_inputs = fluid.layers.fc(
            input=pre_word, size=decoder_size * 3, bias_attr=False)
        h, _, _ = fluid.layers.gru_unit(
            decoder_inputs, hidden, decoder_size * 3,
            param_attr=shared['gru'], bias_attr=False)
        logits = fluid.layers.fc(input=h, size=trg_dict_dim)
    out = dict(
        prefill=prefill,
        prefill_startup=prefill_startup,
        step=step,
        step_startup=step_startup,
        prefill_feeds=['src_word_id'],
        prefill_fetches=[boot],
        token='gen_token',
        logits=logits,
        state=[('gen_hidden', h)],
        prompt='src_word_id',
        start_id=start_id,
        end_id=end_id,
        max_len=max_len)
    if chunk is not None:
        out.update(
            chunk=chunk_prog,
            chunk_startup=chunk_startup,
            chunk_token='gen_ctok',
            chunk_state=[('gen_hidden', chunk_h)],
            chunk_width=chunk)
    return out
