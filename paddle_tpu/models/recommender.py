"""Recommender system on movielens
(reference: tests/book/test_recommender_system.py).

User tower (id/gender/age/job embeddings -> fc) and movie tower (id
embedding + pooled category embeddings + title sequence conv-pool) meet
in cosine similarity scaled to a 5-star rating.
"""

import paddle_tpu.fluid as fluid
from paddle_tpu.dataset import movielens

__all__ = ['build']


def _user_tower(usr, usr_gender, usr_age, usr_job):
    usr_emb = fluid.layers.embedding(
        input=usr, size=[movielens.max_user_id() + 1, 32],
        param_attr=fluid.ParamAttr(name='user_table'))
    usr_fc = fluid.layers.fc(input=usr_emb, size=32)
    gender_emb = fluid.layers.embedding(
        input=usr_gender, size=[2, 16],
        param_attr=fluid.ParamAttr(name='gender_table'))
    gender_fc = fluid.layers.fc(input=gender_emb, size=16)
    age_emb = fluid.layers.embedding(
        input=usr_age, size=[len(movielens.age_table), 16],
        param_attr=fluid.ParamAttr(name='age_table'))
    age_fc = fluid.layers.fc(input=age_emb, size=16)
    job_emb = fluid.layers.embedding(
        input=usr_job, size=[movielens.max_job_id() + 1, 16],
        param_attr=fluid.ParamAttr(name='job_table'))
    job_fc = fluid.layers.fc(input=job_emb, size=16)
    concat = fluid.layers.concat(
        input=[usr_fc, gender_fc, age_fc, job_fc], axis=1)
    return fluid.layers.fc(input=concat, size=200, act='tanh')


def _movie_tower(mov_id, category_id, mov_title_id):
    mov_emb = fluid.layers.embedding(
        input=mov_id, size=[movielens.max_movie_id() + 1, 32],
        param_attr=fluid.ParamAttr(name='movie_table'))
    mov_fc = fluid.layers.fc(input=mov_emb, size=32)
    cat_emb = fluid.layers.embedding(
        input=category_id, size=[movielens.CATEGORY_DICT_SIZE, 32])
    cat_pool = fluid.layers.sequence_pool(input=cat_emb, pool_type='sum')
    title_emb = fluid.layers.embedding(
        input=mov_title_id, size=[movielens.TITLE_DICT_SIZE, 32])
    title_conv = fluid.layers.sequence_conv(
        input=title_emb, num_filters=32, filter_size=3, act='tanh')
    title_pool = fluid.layers.sequence_pool(
        input=title_conv, pool_type='sum')
    concat = fluid.layers.concat(
        input=[mov_fc, cat_pool, title_pool], axis=1)
    return fluid.layers.fc(input=concat, size=200, act='tanh')


def build(lr=0.2):
    feed_names = ['user_id', 'gender_id', 'age_id', 'job_id', 'movie_id',
                  'category_id', 'movie_title', 'score']
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        usr = fluid.layers.data(name='user_id', shape=[1], dtype='int64')
        gender = fluid.layers.data(name='gender_id', shape=[1],
                                   dtype='int64')
        age = fluid.layers.data(name='age_id', shape=[1], dtype='int64')
        job = fluid.layers.data(name='job_id', shape=[1], dtype='int64')
        mov = fluid.layers.data(name='movie_id', shape=[1], dtype='int64')
        cat = fluid.layers.data(name='category_id', shape=[1],
                                dtype='int64', lod_level=1)
        title = fluid.layers.data(name='movie_title', shape=[1],
                                  dtype='int64', lod_level=1)
        score = fluid.layers.data(name='score', shape=[1],
                                  dtype='float32')

        usr_combined = _user_tower(usr, gender, age, job)
        mov_combined = _movie_tower(mov, cat, title)
        similarity = fluid.layers.cos_sim(X=usr_combined, Y=mov_combined)
        scale_infer = fluid.layers.scale(x=similarity, scale=5.0)
        cost = fluid.layers.square_error_cost(input=scale_infer,
                                              label=score)
        avg_cost = fluid.layers.mean(cost)
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=feed_names,
        prediction=scale_infer,
        loss=avg_cost)
