"""Semantic role labeling with a CRF output layer
(reference: tests/book/test_label_semantic_roles.py).

8 input features (word, predicate, 4 context windows, mark) -> embeddings
-> stacked alternating-direction dynamic LSTMs -> per-token scores ->
linear-chain CRF loss + Viterbi decode.
"""

import paddle_tpu.fluid as fluid

__all__ = ['build']


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, pred_dict_len, mark_dict_len, label_dict_len,
            word_dim=8, mark_dim=4, hidden_dim=32, depth=4):
    """(reference test_label_semantic_roles.py db_lstm)"""
    predicate_embedding = fluid.layers.embedding(
        input=predicate, size=[pred_dict_len, word_dim])
    mark_embedding = fluid.layers.embedding(
        input=mark, size=[mark_dict_len, mark_dim])

    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        fluid.layers.embedding(size=[word_dict_len, word_dim], input=x)
        for x in word_input
    ]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0_layers = [
        fluid.layers.fc(input=emb, size=hidden_dim, act='tanh')
        for emb in emb_layers
    ]
    hidden_0 = fluid.layers.sums(input=hidden_0_layers)
    lstm_0, _ = fluid.layers.dynamic_lstm(
        input=hidden_0, size=hidden_dim,
        candidate_activation='relu',
        gate_activation='sigmoid',
        cell_activation='sigmoid')

    # stack L-lstm and R-lstm with direction alternating per layer
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=hidden_dim,
                            act='tanh'),
            fluid.layers.fc(input=input_tmp[1], size=hidden_dim,
                            act='tanh')
        ])
        lstm, _ = fluid.layers.dynamic_lstm(
            input=mix_hidden, size=hidden_dim,
            candidate_activation='relu',
            gate_activation='sigmoid',
            cell_activation='sigmoid',
            is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = fluid.layers.sums(input=[
        fluid.layers.fc(input=input_tmp[0], size=label_dict_len,
                        act='tanh'),
        fluid.layers.fc(input=input_tmp[1], size=label_dict_len,
                        act='tanh')
    ])
    return feature_out


def build(word_dict_len=200,
          pred_dict_len=40,
          mark_dict_len=2,
          label_dict_len=17,
          word_dim=8,
          mark_dim=4,
          hidden_dim=32,
          depth=2,
          lr=0.01):
    feed_names = ['word_data', 'verb_data', 'ctx_n2_data', 'ctx_n1_data',
                  'ctx_0_data', 'ctx_p1_data', 'ctx_p2_data', 'mark_data',
                  'target']
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ins = [
            fluid.layers.data(name=n, shape=[1], dtype='int64', lod_level=1)
            for n in feed_names
        ]
        word_ins, target = ins[:8], ins[8]
        feature_out = db_lstm(*word_ins,
                              word_dict_len=word_dict_len,
                              pred_dict_len=pred_dict_len,
                              mark_dict_len=mark_dict_len,
                              label_dict_len=label_dict_len,
                              word_dim=word_dim,
                              mark_dim=mark_dim,
                              hidden_dim=hidden_dim,
                              depth=depth)
        crf_cost = fluid.layers.linear_chain_crf(
            input=feature_out,
            label=target,
            param_attr=fluid.ParamAttr(name='crfw'))
        avg_cost = fluid.layers.mean(crf_cost)
        crf_decode = fluid.layers.crf_decoding(
            input=feature_out, param_attr=fluid.ParamAttr(name='crfw'))
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=feed_names,
        loss=avg_cost,
        crf_decode=crf_decode)
