"""Transformer (reference:
python/paddle/fluid/tests/unittests/transformer_model.py — the WMT16
Transformer behind test_dist_transformer.py and
test_parallel_executor_transformer.py).

TPU-first shape of the same model: every attention runs through the
fused ``flash_attention`` op (Pallas flash kernel on a single chip,
ring attention over an 'sp' mesh axis under SPMD, dense XLA fallback)
instead of the reference's matmul+softmax+reshape composition
(transformer_model.py:43 multi_head_attention); layouts are static
[B, T, D] with sinusoid position encodings added as program constants;
the vocab projection + label CE use the fused softmax_with_CE head.
"""

import numpy as np

import paddle_tpu.fluid as fluid

__all__ = ['build', 'position_encoding']


def position_encoding(max_len, d_model):
    """Sinusoid table [1, max_len, d_model]
    (reference transformer_model.py position_encoding_init)."""
    pos = np.arange(max_len)[:, None].astype('float64')
    div = np.power(10000.0,
                   -(np.arange(0, d_model, 2).astype('float64') / d_model))
    table = np.zeros((max_len, d_model))
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div[:d_model // 2])
    return table[None].astype('float32')


def _attention(q_in, kv_in, d_model, n_head, causal, name):
    q = fluid.layers.fc(input=q_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    k = fluid.layers.fc(input=kv_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    v = fluid.layers.fc(input=kv_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    ctxv = fluid.layers.flash_attention(
        q, k, v, num_heads=n_head, causal=causal, name=name)
    return fluid.layers.fc(input=ctxv, size=d_model, bias_attr=False,
                           num_flatten_dims=2)


def _add_norm(x, sub, dropout):
    if dropout:
        sub = fluid.layers.dropout(sub, dropout_prob=dropout)
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, sub), begin_norm_axis=2)


def _ffn(x, d_model, d_ff):
    h = fluid.layers.fc(input=x, size=d_ff, act='relu',
                        num_flatten_dims=2)
    return fluid.layers.fc(input=h, size=d_model, num_flatten_dims=2)


def _embed(ids, vocab, d_model, max_len, name):
    emb = fluid.layers.embedding(
        input=ids, size=[vocab, d_model],
        param_attr=fluid.ParamAttr(name=name))
    scaled = fluid.layers.scale(emb, scale=float(d_model)**0.5)
    pos = fluid.layers.assign(position_encoding(max_len, d_model))
    return fluid.layers.elementwise_add(scaled, pos)


def build(src_vocab=1000,
          trg_vocab=1000,
          max_len=32,
          n_layer=2,
          n_head=4,
          d_model=64,
          d_ff=128,
          dropout=0.0,
          lr=0.001):
    """Training program: encoder-decoder over [B, max_len] int64 ids.
    Feeds: src_ids, trg_ids (decoder input), lbl_ids (next tokens)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name='src_ids', shape=[max_len],
                                dtype='int64')
        trg = fluid.layers.data(name='trg_ids', shape=[max_len],
                                dtype='int64')
        lbl = fluid.layers.data(name='lbl_ids', shape=[max_len],
                                dtype='int64')

        enc = _embed(src, src_vocab, d_model, max_len, 'src_emb')
        for i in range(n_layer):
            attn = _attention(enc, enc, d_model, n_head, causal=False,
                              name='enc_self_%d' % i)
            enc = _add_norm(enc, attn, dropout)
            enc = _add_norm(enc, _ffn(enc, d_model, d_ff), dropout)

        dec = _embed(trg, trg_vocab, d_model, max_len, 'trg_emb')
        for i in range(n_layer):
            self_attn = _attention(dec, dec, d_model, n_head, causal=True,
                                   name='dec_self_%d' % i)
            dec = _add_norm(dec, self_attn, dropout)
            cross = _attention(dec, enc, d_model, n_head, causal=False,
                               name='dec_cross_%d' % i)
            dec = _add_norm(dec, cross, dropout)
            dec = _add_norm(dec, _ffn(dec, d_model, d_ff), dropout)

        logits = fluid.layers.fc(input=dec, size=trg_vocab,
                                 num_flatten_dims=2)
        lbl3 = fluid.layers.unsqueeze(lbl, axes=[2])
        cost = fluid.layers.softmax_with_cross_entropy(logits, lbl3)
        avg_cost = fluid.layers.mean(cost)
        prediction = fluid.layers.softmax(logits)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['src_ids', 'trg_ids', 'lbl_ids'],
        prediction=prediction,
        loss=avg_cost)
