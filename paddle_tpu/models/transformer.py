"""Transformer (reference:
python/paddle/fluid/tests/unittests/transformer_model.py — the WMT16
Transformer behind test_dist_transformer.py and
test_parallel_executor_transformer.py).

TPU-first shape of the same model: every attention runs through the
fused ``flash_attention`` op (Pallas flash kernel on a single chip,
ring attention over an 'sp' mesh axis under SPMD, dense XLA fallback)
instead of the reference's matmul+softmax+reshape composition
(transformer_model.py:43 multi_head_attention); layouts are static
[B, T, D] with sinusoid position encodings added as program constants;
the vocab projection + label CE use the fused softmax_with_CE head.
"""

import numpy as np

import paddle_tpu.fluid as fluid

__all__ = ['build', 'position_encoding', 'build_step_decode']


def position_encoding(max_len, d_model):
    """Sinusoid table [1, max_len, d_model]
    (reference transformer_model.py position_encoding_init)."""
    pos = np.arange(max_len)[:, None].astype('float64')
    div = np.power(10000.0,
                   -(np.arange(0, d_model, 2).astype('float64') / d_model))
    table = np.zeros((max_len, d_model))
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div[:d_model // 2])
    return table[None].astype('float32')


def _attention(q_in, kv_in, d_model, n_head, causal, name):
    q = fluid.layers.fc(input=q_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    k = fluid.layers.fc(input=kv_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    v = fluid.layers.fc(input=kv_in, size=d_model, bias_attr=False,
                        num_flatten_dims=2)
    ctxv = fluid.layers.flash_attention(
        q, k, v, num_heads=n_head, causal=causal, name=name)
    return fluid.layers.fc(input=ctxv, size=d_model, bias_attr=False,
                           num_flatten_dims=2)


def _add_norm(x, sub, dropout):
    if dropout:
        sub = fluid.layers.dropout(sub, dropout_prob=dropout)
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, sub), begin_norm_axis=2)


def _ffn(x, d_model, d_ff):
    h = fluid.layers.fc(input=x, size=d_ff, act='relu',
                        num_flatten_dims=2)
    return fluid.layers.fc(input=h, size=d_model, num_flatten_dims=2)


def _embed(ids, vocab, d_model, max_len, name):
    emb = fluid.layers.embedding(
        input=ids, size=[vocab, d_model],
        param_attr=fluid.ParamAttr(name=name))
    scaled = fluid.layers.scale(emb, scale=float(d_model)**0.5)
    pos = fluid.layers.assign(position_encoding(max_len, d_model))
    return fluid.layers.elementwise_add(scaled, pos)


def build(src_vocab=1000,
          trg_vocab=1000,
          max_len=32,
          n_layer=2,
          n_head=4,
          d_model=64,
          d_ff=128,
          dropout=0.0,
          lr=0.001):
    """Training program: encoder-decoder over [B, max_len] int64 ids.
    Feeds: src_ids, trg_ids (decoder input), lbl_ids (next tokens)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name='src_ids', shape=[max_len],
                                dtype='int64')
        trg = fluid.layers.data(name='trg_ids', shape=[max_len],
                                dtype='int64')
        lbl = fluid.layers.data(name='lbl_ids', shape=[max_len],
                                dtype='int64')

        enc = _embed(src, src_vocab, d_model, max_len, 'src_emb')
        for i in range(n_layer):
            attn = _attention(enc, enc, d_model, n_head, causal=False,
                              name='enc_self_%d' % i)
            enc = _add_norm(enc, attn, dropout)
            enc = _add_norm(enc, _ffn(enc, d_model, d_ff), dropout)

        dec = _embed(trg, trg_vocab, d_model, max_len, 'trg_emb')
        for i in range(n_layer):
            self_attn = _attention(dec, dec, d_model, n_head, causal=True,
                                   name='dec_self_%d' % i)
            dec = _add_norm(dec, self_attn, dropout)
            cross = _attention(dec, enc, d_model, n_head, causal=False,
                               name='dec_cross_%d' % i)
            dec = _add_norm(dec, cross, dropout)
            dec = _add_norm(dec, _ffn(dec, d_model, d_ff), dropout)

        logits = fluid.layers.fc(input=dec, size=trg_vocab,
                                 num_flatten_dims=2)
        lbl3 = fluid.layers.unsqueeze(lbl, axes=[2])
        cost = fluid.layers.softmax_with_cross_entropy(logits, lbl3)
        avg_cost = fluid.layers.mean(cost)
        prediction = fluid.layers.softmax(logits)
        test_program = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    return dict(
        main=main,
        startup=startup,
        test=test_program,
        feeds=['src_ids', 'trg_ids', 'lbl_ids'],
        prediction=prediction,
        loss=avg_cost)


def build_step_decode(vocab=1000,
                      d_model=64,
                      d_k=64,
                      max_ctx=32,
                      start_id=0,
                      end_id=1,
                      max_len=16,
                      chunk=None):
    """STEPWISE KV-cache greedy decode for the generation serving lane
    (ISSUE 7): a single-layer incremental-attention decoder LM over a
    dense prompt — the Transformer-shaped workload whose decode state
    is a REAL per-request KV cache, exercising the slot cache's slab
    (``[S, max_ctx, d_k]``) rather than a flat hidden vector.

      prefill: (prompt ids [B, T, 1], lengths [B, 1]) -> the prompt's
          K/V prefix ([B, T, d_k] each — admission zero-pads T up to
          the ``max_ctx`` slab) + the write position (= prompt length);
      step: (token, k_cache, v_cache, pos) -> the token's q/k/v
          projections, k/v scattered into the cache at ``pos`` (one_hot
          blend), dot-product attention over positions < pos+1
          (sequence_mask; later rows are masked until written, so slab
          zero-padding is invisible), logits + advanced state.

    Prefill and step genuinely SHARE weights (ParamAttr-pinned names:
    the embedding and the K/V projections), so the cached prompt
    prefix lives in the same projection space the step extends.  All
    step ops are row-independent: the slot-batched decode scan is
    token-identical to per-request decode.

    ``chunk=C`` (ISSUE 14) additionally builds a CHUNK program — the
    incremental form of prefill over a ``[B, C]`` token block against
    the KV slab at a per-row position offset: the block's K/V
    projections (the SAME shared weights) scatter into rows
    ``pos .. pos+clen-1`` (a per-position one-hot matmul, rows past
    the block's real length ``clen`` masked out), and ``pos`` advances
    by ``clen``.  Chaining ceil(L/C) chunks writes exactly the rows
    the monolithic prefill's admission zero-pad writes (the K/V
    projections are per-token — no cross-token term exists in this
    family's prefill state, so no intra-chunk causal attention is
    needed for exactness), leaving generated tokens identical.  C is
    quantized up to the shared seq-len rung ladder."""
    shared = {
        'emb': fluid.ParamAttr(name='gen_tf_emb'),
        'k': fluid.ParamAttr(name='gen_tf_wk'),
        'v': fluid.ParamAttr(name='gen_tf_wv'),
    }
    prefill, prefill_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prefill, prefill_startup):
        src = fluid.layers.data(name='gen_src', shape=[-1, 1],
                                dtype='int64')
        src_len = fluid.layers.data(name='gen_src_len', shape=[1],
                                    dtype='float32')
        embp = fluid.layers.embedding(src, size=[vocab, d_model],
                                      param_attr=shared['emb'])
        k0 = fluid.layers.fc(embp, d_k, bias_attr=False,
                             num_flatten_dims=2, param_attr=shared['k'])
        v0 = fluid.layers.fc(embp, d_k, bias_attr=False,
                             num_flatten_dims=2, param_attr=shared['v'])
        pos0 = fluid.layers.scale(src_len, scale=1.0)
    step, step_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(step, step_startup):
        token = fluid.layers.data(name='gen_token', shape=[1],
                                  dtype='int64')
        k_cache = fluid.layers.data(name='gen_k', shape=[max_ctx, d_k],
                                    dtype='float32')
        v_cache = fluid.layers.data(name='gen_v', shape=[max_ctx, d_k],
                                    dtype='float32')
        pos = fluid.layers.data(name='gen_pos', shape=[1],
                                dtype='float32')
        embt = fluid.layers.embedding(token, size=[vocab, d_model],
                                      param_attr=shared['emb'])
        q = fluid.layers.fc(embt, d_k, bias_attr=False)
        k_new = fluid.layers.fc(embt, d_k, bias_attr=False,
                                param_attr=shared['k'])
        v_new = fluid.layers.fc(embt, d_k, bias_attr=False,
                                param_attr=shared['v'])

        # scatter this token's k/v into the cache row ``pos``
        onehot = fluid.layers.one_hot(pos, max_ctx)  # [B, max_ctx]
        oh3 = fluid.layers.expand(
            fluid.layers.unsqueeze(onehot, axes=[2]), [1, 1, d_k])
        keep3 = fluid.layers.scale(oh3, scale=-1.0, bias=1.0)

        def scatter(cache, new):
            new3 = fluid.layers.expand(
                fluid.layers.unsqueeze(new, axes=[1]), [1, max_ctx, 1])
            return fluid.layers.elementwise_add(
                fluid.layers.elementwise_mul(cache, keep3),
                fluid.layers.elementwise_mul(new3, oh3))

        k2 = scatter(k_cache, k_new)
        v2 = scatter(v_cache, v_new)

        # dot-product attention over the written prefix (rows <= pos)
        q3 = fluid.layers.expand(
            fluid.layers.unsqueeze(q, axes=[1]), [1, max_ctx, 1])
        scores = fluid.layers.scale(
            fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(k2, q3), dim=2),
            scale=1.0 / float(d_k)**0.5)  # [B, max_ctx]
        pos1 = fluid.layers.scale(pos, scale=1.0, bias=1.0)
        seqmask = fluid.layers.sequence_mask(pos1, maxlen=max_ctx,
                                             dtype='float32')
        masked = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(scores, seqmask),
            fluid.layers.scale(seqmask, scale=1e9, bias=-1e9))
        attn = fluid.layers.softmax(masked)
        attn3 = fluid.layers.expand(
            fluid.layers.unsqueeze(attn, axes=[2]), [1, 1, d_k])
        ctxv = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(v2, attn3), dim=1)  # [B, d_k]
        h = fluid.layers.fc([ctxv, q], d_model, act='tanh')
        logits = fluid.layers.fc(h, vocab)
    chunk_prog = chunk_startup = None
    ck = cv = cpos = None
    if chunk is not None:
        from ..fluid.shape_policy import bucketed_len
        chunk = bucketed_len(int(chunk))
        chunk_prog, chunk_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(chunk_prog, chunk_startup):
            ctok = fluid.layers.data(name='gen_ctok', shape=[chunk, 1],
                                     dtype='int64')
            clen = fluid.layers.data(name='gen_clen', shape=[1],
                                     dtype='float32')
            kc = fluid.layers.data(name='gen_k', shape=[max_ctx, d_k],
                                   dtype='float32')
            vc = fluid.layers.data(name='gen_v', shape=[max_ctx, d_k],
                                   dtype='float32')
            cp = fluid.layers.data(name='gen_pos', shape=[1],
                                   dtype='float32')
            embc = fluid.layers.embedding(ctok, size=[vocab, d_model],
                                          param_attr=shared['emb'])
            k_new = fluid.layers.fc(embc, d_k, bias_attr=False,
                                    num_flatten_dims=2,
                                    param_attr=shared['k'])
            v_new = fluid.layers.fc(embc, d_k, bias_attr=False,
                                    num_flatten_dims=2,
                                    param_attr=shared['v'])
            # block position of token j is pos + j, valid while j < clen
            steps = fluid.layers.assign(
                np.arange(chunk, dtype='float32')[None, :])  # [1, C]
            posj = fluid.layers.elementwise_add(
                fluid.layers.expand(cp, [1, chunk]), steps)  # [B, C]
            scat = fluid.layers.one_hot(posj, max_ctx)  # [B, C, max_ctx]
            maskc = fluid.layers.sequence_mask(clen, maxlen=chunk,
                                               dtype='float32')  # [B, C]
            scat = fluid.layers.elementwise_mul(
                scat, fluid.layers.expand(
                    fluid.layers.unsqueeze(maskc, axes=[2]),
                    [1, 1, max_ctx]))
            covered = fluid.layers.reduce_sum(scat, dim=1)  # [B, max_ctx]
            keep3 = fluid.layers.expand(
                fluid.layers.unsqueeze(
                    fluid.layers.scale(covered, scale=-1.0, bias=1.0),
                    axes=[2]),
                [1, 1, d_k])

            def chunk_scatter(cache, new):
                # rows pos..pos+clen-1 replaced by the block's
                # projections ([B, max_ctx, C] @ [B, C, d_k] — each
                # covered row receives exactly one new value, every
                # other summand is 0), untouched rows keep the slab
                return fluid.layers.elementwise_add(
                    fluid.layers.elementwise_mul(cache, keep3),
                    fluid.layers.matmul(scat, new, transpose_x=True))

            ck = chunk_scatter(kc, k_new)
            cv = chunk_scatter(vc, v_new)
            cpos = fluid.layers.elementwise_add(cp, clen)
    out = dict(
        prefill=prefill,
        prefill_startup=prefill_startup,
        step=step,
        step_startup=step_startup,
        prefill_feeds=['gen_src', 'gen_src_len'],
        prefill_fetches=[k0, v0, pos0],
        token='gen_token',
        logits=logits,
        state=[('gen_k', k2), ('gen_v', v2), ('gen_pos', pos1)],
        prompt='gen_src',
        prompt_len='gen_src_len',
        max_ctx=max_ctx,
        start_id=start_id,
        end_id=end_id,
        max_len=max_len)
    if chunk is not None:
        out.update(
            chunk=chunk_prog,
            chunk_startup=chunk_startup,
            chunk_token='gen_ctok',
            chunk_len='gen_clen',
            chunk_state=[('gen_k', ck), ('gen_v', cv),
                         ('gen_pos', cpos)],
            chunk_width=chunk)
    return out
