"""Legacy ``paddle.trainer`` package surface (reference:
python/paddle/trainer/ — the config-parser generation).  Carries
PyDataProvider2, the data-provider decorator DSL legacy config files
import."""

from . import PyDataProvider2  # noqa: F401
