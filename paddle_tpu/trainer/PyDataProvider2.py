"""PyDataProvider2: the legacy @provider data DSL (reference:
python/paddle/trainer/PyDataProvider2.py:365 provider()).

A config file decorates a generator::

    @provider(input_types={'x': dense_vector(4), 'y': integer_value(2)})
    def process(settings, file_name):
        for line in open(file_name):
            yield parse(line)

The reference wrapped this into a C++-driven PyDataProvider2 object; here
the decorated function becomes a ``DataProvider`` whose ``as_reader``
yields per-sample tuples in input_types order — directly consumable by
the v2 trainer / paddle_tpu.batch readers.  Shuffling honors
``should_shuffle`` with a bounded pool like the reference's pool_size.
"""

import random

from ..v2.data_type import (  # noqa: F401 — the legacy import surface
    dense_vector, dense_vector_sequence, sparse_binary_vector,
    sparse_float_vector, integer_value, integer_value_sequence,
    sparse_binary_vector_sequence, sparse_float_vector_sequence,
    InputType, DataType, SequenceType)

__all__ = [
    'provider', 'CacheType', 'dense_vector', 'dense_vector_sequence',
    'sparse_binary_vector', 'sparse_float_vector', 'integer_value',
    'integer_value_sequence', 'sparse_binary_vector_sequence',
    'sparse_float_vector_sequence',
]


class CacheType(object):
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class DataProviderSettings(object):
    """The ``settings`` object handed to the process function (the
    reference stores input_types and init_hook state on it)."""

    def __init__(self, input_types, **kwargs):
        self.input_types = input_types
        self.__dict__.update(kwargs)


class DataProvider(object):
    """Wrapped provider: call it with a file name (or use as_reader over
    a file list) to iterate samples."""

    def __init__(self, generator, input_types, should_shuffle, pool_size,
                 cache, init_hook, kwargs):
        self._generator = generator
        self.input_types = input_types
        self.should_shuffle = (True if should_shuffle is None
                               else should_shuffle)
        self.pool_size = pool_size
        self.cache = cache
        self.settings = DataProviderSettings(input_types)
        if init_hook is not None:
            init_hook(self.settings, **kwargs)
        self._pass_cache = {}  # keyed by the file tuple (train != test)

    def __call__(self, file_name, *args, **kwargs):
        return self._generator(self.settings, file_name, *args, **kwargs)

    def _ordered(self, sample):
        if isinstance(sample, dict):
            if not isinstance(self.input_types, dict):
                raise TypeError(
                    'provider yielded a dict but input_types is not a '
                    'dict of layer-name -> InputType')
            return tuple(sample[k] for k in self.input_types)
        return tuple(sample) if isinstance(sample, (list, tuple)) \
            else (sample, )

    def as_reader(self, file_list, seed=0):
        """A v2-style reader creator over the files (sample tuples in
        input_types order; bounded shuffle pool per should_shuffle)."""

        key = tuple(file_list)
        pass_counter = [0]

        def reader():
            if self.cache == CacheType.CACHE_PASS_IN_MEM and \
                    key in self._pass_cache:
                samples = list(self._pass_cache[key])
            else:
                samples = []
                for fname in file_list:
                    for sample in self(fname):
                        samples.append(self._ordered(sample))
                if self.cache == CacheType.CACHE_PASS_IN_MEM:
                    self._pass_cache[key] = list(samples)
            if self.should_shuffle:
                # a fresh permutation every pass (the reference
                # reshuffles per pass), deterministic per (seed, pass)
                rng = random.Random(seed * 1000003 + pass_counter[0])
                pass_counter[0] += 1
                if self.pool_size and self.pool_size > 0:
                    # bounded pool shuffle (reference pool_size)
                    pool = []
                    out = []
                    for s in samples:
                        pool.append(s)
                        if len(pool) >= self.pool_size:
                            rng.shuffle(pool)
                            out.extend(pool)
                            pool = []
                    rng.shuffle(pool)
                    out.extend(pool)
                    samples = out
                else:
                    rng.shuffle(samples)
            for s in samples:
                yield s

        return reader


def provider(input_types=None,
             should_shuffle=None,
             pool_size=-1,
             min_pool_size=-1,
             can_over_batch_size=True,
             calc_batch_size=None,
             cache=CacheType.NO_CACHE,
             check=False,
             check_fail_continue=False,
             init_hook=None,
             **outter_kwargs):
    """(reference PyDataProvider2.py:365) Decorate a per-file sample
    generator into a DataProvider."""

    def decorate(fn):
        return DataProvider(fn, input_types, should_shuffle, pool_size,
                            cache, init_hook, outter_kwargs)

    return decorate
