"""v2 optimizers (reference: python/paddle/v2/optimizer.py) mapped onto
the fluid optimizer family."""

from .. import fluid

__all__ = ['Momentum', 'Adam', 'Adamax', 'AdaGrad', 'DecayedAdaGrad',
           'AdaDelta', 'RMSProp', 'ModelAverage', 'L2Regularization']


class L2Regularization(object):
    def __init__(self, rate):
        self.rate = rate


class ModelAverage(object):
    def __init__(self, average_window, **kwargs):
        self.average_window = average_window


class Optimizer(object):
    def __init__(self, **kwargs):
        self.kwargs = kwargs

    def _regularization(self):
        reg = self.kwargs.get('regularization')
        if isinstance(reg, L2Regularization):
            return fluid.regularizer.L2Decay(reg.rate)
        return None

    def to_fluid(self):
        raise NotImplementedError


class Momentum(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Momentum(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            momentum=self.kwargs.get('momentum', 0.9),
            regularization=self._regularization())


class Adam(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Adam(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            beta1=self.kwargs.get('beta1', 0.9),
            beta2=self.kwargs.get('beta2', 0.999),
            epsilon=self.kwargs.get('epsilon', 1e-8),
            regularization=self._regularization())


class Adamax(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Adamax(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            beta1=self.kwargs.get('beta1', 0.9),
            beta2=self.kwargs.get('beta2', 0.999),
            regularization=self._regularization())


class AdaGrad(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Adagrad(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            regularization=self._regularization())


class DecayedAdaGrad(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.DecayedAdagrad(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            decay=self.kwargs.get('rho', 0.95),
            epsilon=self.kwargs.get('epsilon', 1e-6),
            regularization=self._regularization())


class AdaDelta(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.Adadelta(
            learning_rate=self.kwargs.get('learning_rate', 1.0),
            rho=self.kwargs.get('rho', 0.95),
            epsilon=self.kwargs.get('epsilon', 1e-6),
            regularization=self._regularization())


class RMSProp(Optimizer):
    def to_fluid(self):
        return fluid.optimizer.RMSProp(
            learning_rate=self.kwargs.get('learning_rate', 0.001),
            rho=self.kwargs.get('rho', 0.95),
            epsilon=self.kwargs.get('epsilon', 1e-6),
            regularization=self._regularization())
