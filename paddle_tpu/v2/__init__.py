"""paddle.v2 compatibility API (reference: python/paddle/v2/__init__.py).

The reference ships two generations: the legacy v2 layer-DSL engine
(GradientMachine / Layer / Matrix, paddle/legacy/) and Fluid.  This package
keeps the v2 *API* alive — data_type/layer/parameters/trainer.SGD/event/
inference — as a shim over the TPU fluid stack, so v2-era model scripts run
unchanged while executing as compiled XLA programs (SURVEY §2.3/§2.4: the
legacy engine's capabilities are carried by the new engine, not by a second
interpreter)."""

from . import data_type  # noqa: F401
from . import activation  # noqa: F401
from . import pooling  # noqa: F401
from . import layer  # noqa: F401
from . import topology  # noqa: F401
from . import parameters  # noqa: F401
from . import optimizer  # noqa: F401
from . import trainer  # noqa: F401
from . import event  # noqa: F401
from . import inference  # noqa: F401
from .inference import infer  # noqa: F401

from .. import dataset  # noqa: F401
from .. import reader  # noqa: F401
from ..import batch  # noqa: F401

from . import minibatch  # noqa: F401

__all__ = [
    'init', 'data_type', 'activation', 'pooling', 'layer', 'topology',
    'parameters', 'optimizer', 'trainer', 'event', 'inference', 'infer',
    'dataset', 'reader', 'batch',
]

_init_kwargs = {}


def init(**kwargs):
    """(reference v2/__init__.py init — gflags bootstrap; the TPU build
    has nothing to bootstrap, flags come from env at import)"""
    _init_kwargs.update(kwargs)
