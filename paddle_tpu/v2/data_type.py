"""v2 input type declarations (reference: python/paddle/v2/data_type.py,
backed by trainer/PyDataProvider2.py InputType).  Each declares how a
column of a v2 data reader maps to a feed tensor."""


class DataType(object):
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class SequenceType(object):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class InputType(object):
    def __init__(self, dim, seq_type, data_type):
        self.dim = dim
        # SequenceType: NO_SEQUENCE=0, SEQUENCE=1, SUB_SEQUENCE=2
        self.seq_type = seq_type
        self.type = data_type


def dense_vector(dim):
    return InputType(dim, SequenceType.NO_SEQUENCE, DataType.Dense)


def dense_array(dim):
    return InputType(dim, SequenceType.NO_SEQUENCE, DataType.Dense)


def dense_vector_sub_sequence(dim):
    return InputType(dim, 2, DataType.Dense)


def integer_value_sub_sequence(value_range):
    return InputType(value_range, 2, DataType.Index)


def dense_vector_sequence(dim):
    return InputType(dim, SequenceType.SEQUENCE, DataType.Dense)


def integer_value(value_range):
    return InputType(value_range, SequenceType.NO_SEQUENCE, DataType.Index)


def integer_value_sequence(value_range):
    return InputType(value_range, SequenceType.SEQUENCE, DataType.Index)


def sparse_binary_vector(dim):
    return InputType(dim, SequenceType.NO_SEQUENCE, DataType.SparseNonValue)


def sparse_float_vector(dim):
    return InputType(dim, SequenceType.NO_SEQUENCE, DataType.SparseValue)


def sparse_float_vector_sequence(dim):
    return InputType(dim, SequenceType.SEQUENCE, DataType.SparseValue)


def sparse_binary_vector_sequence(dim):
    return InputType(dim, SequenceType.SEQUENCE, DataType.SparseNonValue)
