"""v2 layer DSL (reference: python/paddle/v2/layer.py, auto-generated from
trainer_config_helpers/layers.py).

v2 layers are *declarative nodes*: calling ``paddle.layer.fc(...)`` records
a node in a DAG; nothing is built until ``topology.Topology`` materializes
the DAG into a fluid Program (the reference analogously parses the config
into a protobuf ModelConfig consumed by the C++ GradientMachine).  Here the
"engine" under v2 is the same TPU fluid stack — one compiled XLA program
instead of the legacy Layer/Matrix interpreter (legacy/gserver/)."""

from . import data_type as _data_type
from .activation import BaseActivation, Linear
from .pooling import Max as _MaxPool

from .. import fluid

__all__ = [
    'data', 'fc', 'embedding', 'img_conv', 'img_pool', 'dropout', 'concat',
    'addto', 'classification_cost', 'cross_entropy_cost', 'mse_cost',
    'square_error_cost', 'pooling', 'lstmemory_like', 'batch_norm',
    'memory', 'recurrent_group', 'StaticInput', 'last_seq', 'first_seq',
    'max_id', 'trans', 'scaling', 'slope_intercept', 'sum_cost',
    'rank_cost', 'smooth_l1_cost', 'huber_regression_cost',
    'multi_binary_label_cross_entropy_cost', 'lstmemory', 'gru_like',
]


class Layer(object):
    """One node of the v2 DAG."""

    _counter = [0]

    def __init__(self, kind, parents, build_fn, name=None, size=None):
        Layer._counter[0] += 1
        self.kind = kind
        self.name = name or ('__%s_%d__' % (kind, Layer._counter[0]))
        self.parents = list(parents)
        self._build_fn = build_fn
        self.size = size

    def to_fluid(self, ctx):
        """Materialize (memoized per-build ctx dict) into a fluid var."""
        if self.name in ctx:
            return ctx[self.name]
        parent_vars = [p.to_fluid(ctx) for p in self.parents]
        var = self._build_fn(ctx, *parent_vars)
        ctx[self.name] = var
        return var

    def __repr__(self):
        return 'v2.layer.%s(%s)' % (self.kind, self.name)


def data(name, type, **kwargs):
    """Input declaration (reference layer.py data / data_layer)."""
    t = type

    def build(ctx):
        if t.type == _data_type.DataType.Index:
            return fluid.layers.data(
                name=name, shape=[1], dtype='int64',
                lod_level=1 if t.seq_type else 0)
        return fluid.layers.data(
            name=name, shape=[t.dim], dtype='float32',
            lod_level=1 if t.seq_type else 0)

    layer = Layer('data', [], build, name=name, size=t.dim)
    layer.data_type = t
    return layer


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, BaseActivation):
        return act.name
    return act


def fc(input, size, act=None, name=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(ctx, *parent_vars):
        out = None
        for v in parent_vars:
            term = fluid.layers.fc(v, size=size)
            out = term if out is None else fluid.layers.elementwise_add(
                out, term)
        a = _act_name(act if act is not None else Linear())
        if a == 'softmax':
            return fluid.layers.softmax(out)
        if a:
            return getattr(fluid.layers, a)(out)
        return out

    return Layer('fc', inputs, build, name=name, size=size)


def embedding(input, size, name=None, **kwargs):
    def build(ctx, parent_var):
        vocab = input.size
        return fluid.layers.embedding(parent_var, size=[vocab, size])

    return Layer('embedding', [input], build, name=name, size=size)


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, name=None, **kwargs):
    def build(ctx, parent_var):
        a = _act_name(act)
        return fluid.layers.conv2d(
            parent_var, num_filters=num_filters, filter_size=filter_size,
            stride=stride, padding=padding, act=a)

    return Layer('img_conv', [input], build, name=name, size=num_filters)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             name=None, **kwargs):
    ptype = (pool_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.pool2d(
            parent_var, pool_size=pool_size, pool_type=ptype,
            pool_stride=stride, pool_padding=padding)

    return Layer('img_pool', [input], build, name=name)


def batch_norm(input, act=None, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.batch_norm(parent_var, act=_act_name(act))

    return Layer('batch_norm', [input], build, name=name)


def dropout(input, dropout_rate, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.dropout(parent_var, dropout_prob=dropout_rate)

    return Layer('dropout', [input], build, name=name)


def concat(input, name=None, **kwargs):
    def build(ctx, *parent_vars):
        return fluid.layers.concat(list(parent_vars), axis=1)

    return Layer('concat', list(input), build, name=name)


def addto(input, act=None, name=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(ctx, *parent_vars):
        out = parent_vars[0]
        for v in parent_vars[1:]:
            out = fluid.layers.elementwise_add(out, v)
        a = _act_name(act)
        if a:
            out = getattr(fluid.layers, a)(out)
        return out

    return Layer('addto', inputs, build, name=name)


def pooling(input, pooling_type=None, name=None, **kwargs):
    """Sequence pooling (reference layer.py pooling over sequence
    input)."""
    ptype = (pooling_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.sequence_pool(parent_var, ptype)

    return Layer('pooling', [input], build, name=name)


def lstmemory_like(input, size, name=None, **kwargs):
    """Simple LSTM block: gate projection + dynamic_lstm (the v2
    simple_lstm network; reference networks.py simple_lstm)."""

    def build(ctx, parent_var):
        proj = fluid.layers.fc(parent_var, size=size * 4)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=size * 4)
        return hidden

    return Layer('lstmemory', [input], build, name=name, size=size)


def classification_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        ce = fluid.layers.cross_entropy(input_var, label_var)
        return fluid.layers.mean(ce)

    layer = Layer('classification_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


def cross_entropy_cost(input, label, name=None, **kwargs):
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        se = fluid.layers.square_error_cost(input_var, label_var)
        return fluid.layers.mean(se)

    layer = Layer('square_error_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


mse_cost = square_error_cost


def parse_network(*outputs):
    """Collect the input data layers reachable from outputs in
    declaration order (reference topology.py get_layer traversal)."""
    seen = []

    def walk(layer):
        for p in layer.parents:
            walk(p)
        if layer.kind == 'data' and layer not in seen:
            seen.append(layer)

    for out in outputs:
        walk(out)
    return seen


# ----------------------------------------------------------------------------
# recurrent group DSL (reference layer.py recurrent_group/memory — the v2
# step-function API over the legacy RecurrentGradientMachine; here the
# step builds inside a fluid DynamicRNN block, one masked lax.scan)
# ----------------------------------------------------------------------------
class StaticInput(object):
    """Whole-sequence input visible at every step (reference
    layer.py StaticInput)."""

    def __init__(self, input):
        self.input = input


class _MemoryLayer(Layer):
    """Recurrent state: reads last step's value of the layer named
    ``name``; ``size`` fixes the state width, ``boot_layer`` its init."""

    def __init__(self, name, size, boot_layer=None):
        self.link_name = name
        self.boot_layer = boot_layer

        def build(ctx):
            rnn = ctx.get('__rnn__')
            if rnn is None:
                raise RuntimeError(
                    'memory() is only meaningful inside recurrent_group')
            if self.boot_layer is not None:
                boot_var = self.boot_layer.to_fluid(ctx)
                mem = rnn.memory(init=boot_var)
            else:
                mem = rnn.memory(shape=[size], value=0.0)
            ctx.setdefault('__pending_memories__', []).append(
                (mem, self.link_name))
            return mem

        super(_MemoryLayer, self).__init__(
            'memory', [boot_layer] if boot_layer is not None else [],
            lambda ctx, *pv: build(ctx), size=size)


def memory(name, size, boot_layer=None, **kwargs):
    return _MemoryLayer(name, size, boot_layer)


def _wrap_fluid_var(ctx, var, kind='step_input'):
    layer = Layer(kind, [], lambda _ctx: var)
    ctx[layer.name] = var
    return layer


def recurrent_group(step, input, name=None, **kwargs):
    """Run ``step`` per timestep over sequence inputs (reference
    layer.py:3317 recurrent_group).  ``step`` receives one Layer per
    input (StaticInput wraps whole-sequence inputs) and returns the
    step's output layer; ``memory(name=N)`` inside the step reads the
    previous step's value of the layer named N."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    seq_parents = [i.input if isinstance(i, StaticInput) else i
                   for i in inputs]

    def build(ctx, *parent_vars):
        rnn = fluid.layers.DynamicRNN()
        outer_rnn = ctx.get('__rnn__')
        outer_pending = ctx.pop('__pending_memories__', None)
        ctx['__rnn__'] = rnn
        with rnn.block():
            step_layers = []
            for spec, var in zip(inputs, parent_vars):
                if isinstance(spec, StaticInput):
                    step_layers.append(
                        _wrap_fluid_var(ctx, rnn.static_input(var),
                                        'static_input'))
                else:
                    step_layers.append(
                        _wrap_fluid_var(ctx, rnn.step_input(var)))
            out_layer = step(*step_layers)
            out_var = out_layer.to_fluid(ctx)
            for mem_var, link_name in ctx.pop('__pending_memories__', []):
                target = ctx.get(link_name)
                if target is None:
                    raise RuntimeError(
                        'memory(name=%r): no step layer with that name '
                        'was built' % link_name)
                rnn.update_memory(mem_var, target)
            rnn.output(out_var)
        if outer_rnn is not None:
            ctx['__rnn__'] = outer_rnn
        else:
            ctx.pop('__rnn__', None)
        if outer_pending is not None:
            ctx['__pending_memories__'] = outer_pending
        return rnn()

    layer = Layer('recurrent_group', seq_parents, build, name=name)
    return layer


def lstmemory(input, size=None, name=None, **kwargs):
    """LSTM over a pre-projected [*, 4D] sequence (reference layer.py
    lstmemory: input must already be width 4*size)."""

    def build(ctx, parent_var):
        width = size or (input.size // 4 if input.size else None)
        if width is None:
            raise ValueError(
                'lstmemory: cannot infer the hidden width — the input '
                'layer declares no size; pass size= explicitly')
        hidden, _ = fluid.layers.dynamic_lstm(parent_var, size=width * 4)
        return hidden

    return Layer('lstmemory', [input], build, name=name, size=size)


def gru_like(input, size, name=None, **kwargs):
    """GRU block: gate projection + dynamic_gru (reference networks.py
    simple_gru)."""

    def build(ctx, parent_var):
        proj = fluid.layers.fc(parent_var, size=size * 3)
        return fluid.layers.dynamic_gru(proj, size=size)

    return Layer('gru', [input], build, name=name, size=size)


# ---- sequence/shape layers ----
def last_seq(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.sequence_last_step(parent_var)

    return Layer('last_seq', [input], build, name=name, size=input.size)


def first_seq(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.sequence_first_step(parent_var)

    return Layer('first_seq', [input], build, name=name, size=input.size)


def max_id(input, name=None, **kwargs):
    """Argmax over the feature dim (reference layer.py maxid_layer)."""

    def build(ctx, parent_var):
        _, idx = fluid.layers.topk(parent_var, k=1)
        return idx

    return Layer('max_id', [input], build, name=name, size=1)


def trans(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.transpose(
            parent_var, perm=[1, 0])

    return Layer('trans', [input], build, name=name)


def scaling(input, weight, name=None, **kwargs):
    """Row-wise scale: out[i] = weight[i] * input[i] (reference
    scaling_layer)."""

    def build(ctx, input_var, weight_var):
        return fluid.layers.elementwise_mul(input_var, weight_var, axis=0)

    return Layer('scaling', [input, weight], build, name=name,
                 size=input.size)


def slope_intercept(input, slope=1.0, intercept=0.0, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.scale(
            parent_var, scale=float(slope), bias=float(intercept))

    return Layer('slope_intercept', [input], build, name=name,
                 size=input.size)


# ---- cost layers (reference layer.py cost family) ----
def _cost_layer(kind, parents, build, name, prediction=None):
    layer = Layer(kind, parents, build, name=name)
    layer.is_cost = True
    if prediction is not None:
        layer.prediction_parent = prediction
    return layer


def sum_cost(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.reduce_sum(parent_var)

    return _cost_layer('sum_cost', [input], build, name, prediction=input)


def rank_cost(left, right, label, name=None, **kwargs):
    """RankNet pairwise cost (reference layer.py rank_cost)."""

    def build(ctx, left_var, right_var, label_var):
        return fluid.layers.mean(
            fluid.layers.rank_loss(label_var, left_var, right_var))

    return _cost_layer('rank_cost', [left, right, label], build, name)


def smooth_l1_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        return fluid.layers.mean(
            fluid.layers.smooth_l1(input_var, label_var))

    return _cost_layer('smooth_l1_cost', [input, label], build, name,
                      prediction=input)


def huber_regression_cost(input, label, delta=1.0, name=None, **kwargs):
    """Huber loss with threshold delta (reference layer.py
    huber_regression_cost): 0.5 d^2 inside |d|<=delta, delta(|d| -
    0.5 delta) outside."""

    def build(ctx, input_var, label_var):
        diff = fluid.layers.elementwise_sub(input_var, label_var)
        absd = fluid.layers.abs(diff)
        quad = fluid.layers.scale(
            fluid.layers.elementwise_mul(diff, diff), scale=0.5)
        lin = fluid.layers.scale(
            fluid.layers.scale(absd, bias=-0.5 * float(delta)),
            scale=float(delta))
        small = fluid.layers.cast(
            fluid.layers.less_than(
                absd,
                fluid.layers.fill_constant_batch_size_like(
                    absd, shape=[-1, 1], value=float(delta),
                    dtype='float32')), 'float32')
        per = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(small, quad),
            fluid.layers.elementwise_mul(
                fluid.layers.scale(small, scale=-1.0, bias=1.0), lin))
        return fluid.layers.mean(per)

    return _cost_layer('huber_regression_cost', [input, label], build,
                       name, prediction=input)


def multi_binary_label_cross_entropy_cost(input, label, name=None,
                                          **kwargs):
    """Per-label sigmoid cross entropy (reference layer.py
    multi_binary_label_cross_entropy)."""

    def build(ctx, input_var, label_var):
        ce = fluid.layers.sigmoid_cross_entropy_with_logits(
            input_var, label_var)
        return fluid.layers.mean(ce)

    return _cost_layer('multi_binary_label_cross_entropy',
                       [input, label], build, name, prediction=input)
