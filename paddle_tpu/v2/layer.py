"""v2 layer DSL (reference: python/paddle/v2/layer.py, auto-generated from
trainer_config_helpers/layers.py).

v2 layers are *declarative nodes*: calling ``paddle.layer.fc(...)`` records
a node in a DAG; nothing is built until ``topology.Topology`` materializes
the DAG into a fluid Program (the reference analogously parses the config
into a protobuf ModelConfig consumed by the C++ GradientMachine).  Here the
"engine" under v2 is the same TPU fluid stack — one compiled XLA program
instead of the legacy Layer/Matrix interpreter (legacy/gserver/)."""

from . import data_type as _data_type
from .activation import BaseActivation, Linear
from .pooling import Max as _MaxPool

from .. import fluid

__all__ = [
    'data', 'fc', 'embedding', 'img_conv', 'img_pool', 'dropout', 'concat',
    'addto', 'classification_cost', 'cross_entropy_cost', 'mse_cost',
    'square_error_cost', 'pooling', 'lstmemory_like', 'batch_norm',
]


class Layer(object):
    """One node of the v2 DAG."""

    _counter = [0]

    def __init__(self, kind, parents, build_fn, name=None, size=None):
        Layer._counter[0] += 1
        self.kind = kind
        self.name = name or ('__%s_%d__' % (kind, Layer._counter[0]))
        self.parents = list(parents)
        self._build_fn = build_fn
        self.size = size

    def to_fluid(self, ctx):
        """Materialize (memoized per-build ctx dict) into a fluid var."""
        if self.name in ctx:
            return ctx[self.name]
        parent_vars = [p.to_fluid(ctx) for p in self.parents]
        var = self._build_fn(ctx, *parent_vars)
        ctx[self.name] = var
        return var

    def __repr__(self):
        return 'v2.layer.%s(%s)' % (self.kind, self.name)


def data(name, type, **kwargs):
    """Input declaration (reference layer.py data / data_layer)."""
    t = type

    def build(ctx):
        if t.type == _data_type.DataType.Index:
            return fluid.layers.data(
                name=name, shape=[1], dtype='int64',
                lod_level=1 if t.seq_type else 0)
        return fluid.layers.data(
            name=name, shape=[t.dim], dtype='float32',
            lod_level=1 if t.seq_type else 0)

    layer = Layer('data', [], build, name=name, size=t.dim)
    layer.data_type = t
    return layer


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, BaseActivation):
        return act.name
    return act


def fc(input, size, act=None, name=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(ctx, *parent_vars):
        out = None
        for v in parent_vars:
            term = fluid.layers.fc(v, size=size)
            out = term if out is None else fluid.layers.elementwise_add(
                out, term)
        a = _act_name(act if act is not None else Linear())
        if a == 'softmax':
            return fluid.layers.softmax(out)
        if a:
            return getattr(fluid.layers, a)(out)
        return out

    return Layer('fc', inputs, build, name=name, size=size)


def embedding(input, size, name=None, **kwargs):
    def build(ctx, parent_var):
        vocab = input.size
        return fluid.layers.embedding(parent_var, size=[vocab, size])

    return Layer('embedding', [input], build, name=name, size=size)


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, name=None, **kwargs):
    def build(ctx, parent_var):
        a = _act_name(act)
        return fluid.layers.conv2d(
            parent_var, num_filters=num_filters, filter_size=filter_size,
            stride=stride, padding=padding, act=a)

    return Layer('img_conv', [input], build, name=name, size=num_filters)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             name=None, **kwargs):
    ptype = (pool_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.pool2d(
            parent_var, pool_size=pool_size, pool_type=ptype,
            pool_stride=stride, pool_padding=padding)

    return Layer('img_pool', [input], build, name=name)


def batch_norm(input, act=None, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.batch_norm(parent_var, act=_act_name(act))

    return Layer('batch_norm', [input], build, name=name)


def dropout(input, dropout_rate, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.dropout(parent_var, dropout_prob=dropout_rate)

    return Layer('dropout', [input], build, name=name)


def concat(input, name=None, **kwargs):
    def build(ctx, *parent_vars):
        return fluid.layers.concat(list(parent_vars), axis=1)

    return Layer('concat', list(input), build, name=name)


def addto(input, act=None, name=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(ctx, *parent_vars):
        out = parent_vars[0]
        for v in parent_vars[1:]:
            out = fluid.layers.elementwise_add(out, v)
        a = _act_name(act)
        if a:
            out = getattr(fluid.layers, a)(out)
        return out

    return Layer('addto', inputs, build, name=name)


def pooling(input, pooling_type=None, name=None, **kwargs):
    """Sequence pooling (reference layer.py pooling over sequence
    input)."""
    ptype = (pooling_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.sequence_pool(parent_var, ptype)

    return Layer('pooling', [input], build, name=name)


def lstmemory_like(input, size, name=None, **kwargs):
    """Simple LSTM block: gate projection + dynamic_lstm (the v2
    simple_lstm network; reference networks.py simple_lstm)."""

    def build(ctx, parent_var):
        proj = fluid.layers.fc(parent_var, size=size * 4)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=size * 4)
        return hidden

    return Layer('lstmemory', [input], build, name=name, size=size)


def classification_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        ce = fluid.layers.cross_entropy(input_var, label_var)
        return fluid.layers.mean(ce)

    layer = Layer('classification_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


def cross_entropy_cost(input, label, name=None, **kwargs):
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        se = fluid.layers.square_error_cost(input_var, label_var)
        return fluid.layers.mean(se)

    layer = Layer('square_error_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


mse_cost = square_error_cost


def parse_network(*outputs):
    """Collect the input data layers reachable from outputs in
    declaration order (reference topology.py get_layer traversal)."""
    seen = []

    def walk(layer):
        for p in layer.parents:
            walk(p)
        if layer.kind == 'data' and layer not in seen:
            seen.append(layer)

    for out in outputs:
        walk(out)
    return seen
