"""v2 layer DSL (reference: python/paddle/v2/layer.py, auto-generated from
trainer_config_helpers/layers.py).

v2 layers are *declarative nodes*: calling ``paddle.layer.fc(...)`` records
a node in a DAG; nothing is built until ``topology.Topology`` materializes
the DAG into a fluid Program (the reference analogously parses the config
into a protobuf ModelConfig consumed by the C++ GradientMachine).  Here the
"engine" under v2 is the same TPU fluid stack — one compiled XLA program
instead of the legacy Layer/Matrix interpreter (legacy/gserver/)."""

import numpy as np

from . import data_type as _data_type
from .activation import BaseActivation, Linear
from .pooling import Max as _MaxPool

from .. import fluid

__all__ = [
    'data', 'fc', 'embedding', 'img_conv', 'img_pool', 'dropout', 'concat',
    'addto', 'classification_cost', 'cross_entropy_cost', 'mse_cost',
    'square_error_cost', 'pooling', 'lstmemory_like', 'batch_norm',
    'memory', 'recurrent_group', 'StaticInput', 'last_seq', 'first_seq',
    'max_id', 'trans', 'scaling', 'slope_intercept', 'sum_cost',
    'rank_cost', 'smooth_l1_cost', 'huber_regression_cost',
    'multi_binary_label_cross_entropy_cost', 'lstmemory', 'gru_like',
    # round-3 tail (VERDICT r2 next-#8)
    'cos_sim', 'maxout', 'block_expand', 'expand', 'repeat', 'seq_concat',
    'seq_reshape', 'interpolation', 'power', 'sum_to_one_norm', 'clip',
    'pad', 'rotate', 'img_cmrnorm', 'bilinear_interp', 'row_conv',
    'multiplex', 'dot_prod', 'out_prod', 'l2_distance', 'sampling_id',
    'print_layer', 'gru_step', 'lstm_step', 'crf', 'crf_decoding', 'ctc',
    'hsigmoid', 'nce', 'huber_classification_cost', 'mixed',
    'full_matrix_projection', 'trans_full_matrix_projection',
    'identity_projection', 'table_projection', 'dotmul_projection',
    'context_projection', 'conv_projection',
    # second tail batch
    'prelu', 'crop', 'sub_seq', 'kmax_seq_score', 'linear_comb',
    'convex_comb', 'tensor_product', 'conv_shift', 'scale_shift',
    'gated_unit', 'roi_pool', 'priorbox', 'cross_channel_norm',
    # third tail batch
    'resize', 'row_l2_norm', 'switch_order', 'upsample', 'spp',
    'recurrent', 'img_conv3d', 'img_pool3d', 'factorization_machine',
    'scaling_projection', 'slice_projection', 'dotmul_operator',
    'detection_output', 'scale_sub_region', 'conv_operator',
    # round-4: the last legacy-DSL builders (VERDICT r3 next-#4)
    'sub_nested_seq', 'beam_search', 'GeneratedInput', 'BaseGeneratedInput',
    'BeamInput', 'cross_entropy_over_beam', 'AggregateLevel',
    'ExpandLevel',
]


class Layer(object):
    """One node of the v2 DAG."""

    _counter = [0]

    def __init__(self, kind, parents, build_fn, name=None, size=None):
        Layer._counter[0] += 1
        self.kind = kind
        self.name = name or ('__%s_%d__' % (kind, Layer._counter[0]))
        self.parents = list(parents)
        self._build_fn = build_fn
        self.size = size

    def to_fluid(self, ctx):
        """Materialize (memoized per-build ctx dict) into a fluid var."""
        if self.name in ctx:
            return ctx[self.name]
        parent_vars = [p.to_fluid(ctx) for p in self.parents]
        var = self._build_fn(ctx, *parent_vars)
        ctx[self.name] = var
        return var

    def __repr__(self):
        return 'v2.layer.%s(%s)' % (self.kind, self.name)


def data(name, type, **kwargs):
    """Input declaration (reference layer.py data / data_layer).
    SUB_SEQUENCE (seq_type=2) declares a nested 2-level LoD input: the
    runtime carries it padded [rows, T, ...] with inner lengths plus the
    outer sub-sequences-per-sequence level (ops/registry.py ROWS_SUFFIX
    — SURVEY §5.7 nested case)."""
    t = type

    def build(ctx):
        lod = int(getattr(t, 'seq_type', 0) or 0)
        if t.type == _data_type.DataType.Index:
            return fluid.layers.data(
                name=name, shape=[1], dtype='int64', lod_level=lod)
        return fluid.layers.data(
            name=name, shape=[t.dim], dtype='float32', lod_level=lod)

    layer = Layer('data', [], build, name=name, size=t.dim)
    layer.data_type = t
    return layer




def _reshape_to_nchw(v, flat_size, num_channels, who):
    """Recover [B, C, H, W] from a flat legacy feed (the config_parser
    height/width convention: square spatial extent).  Validates the
    square assumption instead of silently mis-shaping."""
    c = num_channels or 1
    if flat_size is None or flat_size % c:
        raise ValueError(
            '%s: input size %r is not divisible by num_channels %r' %
            (who, flat_size, c))
    hw = int(round((flat_size // c) ** 0.5))
    if hw * hw * c != flat_size:
        raise ValueError(
            '%s: input size %r with num_channels %r is not a square '
            'image (inferred side %r); reshape explicitly for '
            'non-square inputs' % (who, flat_size, c, hw))
    return fluid.layers.reshape(v, shape=[-1, c, hw, hw])


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, BaseActivation):
        return act.name
    return act


class _LegacyDefaultStdNormal(fluid.initializer.NormalInitializer):
    """Gaussian around a requested mean with the legacy default std.

    The legacy config_parser's unset-initial_std default is
    1/sqrt(fan_in) (reference config_parser.py parameter defaults), and
    fan_in is only known once the parameter shape exists — so resolve
    the std at init-op emission time."""

    def __call__(self, var, block):
        shape = list(var.shape)
        fan_in = shape[0] if len(shape) <= 2 else \
            int(np.prod(shape[1:]))
        self._std_dev = 1.0 / float(max(fan_in, 1)) ** 0.5
        return super(_LegacyDefaultStdNormal, self).__call__(var, block)


def _fluid_attr(attr):
    """Map a legacy ParameterAttribute (reference
    trainer_config_helpers/layers.py:349 — the argument every
    parameterized legacy layer takes) onto a fluid ParamAttr.

    Duck-typed so both trainer_config_helpers.attrs.ParameterAttribute
    and plain fluid ParamAttr/str/False flow through without this
    module importing the DSL layer above it.  Semantics carried:
    initial_std/initial_mean -> gaussian initializer (std==0 exactly
    collapses to a constant, the reference's is_static-like use; std
    UNSET with a mean keeps the legacy default std of 1/sqrt(fan_in) so
    symmetry still breaks), name and learning_rate pass through, False
    means "no parameter" (bias off)."""
    if attr is None or attr is False or isinstance(
            attr, (fluid.ParamAttr, str)):
        return attr
    std = getattr(attr, 'initial_std', None)
    mean = getattr(attr, 'initial_mean', None)
    init = None
    if std is not None or mean is not None:
        mean = 0.0 if mean is None else float(mean)
        if std is None:
            init = _LegacyDefaultStdNormal(loc=mean)
        elif float(std) == 0.0:
            init = fluid.initializer.ConstantInitializer(mean)
        else:
            init = fluid.initializer.NormalInitializer(loc=mean,
                                                       scale=float(std))
    kw = {}
    if getattr(attr, 'name', None):
        kw['name'] = attr.name
    if getattr(attr, 'learning_rate', None) is not None:
        kw['learning_rate'] = attr.learning_rate
    return fluid.ParamAttr(initializer=init, **kw)


def fc(input, size, act=None, name=None, param_attr=None, bias_attr=None,
       **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    # the legacy contract: one weight attr per input (or one broadcast
    # over all), a single bias attr after the sum — exactly fluid fc's
    # own multi-input handling, so delegate whole
    if isinstance(param_attr, (list, tuple)):
        p_attr = [_fluid_attr(a) for a in param_attr]
    else:
        p_attr = _fluid_attr(param_attr)

    def build(ctx, *parent_vars):
        out = fluid.layers.fc(list(parent_vars), size=size,
                              param_attr=p_attr,
                              bias_attr=_fluid_attr(bias_attr))
        # reference default activation: Tanh (wrap_act_default,
        # trainer_config_helpers/layers.py:1013) — NOT linear
        from .activation import Tanh
        a = _act_name(act if act is not None else Tanh())
        if a == 'softmax':
            return fluid.layers.softmax(out)
        if a:
            return getattr(fluid.layers, a)(out)
        return out

    return Layer('fc', inputs, build, name=name, size=size)


def embedding(input, size, name=None, param_attr=None, **kwargs):
    def build(ctx, parent_var):
        vocab = input.size
        return fluid.layers.embedding(parent_var, size=[vocab, size],
                                      param_attr=_fluid_attr(param_attr))

    return Layer('embedding', [input], build, name=name, size=size)


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, name=None, param_attr=None,
             bias_attr=None, **kwargs):
    def build(ctx, parent_var):
        # reference default activation: ReLU (layers.py:2508)
        from .activation import Relu
        a = _act_name(act if act is not None else Relu())
        v = parent_var
        if len(v.shape) == 2:
            # legacy configs feed images as flat dense vectors (the
            # reference config_parser recovered geometry the same way)
            v = _reshape_to_nchw(v, input.size, num_channels, 'img_conv')
        return fluid.layers.conv2d(
            v, num_filters=num_filters, filter_size=filter_size,
            stride=stride, padding=padding, act=a,
            param_attr=_fluid_attr(param_attr),
            bias_attr=_fluid_attr(bias_attr))

    return Layer('img_conv', [input], build, name=name, size=num_filters)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             name=None, **kwargs):
    ptype = (pool_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.pool2d(
            parent_var, pool_size=pool_size, pool_type=ptype,
            pool_stride=stride, pool_padding=padding)

    return Layer('img_pool', [input], build, name=name)


def batch_norm(input, act=None, name=None, epsilon=1e-5,
               moving_average_fraction=0.9, use_global_stats=None,
               param_attr=None, bias_attr=None, **kwargs):
    """(reference batch_norm_layer): epsilon, the moving-average
    momentum, frozen-statistics mode, and the scale/shift attrs all
    forward to fluid batch_norm."""
    def build(ctx, parent_var):
        from .activation import Relu
        return fluid.layers.batch_norm(
            parent_var,
            act=_act_name(act if act is not None else Relu()),
            use_global_stats=use_global_stats,
            momentum=moving_average_fraction, epsilon=epsilon,
            param_attr=_fluid_attr(param_attr),
            bias_attr=_fluid_attr(bias_attr))

    return Layer('batch_norm', [input], build, name=name)


def dropout(input, dropout_rate, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.dropout(parent_var, dropout_prob=dropout_rate)

    return Layer('dropout', [input], build, name=name)


def concat(input, name=None, **kwargs):
    def build(ctx, *parent_vars):
        return fluid.layers.concat(list(parent_vars), axis=1)

    return Layer('concat', list(input), build, name=name)


def addto(input, act=None, name=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(ctx, *parent_vars):
        out = parent_vars[0]
        for v in parent_vars[1:]:
            out = fluid.layers.elementwise_add(out, v)
        a = _act_name(act)
        if a:
            out = getattr(fluid.layers, a)(out)
        return out

    return Layer('addto', inputs, build, name=name)


class AggregateLevel(object):
    """Pooling level over nested sequences (reference layers.py:291):
    TO_NO_SEQUENCE aggregates the whole (possibly nested) sample;
    TO_SEQUENCE aggregates each sub-sequence of a nested sample."""
    TO_NO_SEQUENCE = 'non-seq'
    TO_SEQUENCE = 'seq'
    # legacy aliases (reference keeps both spellings)
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


def pooling(input, pooling_type=None, name=None,
            agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    """Sequence pooling (reference layer.py pooling).  ``agg_level``
    only matters for nested (SUB_SEQUENCE) inputs — see
    AggregateLevel."""
    ptype = (pooling_type or _MaxPool()).name

    def build(ctx, parent_var):
        return fluid.layers.sequence_pool(
            parent_var, ptype,
            agg_to_no_sequence=(agg_level != AggregateLevel.TO_SEQUENCE))

    return Layer('pooling', [input], build, name=name)


def lstmemory_like(input, size, name=None, **kwargs):
    """Simple LSTM block: gate projection + dynamic_lstm (the v2
    simple_lstm network; reference networks.py simple_lstm)."""

    def build(ctx, parent_var):
        proj = fluid.layers.fc(parent_var, size=size * 4)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=size * 4)
        return hidden

    return Layer('lstmemory', [input], build, name=name, size=size)


def classification_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        ce = fluid.layers.cross_entropy(input_var, label_var)
        return fluid.layers.mean(ce)

    layer = Layer('classification_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


def cross_entropy_cost(input, label, name=None, **kwargs):
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        se = fluid.layers.square_error_cost(input_var, label_var)
        return fluid.layers.mean(se)

    layer = Layer('square_error_cost', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


mse_cost = square_error_cost


def parse_network(*outputs):
    """Collect the input data layers reachable from outputs in
    declaration order (reference topology.py get_layer traversal)."""
    seen = []

    def walk(layer):
        for p in layer.parents:
            walk(p)
        if layer.kind == 'data' and layer not in seen:
            seen.append(layer)

    for out in outputs:
        walk(out)
    return seen


# ----------------------------------------------------------------------------
# recurrent group DSL (reference layer.py recurrent_group/memory — the v2
# step-function API over the legacy RecurrentGradientMachine; here the
# step builds inside a fluid DynamicRNN block, one masked lax.scan)
# ----------------------------------------------------------------------------
class StaticInput(object):
    """Whole-sequence input visible at every step (reference
    layer.py StaticInput)."""

    def __init__(self, input):
        self.input = input


class _MemoryLayer(Layer):
    """Recurrent state: reads last step's value of the layer named
    ``name``; ``size`` fixes the state width, ``boot_layer`` its init."""

    def __init__(self, name, size, boot_layer=None):
        self.link_name = name
        self.boot_layer = boot_layer

        def build(ctx):
            rnn = ctx.get('__rnn__')
            if rnn is None:
                raise RuntimeError(
                    'memory() is only meaningful inside recurrent_group')
            if self.boot_layer is not None:
                boot_var = self.boot_layer.to_fluid(ctx)
                mem = rnn.memory(init=boot_var)
            else:
                mem = rnn.memory(shape=[size], value=0.0)
            ctx.setdefault('__pending_memories__', []).append(
                (mem, self.link_name))
            return mem

        super(_MemoryLayer, self).__init__(
            'memory', [boot_layer] if boot_layer is not None else [],
            lambda ctx, *pv: build(ctx), size=size)


def memory(name, size, boot_layer=None, **kwargs):
    return _MemoryLayer(name, size, boot_layer)


def _wrap_fluid_var(ctx, var, kind='step_input'):
    layer = Layer(kind, [], lambda _ctx: var)
    ctx[layer.name] = var
    return layer


def recurrent_group(step, input, name=None, reverse=False, **kwargs):
    """Run ``step`` per timestep over sequence inputs (reference
    layer.py:3317 recurrent_group).  ``step`` receives one Layer per
    input (StaticInput wraps whole-sequence inputs) and returns the
    step's output layer; ``memory(name=N)`` inside the step reads the
    previous step's value of the layer named N.  ``reverse=True`` scans
    each sequence back-to-front with outputs aligned to the ORIGINAL
    positions (mask-aware flip -> forward scan -> flip back, the
    dynamic_lstm(is_reverse=) mechanism)."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    seq_parents = [i.input if isinstance(i, StaticInput) else i
                   for i in inputs]

    def build(ctx, *parent_vars):
        if reverse:
            parent_vars = tuple(
                v if isinstance(spec, StaticInput)
                else fluid.layers.sequence_reverse(v)
                for spec, v in zip(inputs, parent_vars))
        rnn = fluid.layers.DynamicRNN()
        outer_rnn = ctx.get('__rnn__')
        outer_pending = ctx.pop('__pending_memories__', None)
        ctx['__rnn__'] = rnn
        with rnn.block():
            step_layers = []
            for spec, var in zip(inputs, parent_vars):
                if isinstance(spec, StaticInput):
                    step_layers.append(
                        _wrap_fluid_var(ctx, rnn.static_input(var),
                                        'static_input'))
                else:
                    step_layers.append(
                        _wrap_fluid_var(ctx, rnn.step_input(var)))
            out_layer = step(*step_layers)
            out_var = out_layer.to_fluid(ctx)
            for mem_var, link_name in ctx.pop('__pending_memories__', []):
                target = ctx.get(link_name)
                if target is None:
                    raise RuntimeError(
                        'memory(name=%r): no step layer with that name '
                        'was built' % link_name)
                rnn.update_memory(mem_var, target)
            rnn.output(out_var)
        if outer_rnn is not None:
            ctx['__rnn__'] = outer_rnn
        else:
            ctx.pop('__rnn__', None)
        if outer_pending is not None:
            ctx['__pending_memories__'] = outer_pending
        out = rnn()
        if reverse:
            out = fluid.layers.sequence_reverse(out)
        return out

    layer = Layer('recurrent_group', seq_parents, build, name=name)
    return layer


def lstmemory(input, size=None, name=None, reverse=False, param_attr=None,
              bias_attr=None, **kwargs):
    """LSTM over a pre-projected [*, 4D] sequence (reference layer.py
    lstmemory: input must already be width 4*size)."""

    def build(ctx, parent_var):
        width = size or (input.size // 4 if input.size else None)
        if width is None:
            raise ValueError(
                'lstmemory: cannot infer the hidden width — the input '
                'layer declares no size; pass size= explicitly')
        hidden, _ = fluid.layers.dynamic_lstm(
            parent_var, size=width * 4, is_reverse=reverse,
            param_attr=_fluid_attr(param_attr),
            bias_attr=_fluid_attr(bias_attr))
        return hidden

    return Layer('lstmemory', [input], build, name=name, size=size)


def gru_like(input, size, name=None, reverse=False, param_attr=None,
             bias_attr=None, project=None, **kwargs):
    """GRU block (reference grumemory, layers.py:1605).  The reference
    contract is that grumemory's input IS the pre-projected size*3
    gate input (it asserts input.size == 3*size and never projects).

    ``project``: False = never project (the reference contract; raises
    if the parent is not 3*size wide), True = always add the learned
    gate projection (reference gru_group's mixed_layer), None = infer
    from the parent width — composites pass an explicit value so a
    coincidental 3*size-wide raw input cannot silently change the
    architecture."""

    def build(ctx, parent_var):
        v = parent_var
        is_gate_width = int(v.shape[-1]) == size * 3
        if project is False and not is_gate_width:
            raise ValueError(
                'grumemory: input width %r is not the pre-projected '
                'gate width %r (reference layers.py:1605 contract)' %
                (int(v.shape[-1]), size * 3))
        if project is True or (project is None and not is_gate_width):
            if project is None:
                # the reference grumemory FATALS here (input.size must be
                # 3*size, layers.py:1605); auto-projecting keeps lenient
                # configs training but must not do so silently — a
                # mis-wired width now trains a different architecture
                # (ADVICE r4 #2)
                import warnings
                warnings.warn(
                    'grumemory: input width %d != 3*size (%d); inserting '
                    'a learned gate projection the reference would '
                    'reject. Pass project=True to silence, or '
                    'project=False for the strict reference contract.'
                    % (int(v.shape[-1]), size * 3), stacklevel=2)
            v = fluid.layers.fc(v, size=size * 3)
        return fluid.layers.dynamic_gru(v, size=size,
                                        is_reverse=reverse,
                                        param_attr=_fluid_attr(param_attr),
                                        bias_attr=_fluid_attr(bias_attr))

    return Layer('gru', [input], build, name=name, size=size)


# ---- sequence/shape layers ----
def last_seq(input, name=None,
             agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.sequence_pool(
            parent_var, 'last',
            agg_to_no_sequence=(agg_level != AggregateLevel.TO_SEQUENCE))

    return Layer('last_seq', [input], build, name=name, size=input.size)


def first_seq(input, name=None,
              agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.sequence_pool(
            parent_var, 'first',
            agg_to_no_sequence=(agg_level != AggregateLevel.TO_SEQUENCE))

    return Layer('first_seq', [input], build, name=name, size=input.size)


def max_id(input, name=None, **kwargs):
    """Argmax over the feature dim (reference layer.py maxid_layer)."""

    def build(ctx, parent_var):
        _, idx = fluid.layers.topk(parent_var, k=1)
        return idx

    return Layer('max_id', [input], build, name=name, size=1)


def trans(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.transpose(
            parent_var, perm=[1, 0])

    return Layer('trans', [input], build, name=name)


def scaling(input, weight, name=None, **kwargs):
    """Row-wise scale: out[i] = weight[i] * input[i] (reference
    scaling_layer)."""

    def build(ctx, input_var, weight_var):
        return fluid.layers.elementwise_mul(input_var, weight_var, axis=0)

    return Layer('scaling', [input, weight], build, name=name,
                 size=input.size)


def slope_intercept(input, slope=1.0, intercept=0.0, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.scale(
            parent_var, scale=float(slope), bias=float(intercept))

    return Layer('slope_intercept', [input], build, name=name,
                 size=input.size)


# ---- cost layers (reference layer.py cost family) ----
def _cost_layer(kind, parents, build, name, prediction=None):
    layer = Layer(kind, parents, build, name=name)
    layer.is_cost = True
    if prediction is not None:
        layer.prediction_parent = prediction
    return layer


def sum_cost(input, name=None, **kwargs):
    def build(ctx, parent_var):
        return fluid.layers.reduce_sum(parent_var)

    return _cost_layer('sum_cost', [input], build, name, prediction=input)


def rank_cost(left, right, label, name=None, **kwargs):
    """RankNet pairwise cost (reference layer.py rank_cost)."""

    def build(ctx, left_var, right_var, label_var):
        return fluid.layers.mean(
            fluid.layers.rank_loss(label_var, left_var, right_var))

    return _cost_layer('rank_cost', [left, right, label], build, name)


def smooth_l1_cost(input, label, name=None, **kwargs):
    def build(ctx, input_var, label_var):
        return fluid.layers.mean(
            fluid.layers.smooth_l1(input_var, label_var))

    return _cost_layer('smooth_l1_cost', [input, label], build, name,
                      prediction=input)


def huber_regression_cost(input, label, delta=1.0, name=None, **kwargs):
    """Huber loss with threshold delta (reference layer.py
    huber_regression_cost): 0.5 d^2 inside |d|<=delta, delta(|d| -
    0.5 delta) outside."""

    def build(ctx, input_var, label_var):
        diff = fluid.layers.elementwise_sub(input_var, label_var)
        absd = fluid.layers.abs(diff)
        quad = fluid.layers.scale(
            fluid.layers.elementwise_mul(diff, diff), scale=0.5)
        lin = fluid.layers.scale(
            fluid.layers.scale(absd, bias=-0.5 * float(delta)),
            scale=float(delta))
        small = fluid.layers.cast(
            fluid.layers.less_than(
                absd,
                fluid.layers.fill_constant_batch_size_like(
                    absd, shape=[-1, 1], value=float(delta),
                    dtype='float32')), 'float32')
        per = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(small, quad),
            fluid.layers.elementwise_mul(
                fluid.layers.scale(small, scale=-1.0, bias=1.0), lin))
        return fluid.layers.mean(per)

    return _cost_layer('huber_regression_cost', [input, label], build,
                       name, prediction=input)


def multi_binary_label_cross_entropy_cost(input, label, name=None,
                                          **kwargs):
    """Per-label sigmoid cross entropy (reference layer.py
    multi_binary_label_cross_entropy)."""

    def build(ctx, input_var, label_var):
        ce = fluid.layers.sigmoid_cross_entropy_with_logits(
            input_var, label_var)
        return fluid.layers.mean(ce)

    return _cost_layer('multi_binary_label_cross_entropy',
                       [input, label], build, name, prediction=input)


# ---- round-3 layer tail (VERDICT r2 next-#8: the most-used missing v2
# kinds, each a declarative node over the fluid stack; reference
# python/paddle/v2/layer.py auto-generates these from
# trainer_config_helpers/layers.py builders of the same names) ----
def cos_sim(a, b, scale=1.0, name=None, **kwargs):
    def build(ctx, av, bv):
        return fluid.layers.scale(fluid.layers.cos_sim(av, bv),
                                  scale=float(scale))

    return Layer('cos_sim', [a, b], build, name=name, size=1)


def maxout(input, groups, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.maxout(v, groups=groups)

    return Layer('maxout', [input], build, name=name)


def block_expand(input, block_x, block_y, stride_x=1, stride_y=1,
                 padding_x=0, padding_y=0, name=None, **kwargs):
    """Image -> sequence of flattened blocks (reference block_expand_layer
    / operators/im2sequence_op.cc)."""

    def build(ctx, v):
        return fluid.layers.im2sequence(
            v, filter_size=[block_y, block_x],
            stride=[stride_y, stride_x], padding=[padding_y, padding_x])

    return Layer('block_expand', [input], build, name=name)


class ExpandLevel(object):
    """Expansion level (reference layers.py:1838): FROM_NO_SEQUENCE
    expands per-sample values over a sequence; FROM_SEQUENCE expands a
    plain sequence's items over a NESTED ref's sub-sequences."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE  # legacy alias


def expand(input, expand_as, name=None,
           expand_level=ExpandLevel.FROM_NO_SEQUENCE, **kwargs):
    def build(ctx, v, ref):
        return fluid.layers.sequence_expand(
            v, ref,
            expand_from_sequence=(
                expand_level == ExpandLevel.FROM_SEQUENCE))

    return Layer('expand', [input, expand_as], build, name=name,
                 size=input.size)


def repeat(input, num_repeats, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.expand(v, expand_times=[1, num_repeats])

    return Layer('repeat', [input], build, name=name)


def seq_concat(a, b, name=None, **kwargs):
    """Per-instance TIME concatenation (reference seq_concat_layer)."""

    def build(ctx, av, bv):
        return fluid.layers.sequence_concat([av, bv])

    return Layer('seq_concat', [a, b], build, name=name, size=a.size)


def seq_reshape(input, reshape_size, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.sequence_reshape(v, new_dim=reshape_size)

    return Layer('seq_reshape', [input], build, name=name,
                 size=reshape_size)


def interpolation(input, weight, name=None, **kwargs):
    """w*x + (1-w)*y with per-row weight (reference interpolation_layer).
    ``input`` is [x, y]."""
    x, y = input

    def build(ctx, xv, yv, wv):
        wx = fluid.layers.elementwise_mul(xv, wv, axis=0)
        wy = fluid.layers.elementwise_mul(
            yv, fluid.layers.scale(wv, scale=-1.0, bias=1.0), axis=0)
        return fluid.layers.elementwise_add(wx, wy)

    return Layer('interpolation', [x, y, weight], build, name=name,
                 size=x.size)


def power(input, weight, name=None, **kwargs):
    """out[i] = input[i] ^ weight[i] (reference power_layer)."""

    def build(ctx, v, wv):
        return fluid.layers.elementwise_pow(v, wv, axis=0)

    return Layer('power', [input, weight], build, name=name,
                 size=input.size)


def sum_to_one_norm(input, name=None, **kwargs):
    def build(ctx, v):
        s = fluid.layers.reduce_sum(v, dim=1, keep_dim=True)
        return fluid.layers.elementwise_div(v, s)

    return Layer('sum_to_one_norm', [input], build, name=name,
                 size=input.size)


def clip(input, min, max, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.clip(v, min=float(min), max=float(max))

    return Layer('clip', [input], build, name=name, size=input.size)


def pad(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kwargs):
    def build(ctx, v):
        paddings = []
        for p in (pad_c, pad_h, pad_w):
            paddings += list(p) if p else [0, 0]
        return fluid.layers.pad(v, paddings=[0, 0] + paddings)

    return Layer('pad', [input], build, name=name)


def rotate(input, height, width, name=None, **kwargs):
    """90-degree CCW rotation of the HxW planes (reference
    rotate_layer)."""

    def build(ctx, v):
        c = (input.size or height * width) // (height * width)
        img = fluid.layers.reshape(v, shape=[-1, c, height, width])
        t = fluid.layers.transpose(img, perm=[0, 1, 3, 2])
        rev = fluid.layers.reverse(t, axis=2)
        return fluid.layers.reshape(rev, shape=[-1, c * height * width])

    return Layer('rotate', [input], build, name=name, size=input.size)


def img_cmrnorm(input, size=5, scale=0.0001, power=0.75, name=None,
                **kwargs):
    def build(ctx, v):
        return fluid.layers.lrn(v, n=size, alpha=scale, beta=power)

    return Layer('img_cmrnorm', [input], build, name=name)


def bilinear_interp(input, out_size_x, out_size_y, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.image_resize(
            v, out_shape=[out_size_y, out_size_x], resample='BILINEAR')

    return Layer('bilinear_interp', [input], build, name=name)


def row_conv(input, context_len, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.row_conv(v, future_context_size=context_len)

    return Layer('row_conv', [input], build, name=name, size=input.size)


def multiplex(input, name=None, **kwargs):
    """input[0] is the per-row selector into input[1:] (reference
    multiplex_layer)."""

    def build(ctx, idx, *choices):
        return fluid.layers.multiplex(list(choices), idx)

    return Layer('multiplex', list(input), build, name=name)


def dot_prod(a, b, name=None, **kwargs):
    def build(ctx, av, bv):
        return fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(av, bv), dim=1, keep_dim=True)

    return Layer('dot_prod', [a, b], build, name=name, size=1)


def out_prod(a, b, name=None, **kwargs):
    """Row-wise outer product flattened (reference out_prod_layer)."""

    def build(ctx, av, bv):
        m, n = a.size, b.size
        ar = fluid.layers.reshape(av, shape=[-1, m, 1])
        br = fluid.layers.reshape(bv, shape=[-1, 1, n])
        return fluid.layers.reshape(
            fluid.layers.matmul(ar, br), shape=[-1, m * n])

    return Layer('out_prod', [a, b], build, name=name,
                 size=(a.size or 0) * (b.size or 0))


def l2_distance(a, b, name=None, **kwargs):
    def build(ctx, av, bv):
        d = fluid.layers.elementwise_sub(av, bv)
        return fluid.layers.sqrt(
            fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(d, d), dim=1, keep_dim=True))

    return Layer('l2_distance', [a, b], build, name=name, size=1)


def sampling_id(input, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.sampling_id(v)

    return Layer('sampling_id', [input], build, name=name, size=1)


def print_layer(input, message=None, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.Print(v, message=message or '')

    return Layer('print', [input], build, name=name, size=input.size)


def gru_step(input, state, size, act=None, gate_act=None, name=None,
             **kwargs):
    """One GRU step inside a recurrent_group (reference gru_step_layer /
    operators/gru_unit_op.cc)."""

    def build(ctx, iv, sv):
        h, _, _ = fluid.layers.gru_unit(
            input=iv, hidden=sv, size=size * 3,
            activation=_act_name(act) or 'tanh',
            gate_activation=_act_name(gate_act) or 'sigmoid')
        return h

    return Layer('gru_step', [input, state], build, name=name, size=size)


def lstm_step(input, state, cell, size, act=None, gate_act=None,
              name=None, **kwargs):
    """One LSTM step (reference lstm_step_layer / lstm_unit_op): returns
    the hidden; the cell state is published under '<layer name>@cell'
    for get_output_layer(arg_name='cell')."""
    layer_box = []

    def build(ctx, iv, sv, cv):
        h, c = fluid.layers.lstm_unit(
            x_t=iv, hidden_t_prev=sv, cell_t_prev=cv)
        ctx['%s@cell' % layer_box[0].name] = c
        return h

    layer = Layer('lstm_step', [input, state, cell], build, name=name,
                  size=size)
    layer_box.append(layer)
    return layer


def crf(input, label, size=None, name=None, **kwargs):
    """Linear-chain CRF cost (reference crf_layer /
    operators/linear_chain_crf_op.cc)."""

    def build(ctx, iv, lv):
        ll = fluid.layers.linear_chain_crf(
            input=iv, label=lv,
            param_attr=fluid.ParamAttr(name=(name or 'crf') + '_w'))
        return fluid.layers.mean(ll)

    return _cost_layer('crf', [input, label], build, name,
                       prediction=input)


def crf_decoding(input, size=None, label=None, name=None, **kwargs):
    def build(ctx, iv, *rest):
        return fluid.layers.crf_decoding(
            input=iv, param_attr=fluid.ParamAttr(
                name=(name or 'crf') + '_w'))

    parents = [input] + ([label] if label is not None else [])
    return Layer('crf_decoding', parents, build, name=name, size=1)


def ctc(input, label, size=None, blank=0, norm_by_times=False, name=None,
        **kwargs):
    """CTC cost (reference ctc_layer / warp_ctc_layer -> warpctc_op)."""

    def build(ctx, iv, lv):
        loss = fluid.layers.warpctc(input=iv, label=lv, blank=blank,
                                    norm_by_times=norm_by_times)
        return fluid.layers.mean(loss)

    return _cost_layer('ctc', [input, label], build, name,
                       prediction=input)


def hsigmoid(input, label, num_classes, name=None, **kwargs):
    def build(ctx, iv, lv):
        return fluid.layers.mean(
            fluid.layers.hsigmoid(iv, lv, num_classes))

    return _cost_layer('hsigmoid', [input, label], build, name,
                       prediction=input)


def nce(input, label, num_classes, num_neg_samples=10, name=None,
        **kwargs):
    def build(ctx, iv, lv):
        return fluid.layers.mean(
            fluid.layers.nce(input=iv, label=lv, num_total_classes=
                             num_classes,
                             num_neg_samples=num_neg_samples))

    return _cost_layer('nce', [input, label], build, name,
                       prediction=input)


def huber_classification_cost(input, label, name=None, **kwargs):
    """Huber loss for {0,1} classification on a +-1 margin (reference
    huber_classification_cost): y' = 2y-1, quadratic inside the margin,
    linear beyond."""

    def build(ctx, iv, lv):
        y = fluid.layers.scale(fluid.layers.cast(lv, 'float32'),
                               scale=2.0, bias=-1.0)
        z = fluid.layers.elementwise_mul(iv, y)
        one_minus = fluid.layers.scale(z, scale=-1.0, bias=1.0)
        hinge = fluid.layers.relu(one_minus)
        inside = fluid.layers.cast(
            fluid.layers.less_than(
                fluid.layers.scale(z, scale=-1.0),
                fluid.layers.fill_constant_batch_size_like(
                    z, shape=[-1, 1], value=1.0, dtype='float32')),
            'float32')  # z > -1
        quad = fluid.layers.elementwise_mul(hinge, hinge)
        lin = fluid.layers.scale(z, scale=-4.0)  # -4z for z < -1
        per = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(inside, quad),
            fluid.layers.elementwise_mul(
                fluid.layers.scale(inside, scale=-1.0, bias=1.0), lin))
        return fluid.layers.mean(per)

    return _cost_layer('huber_classification_cost', [input, label],
                       build, name, prediction=input)


# ---- mixed layer + projections (reference mixed_layer; each projection
# contributes a term summed by the mixed node) ----
class _Projection(object):
    def __init__(self, parent, term_fn, size=None):
        self.parent = parent
        self.term_fn = term_fn
        self.size = size


def full_matrix_projection(input, size, **kwargs):
    return _Projection(
        input, lambda v: fluid.layers.fc(v, size=size, bias_attr=False),
        size=size)


def trans_full_matrix_projection(input, size, **kwargs):
    return _Projection(
        input, lambda v: fluid.layers.fc(v, size=size, bias_attr=False),
        size=size)


def identity_projection(input, **kwargs):
    return _Projection(input, lambda v: v, size=input.size)


def table_projection(input, size, **kwargs):
    vocab = input.size

    def term(v):
        return fluid.layers.embedding(v, size=[vocab, size])

    return _Projection(input, term, size=size)


def dotmul_projection(input, **kwargs):
    size = input.size

    def term(v):
        w = fluid.layers.create_parameter(shape=[size], dtype='float32')
        return fluid.layers.elementwise_mul(v, w, axis=1)

    return _Projection(input, term, size=size)


def context_projection(input, context_len, context_start=None, **kwargs):
    """Parameter-free context concatenation (reference
    context_projection / math/context_project.h): out[t] is the window
    [t+start, t+start+context_len) of rows concatenated feature-wise,
    zero-padded outside the sequence.  No trainable weight — the
    reference's trainable variant is sequence_conv, kept separate."""
    start = (-((context_len - 1) // 2) if context_start is None
             else context_start)

    def term(v):
        # time shifts need the padded runtime layout: one op, lowered in
        # ops/sequence_ops.py:_context_project over the [B, T, D] view
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('context_project')
        out = helper.create_variable_for_type_inference(dtype=v.dtype)
        out.shape = tuple(v.shape[:-1]) + (
            (v.shape[-1] or 0) * context_len, )
        helper.append_op(
            type='context_project',
            inputs={'X': [v]},
            outputs={'Out': [out]},
            attrs={'context_len': int(context_len),
                   'context_start': int(start)})
        return out

    return _Projection(input, term, size=input.size * context_len)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, **kwargs):
    def term(v):
        return fluid.layers.conv2d(
            v, num_filters=num_filters, filter_size=filter_size,
            stride=stride, padding=padding, bias_attr=False)

    return _Projection(input, term, size=num_filters)


def mixed(size=None, input=None, act=None, bias_attr=None, name=None,
          **kwargs):
    """Sum of projection terms + optional activation (reference
    mixed_layer)."""
    projs = input if isinstance(input, (list, tuple)) else [input]
    parents = [p.parent for p in projs]

    def build(ctx, *parent_vars):
        out = None
        for proj, v in zip(projs, parent_vars):
            term = proj.term_fn(v)
            out = term if out is None else \
                fluid.layers.elementwise_add(out, term)
        a = _act_name(act)
        if a:
            out = getattr(fluid.layers, a)(out)
        return out

    return Layer('mixed', parents, build, name=name,
                 size=size or projs[0].size)


# ---- second tail batch: the remaining commonly-used legacy kinds ----
def prelu(input, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.prelu(v, mode='all')

    return Layer('prelu', [input], build, name=name, size=input.size)


def crop(input, shape=None, offsets=None, name=None, **kwargs):
    def build(ctx, v):
        return fluid.layers.crop(v, shape=shape, offsets=offsets)

    size = None
    if shape:
        size = 1
        for d in list(shape)[1:]:  # dim 0 is batch
            size *= int(d)
    return Layer('crop', [input], build, name=name, size=size)


def sub_seq(input, starts, ends, name=None, **kwargs):
    """Per-sequence time slice (reference sub_seq_layer): ``starts``/
    ``ends`` are END-EXCLUSIVE positions; sequence_slice takes (offset,
    LENGTH), so length = ends - starts."""

    def build(ctx, v, sv, ev):
        length = fluid.layers.elementwise_sub(ev, sv)
        return fluid.layers.sequence_slice(v, sv, length)

    return Layer('sub_seq', [input, starts, ends], build, name=name,
                 size=input.size)


class BaseGeneratedInput(object):
    """Marker base for generation-time inputs of beam_search
    (reference layers.py:4282)."""

    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """The previously-generated word fed back into the step: an
    embedding lookup (shared table ``embedding_name``) of the last
    step's predicted ids (reference layers.py:4294)."""

    def __init__(self, size, embedding_name, embedding_size):
        super(GeneratedInput, self).__init__()
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class _BeamRNNAdapter(object):
    """ctx['__rnn__'] stand-in inside beam_search: memory() calls from
    the step DSL land on the StaticRNN decode loop, with boot values
    beam-expanded from [B, H] to the static [B*K, H] beam layout."""

    def __init__(self, rnn, batch_ref, beam_size):
        self._rnn = rnn
        self._batch_ref = batch_ref
        self._k = beam_size

    def memory(self, init=None, shape=None, value=0.0):
        if init is not None:
            return self._rnn.memory(
                init=fluid.layers.beam_expand(init, self._k))
        return self._rnn.memory(shape=list(shape),
                                batch_ref=self._batch_ref,
                                init_value=value, ref_batch_dim_idx=0)

    def update_memory(self, mem, var):
        self._rnn.update_memory(mem, var)


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=500,
                name=None, num_results_per_sample=None, **kwargs):
    """Generation-mode recurrent group: run ``step`` per decode step and
    beam-search over its softmax output (reference layers.py:4485).

    TPU-native mechanism (SURVEY §5.7): instead of the reference's
    RecurrentLayerGroupSetGenerator machinery over growing LoD beams,
    the decode loop is a StaticRNN of ``max_length`` steps on the static
    [B*K] beam layout — topk + the beam_search op select survivors,
    every step memory is re-wired to its surviving parent row by
    gather-by-parent_idx, and beam_search_decode backtracks the parent
    pointers into finished sentences (ops/beam_search_ops.py).

    ``step`` is the same DSL callable recurrent_group takes; ``memory()``
    boots are beam-expanded to [B*K, H].  Boot layers must derive from
    the static inputs (built in the parent block).  Returns the decoded
    ids [B, num_results_per_sample, <=max_length]."""
    if num_results_per_sample is None:
        num_results_per_sample = beam_size
    if num_results_per_sample > beam_size:
        raise ValueError('num_results_per_sample (%d) must not exceed '
                         'beam_size (%d)'
                         % (num_results_per_sample, beam_size))
    inputs = input if isinstance(input, (list, tuple)) else [input]
    gen_idx = -1
    static_specs = []
    for i, each in enumerate(inputs):
        if isinstance(each, Layer):
            raise TypeError('in beam_search, none of the inputs may be a '
                            'plain layer: wrap whole-sequence context in '
                            'StaticInput')
        if isinstance(each, BaseGeneratedInput):
            if gen_idx != -1:
                raise ValueError('beam_search accepts only one '
                                 'GeneratedInput')
            gen_idx = i
        else:
            static_specs.append(each)
    if gen_idx == -1:
        raise ValueError('beam_search: no GeneratedInput given')
    gipt = inputs[gen_idx]
    gipt.bos_id, gipt.eos_id = bos_id, eos_id
    seq_parents = [s.input for s in static_specs]

    def build(ctx, *static_vars):
        if not static_vars:
            raise ValueError(
                'beam_search needs at least one StaticInput to anchor '
                'the batch dimension (the encoder context)')
        anchor = static_vars[0]
        anchor_beam = fluid.layers.beam_expand(anchor, beam_size)
        init_ids = fluid.layers.fill_constant_batch_size_like(
            input=anchor_beam, shape=[-1, 1], value=float(bos_id),
            dtype='int64')
        init_scores = fluid.layers.beam_init_scores(anchor, beam_size)

        rnn = fluid.layers.StaticRNN()
        ticker = fluid.layers.fill_constant_batch_size_like(
            input=init_scores, shape=[max_length, -1, 1], value=0.0,
            dtype='float32', input_dim_idx=0, output_dim_idx=1)
        outer_rnn = ctx.get('__rnn__')
        outer_pending = ctx.pop('__pending_memories__', None)
        with rnn.step():
            rnn.step_input(ticker)
            prev_ids = rnn.memory(init=init_ids)
            prev_scores = rnn.memory(init=init_scores)
            ctx['__rnn__'] = _BeamRNNAdapter(rnn, anchor_beam, beam_size)

            trg_emb = fluid.layers.embedding(
                prev_ids, size=[gipt.size, gipt.embedding_size],
                dtype='float32',
                param_attr=fluid.ParamAttr(name=gipt.embedding_name))
            step_layers = []
            si = 0
            for i, spec in enumerate(inputs):
                if i == gen_idx:
                    step_layers.append(
                        _wrap_fluid_var(ctx, trg_emb, 'generated_input'))
                else:
                    step_layers.append(_wrap_fluid_var(
                        ctx,
                        fluid.layers.beam_expand(static_vars[si],
                                                 beam_size),
                        'static_input'))
                    si += 1
            out_layer = step(*step_layers)
            out_var = out_layer.to_fluid(ctx)  # [B*K, V] next-word probs

            topk_scores, topk_indices = fluid.layers.topk(
                out_var, k=beam_size)
            accu_scores = fluid.layers.elementwise_add(
                fluid.layers.log(topk_scores), prev_scores)
            sel_ids, sel_scores, parent_idx = fluid.layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                beam_size, end_id=eos_id)
            for mem_var, link_name in ctx.pop('__pending_memories__', []):
                target = ctx.get(link_name)
                if target is None:
                    raise RuntimeError(
                        'memory(name=%r): no step layer with that name '
                        'was built' % link_name)
                rnn.update_memory(
                    mem_var, fluid.layers.gather(target, parent_idx))
            rnn.update_memory(prev_ids, sel_ids)
            rnn.update_memory(prev_scores, sel_scores)
            rnn.output(sel_ids, sel_scores, parent_idx)
        if outer_rnn is not None:
            ctx['__rnn__'] = outer_rnn
        else:
            ctx.pop('__rnn__', None)
        if outer_pending is not None:
            ctx['__pending_memories__'] = outer_pending

        ids_arr, scores_arr, parents_arr = rnn()
        sent_ids, _sent_scores = fluid.layers.beam_search_decode(
            ids_arr, scores_arr, parents_arr, beam_size=beam_size,
            end_id=eos_id)
        if num_results_per_sample < beam_size:
            sent_ids = fluid.layers.slice(
                sent_ids, axes=[1], starts=[0],
                ends=[num_results_per_sample])
        return sent_ids

    return Layer('beam_search', seq_parents, build, name=name)


class BeamInput(object):
    """One beam expansion for cross_entropy_over_beam (reference
    layers.py:6441): scores over all candidates (nested seq of width-1
    rows), the top-k selected candidate ids, and the gold candidate."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name=None, **kwargs):
    """Learning-to-search cost over beam expansions (reference
    layers.py:6465; kernel CrossEntropyOverBeam.cpp — see
    ops/beam_search_ops.py for the TPU-native split: host path
    construction + in-XLA gather/softmax so the score gradient flows)."""
    beams = [input] if isinstance(input, BeamInput) else list(input)
    for bm in beams:
        if not isinstance(bm, BeamInput):
            raise TypeError('cross_entropy_over_beam takes BeamInput '
                            'objects, got %r' % (bm, ))
    parents = []
    for bm in beams:
        parents += [bm.candidate_scores, bm.selected_candidates, bm.gold]

    def build(ctx, *parent_vars):
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('cross_entropy_over_beam')
        out = helper.create_variable_for_type_inference(dtype='float32')
        out.shape = (-1, 1)
        helper.append_op(
            type='cross_entropy_over_beam',
            inputs={'Scores': list(parent_vars[0::3]),
                    'Ids': list(parent_vars[1::3]),
                    'Gold': list(parent_vars[2::3])},
            outputs={'Out': [out]})
        return fluid.layers.mean(out)

    layer = Layer('cross_entropy_over_beam', parents, build, name=name,
                  size=1)
    layer.is_cost = True
    return layer


def sub_nested_seq(input, selected_indices, name=None, **kwargs):
    """Trim a nested sequence to the sub-sequences picked by
    ``selected_indices`` [B, k] (reference sub_nested_seq_layer;
    SubNestedSequenceLayer.cpp) — its own op lowering because both LoD
    levels live only on the padded runtime layout
    (ops/sequence_ops.py sub_nested_seq)."""

    def build(ctx, v, sv):
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('sub_nested_seq')
        out = helper.create_variable_for_type_inference(dtype=v.dtype)
        out.shape = v.shape
        helper.append_op(
            type='sub_nested_seq',
            inputs={'X': [v], 'SelectedIndices': [sv]},
            outputs={'Out': [out]})
        return out

    return Layer('sub_nested_seq', [input, selected_indices], build,
                 name=name, size=input.size)


def kmax_seq_score(input, beam_size=1, name=None, **kwargs):
    """Top-k INDICES per sequence, -1 past min(k, len) (reference
    kmax_seq_score_layer outputs selected ids, KmaxSeqScoreLayer.cpp:52)
    — its own op lowering (ops/sequence_ops.py) because the time axis
    only exists on the padded runtime layout.  Feeds
    sub_nested_seq(selected_indices=...) directly."""

    def build(ctx, v):
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('kmax_seq_score')
        out = helper.create_variable_for_type_inference(dtype=v.dtype)
        out.shape = (v.shape[0] if v.shape else -1, beam_size)
        helper.append_op(
            type='kmax_seq_score',
            inputs={'X': [v]},
            outputs={'Out': [out]},
            attrs={'beam_size': int(beam_size)})
        return out

    return Layer('kmax_seq_score', [input], build, name=name,
                 size=beam_size)


def linear_comb(weights, vectors, size=None, name=None, **kwargs):
    """out = sum_i w[i] * vec_block[i] (reference linear_comb_layer):
    weights [B, M], vectors [B, M*size] viewed as M blocks of size."""

    def build(ctx, wv, vv):
        m = weights.size
        d = size or (vectors.size // m)
        v3 = fluid.layers.reshape(vv, shape=[-1, m, d])
        w3 = fluid.layers.reshape(wv, shape=[-1, m, 1])
        return fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(v3, w3), dim=1)

    return Layer('linear_comb', [weights, vectors], build, name=name,
                 size=size or (vectors.size // weights.size
                               if vectors.size and weights.size else None))


convex_comb = linear_comb


def tensor_product(a, b, size, name=None, **kwargs):
    """Bilinear tensor product (reference tensor_layer): out[:, k] =
    a W_k b^T with one [Da, Db] weight slice per output."""

    def build(ctx, av, bv):
        da, db = a.size, b.size
        w = fluid.layers.create_parameter(
            shape=[da, size * db], dtype='float32')
        # [B, Da] @ [Da, K*Db] -> [B, K, Db]; then row-dot with b
        proj = fluid.layers.reshape(
            fluid.layers.matmul(av, w), shape=[-1, size, db])
        b3 = fluid.layers.reshape(bv, shape=[-1, 1, db])
        return fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(proj, b3), dim=2)

    return Layer('tensor_product', [a, b], build, name=name, size=size)


def conv_shift(a, b, name=None, **kwargs):
    """Circular correlation (reference conv_shift_layer /
    operators/conv_shift_op.cc): out[:, i] = sum_j a[:, i+j-M/2 mod N]
    * b[:, j] with b the odd-width kernel."""

    if b.size is None or b.size % 2 != 1:
        raise ValueError(
            'conv_shift kernel width must be odd (reference '
            'conv_shift_op.cc requires 2N+1); got %r' % (b.size, ))

    def build(ctx, av, bv):
        n, m = a.size, b.size
        half = m // 2
        parts = []
        for j in range(m):
            shift = j - half
            # roll a by -shift (circular) via concat of slices
            k = shift % n
            if k == 0:
                rolled = av
            else:
                left = fluid.layers.slice(av, axes=[1], starts=[k],
                                          ends=[n])
                right = fluid.layers.slice(av, axes=[1], starts=[0],
                                           ends=[k])
                rolled = fluid.layers.concat([left, right], axis=1)
            wj = fluid.layers.slice(bv, axes=[1], starts=[j],
                                    ends=[j + 1])
            parts.append(fluid.layers.elementwise_mul(rolled, wj,
                                                      axis=0))
        out = parts[0]
        for p in parts[1:]:
            out = fluid.layers.elementwise_add(out, p)
        return out

    return Layer('conv_shift', [a, b], build, name=name, size=a.size)


def scale_shift(input, name=None, **kwargs):
    """y = w*x + b with scalar learned w, b (reference
    scale_shift_layer)."""

    def build(ctx, v):
        w = fluid.layers.create_parameter(
            shape=[1], dtype='float32',
            default_initializer=fluid.initializer.Constant(1.0))
        b = fluid.layers.create_parameter(
            shape=[1], dtype='float32',
            default_initializer=fluid.initializer.Constant(0.0))
        return fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(v, w, axis=0), b, axis=0)

    return Layer('scale_shift', [input], build, name=name,
                 size=input.size)


def gated_unit(input, size, name=None, **kwargs):
    """GLU block: act(fc(x)) * sigmoid(fc(x)) (reference
    gated_unit_layer)."""

    def build(ctx, v):
        a = fluid.layers.fc(v, size=size)
        g = fluid.layers.fc(v, size=size, act='sigmoid')
        return fluid.layers.elementwise_mul(a, g)

    return Layer('gated_unit', [input], build, name=name, size=size)


# ---- detection-flavored legacy kinds (over the fluid detection stack) ----
def roi_pool(input, rois, pooled_width, pooled_height, spatial_scale=1.0,
             name=None, **kwargs):
    """(reference roi_pool_layer -> operators/roi_pool_op.cc)"""

    def build(ctx, v, rv):
        return fluid.layers.roi_pool(
            v, rv, pooled_height=pooled_height, pooled_width=pooled_width,
            spatial_scale=spatial_scale)

    return Layer('roi_pool', [input, rois], build, name=name)


def priorbox(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
             variance=None, num_channels=3, name=None, **kwargs):
    """(reference priorbox_layer -> operators/detection/prior_box_op.cc);
    returns the [H*W*P, 4] boxes (variances ride the ctx under
    '<name>@variances' for get_output-style access)."""
    layer_box = []

    def build(ctx, v, img):
        if len(img.shape) == 2:
            img = _reshape_to_nchw(img, image.size, num_channels,
                                   'priorbox')
        # fluid.prior_box owns list coercion and the reference defaults
        box_kwargs = {'min_sizes': min_sizes}
        if max_sizes is not None:
            box_kwargs['max_sizes'] = max_sizes
        if aspect_ratios is not None:
            box_kwargs['aspect_ratios'] = aspect_ratios
        if variance is not None:
            box_kwargs['variance'] = variance
        boxes, variances = fluid.layers.prior_box(v, img, **box_kwargs)
        ctx['%s@variances' % layer_box[0].name] = variances
        return boxes

    layer = Layer('priorbox', [input, image], build, name=name)
    layer_box.append(layer)
    return layer


def cross_channel_norm(input, num_channels=None, name=None, **kwargs):
    """Per-position L2 normalization across channels with a LEARNED
    per-channel scale (reference CrossChannelNormLayer, the SSD conv4_3
    norm — scale conventionally initialized to 20)."""

    def build(ctx, v):
        if len(v.shape) == 2:
            v = _reshape_to_nchw(v, input.size, num_channels,
                                 'cross_channel_norm')
        normed = fluid.layers.l2_normalize(v, axis=1)
        c_dim = int(v.shape[1])
        scale = fluid.layers.create_parameter(
            shape=[c_dim], dtype='float32',
            default_initializer=fluid.initializer.Constant(20.0))
        return fluid.layers.elementwise_mul(normed, scale, axis=1)

    return Layer('cross_channel_norm', [input], build, name=name,
                 size=input.size)


# ---- third tail batch (closing the reference layers.py inventory) ----
def resize(input, size, name=None, **kwargs):
    """Re-chunk rows to width ``size`` (reference resize_layer: a [B, N]
    batch becomes [B*N/size, size])."""

    def build(ctx, v):
        return fluid.layers.reshape(v, shape=[-1, int(size)])

    return Layer('resize', [input], build, name=name, size=size)


def row_l2_norm(input, name=None, **kwargs):
    """x / ||x||_2 per row (reference row_l2_norm_layer)."""

    def build(ctx, v):
        return fluid.layers.l2_normalize(v, axis=-1)

    return Layer('row_l2_norm', [input], build, name=name,
                 size=input.size)


def switch_order(input, reshape_from='NCHW', reshape_to='NHWC',
                 name=None, **kwargs):
    """Permute image dims (reference switch_order_layer)."""
    perm = {'NCHW': {'NHWC': [0, 2, 3, 1]},
            'NHWC': {'NCHW': [0, 3, 1, 2]}}[reshape_from][reshape_to]

    def build(ctx, v):
        return fluid.layers.transpose(v, perm=perm)

    return Layer('switch_order', [input], build, name=name)


def upsample(input, scale=2, upsample_mode='nearest', name=None,
             **kwargs):
    """Integer-factor spatial upsampling (reference upsample_layer)."""

    def build(ctx, v):
        h, w = int(v.shape[2]), int(v.shape[3])
        return fluid.layers.image_resize(
            v, out_shape=[h * int(scale), w * int(scale)],
            resample='NEAREST' if upsample_mode == 'nearest'
            else 'BILINEAR')

    return Layer('upsample', [input], build, name=name)


def spp(input, pyramid_height=2, pool_type=None, name=None, **kwargs):
    """Spatial pyramid pooling (reference spp_layer /
    operators/spp_op.cc): pool at pyramid levels 0..H-1 into exactly
    4^l bins each (padding up to a bin multiple first, as the
    reference's padded pooling does), concatenated per channel."""
    ptype = (pool_type.name if pool_type is not None else 'max')

    def build(ctx, v):
        c, h, w = int(v.shape[1]), int(v.shape[2]), int(v.shape[3])
        parts = []
        for level in range(int(pyramid_height)):
            bins = 2 ** level
            ph = bins * (-(-h // bins))  # pad to a bin multiple
            pw = bins * (-(-w // bins))
            vv = v
            if (ph, pw) != (h, w):
                vv = fluid.layers.pad(
                    v, paddings=[0, 0, 0, 0, 0, ph - h, 0, pw - w])
            pooled = fluid.layers.pool2d(
                vv, pool_size=[ph // bins, pw // bins], pool_type=ptype,
                pool_stride=[ph // bins, pw // bins])
            parts.append(fluid.layers.reshape(pooled, shape=[-1, c *
                                                             bins * bins]))
        return fluid.layers.concat(parts, axis=1)

    return Layer('spp', [input], build, name=name)


def recurrent(input, size=None, act=None, reverse=False, name=None,
              param_attr=None, bias_attr=None, **kwargs):
    """Plain full-matrix recurrence out_t = act(in_t + out_{t-1} W)
    (reference recurrent_layer) — expressed through the recurrent_group
    step DSL over the fluid scan (state update by the memory's
    name-match contract)."""
    width = size or input.size
    if input.size is not None and width != input.size:
        raise ValueError(
            'recurrent_layer: the reference recurrence is out_t = '
            'act(in_t + out_(t-1) W), so input width (%r) must equal '
            'size (%r) — project with fc_layer first' %
            (input.size, width))
    state = '%s@state' % (name or 'recurrent_%d' % (Layer._counter[0], ))
    from .activation import Tanh

    def step(ipt):
        mem = memory(name=state, size=width)
        # reference math exactly: in_t enters UNPROJECTED; only the
        # carried state passes through the weight (+ the layer bias),
        # LINEARLY — fc's Tanh default would wrap the state product
        # before the addto and change the recurrence
        rec = fc(input=mem, size=width, act=Linear(),
                 param_attr=param_attr, bias_attr=bias_attr)
        return addto(input=[ipt, rec], act=act or Tanh(), name=state)

    out = recurrent_group(step=step, input=input, name=name,
                          reverse=reverse)
    out.size = width
    return out


def img_conv3d(input, filter_size, num_filters, num_channels=None,
               stride=1, padding=0, act=None, name=None, **kwargs):
    def build(ctx, v):
        if len(v.shape) == 2:
            # flat legacy volume feeds recover [B, C, D, H, W] via
            # num_channels + a cubic spatial extent (img_conv's 2-D
            # convention, one rank up)
            c = num_channels or 1
            side = int(round((input.size // c) ** (1.0 / 3.0)))
            v = fluid.layers.reshape(
                v, shape=[-1, c, side, side, side])
        from .activation import Relu
        return fluid.layers.conv3d(
            v, num_filters=num_filters, filter_size=filter_size,
            stride=stride, padding=padding,
            act=_act_name(act if act is not None else Relu()))

    return Layer('img_conv3d', [input], build, name=name,
                 size=num_filters)


def img_pool3d(input, pool_size, stride=1, padding=0, pool_type=None,
               name=None, **kwargs):
    ptype = (pool_type or _MaxPool()).name

    def build(ctx, v):
        return fluid.layers.pool3d(
            v, pool_size=pool_size, pool_type=ptype, pool_stride=stride,
            pool_padding=padding)

    return Layer('img_pool3d', [input], build, name=name)


def factorization_machine(input, factor_size, name=None, **kwargs):
    """Second-order FM interaction term (reference
    factorization_machine layer): 0.5 * sum((xV)^2 - (x^2)(V^2))."""

    def build(ctx, v):
        n = input.size
        vmat = fluid.layers.create_parameter(
            shape=[n, int(factor_size)], dtype='float32')
        xv = fluid.layers.matmul(v, vmat)                   # [B, k]
        x2v2 = fluid.layers.matmul(
            fluid.layers.square(v), fluid.layers.square(vmat))
        return fluid.layers.scale(
            fluid.layers.reduce_sum(
                fluid.layers.elementwise_sub(
                    fluid.layers.square(xv), x2v2),
                dim=1, keep_dim=True),
            scale=0.5)

    return Layer('factorization_machine', [input], build, name=name,
                 size=1)


def scaling_projection(input, **kwargs):
    """w * x with one learned scalar (reference scaling_projection)."""

    def term(v):
        w = fluid.layers.create_parameter(shape=[1], dtype='float32')
        return fluid.layers.elementwise_mul(v, w, axis=0)

    return _Projection(input, term, size=input.size)


def slice_projection(input, slices, **kwargs):
    """Column slices of the input concatenated (reference
    slice_projection; slices = [(start, end), ...])."""
    width = sum(e - s for s, e in slices)

    def term(v):
        parts = [fluid.layers.slice(v, axes=[1], starts=[s], ends=[e])
                 for s, e in slices]
        return parts[0] if len(parts) == 1 else fluid.layers.concat(
            parts, axis=1)

    return _Projection(input, term, size=width)


def dotmul_operator(a, b, scale=1.0, **kwargs):
    """Elementwise scale*a*b mixed-layer term (reference
    dotmul_operator — a two-input operator): expressed as an identity
    projection of a hidden product node, so mixed()'s one-parent-per-
    term contract holds."""
    prod = Layer(
        'dotmul_op', [a, b],
        lambda ctx, va, vb: fluid.layers.scale(
            fluid.layers.elementwise_mul(va, vb), scale=float(scale)),
        size=a.size)
    return identity_projection(prod)


def detection_output(loc, conf, priorbox_layer_out, num_classes,
                     nms_threshold=0.45, name=None, **kwargs):
    """SSD decode + NMS (reference detection_output_layer ->
    operators/detection/detection_output).  Flat conv outputs reshape
    to the [N, P, 4] / [N, P, C] layout fluid.detection_output expects
    (num_classes sizes the score reshape)."""

    def build(ctx, loc_v, conf_v, pb_v):
        variances = ctx.get('%s@variances' % priorbox_layer_out.name)
        if len(loc_v.shape) == 2:
            loc_v = fluid.layers.reshape(loc_v, shape=[0, -1, 4])
        if len(conf_v.shape) == 2:
            conf_v = fluid.layers.reshape(
                conf_v, shape=[0, -1, int(num_classes)])
        return fluid.layers.detection_output(
            loc_v, conf_v, pb_v, variances,
            nms_threshold=nms_threshold)

    return Layer('detection_output', [loc, conf, priorbox_layer_out],
                 build, name=name)


def scale_sub_region(input, indices, value=1.0, num_channels=None,
                     name=None, **kwargs):
    """Scale values inside per-sample [C, H, W] boxes (reference
    scale_sub_region_layer; indices rows are 1-based inclusive
    [c0, c1, h0, h1, w0, w1])."""

    def build(ctx, v, iv):
        if len(v.shape) == 2:
            v = _reshape_to_nchw(v, input.size, num_channels,
                                 'scale_sub_region')
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('scale_sub_region')
        out = helper.create_variable_for_type_inference(dtype=v.dtype)
        out.shape = v.shape
        helper.append_op(
            type='scale_sub_region',
            inputs={'X': [v], 'Indices': [iv]},
            outputs={'Out': [out]},
            attrs={'value': float(value)})
        return out

    return Layer('scale_sub_region', [input, indices], build, name=name,
                 size=input.size)


def conv_operator(img, filter, filter_size, num_filters,
                  num_channels=None, stride=1, padding=0,
                  filter_size_y=None, stride_y=None, padding_y=None,
                  trans=False, **kwargs):
    """Dynamic-filter conv mixed-layer operator (reference
    conv_operator: the filter VALUES come from the ``filter`` layer's
    per-sample output, reshaped to [O, C, kh, kw] — not a trained
    parameter).  The term flattens to the 2-D [B, O*H'*W'] layout every
    mixed projection carries, with the size computed from the conv
    arithmetic."""
    if trans:
        raise NotImplementedError(
            'conv_operator(trans=True): transposed dynamic-filter conv '
            'is not carried — use conv2d_transpose at the fluid level')
    kh = int(filter_size)
    kw = int(filter_size_y if filter_size_y is not None else filter_size)
    sh = int(stride)
    sw = int(stride_y if stride_y is not None else stride)
    ph = int(padding)
    pw = int(padding_y if padding_y is not None else padding)
    c = num_channels or 1
    side = int(round((img.size // c) ** 0.5))
    out_h = (side + 2 * ph - kh) // sh + 1
    out_w = (side + 2 * pw - kw) // sw + 1
    term_size = int(num_filters) * out_h * out_w

    def build(ctx, img_v, filt_v):
        v = img_v
        if len(v.shape) == 2:
            v = _reshape_to_nchw(v, img.size, num_channels,
                                 'conv_operator')
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper('dynamic_conv2d')
        out = helper.create_variable_for_type_inference(dtype=v.dtype)
        out.shape = (-1, int(num_filters), out_h, out_w)
        helper.append_op(
            type='dynamic_conv2d',
            inputs={'X': [v], 'Filter': [filt_v]},
            outputs={'Out': [out]},
            attrs={'num_filters': int(num_filters),
                   'filter_size': [kh, kw],
                   'strides': [sh, sw],
                   'paddings': [ph, pw]})
        # mixed terms are 2-D [B, size]: flatten the conv map
        return fluid.layers.reshape(out, shape=[0, -1])

    prod = Layer('conv_op', [img, filter], build, size=term_size)
    return identity_projection(prod)
