"""v2 inference (reference: python/paddle/v2/inference.py)."""

import numpy as np

from .trainer import _build_feed
from .. import fluid

__all__ = ['infer', 'Inference']


class Inference(object):
    def __init__(self, output_layer, parameters):
        from .layer import parse_network
        outputs = (output_layer if isinstance(output_layer, (list, tuple))
                   else [output_layer])
        self.parameters = parameters
        self.topology = parameters.topology
        # input columns = the data layers the OUTPUTS depend on, in
        # declaration order (reference v2 infer feeding semantics) — NOT a
        # positional prefix of the cost DAG's inputs
        self.data_layers = parse_network(*outputs)
        program = self.topology.main_program
        ctx = self.topology._ctx
        if any(self.topology.var_of(out) is None for out in outputs):
            # outputs outside the cost DAG build into a CLONE so the
            # shared training topology is never mutated
            program = self.topology.main_program.clone()
            ctx = dict(ctx)
            with fluid.program_guard(program,
                                     self.topology.startup_program):
                for out in outputs:
                    out.to_fluid(ctx)
        self.output_names = [ctx[out.name].name for out in outputs]
        # prune away the cost branch so label inputs are not required
        # (reference inference.py builds from the pruned inference proto)
        pruned = program.prune(self.output_names)
        self._program = pruned.clone(for_test=True)
        place = (fluid.TPUPlace() if fluid.core.is_compiled_with_tpu()
                 else fluid.CPUPlace())
        self._exe = fluid.Executor(place)

    def infer(self, input, feeding=None, field='value'):
        # with an explicit feeding map, wider rows are fine — _build_feed
        # selects the mapped columns; only the positional default needs
        # the column count to match exactly
        if feeding is None and len(input[0]) != len(self.data_layers):
            raise ValueError(
                'infer input has %d columns but the output layer depends '
                'on %d data layers (%s); pass feeding={name: column} for '
                'wider rows' %
                (len(input[0]), len(self.data_layers),
                 [l.name for l in self.data_layers]))
        feed = _build_feed(self.data_layers, input, feeding)
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self.output_names,
                             scope=self.parameters.scope)
        outs = [np.asarray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def infer(output_layer, parameters, input, feeding=None, field='value'):
    return Inference(output_layer, parameters).infer(input, feeding=feeding,
                                                     field=field)
