"""v2 Parameters: name->ndarray view over a fluid Scope
(reference: python/paddle/v2/parameters.py — there a gradient-machine
parameter pool with to_tar/from_tar; here the pool is the Scope the
compiled program trains in)."""

import tarfile
import io

import numpy as np

from .. import fluid
from .topology import Topology


class Parameters(object):
    def __init__(self, topology):
        self.topology = topology
        self.scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(self.scope):
            exe.run(topology.startup_program)

    def names(self):
        return [p.name for p in
                self.topology.main_program.global_block().all_parameters()]

    def keys(self):
        return self.names()

    def __iter__(self):
        return iter(self.names())

    def __getitem__(self, name):
        var = self.scope.find_var(name)
        if var is None or var.value() is None:
            raise KeyError(name)
        return np.asarray(var.value())

    def __setitem__(self, name, value):
        var = self.scope.find_var(name)
        if var is None:
            raise KeyError(name)
        var.set_value(np.asarray(value))

    def get(self, name):
        return self[name]

    def set(self, name, value):
        self[name] = value

    # --- serialization (reference parameters.py to_tar/from_tar) ---
    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode='w') as tar:
            for name in self.names():
                buf = io.BytesIO()
                np.save(buf, self[name], allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name + '.npy')
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    def from_tar(self, f):
        with tarfile.open(fileobj=f, mode='r') as tar:
            for member in tar.getmembers():
                name = member.name[:-4]  # strip .npy
                # tarfile's file objects lack fileno(); buffer through
                # BytesIO for np.load
                arr = np.load(io.BytesIO(tar.extractfile(member).read()),
                              allow_pickle=False)
                if self.scope.find_var(name) is not None:
                    self[name] = arr
        return self

    @staticmethod
    def from_tar_new(topology, f):
        p = Parameters(topology)
        p.from_tar(f)
        return p


def create(cost):
    """(reference parameters.py create(topology))"""
    topo = cost if isinstance(cost, Topology) else Topology(cost)
    return Parameters(topo)
