"""(reference: python/paddle/v2/minibatch.py)"""

from .. import batch  # noqa: F401

__all__ = ['batch']
