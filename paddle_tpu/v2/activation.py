"""v2 activation objects (reference: python/paddle/v2/activation.py over
trainer_config_helpers/activations.py)."""


class BaseActivation(object):
    name = None

    def __repr__(self):
        return 'activation.%s' % type(self).__name__


class Linear(BaseActivation):
    name = None


class Relu(BaseActivation):
    name = 'relu'


class Sigmoid(BaseActivation):
    name = 'sigmoid'


class Tanh(BaseActivation):
    name = 'tanh'


class Softmax(BaseActivation):
    name = 'softmax'


class Exp(BaseActivation):
    name = 'exp'


class Log(BaseActivation):
    name = 'log'


class Square(BaseActivation):
    name = 'square'


class SoftRelu(BaseActivation):
    name = 'soft_relu'
