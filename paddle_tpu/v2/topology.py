"""v2 Topology: materializes the layer DAG into fluid Programs
(reference: python/paddle/v2/topology.py serializing to a protobuf
ModelConfig; here the artifact is a fluid Program compiled to XLA)."""

from . import layer as v2_layer
from .. import fluid


class Topology(object):
    def __init__(self, cost):
        costs = cost if isinstance(cost, (list, tuple)) else [cost]
        self.costs = list(costs)
        self.data_layers = v2_layer.parse_network(*self.costs)
        self.main_program = fluid.Program()
        self.startup_program = fluid.Program()
        self._ctx = {}
        with fluid.program_guard(self.main_program, self.startup_program):
            cost_vars = [c.to_fluid(self._ctx) for c in self.costs]
            self.cost_var = cost_vars[0]
            if len(cost_vars) > 1:
                total = cost_vars[0]
                for v in cost_vars[1:]:
                    total = fluid.layers.elementwise_add(total, v)
                self.cost_var = total
        # prediction output (for inference) where declared
        self.prediction_var = None
        pred_parent = getattr(self.costs[0], 'prediction_parent', None)
        if pred_parent is not None:
            self.prediction_var = self._ctx.get(pred_parent.name)

    def var_of(self, layer):
        return self._ctx.get(layer.name)

    def data_type(self):
        return [(l.name, l.data_type) for l in self.data_layers]

    def proto(self):
        """Program-as-config (the reference returns ModelConfig proto)."""
        return self.main_program
