"""v2 SGD trainer (reference: python/paddle/v2/trainer.py — there driving
the C++ GradientMachine via swig; here compiling the topology's fluid
Program once and stepping it on TPU/CPU)."""

import numpy as np

from . import event as v2_event
from . import data_type as _dt
from .topology import Topology
from .. import fluid

__all__ = ['SGD']


def _build_feed(data_layers, data_batch, feeding=None):
    """Convert a v2 minibatch (list of per-sample tuples) into a fluid
    feed dict according to each data layer's InputType (reference
    py_paddle DataProviderConverter)."""
    if feeding is None:
        order = {i: i for i in range(len(data_layers))}
    else:
        order = {i: feeding[l.name] for i, l in enumerate(data_layers)}
    feed = {}
    for i, layer in enumerate(data_layers):
        col = [sample[order[i]] for sample in data_batch]
        t = layer.data_type
        if t.seq_type == 2:  # nested: sample = list of sub-sequences
            width, dt = ((1, np.int64) if t.type == _dt.DataType.Index
                         else (t.dim, np.float32))
            chunks, inner, outer = [], [], []
            for sample_rows in col:
                outer.append(len(sample_rows))
                for sub in sample_rows:
                    arr = np.asarray(sub, dt).reshape(-1, width)
                    chunks.append(arr)
                    inner.append(len(arr))
            flat = (np.concatenate(chunks) if chunks
                    else np.zeros((0, width), dt))
            lt = fluid.core.LoDTensor(flat)
            lt.set_recursive_sequence_lengths([outer, inner])
            feed[layer.name] = lt
        elif t.seq_type:  # variable-length rows -> LoDTensor
            if t.type == _dt.DataType.Index:
                flat = np.concatenate(
                    [np.asarray(r, np.int64).reshape(-1, 1) for r in col])
            else:
                flat = np.concatenate(
                    [np.asarray(r, np.float32).reshape(-1, t.dim)
                     for r in col])
            lt = fluid.core.LoDTensor(flat)
            lt.set_recursive_sequence_lengths([[len(r) for r in col]])
            feed[layer.name] = lt
        elif t.type == _dt.DataType.Index:
            feed[layer.name] = np.asarray(col, np.int64).reshape(-1, 1)
        else:
            feed[layer.name] = np.asarray(
                col, np.float32).reshape(len(col), t.dim)
    return feed


class SGD(object):
    """(reference v2/trainer.py:37 SGD)"""

    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True, **kwargs):
        self.topology = (cost if isinstance(cost, Topology)
                         else parameters.topology)
        self.parameters = parameters
        self._train_program = self.topology.main_program.clone()
        self._test_program = self.topology.main_program.clone(for_test=True)
        # optimizer accumulators initialize via their own startup program:
        # the topology startup already ran when Parameters was created, and
        # re-running it would re-randomize the weights
        opt_startup = fluid.Program()
        with fluid.program_guard(self._train_program, opt_startup):
            cost_var = self._train_program.global_block().var(
                self.topology.cost_var.name)
            update_equation.to_fluid().minimize(cost_var)
        with fluid.scope_guard(parameters.scope):
            fluid.Executor(fluid.CPUPlace()).run(opt_startup)
        self._place = (fluid.TPUPlace()
                       if fluid.core.is_compiled_with_tpu()
                       else fluid.CPUPlace())
        self._exe = fluid.Executor(self._place)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        if event_handler is None:
            event_handler = lambda e: None
        cost_name = self.topology.cost_var.name
        data_layers = self.topology.data_layers
        with fluid.scope_guard(self.parameters.scope):
            for pass_id in range(num_passes):
                event_handler(v2_event.BeginPass(pass_id))
                for batch_id, data_batch in enumerate(reader()):
                    event_handler(
                        v2_event.BeginIteration(pass_id, batch_id))
                    feed = _build_feed(data_layers, data_batch, feeding)
                    cost, = self._exe.run(self._train_program, feed=feed,
                                          fetch_list=[cost_name])
                    event_handler(v2_event.EndIteration(
                        pass_id, batch_id,
                        float(np.asarray(cost).flatten()[0])))
                event_handler(v2_event.EndPass(pass_id))

    def test(self, reader, feeding=None):
        cost_name = self.topology.cost_var.name
        data_layers = self.topology.data_layers
        costs, n = 0.0, 0
        with fluid.scope_guard(self.parameters.scope):
            for data_batch in reader():
                feed = _build_feed(data_layers, data_batch, feeding)
                cost, = self._exe.run(self._test_program, feed=feed,
                                      fetch_list=[cost_name])
                costs += float(np.asarray(cost).flatten()[0])
                n += 1
        return v2_event.TestResult(cost=costs / max(n, 1))
