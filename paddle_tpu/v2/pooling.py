"""v2 pooling objects (reference: python/paddle/v2/pooling.py)."""


class BasePool(object):
    name = None


class Max(BasePool):
    name = 'max'


class Avg(BasePool):
    name = 'average'


class Sum(BasePool):
    name = 'sum'


class SqrtAvg(BasePool):
    name = 'sqrt'
