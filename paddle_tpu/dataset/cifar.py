"""CIFAR-shaped dataset (reference: python/paddle/dataset/cifar.py).

Synthetic 3x32x32 images with class-dependent colour/structure statistics.
Sample format matches the reference: (3072-float32 flattened image, int64
label)."""

import numpy as np

__all__ = ['train10', 'test10', 'train100', 'test100']

_IMG = 3 * 32 * 32


def _reader_creator(seed, n, num_classes):
    def reader():
        rng0 = np.random.RandomState(123)
        templates = rng0.uniform(-1, 1, size=(num_classes, _IMG)).astype(
            'float32')
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, num_classes))
            img = templates[label] + 0.4 * rng.standard_normal(_IMG).astype(
                'float32')
            yield np.clip(img, -1, 1).astype('float32'), label

    return reader


def train10(n=2048):
    return _reader_creator(21, n, 10)


def test10(n=512):
    return _reader_creator(22, n, 10)


def train100(n=2048):
    return _reader_creator(23, n, 100)


def test100(n=512):
    return _reader_creator(24, n, 100)
