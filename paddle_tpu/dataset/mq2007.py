"""MQ2007 LETOR ranking-shaped dataset (reference:
python/paddle/dataset/mq2007.py).  Synthetic: 46-dim feature vectors whose
first coordinate carries the relevance signal, so rank losses order pairs
correctly.  Formats match the reference:

* pairwise: yields (relevant_doc_vec, irrelevant_doc_vec)
* listwise: yields (label_list, feature_matrix)
* pointwise: yields (feature_vec, label)
"""

import numpy as np

__all__ = ['train', 'test']

_DIM = 46


def _make_doc(rng, rel):
    v = rng.standard_normal(_DIM).astype(np.float32) * 0.1
    v[0] += rel
    return v


def _reader_creator(seed, n_queries, format):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_queries):
            n_docs = int(rng.randint(4, 10))
            rels = rng.randint(0, 3, size=n_docs)
            docs = [_make_doc(rng, r) for r in rels]
            if format == 'pairwise':
                for i in range(n_docs):
                    for j in range(n_docs):
                        if rels[i] > rels[j]:
                            yield docs[i], docs[j]
            elif format == 'listwise':
                yield list(map(int, rels)), docs
            else:  # pointwise
                for d, r in zip(docs, rels):
                    yield d, int(r)

    return reader


def train(format='pairwise', n_queries=200):
    return _reader_creator(89, n_queries, format)


def test(format='pairwise', n_queries=50):
    return _reader_creator(97, n_queries, format)
