"""CTR (click-through-rate) dataset, criteo-display-ads shaped
(reference capability: the distributed-lookup-table / CTR config in
BASELINE.json; Paddle's classic CTR demo feeds 13 dense "I" features and
26 categorical "C" features hashed into a large sparse id space).

Deterministic synthetic generator (zero network egress): each sample is
(dense[13] float, sparse ids[26] int64 in [0, sparse_dim), label {0,1}),
with the label correlated to both dense and sparse features so models can
actually learn.
"""

import numpy as np

__all__ = ['train', 'test', 'zipf_batch', 'DENSE_DIM', 'SPARSE_SLOTS',
           'SPARSE_DIM']

DENSE_DIM = 13
SPARSE_SLOTS = 26
SPARSE_DIM = 10000


def zipf_batch(rng, rows, vocab=SPARSE_DIM):
    """One skewed CTR feed batch (ISSUE 11): zipfian ids — mass on a
    few hot rows, a long tail — the id distribution the sparse lane
    exists for, plus dense features and labels.  The ONE construction
    shared by bench.py's ctr config, perf_gate's sparse_grad stream
    and load_gen's --ctr-frac traffic class, so the skew parameter and
    slot layout can never silently diverge between them."""
    return {
        'dense': rng.standard_normal((rows, DENSE_DIM)).astype('float32'),
        'sparse_ids': (rng.zipf(1.2, size=(rows, SPARSE_SLOTS)) % vocab)
        .astype('int64'),
        'label': rng.randint(0, 2, (rows, 1)).astype('int64'),
    }


def _reader(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        # a fixed per-id weight makes sparse features informative
        id_w = np.sin(np.arange(SPARSE_DIM) * 0.37)
        w_dense = rng.standard_normal(DENSE_DIM)
        for _ in range(n):
            dense = rng.standard_normal(DENSE_DIM).astype('float32')
            ids = (rng.zipf(1.2, size=SPARSE_SLOTS) % SPARSE_DIM).astype(
                'int64')
            logit = dense @ w_dense * 0.5 + id_w[ids].sum() * 0.8
            label = np.int64(1 / (1 + np.exp(-logit)) > rng.rand())
            yield dense, ids, label

    return reader


def train(n=4096, seed=0):
    return _reader(seed, n)


def test(n=512, seed=1):
    return _reader(seed + 10007, n)
