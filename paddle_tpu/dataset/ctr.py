"""CTR (click-through-rate) dataset, criteo-display-ads shaped
(reference capability: the distributed-lookup-table / CTR config in
BASELINE.json; Paddle's classic CTR demo feeds 13 dense "I" features and
26 categorical "C" features hashed into a large sparse id space).

Deterministic synthetic generator (zero network egress): each sample is
(dense[13] float, sparse ids[26] int64 in [0, sparse_dim), label {0,1}),
with the label correlated to both dense and sparse features so models can
actually learn.
"""

import numpy as np

__all__ = ['train', 'test', 'zipf_batch', 'DENSE_DIM', 'SPARSE_SLOTS',
           'SPARSE_DIM']

DENSE_DIM = 13
SPARSE_SLOTS = 26
SPARSE_DIM = 10000


def zipf_batch(rng, rows, vocab=SPARSE_DIM, hot_frac=None):
    """One skewed CTR feed batch (ISSUE 11): zipfian ids — mass on a
    few hot rows, a long tail — the id distribution the sparse lane
    exists for, plus dense features and labels.  The ONE construction
    shared by bench.py's ctr config, perf_gate's sparse_grad /
    embed_cache streams and load_gen's --ctr-frac traffic class, so
    the skew parameter and slot layout can never silently diverge
    between them.

    ``hot_frac`` (ISSUE 12) sharpens the skew beyond what zipf(1.2)'s
    heavy tail gives: with probability hot_frac a lookup folds into a
    HOT set of vocab/16 ids (the rest spread over the cold range) —
    the regime where a small HBM hot-row cache absorbs nearly every
    lookup.  None (the default) keeps the plain zipf stream, drawing
    the identical rng sequence as before the knob existed."""
    # draw order (dense, ids[, hot mask], label) is part of the shared-
    # stream contract: hot_frac=None consumes exactly the pre-knob
    # sequence
    dense = rng.standard_normal((rows, DENSE_DIM)).astype('float32')
    base = rng.zipf(1.2, size=(rows, SPARSE_SLOTS))
    if hot_frac is not None:
        if not 0.0 < float(hot_frac) < 1.0:
            raise ValueError('zipf_batch: hot_frac must be in (0, 1), '
                             'got %r' % (hot_frac, ))
        hot_n = max(int(vocab) // 16, 1)
        hot = rng.random_sample((rows, SPARSE_SLOTS)) < float(hot_frac)
        ids = np.where(hot, base % hot_n,
                       hot_n + base % max(int(vocab) - hot_n, 1))
    else:
        ids = base % vocab
    return {
        'dense': dense,
        'sparse_ids': ids.astype('int64'),
        'label': rng.randint(0, 2, (rows, 1)).astype('int64'),
    }


def _reader(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        # a fixed per-id weight makes sparse features informative
        id_w = np.sin(np.arange(SPARSE_DIM) * 0.37)
        w_dense = rng.standard_normal(DENSE_DIM)
        for _ in range(n):
            dense = rng.standard_normal(DENSE_DIM).astype('float32')
            ids = (rng.zipf(1.2, size=SPARSE_SLOTS) % SPARSE_DIM).astype(
                'int64')
            logit = dense @ w_dense * 0.5 + id_w[ids].sum() * 0.8
            label = np.int64(1 / (1 + np.exp(-logit)) > rng.rand())
            yield dense, ids, label

    return reader


def train(n=4096, seed=0):
    return _reader(seed, n)


def test(n=512, seed=1):
    return _reader(seed + 10007, n)
