"""UCI-housing-shaped regression dataset
(reference: python/paddle/dataset/uci_housing.py) — synthetic linear data
with noise; 13 features, scalar target."""

import numpy as np

__all__ = ['train', 'test', 'feature_range', 'FEATURE_DIM']

FEATURE_DIM = 13


def _make(seed, n):
    rng = np.random.RandomState(seed)
    w = np.linspace(-2.0, 2.0, FEATURE_DIM).astype('float32')
    x = rng.uniform(-1, 1, size=(n, FEATURE_DIM)).astype('float32')
    y = (x @ w + 0.5 + 0.05 * rng.standard_normal(n)).astype('float32')
    return x, y


def _reader_creator(seed, n):
    def reader():
        x, y = _make(seed, n)
        for i in range(n):
            yield x[i], y[i:i + 1]

    return reader


def train(n=404):
    return _reader_creator(3, n)


def test(n=102):
    return _reader_creator(5, n)


def feature_range(maximums, minimums):
    pass
