"""WMT14 en-fr-shaped translation dataset (reference:
python/paddle/dataset/wmt14.py).  Synthetic parallel corpus: the "target"
is a deterministic function of the source so a seq2seq model can actually
drive its loss down.  Sample format matches the reference:
(src_ids, trg_ids, trg_ids_next) with <s>=0, <e>=1, <unk>=2."""

import numpy as np

__all__ = ['train', 'test', 'get_dict']

START, END, UNK = 0, 1, 2


def get_dict(dict_size, reverse=False):
    src = {('s%d' % i): i for i in range(dict_size)}
    trg = {('t%d' % i): i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def _reader_creator(seed, n, dict_size):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, size=length)
            # target: reversed source shifted by one vocab slot
            trg = np.clip(src[::-1] + 1, 3, dict_size - 1)
            trg_ids = [START] + list(map(int, trg))
            trg_next = list(map(int, trg)) + [END]
            yield list(map(int, src)), trg_ids, trg_next

    return reader


def train(dict_size, n=2000):
    return _reader_creator(53, n, dict_size)


def test(dict_size, n=400):
    return _reader_creator(59, n, dict_size)
