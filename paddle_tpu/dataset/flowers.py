"""Flowers-102-shaped image dataset (reference:
python/paddle/dataset/flowers.py).  Synthetic (zero-egress): class-dependent
color statistics so conv models genuinely separate classes.  Sample format
matches the reference reader: (flat float32 image of 3*H*W, int label)."""

import numpy as np

__all__ = ['train', 'test', 'valid']

CLASS_NUM = 102
_SHAPE = (3, 64, 64)


def _reader_creator(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, CLASS_NUM))
            base = np.zeros(_SHAPE, np.float32)
            base[label % 3] = (label / float(CLASS_NUM))
            img = base + 0.1 * rng.standard_normal(_SHAPE).astype(
                np.float32)
            yield img.flatten(), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False, n=1020):
    return _reader_creator(31, n)


def test(mapper=None, buffered_size=1024, use_xmap=False, n=510):
    return _reader_creator(37, n)


def valid(mapper=None, buffered_size=1024, use_xmap=False, n=510):
    return _reader_creator(41, n)
