"""VOC2012 segmentation-shaped dataset (reference:
python/paddle/dataset/voc2012.py).  Synthetic; sample format matches the
reference reader: (flat float32 image 3*H*W, flat int32 label mask H*W)."""

import numpy as np

__all__ = ['train', 'test', 'val']

_SHAPE = (3, 32, 32)
_CLASSES = 21


def _reader_creator(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        h, w = _SHAPE[1], _SHAPE[2]
        for _ in range(n):
            cls = int(rng.randint(1, _CLASSES))
            mask = np.zeros((h, w), np.int32)
            x0, y0 = rng.randint(0, w // 2), rng.randint(0, h // 2)
            mask[y0:y0 + h // 2, x0:x0 + w // 2] = cls
            img = 0.1 * rng.standard_normal(_SHAPE).astype(np.float32)
            img[cls % 3] += (mask > 0).astype(np.float32) * 0.8
            yield img.flatten(), mask.flatten()

    return reader


def train(n=800):
    return _reader_creator(73, n)


def test(n=200):
    return _reader_creator(79, n)


def val(n=200):
    return _reader_creator(83, n)
