"""MovieLens-shaped recommender dataset
(reference: python/paddle/dataset/movielens.py).

Deterministic synthetic users/movies with the same reader record layout:
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
score)."""

import numpy as np

__all__ = [
    'train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
    'age_table', 'movie_categories', 'CATEGORY_DICT_SIZE',
    'TITLE_DICT_SIZE'
]

_USERS = 100
_MOVIES = 80
_JOBS = 21
_AGES = 7
_CATEGORIES = 18
_TITLE_VOCAB = 150
_RATINGS = 1500

age_table = [1, 18, 25, 35, 45, 50, 56]
CATEGORY_DICT_SIZE = _CATEGORIES
TITLE_DICT_SIZE = _TITLE_VOCAB


def max_user_id():
    return _USERS


def max_movie_id():
    return _MOVIES


def max_job_id():
    return _JOBS


def movie_categories():
    return {('cat%d' % i): i for i in range(_CATEGORIES)}


def _movies(rng):
    movies = {}
    for mid in range(1, _MOVIES + 1):
        ncat = rng.randint(1, 4)
        cats = rng.choice(_CATEGORIES, size=ncat, replace=False).tolist()
        ntitle = rng.randint(1, 5)
        title = rng.randint(0, _TITLE_VOCAB, size=ntitle).tolist()
        movies[mid] = (cats, title)
    return movies


def _reader_creator(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        movies = _movies(np.random.RandomState(99))
        for _ in range(n):
            uid = int(rng.randint(1, _USERS + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, _AGES))
            job = int(rng.randint(0, _JOBS))
            mid = int(rng.randint(1, _MOVIES + 1))
            cats, title = movies[mid]
            # score correlated with ids so the model has signal to learn
            score = float(((uid * 7 + mid * 3) % 5) + 1)
            yield (uid, gender, age, job, mid, cats, title, score)

    return reader


def train():
    return _reader_creator(21, _RATINGS)


def test():
    return _reader_creator(23, _RATINGS // 5)
