"""imikolov-shaped PTB language-model dataset
(reference: python/paddle/dataset/imikolov.py).

Deterministic synthetic corpus (no network egress): sentences drawn from a
zipf-ish distribution; the same reader contract — N-gram tuples or
sequence pairs."""

import numpy as np

__all__ = ['build_dict', 'train', 'test', 'NGram']

_VOCAB = 200
_SENTENCES = 500


def _corpus(seed):
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(_SENTENCES):
        n = rng.randint(5, 15)
        # zipf-flavored draw bounded to vocab
        words = (rng.zipf(1.3, size=n) % (_VOCAB - 2)) + 2
        sents.append([int(w) for w in words])
    return sents


def build_dict(min_word_freq=0):
    """word -> id map; ids 0..N-1.  <s>=0, <e>=1 by convention here."""
    return {('w%d' % i): i for i in range(_VOCAB)}


def _ngram_reader(seed, n):
    def reader():
        for sent in _corpus(seed):
            if len(sent) < n:
                continue
            for i in range(n, len(sent) + 1):
                yield tuple(sent[i - n:i])

    return reader


def _seq_reader(seed):
    def reader():
        for sent in _corpus(seed):
            yield sent[:-1], sent[1:]

    return reader


def train(word_idx=None, n=5, data_type='NGRAM'):
    if data_type == 'NGRAM':
        return _ngram_reader(11, n)
    return _seq_reader(11)


def test(word_idx=None, n=5, data_type='NGRAM'):
    if data_type == 'NGRAM':
        return _ngram_reader(13, n)
    return _seq_reader(13)


class NGram(object):
    pass
