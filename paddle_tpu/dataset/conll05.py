"""CoNLL-2005 SRL-shaped dataset (reference:
python/paddle/dataset/conll05.py).  Synthetic: each sample is the
reference's 9-column tuple of aligned sequences
(word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred, mark, label)."""

import numpy as np

__all__ = ['get_dict', 'get_embedding', 'test']

_WORD_DICT = 4000
_VERB_DICT = 200
_LABEL_DICT = 59  # 2 * 29 BIO tags + O, reference label dict size


def get_dict():
    word_dict = {('w%d' % i): i for i in range(_WORD_DICT)}
    verb_dict = {('v%d' % i): i for i in range(_VERB_DICT)}
    label_dict = {('l%d' % i): i for i in range(_LABEL_DICT)}
    return word_dict, verb_dict, label_dict


def get_embedding(word_dim=32):
    rng = np.random.RandomState(5)
    return rng.standard_normal((_WORD_DICT, word_dim)).astype(np.float32)


def _reader_creator(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(5, 30))
            words = rng.randint(0, _WORD_DICT, size=length)
            pred_pos = int(rng.randint(0, length))
            pred = rng.randint(0, _VERB_DICT, size=length)
            mark = np.zeros(length, np.int64)
            mark[pred_pos] = 1

            def ctx(shift):
                idx = np.clip(
                    np.arange(length) + shift, 0, length - 1)
                return words[idx]

            # labels correlate with distance to the predicate so a CRF
            # tagger genuinely learns structure
            label = np.minimum(
                np.abs(np.arange(length) - pred_pos), _LABEL_DICT - 1)
            cols = (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2), pred,
                    mark, label)
            yield tuple(list(map(int, c)) for c in cols)

    return reader


def test(n=500):
    return _reader_creator(23, n)


def train(n=2000):
    return _reader_creator(19, n)
