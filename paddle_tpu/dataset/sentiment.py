"""Movie-review sentiment dataset (reference:
python/paddle/dataset/sentiment.py over nltk movie_reviews).  Synthetic;
sample format matches: (list of word ids, label in {0, 1})."""

import numpy as np

__all__ = ['get_word_dict', 'train', 'test']

_VOCAB = 2000


def get_word_dict():
    return {('w%d' % i): i for i in range(_VOCAB)}


def _reader_creator(seed, n):
    def reader():
        rng = np.random.RandomState(seed)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(10, 50))
            lo, hi = (0, half) if label else (half, _VOCAB)
            yield list(map(int, rng.randint(lo, hi, size=length))), label

    return reader


def train(n=1600):
    return _reader_creator(43, n)


def test(n=400):
    return _reader_creator(47, n)
