"""WMT16 multimodal-task-shaped translation dataset (reference:
python/paddle/dataset/wmt16.py).  Synthetic; sample format matches:
(src_ids, trg_ids, trg_ids_next)."""

from . import wmt14 as _wmt14

__all__ = ['train', 'test', 'validation', 'get_dict']


def get_dict(lang, dict_size, reverse=False):
    src, trg = _wmt14.get_dict(dict_size, reverse)
    return src if lang == 'en' else trg


def train(src_dict_size, trg_dict_size, src_lang='en', n=2000):
    return _wmt14._reader_creator(61, n, min(src_dict_size, trg_dict_size))


def test(src_dict_size, trg_dict_size, src_lang='en', n=400):
    return _wmt14._reader_creator(67, n, min(src_dict_size, trg_dict_size))


def validation(src_dict_size, trg_dict_size, src_lang='en', n=400):
    return _wmt14._reader_creator(71, n, min(src_dict_size, trg_dict_size))
