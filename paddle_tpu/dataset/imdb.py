"""IMDB-shaped sentiment dataset (reference: python/paddle/dataset/imdb.py).

Synthetic: two vocab regions are biased positive/negative so an embedding +
LSTM model genuinely converges.  Sample format matches the reference:
(list of int64 word ids — variable length, int64 label in {0, 1})."""

import numpy as np

__all__ = ['train', 'test', 'word_dict']

_VOCAB = 5149  # mirrors the reference's imdb.word_dict() size ballpark


def word_dict(vocab_size=_VOCAB):
    return {('w%d' % i): i for i in range(vocab_size)}


def _reader_creator(seed, n, vocab_size):
    def reader():
        rng = np.random.RandomState(seed)
        half = vocab_size // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            if label == 1:
                words = rng.randint(0, half, size=length)
            else:
                words = rng.randint(half, vocab_size, size=length)
            yield list(map(int, words)), label

    return reader


def train(word_idx=None, n=2000):
    vocab = len(word_idx) if word_idx else _VOCAB
    return _reader_creator(13, n, vocab)


def test(word_idx=None, n=500):
    vocab = len(word_idx) if word_idx else _VOCAB
    return _reader_creator(17, n, vocab)
