"""MNIST-shaped dataset (reference: python/paddle/dataset/mnist.py).

Synthetic digits: each class is a fixed random template plus noise, so a
small MLP/LeNet genuinely learns and loss decreases — good enough for the
book-chapter convergence tests without network access.  Sample format
matches the reference: (784-float32 image in [-1, 1], int64 label).
"""

import numpy as np

__all__ = ['train', 'test', 'IMAGE_SIZE', 'NUM_CLASSES']

IMAGE_SIZE = 784
NUM_CLASSES = 10


def _templates(seed=42):
    rng = np.random.RandomState(seed)
    return rng.uniform(-1.0, 1.0, size=(NUM_CLASSES, IMAGE_SIZE)).astype(
        'float32')


def _reader_creator(num_samples, seed):
    def reader():
        templates = _templates()
        rng = np.random.RandomState(seed)
        for _ in range(num_samples):
            label = int(rng.randint(0, NUM_CLASSES))
            img = templates[label] + 0.35 * rng.standard_normal(
                IMAGE_SIZE).astype('float32')
            yield np.clip(img, -1.0, 1.0).astype('float32'), label

    return reader


def train(num_samples=2048):
    return _reader_creator(num_samples, seed=7)


def test(num_samples=512):
    return _reader_creator(num_samples, seed=11)
