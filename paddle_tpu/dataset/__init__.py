"""Datasets (reference: python/paddle/dataset/).

The reference downloads real corpora; this build (zero-egress environment)
provides deterministic synthetic generators with the same reader-creator
signatures so every book/benchmark model runs unmodified.  Real-data loaders
can be pointed at local files.
"""

from . import mnist  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import cifar  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import ctr  # noqa: F401
from . import flowers  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import voc2012  # noqa: F401
from . import mq2007  # noqa: F401

__all__ = ['mnist', 'uci_housing', 'imdb', 'cifar', 'imikolov', 'movielens',
           'ctr', 'flowers', 'conll05', 'sentiment', 'wmt14', 'wmt16',
           'voc2012', 'mq2007']
