"""Deployment predictor API
(reference: paddle/fluid/inference/api/paddle_inference_api.h:67-177 —
PaddleTensor / PaddlePredictor / CreatePaddlePredictor).

The engine-agnostic ABI maps to Python: a Predictor owns a compiled
inference program + scope; ``run`` takes named inputs and returns outputs;
``clone`` shares weights with an independent compile cache (the reference's
Clone shares the scope, api_impl.cc:89).  The analysis/TensorRT engines'
role (graph fusion) is played by XLA itself.
"""

import numpy as np

from . import fluid
from .fluid import core

__all__ = ['PaddleTensor', 'NativeConfig', 'PaddlePredictor',
           'create_paddle_predictor']


class PaddleTensor(object):
    """(reference paddle_inference_api.h:67)"""

    def __init__(self, name=None, data=None, lod=None):
        self.name = name
        self.data = np.asarray(data) if data is not None else None
        self.lod = lod or []

    @property
    def shape(self):
        return list(self.data.shape) if self.data is not None else []


class NativeConfig(object):
    """(reference paddle_inference_api.h NativeConfig)"""

    def __init__(self,
                 model_dir=None,
                 prog_file=None,
                 param_file=None,
                 use_tpu=True,
                 device=0,
                 half_precision=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.param_file = param_file
        self.use_tpu = use_tpu
        self.device = device
        # 'bfloat16' (TPU-native) or 'float16': run the loaded program
        # through InferenceTranspiler (BN fold) + Float16Transpiler so
        # the graph computes in half precision while feeds/fetches stay
        # f32 (reference contrib/float16 flow)
        self.half_precision = half_precision


class PaddlePredictor(object):
    """(reference paddle_inference_api.h:90 / NativePaddlePredictor)"""

    def __init__(self, config, _shared_scope=None, _shared_model=None):
        self._config = config
        place = fluid.TPUPlace(config.device) if config.use_tpu and \
            core.is_compiled_with_tpu() else fluid.CPUPlace()
        self._exe = fluid.Executor(place)
        self._scope = _shared_scope or core.Scope()
        with fluid.scope_guard(self._scope):
            if _shared_model is not None:
                # clone: share the (possibly transpiled) program — the
                # BN-fold scope rewrite is not idempotent, so a clone
                # must never reload + re-transpile against the shared
                # scope
                (self._program, self._feed_names,
                 self._fetch_targets) = _shared_model
                return
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                 config.model_dir,
                 self._exe,
                 model_filename=config.prog_file,
                 params_filename=config.param_file)
            if getattr(config, 'half_precision', None):
                fluid.InferenceTranspiler().transpile(
                    self._program, scope=self._scope)
                fluid.Float16Transpiler().transpile(
                    self._program, scope=self._scope,
                    dtype=config.half_precision,
                    feeded_var_names=self._feed_names,
                    fetch_var_names=self._fetch_targets)

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return [v.name for v in self._fetch_targets]

    def run(self, inputs, batch_size=-1):
        """inputs: list of PaddleTensor (positional per feed_names) or a
        {name: array} dict.  Returns a list of PaddleTensor."""
        if isinstance(inputs, dict):
            feed = dict(inputs)
        else:
            feed = {}
            for i, t in enumerate(inputs):
                name = t.name or self._feed_names[i]
                value = t.data
                if t.lod:
                    lt = core.LoDTensor(np.asarray(value))
                    lt.set_lod(t.lod)
                    value = lt
                feed[name] = value
        with fluid.scope_guard(self._scope):
            outs = self._exe.run(
                self._program, feed=feed, fetch_list=self._fetch_targets)
        return [
            PaddleTensor(name=v.name, data=o)
            for v, o in zip(self._fetch_targets, outs)
        ]

    def clone(self):
        """New predictor sharing weights (reference Run/Clone contract)."""
        return PaddlePredictor(
            self._config, _shared_scope=self._scope,
            _shared_model=(self._program, self._feed_names,
                           self._fetch_targets))


def create_paddle_predictor(config):
    """(reference CreatePaddlePredictor<ConfigT>, :177)"""
    return PaddlePredictor(config)
