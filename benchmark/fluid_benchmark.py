"""Benchmark harness over the BASELINE model configs (reference:
benchmark/fluid/fluid_benchmark.py — its --model/--batch_size/--iterations
/--device CLI over the models in benchmark/fluid/models/).

    python benchmark/fluid_benchmark.py --model resnet --batch_size 64 \
        --iterations 10 --device TPU [--amp]

Models: mnist, resnet, vgg, stacked_lstm (IMDB), machine_translation
(WMT14 seq2seq), ctr (sparse).  Prints one JSON line per run with
examples/sec (imgs/sec or tokens/sec to match the reference's reporting).
"""

import argparse
import json
import time

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.dataset as dataset


def _lod_feed(rows, dtype, dim=1):
    flat = np.concatenate(
        [np.asarray(r, dtype).reshape(-1, dim) for r in rows])
    return fluid.create_lod_tensor(flat, [[len(r) for r in rows]])


def _mnist(args, rng):
    from paddle_tpu.models import mnist
    model = mnist.build(nn_type='conv' if args.use_conv else 'mlp',
                        img_shape=(1, 28, 28) if args.use_conv else (784, ))
    shape = (args.batch_size, 1, 28, 28) if args.use_conv else (
        args.batch_size, 784)
    feed = {
        'img': rng.standard_normal(shape).astype('float32'),
        'label': rng.randint(0, 10, (args.batch_size, 1)).astype('int64'),
    }
    return model, feed, args.batch_size, 'imgs/sec'


def _resnet(args, rng):
    from paddle_tpu.models import resnet
    model = resnet.build(depth=50, class_dim=1000,
                         image_shape=(3, 224, 224), lr=0.1)
    feed = {
        'img': rng.standard_normal(
            (args.batch_size, 3, 224, 224)).astype('float32'),
        'label': rng.randint(0, 1000,
                             (args.batch_size, 1)).astype('int64'),
    }
    return model, feed, args.batch_size, 'imgs/sec'


def _vgg(args, rng):
    from paddle_tpu.models import vgg
    model = vgg.build(class_dim=1000, image_shape=(3, 224, 224))
    feed = {
        'img': rng.standard_normal(
            (args.batch_size, 3, 224, 224)).astype('float32'),
        'label': rng.randint(0, 1000,
                             (args.batch_size, 1)).astype('int64'),
    }
    return model, feed, args.batch_size, 'imgs/sec'


def _stacked_lstm(args, rng):
    from paddle_tpu.models import stacked_lstm
    model = stacked_lstm.build()
    seq_len = args.seq_len
    rows = [rng.randint(0, 5149, size=(seq_len, 1)).tolist()
            for _ in range(args.batch_size)]
    feed = {
        'words': _lod_feed(rows, 'int64'),
        'label': rng.randint(0, 2, (args.batch_size, 1)).astype('int64'),
    }
    return model, feed, args.batch_size * seq_len, 'tokens/sec'


def _machine_translation(args, rng):
    from paddle_tpu.models import seq2seq
    # reference get_model dims (benchmark/fluid/models/machine_translation.py:
    # embedding_dim=512, encoder/decoder_size=512, dict_size=30000)
    model = seq2seq.build(src_dict_dim=30000, trg_dict_dim=30000,
                          embedding_dim=512, encoder_size=512,
                          decoder_size=512)
    seq_len = args.seq_len
    src = [rng.randint(3, 30000, size=(seq_len, 1)).tolist()
           for _ in range(args.batch_size)]
    trg = [rng.randint(3, 30000, size=(seq_len, 1)).tolist()
           for _ in range(args.batch_size)]
    feed = {
        'src_word_id': _lod_feed(src, 'int64'),
        'target_language_word': _lod_feed(trg, 'int64'),
        'target_language_next_word': _lod_feed(trg, 'int64'),
    }
    return model, feed, args.batch_size * seq_len, 'tokens/sec'


def _ctr(args, rng):
    from paddle_tpu.models import ctr
    from paddle_tpu.dataset import ctr as ctr_data
    model = ctr.build()
    feed = {
        'dense': rng.standard_normal(
            (args.batch_size, ctr_data.DENSE_DIM)).astype('float32'),
        'sparse_ids': rng.randint(
            0, ctr_data.SPARSE_DIM,
            (args.batch_size, ctr_data.SPARSE_SLOTS)).astype('int64'),
        'label': rng.randint(0, 2, (args.batch_size, 1)).astype('int64'),
    }
    return model, feed, args.batch_size, 'examples/sec'


def _transformer(args, rng):
    from paddle_tpu.models import transformer
    seq_len = args.seq_len
    model = transformer.build(src_vocab=30000, trg_vocab=30000,
                              max_len=seq_len, n_layer=6, n_head=8,
                              d_model=512, d_ff=2048)
    src = rng.randint(2, 30000, (args.batch_size, seq_len)).astype('int64')
    trg = np.concatenate(
        [np.zeros((args.batch_size, 1), 'int64'), src[:, :-1]], axis=1)
    feed = {'src_ids': src, 'trg_ids': trg, 'lbl_ids': src}
    return model, feed, args.batch_size * seq_len, 'tokens/sec'


MODELS = {
    'mnist': _mnist,
    'resnet': _resnet,
    'vgg': _vgg,
    'stacked_lstm': _stacked_lstm,
    'machine_translation': _machine_translation,
    'transformer': _transformer,
    'ctr': _ctr,
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', choices=sorted(MODELS), default='mnist')
    parser.add_argument('--batch_size', type=int, default=32)
    parser.add_argument('--iterations', type=int, default=10)
    parser.add_argument('--skip_batch_num', type=int, default=2)
    parser.add_argument('--seq_len', type=int, default=32)
    parser.add_argument('--use_conv', action='store_true')
    parser.add_argument('--amp', action='store_true',
                        help='bf16 matmul/conv inputs (TPU MXU format)')
    parser.add_argument('--device', choices=['CPU', 'TPU'], default='TPU')
    args = parser.parse_args()

    rng = np.random.RandomState(0)
    model, feed, examples_per_step, unit = MODELS[args.model](args, rng)
    use_tpu = (args.device == 'TPU' and
               fluid.core.is_compiled_with_tpu())
    place = fluid.TPUPlace() if use_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    scope = fluid.core.Scope()
    if args.iterations < 1:
        raise SystemExit('--iterations must be >= 1')
    with fluid.scope_guard(scope), fluid.amp_guard(args.amp):
        exe.run(model['startup'])
        for _ in range(args.skip_batch_num):
            exe.run(model['main'], feed=feed, fetch_list=[model['loss']])
        t0 = time.time()
        for _ in range(args.iterations):
            loss_v = exe.run(model['main'], feed=feed,
                             fetch_list=[model['loss']])
        elapsed = time.time() - t0
    rate = examples_per_step * args.iterations / elapsed
    print(json.dumps({
        'model': args.model,
        'batch_size': args.batch_size,
        'device': 'TPU' if use_tpu else 'CPU',
        'amp': bool(args.amp),
        'rate': round(rate, 2),
        'unit': unit,
        'last_loss': float(np.asarray(loss_v[0]).flatten()[0]),
    }))


if __name__ == '__main__':
    main()
