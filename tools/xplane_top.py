"""Top device-time ops from a JAX profiler capture (xplane).

The axon tunnel makes wall-clock noisy (±30%/min), but xplane device
slices are chip-truth — this is the instrument that found the round-4
CE-backward convert (13% of step).  Usage:

    import tools.xplane_top as xt
    with xt.capture('/tmp/tracedir'):
        ... run steps ...
    rows = xt.top_ops('/tmp/tracedir')      # [(name, total_us, count)]
    xt.print_top('/tmp/tracedir', n=30)

or from the CLI:  python tools/xplane_top.py /tmp/tracedir [N]
"""

import contextlib
import glob
import os
import re
from collections import defaultdict


@contextlib.contextmanager
def capture(trace_dir):
    import jax
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def _find_xplanes(trace_dir):
    return glob.glob(os.path.join(trace_dir, 'plugins', 'profile', '*',
                                  '*.xplane.pb'))


def device_planes(trace_dir):
    """Yield (plane_name, plane) for accelerator planes in the capture."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    for path in sorted(_find_xplanes(trace_dir), key=os.path.getmtime):
        space = xplane_pb2.XSpace()
        with open(path, 'rb') as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            if ('TPU' in plane.name or 'device' in plane.name.lower()) \
                    and 'host' not in plane.name.lower():
                yield plane.name, plane


def top_ops(trace_dir, merge_fusion_params=True):
    """Aggregate device event durations by event name across all device
    planes.  Returns [(name, total_us, count)] sorted by total desc."""
    totals = defaultdict(lambda: [0.0, 0])
    for _, plane in device_planes(trace_dir):
        for line in plane.lines:
            # 'XLA Ops' carries the per-op device slices; 'Steps'/'XLA
            # Modules' duplicate whole-step spans and 'Async XLA Ops'
            # overlap real compute — both would double-count
            if line.name != 'XLA Ops':
                continue
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                if merge_fusion_params:
                    name = re.sub(r'^%', '', name)
                    name = re.sub(r'[.\-][0-9]+( = .*)?$', '', name)
                totals[name][0] += ev.duration_ps / 1e6
                totals[name][1] += 1
    rows = [(k, v[0], v[1]) for k, v in totals.items()]
    rows.sort(key=lambda r: -r[1])
    return rows


def total_device_us(trace_dir):
    return sum(r[1] for r in top_ops(trace_dir))


def print_top(trace_dir, n=30):
    rows = top_ops(trace_dir)
    total = sum(r[1] for r in rows) or 1.0
    print('%-72s %12s %8s %6s' % ('op', 'total_us', 'count', '%'))
    for name, us, cnt in rows[:n]:
        print('%-72s %12.1f %8d %5.1f%%' %
              (name[:72], us, cnt, 100.0 * us / total))
    print('TOTAL device us: %.1f' % total)


if __name__ == '__main__':
    import sys
    print_top(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 30)
