"""Open-loop serving load harness CLI (ISSUE 8).

Drives a ModelRegistry with a seeded Poisson request stream
(serving.OpenLoopLoadGen) and prints one JSON report line: sustained
req/s, p50/p99/p99.9 latency, GOODPUT (responses inside their
deadline), shed / overload-rejected / late counts, plus the registry's
own metrics snapshot.  Works against synthetic built-in models (the
default — zero setup, runs on CPU or TPU) or a directory of
save_inference_model exports.

Generate traffic (ISSUE 9): ``--generate-frac`` routes that share of
the offered stream to a synthetic generation model's continuous-
batching decode lane (kind='generate' TrafficClass); the report then
carries a ``decode`` block per generation model — decode tokens/s over
the offered window and HOST-SYNCS-PER-TOKEN (device-idling host round
trips the chained decode lane avoids; compare --decode-depth 1 vs 2
to see the pipelining win under open-loop load).

Fleet (ISSUE 17): ``--replicas N`` serves the SAME offered stream
through N replica registries behind ``serving.ReplicaServer`` +
``serving.FleetRouter`` (the resilient, affinity-aware fleet tier) —
the report gains a ``fleet`` block with the router's dispatch /
failover / overload counters and one per-replica block each carrying
that replica's registry view.  Synthetic forward + generate traffic
only (``--model-dir`` and ``--ctr-frac`` stay single-registry).

Parameter servers (ISSUE 19): ``--pservers N`` bypasses the serving
stack and drives the sharded embedding tier directly — ``--requests``
seeded zipfian id batches (``dataset.ctr.zipf_batch``) fetch + push
through a ``ShardedEmbeddingClient`` over N row-range ``PServerShard``
processes; the one-line report carries rows/s, per-shard RPC counters,
and a hard ``bitwise_parity`` check against an identically-driven
single-process ``AsyncSparseEmbedding`` master.

Overload retries (ISSUE 15): ``--retry-overloaded`` honors the typed
``OverloadedError``'s ``retry_after_s`` hint — ONE seeded re-submit
per rejected request, fired between arrivals so the offered stream's
timing is untouched; the report gains ``overload_retries`` and
``retry_success``, so the harness exercises the documented client
contract instead of just recording the hint.

Examples:

    # overload a single synthetic model 3x past its measured capacity,
    # 50ms deadlines, deadline scheduling:
    python tools/load_gen.py --requests 500 --overload 3 --deadline-ms 50

    # absolute rate, two models, mixed priorities, FIFO baseline:
    python tools/load_gen.py --models 2 --rate 400 --scheduling fifo

    # 30% generate traffic through the chained decode lane:
    python tools/load_gen.py --generate-frac 0.3 --rate 50

    # your own exported model dir:
    python tools/load_gen.py --model-dir /models/ranker --rate 100
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_generation(seed, max_len=8, chunk=None):
    """One tiny stepwise NMT decode model (prefill + step programs)
    + its GenerationSpec and scope — the synthetic generate-traffic
    target (the same toy the decode perf gates drive).  ``chunk``
    (ISSUE 14) builds the chunked-prefill program too, for
    --gen-chunk traffic."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import serving
    from paddle_tpu.models import seq2seq
    m = seq2seq.build_step_decode(
        src_dict_dim=50, trg_dict_dim=40, embedding_dim=8,
        encoder_size=16, decoder_size=16, max_len=max_len,
        chunk=chunk)
    m['prefill'].random_seed = seed
    place = (fluid.TPUPlace() if fluid.core.is_compiled_with_tpu()
             else fluid.CPUPlace())
    exe = fluid.Executor(place)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(m['prefill_startup'])
        if chunk is not None:
            exe.run(m['chunk_startup'])
        exe.run(m['step_startup'])
    return m, serving.GenerationSpec.from_model(m), scope


def _build_ctr(seed, vocab):
    """The zipfian-id CTR traffic target (ISSUE 11): a small wide&deep
    CTR inference program (models/ctr) + its scope.  Requests are
    skewed id-batches — zipf mass on a few hot rows, a long tail — the
    sparse-embedding serving shape; the report's ``ctr`` block carries
    rows/s over the offered window."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import ctr as ctr_model
    with fluid.unique_name.guard():
        m = ctr_model.build(sparse_dim=vocab, embed_size=16,
                            hidden_sizes=(32, 16), is_sparse=True)
    m['main'].random_seed = seed
    m['startup'].random_seed = seed
    place = (fluid.TPUPlace() if fluid.core.is_compiled_with_tpu()
             else fluid.CPUPlace())
    exe = fluid.Executor(place)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(m['startup'])
    return m, scope


def _build_synthetic(seed, dim=16, classes=64):
    """One tiny dense scorer program (f32, softmax head) + its scope —
    the same padding-neutral shape the serving perf gates use."""
    import paddle_tpu.fluid as fluid
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data('x', shape=[-1, dim], dtype='float32')
        pooled = fluid.layers.reduce_sum(x, dim=1)
        pred = fluid.layers.fc(pooled, classes, act='softmax')
    place = (fluid.TPUPlace() if fluid.core.is_compiled_with_tpu()
             else fluid.CPUPlace())
    exe = fluid.Executor(place)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    return prog.clone(for_test=True), pred, scope, place


def _run_fleet(args):
    """--replicas N (ISSUE 17): N replica registries — identical
    synthetic weights (same build seeds) — behind ReplicaServer +
    FleetRouter, serving ONE offered stream.  The report keeps the
    loadgen surface (goodput, percentiles, shed/overload counts) and
    gains ``fleet`` (router dispatch/failover/overload counters, per-
    replica dispatch shares) plus one block per replica with that
    registry's own overload/queue view."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu import serving

    if args.model_dir:
        raise SystemExit('--replicas serves the synthetic fleet; '
                         '--model-dir is single-registry only')
    if args.ctr_frac > 0:
        raise SystemExit('--replicas does not combine with --ctr-frac '
                         '(the ctr report block reads single-registry '
                         'engine internals)')

    def _mk_cfg(**extra):
        return serving.ServingConfig(
            max_batch_size=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            scheduling=args.scheduling,
            admit_queue_depth=args.admit_depth,
            admit_queue_age_ms=args.admit_age_ms, **extra)

    dim = 16
    names = ['syn%d' % i for i in range(max(args.models, 1))]
    gen_names = []
    regs = []
    for _ in range(args.replicas):
        reg = serving.ModelRegistry(config=_mk_cfg())
        for i, name in enumerate(names):
            # same seed per model across replicas: identical weights,
            # so any replica answers any request identically
            prog, pred, scope, _ = _build_synthetic(seed=i + 1, dim=dim)
            reg.load(name, program=prog, feed_names=['x'],
                     fetch_list=[pred], scope=scope)
        regs.append(reg)

    def feed_fn(rng, _dim=dim):
        return {'x': rng.rand(args.rows, args.seq,
                              _dim).astype('float32')}

    gen_feed_fn = None
    if args.generate_frac > 0:
        if not (0.0 < args.generate_frac < 1.0):
            raise SystemExit('--generate-frac must be in (0, 1)')
        for reg in regs:
            gm, gspec, gscope = _build_generation(
                seed=args.seed + 1, max_len=args.gen_max_len,
                chunk=args.gen_chunk)
            reg.load('gen0', program=gm['prefill'],
                     feed_names=gm['prefill_feeds'],
                     fetch_list=gm['prefill_fetches'], scope=gscope,
                     generation=gspec, config=_mk_cfg(
                         decode_pipeline_depth=args.decode_depth,
                         prefill_chunk=(gspec.chunk_width
                                        if args.gen_chunk is not None
                                        else None)))
        gen_names.append('gen0')
        lo = 3
        hi = (max(args.gen_prompt_len, lo + 1)
              if args.gen_prompt_len is not None else 9)

        def gen_feed_fn(rng, _lo=lo, _hi=hi):
            l = int(rng.randint(_lo, _hi + 1))
            return {'src_word_id': fluid.create_lod_tensor(
                rng.randint(2, 50, size=(l, 1)).tolist(), [[l]])}

    classes = []
    fwd_weight = max(1.0 - args.generate_frac, 1e-6) / len(names)
    for name in names:
        if args.priority_frac > 0:
            classes.append(serving.TrafficClass(
                feed_fn, model=name,
                weight=fwd_weight * args.priority_frac,
                deadline_ms=args.deadline_ms, priority=1,
                name=name + ':p1'))
        classes.append(serving.TrafficClass(
            feed_fn, model=name,
            weight=fwd_weight * max(1.0 - args.priority_frac, 1e-6),
            deadline_ms=args.deadline_ms, priority=0,
            name=name + ':p0'))
    for name in gen_names:
        classes.append(serving.TrafficClass(
            gen_feed_fn, model=name, kind='generate',
            weight=args.generate_frac, max_len=args.gen_max_len,
            deadline_ms=args.deadline_ms, name=name + ':generate'))

    servers, router = [], None
    try:
        rng = np.random.RandomState(args.seed)
        for reg in regs:
            reg.start()
            # warm every replica's serving signatures DIRECTLY (the
            # router would only warm whichever replica it picked)
            for name in names:
                reg.infer(name, feed_fn(rng), timeout=600)
            for name in gen_names:
                reg.generate(name, gen_feed_fn(rng), timeout=600)
        servers = [serving.ReplicaServer(reg) for reg in regs]
        router = serving.FleetRouter(servers, timeout=600.0)
        t0 = time.time()
        burst = [router.submit(names[i % len(names)], feed_fn(rng))
                 for i in range(16)]
        for f in burst:
            f.result(600)
        capacity = 16 / max(time.time() - t0, 1e-9)
        rate = args.rate if args.rate else capacity * args.overload
        gen = serving.OpenLoopLoadGen(
            router, classes, rate=rate,
            n_requests=None if args.duration else args.requests,
            duration_s=args.duration, seed=args.seed,
            retry_overloaded=args.retry_overloaded)
        report = gen.run()
        report['measured_capacity_req_s'] = round(capacity, 3)
        fleet = router.metrics()
        report['fleet'] = fleet
        report['replicas'] = {}
        for idx, reg in enumerate(regs):
            metrics = reg.metrics()
            block = {
                'dispatches': fleet['replicas'][idx]['dispatches'],
                'overload_rejects': metrics['overload_rejects'],
                'models': {
                    n: {k: metrics['models'][n][k]
                        for k in ('shed', 'queue_depth', 'compiles',
                                  'p50_latency_ms', 'p99_latency_ms')}
                    for n in names + gen_names
                },
            }
            if gen_names:
                block['decode'] = {
                    n: (reg._entry(n).engine.metrics()['decode'] or {})
                    for n in gen_names
                }
            report['replicas'][idx] = block
    finally:
        if router is not None:
            router.close()
        for srv in servers:
            srv.close()
        for reg in regs:
            reg.stop()
    print(json.dumps(report), flush=True)
    return report


def _run_pserver(args):
    """--pservers N (ISSUE 19): drive the sharded parameter-server
    embedding tier directly — fetch_rows + push_grad over a
    ``ShardedEmbeddingClient`` across N row-range ``PServerShard``
    processes, fed the seeded zipfian id stream
    (``dataset.ctr.zipf_batch``, the one shared skew construction).
    The report carries rows/s for the fetch+push loop, the per-shard
    RPC counters, and ``bitwise_parity`` vs an identically-driven
    single-process ``AsyncSparseEmbedding`` master — the tier's
    correctness bar, measured on the way out."""
    import numpy as np
    from paddle_tpu.dataset import ctr as ctr_data
    from paddle_tpu.distributed import (AsyncSparseEmbedding,
                                        PServerShard,
                                        ShardedEmbeddingClient,
                                        shard_row_ranges)

    if args.model_dir or args.ctr_frac > 0 or args.generate_frac > 0 \
            or args.replicas > 1:
        raise SystemExit('--pservers drives the embedding tier '
                         'directly; it does not combine with '
                         '--model-dir/--ctr-frac/--generate-frac/'
                         '--replicas')
    vocab, dim, lr = args.ctr_vocab, 16, 0.05
    batches = max(args.requests, 1)
    rng = np.random.RandomState(args.seed)
    init = np.random.RandomState(args.seed + 1).rand(
        vocab, dim).astype('float32')
    feeds = [ctr_data.zipf_batch(rng, args.rows, vocab,
                                 hot_frac=args.ctr_hot_frac)
             for _ in range(batches)]
    grads = [np.random.RandomState(1000 + i).rand(
        f['sparse_ids'].size, dim).astype('float32')
        for i, f in enumerate(feeds)]

    shards = [PServerShard({'emb': init[lo:hi]}, row_start=lo, lr=lr)
              for lo, hi in shard_row_ranges(vocab, args.pservers)]
    client = ShardedEmbeddingClient([s.endpoint for s in shards])
    rows_seen = 0
    t0 = time.time()
    for f, g in zip(feeds, grads):
        ids = f['sparse_ids'].ravel()
        client.fetch_rows(ids)
        client.push_grad(ids, g)
        rows_seen += ids.size
    client.drain()
    elapsed = max(time.time() - t0, 1e-9)
    sharded_table = client.table()
    rpc = client.metrics()

    # the single-process master, identically driven: parity is part
    # of the report, not a separate test run
    single = AsyncSparseEmbedding(vocab, dim, lr=lr, table=init)
    for f, g in zip(feeds, grads):
        ids = f['sparse_ids'].ravel()
        single.fetch_rows(ids)
        single.push_grad(ids, g)
    single.drain()
    parity = bool(np.array_equal(sharded_table, single.table()))

    report = {
        'pservers': args.pservers,
        'vocab': vocab,
        'embed_dim': dim,
        'batches': batches,
        'rows_per_batch': int(feeds[0]['sparse_ids'].size),
        'rows_per_sec': round(rows_seen / elapsed, 1),
        'pushed': rpc['pushed'],
        'applied': rpc['applied'],
        'bitwise_parity': parity,
        'rpc_calls': sum(m['calls'] for m in rpc['shards']),
        'rpc_retries': sum(m['retries'] for m in rpc['shards']),
        'rpc_failovers': sum(m['failovers'] for m in rpc['shards']),
        'shard_rows': [s.metrics()['rows'] for s in shards],
    }
    client.close()
    for s in shards:
        s.close()
    single.close()
    assert parity, ('sharded tier diverged from the single-process '
                    'master', report)
    print(json.dumps(report), flush=True)
    return report


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('--rate', type=float, default=None,
                   help='offered req/s (Poisson intensity); default: '
                        'measured capacity x --overload')
    p.add_argument('--overload', type=float, default=2.0,
                   help='rate multiplier over measured capacity when '
                        '--rate is not given (default 2.0)')
    p.add_argument('--requests', type=int, default=200)
    p.add_argument('--duration', type=float, default=None,
                   help='offered seconds (overrides --requests when set)')
    p.add_argument('--deadline-ms', type=float, default=None,
                   help='per-request deadline; unset = no deadlines '
                        '(everything counts toward goodput)')
    p.add_argument('--priority-frac', type=float, default=0.0,
                   help='fraction of traffic submitted at priority 1 '
                        '(the rest at 0)')
    p.add_argument('--generate-frac', type=float, default=0.0,
                   help='fraction of traffic routed to a synthetic '
                        'generation model\'s decode lane '
                        '(kind=generate; reports decode tokens/s and '
                        'host-syncs-per-token)')
    p.add_argument('--gen-max-len', type=int, default=8,
                   help='generation budget per generate request')
    p.add_argument('--gen-prompt-len', type=int, default=None,
                   help='LONG-prompt generate traffic (ISSUE 14): '
                        'prompts draw lengths up to this bound '
                        '(default: the short 3..9 mix) — the regime '
                        'where monolithic prefill stalls in-flight '
                        'decodes; pair with --gen-chunk to bound the '
                        'stall')
    p.add_argument('--gen-chunk', type=int, default=None,
                   help='serve generate traffic with CHUNKED prefill '
                        '(ServingConfig prefill_chunk=C, rung-'
                        'quantized); the decode report then carries '
                        'prefill_chunks and the bounded stall gauge')
    p.add_argument('--ctr-frac', type=float, default=0.0,
                   help='fraction of traffic routed to a sparse-'
                        'embedding CTR model as seeded ZIPFIAN '
                        'id-batches (ISSUE 11); the report gains a '
                        'ctr block with rows/s')
    p.add_argument('--ctr-vocab', type=int, default=4096,
                   help='CTR embedding vocab for --ctr-frac traffic')
    p.add_argument('--ctr-hot-frac', type=float, default=None,
                   help='sharpen the CTR id skew (ISSUE 12): this '
                        'fraction of lookups folds into a hot set of '
                        'vocab/16 ids — the hot-row embedding cache '
                        'regime (None keeps the plain zipf stream)')
    p.add_argument('--decode-depth', type=int, default=2,
                   help='decode_pipeline_depth of the generation '
                        'model (1 = per-scan-sync baseline)')
    p.add_argument('--models', type=int, default=1,
                   help='number of synthetic models to mix across')
    p.add_argument('--pservers', type=int, default=0,
                   help='drive the sharded parameter-server embedding '
                        'tier (ISSUE 19): fetch+push --requests seeded '
                        'zipfian batches over N row-range shards and '
                        'report rows/s, RPC counters, and bitwise '
                        'parity vs the single-process master')
    p.add_argument('--replicas', type=int, default=1,
                   help='serve through N replica registries behind '
                        'the fleet router (ISSUE 17); the report '
                        'gains fleet + per-replica blocks')
    p.add_argument('--model-dir', default=None,
                   help='serve this save_inference_model dir instead '
                        'of synthetic models (single feed)')
    p.add_argument('--rows', type=int, default=4,
                   help='rows per request')
    p.add_argument('--seq', type=int, default=12,
                   help='synthetic request trailing extent')
    p.add_argument('--max-batch', type=int, default=16)
    p.add_argument('--max-wait-ms', type=float, default=2.0)
    p.add_argument('--scheduling', choices=['edf', 'fifo'], default='edf')
    p.add_argument('--admit-depth', type=int, default=None,
                   help='overload admission watermark: queue depth')
    p.add_argument('--admit-age-ms', type=float, default=None,
                   help='overload admission watermark: oldest queue age')
    p.add_argument('--retry-overloaded', action='store_true',
                   help='honor the OverloadedError.retry_after_s hint '
                        'with ONE seeded re-submit per rejected '
                        'request (ISSUE 15); the report gains '
                        'overload_retries/retry_success')
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args(argv)

    import numpy as np
    import paddle_tpu.fluid as fluid  # noqa: F401 (registers flags)
    from paddle_tpu import serving

    if args.pservers > 0:
        return _run_pserver(args)
    if args.replicas > 1:
        return _run_fleet(args)

    cfg = serving.ServingConfig(
        max_batch_size=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        scheduling=args.scheduling,
        admit_queue_depth=args.admit_depth,
        admit_queue_age_ms=args.admit_age_ms)
    reg = serving.ModelRegistry(config=cfg)
    names = []
    if args.model_dir:
        reg.load('model', args.model_dir)
        names.append('model')
        feed_name = reg._entry('model').engine._feed_names[0]

        def feed_fn(rng, _dim=None):
            # the exported model declares its own feed shape; fall back
            # to a flat f32 vector when dims are dynamic
            var = (reg._entry('model').engine._program
                   .global_block().vars[feed_name])
            shape = [int(d) if int(d) > 0 else args.seq
                     for d in var.shape]
            shape[0] = args.rows
            return {feed_name: rng.rand(*shape).astype('float32')}
    else:
        dim = 16
        for i in range(max(args.models, 1)):
            name = 'syn%d' % i
            prog, pred, scope, place = _build_synthetic(seed=i + 1,
                                                        dim=dim)
            reg.load(name, program=prog, feed_names=['x'],
                     fetch_list=[pred], scope=scope)
            names.append(name)

        def feed_fn(rng, _dim=dim):
            return {'x': rng.rand(args.rows, args.seq,
                                  _dim).astype('float32')}

    gen_names = []
    if args.generate_frac > 0:
        if not (0.0 < args.generate_frac < 1.0):
            raise SystemExit('--generate-frac must be in (0, 1)')
        gm, gspec, gscope = _build_generation(seed=args.seed + 1,
                                              max_len=args.gen_max_len,
                                              chunk=args.gen_chunk)
        gcfg = serving.ServingConfig(
            max_batch_size=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            scheduling=args.scheduling,
            decode_pipeline_depth=args.decode_depth,
            prefill_chunk=(gspec.chunk_width
                           if args.gen_chunk is not None else None))
        reg.load('gen0', program=gm['prefill'],
                 feed_names=gm['prefill_feeds'],
                 fetch_list=gm['prefill_fetches'], scope=gscope,
                 generation=gspec, config=gcfg)
        gen_names.append('gen0')
        lo = 3
        hi = (max(args.gen_prompt_len, lo + 1)
              if args.gen_prompt_len is not None else 9)

        def gen_feed_fn(rng, _lo=lo, _hi=hi):
            import paddle_tpu.fluid as fluid
            l = int(rng.randint(_lo, _hi + 1))
            return {'src_word_id': fluid.create_lod_tensor(
                rng.randint(2, 50, size=(l, 1)).tolist(), [[l]])}

    ctr_names = []
    if args.ctr_frac > 0:
        if not (0.0 < args.ctr_frac < 1.0) or \
                args.ctr_frac + args.generate_frac >= 1.0:
            raise SystemExit('--ctr-frac must be in (0, 1) and leave a '
                             'forward share with --generate-frac')
        cm, cscope = _build_ctr(seed=args.seed + 2,
                                vocab=args.ctr_vocab)
        reg.load('ctr0', program=cm['test'], feed_names=cm['feeds'],
                 fetch_list=[cm['prediction']], scope=cscope)
        ctr_names.append('ctr0')

        def ctr_feed_fn(rng, _v=args.ctr_vocab, _rows=args.rows,
                        _hot=args.ctr_hot_frac):
            from paddle_tpu.dataset import ctr as ctr_data
            return ctr_data.zipf_batch(rng, _rows, _v, hot_frac=_hot)

    classes = []
    # the forward share splits across the forward models: per-model
    # weights must sum to (1 - generate_frac - ctr_frac) or the special
    # classes' documented shares of the offered stream dilute as
    # --models grows
    fwd_weight = max(1.0 - args.generate_frac - args.ctr_frac, 1e-6) \
        / max(len(names), 1)
    for name in names:
        if args.priority_frac > 0:
            classes.append(serving.TrafficClass(
                feed_fn, model=name,
                weight=fwd_weight * args.priority_frac,
                deadline_ms=args.deadline_ms, priority=1,
                name=name + ':p1'))
        classes.append(serving.TrafficClass(
            feed_fn, model=name,
            weight=fwd_weight * max(1.0 - args.priority_frac, 1e-6),
            deadline_ms=args.deadline_ms, priority=0,
            name=name + ':p0'))
    for name in gen_names:
        classes.append(serving.TrafficClass(
            gen_feed_fn, model=name, kind='generate',
            weight=args.generate_frac, max_len=args.gen_max_len,
            deadline_ms=args.deadline_ms, name=name + ':generate'))
    for name in ctr_names:
        classes.append(serving.TrafficClass(
            ctr_feed_fn, model=name, weight=args.ctr_frac,
            deadline_ms=args.deadline_ms, name=name + ':ctr'))

    with reg:
        # warm every model's serving signature, then measure capacity
        # with a short closed burst (the rate anchor for --overload)
        rng = np.random.RandomState(args.seed)
        for name in names:
            reg.infer(name, feed_fn(rng), timeout=600)
        for name in gen_names:
            # warm the prefill rungs + the decode-scan executable
            reg.generate(name, gen_feed_fn(rng), timeout=600)
        for name in ctr_names:
            reg.infer(name, ctr_feed_fn(rng), timeout=600)
        # decode baseline AFTER warmup: the report's tokens/s and
        # host-syncs-per-token must cover the offered stream only
        decode_base = {
            name: dict(reg._entry(name).engine.metrics()['decode']
                       or {})
            for name in gen_names
        }
        ctr_base = {
            name: int(reg._entry(name).engine.metrics()['rows'])
            for name in ctr_names
        }
        t0 = time.time()
        burst = []
        deadline = time.time() + 60.0
        for i in range(16):
            while True:
                try:
                    burst.append(reg.submit(names[i % len(names)],
                                            feed_fn(rng)))
                    break
                except serving.OverloadedError as e:
                    # a tight --admit-depth can reject the closed
                    # calibration burst itself: under
                    # --retry-overloaded honor the hint (the
                    # documented client contract), bounded by a
                    # deadline so a wedged registry surfaces the
                    # typed error instead of hanging the CLI
                    if not args.retry_overloaded or \
                            time.time() >= deadline:
                        raise
                    time.sleep(max(e.retry_after_s, 1e-3))
        for f in burst:
            f.result(600)
        capacity = 16 / max(time.time() - t0, 1e-9)
        rate = args.rate if args.rate else capacity * args.overload
        gen = serving.OpenLoopLoadGen(
            reg, classes, rate=rate,
            # --duration overrides --requests (which always has its
            # default); the loadgen only reads duration_s when
            # n_requests is None
            n_requests=None if args.duration else args.requests,
            duration_s=args.duration, seed=args.seed,
            retry_overloaded=args.retry_overloaded)
        report = gen.run()
        report['measured_capacity_req_s'] = round(capacity, 3)
        metrics = reg.metrics()
        report['registry'] = {
            'overload_rejects': metrics['overload_rejects'],
            'models': {
                n: {k: metrics['models'][n][k]
                    for k in ('shed', 'queue_depth', 'compiles',
                              'p50_latency_ms', 'p99_latency_ms')}
                for n in names + gen_names + ctr_names
            },
        }
        if ctr_names:
            # zipfian CTR traffic deliverable (ISSUE 11): embedding
            # id-rows served per second over the measured window
            report['ctr'] = {}
            for name in ctr_names:
                rows = int(reg._entry(name).engine.metrics()['rows']) \
                    - ctr_base[name]
                report['ctr'][name] = {
                    'rows': rows,
                    'rows_per_s': round(
                        rows / max(report['elapsed_s'], 1e-9), 3),
                    'vocab': args.ctr_vocab,
                }
        if gen_names:
            # decode-lane deliverables (ISSUE 9): tokens/s over the
            # measured window and host-syncs-per-token — the number
            # the chained lane (decode_pipeline_depth >= 2) drives
            # toward zero vs one-per-scan on the synced baseline
            report['decode'] = {}
            for name in gen_names:
                d = reg._entry(name).engine.metrics()['decode'] or {}
                base = decode_base.get(name) or {}
                tokens = (d.get('tokens') or 0) - \
                    (base.get('tokens') or 0)
                syncs = (d.get('host_syncs') or 0) - \
                    (base.get('host_syncs') or 0)
                report['decode'][name] = {
                    'tokens': tokens,
                    'tokens_per_s': round(
                        tokens / max(report['elapsed_s'], 1e-9), 3),
                    'host_syncs': syncs,
                    'host_syncs_per_token': (
                        round(syncs / tokens, 4) if tokens else None),
                    'chain_flushes': (d.get('chain_flushes') or 0) -
                    (base.get('chain_flushes') or 0),
                    'decode_pipeline_depth': args.decode_depth,
                    # chunked prefill (ISSUE 14): chunk dispatches over
                    # the measured window + the cumulative inter-token
                    # stall gauge (worker-cycle units; bounded by one
                    # chunk under --gen-chunk, by the longest prompt
                    # without it)
                    'prefill_chunks': (d.get('prefill_chunks') or 0) -
                    (base.get('prefill_chunks') or 0),
                    'prefill_chunk': args.gen_chunk,
                    'max_decode_stall_cycles':
                        d.get('max_decode_stall_cycles'),
                }
    reg.stop()
    print(json.dumps(report), flush=True)
    return report


if __name__ == '__main__':
    main()
