"""Open-loop serving load harness CLI (ISSUE 8).

Drives a ModelRegistry with a seeded Poisson request stream
(serving.OpenLoopLoadGen) and prints one JSON report line: sustained
req/s, p50/p99/p99.9 latency, GOODPUT (responses inside their
deadline), shed / overload-rejected / late counts, plus the registry's
own metrics snapshot.  Works against synthetic built-in models (the
default — zero setup, runs on CPU or TPU) or a directory of
save_inference_model exports.

Examples:

    # overload a single synthetic model 3x past its measured capacity,
    # 50ms deadlines, deadline scheduling:
    python tools/load_gen.py --requests 500 --overload 3 --deadline-ms 50

    # absolute rate, two models, mixed priorities, FIFO baseline:
    python tools/load_gen.py --models 2 --rate 400 --scheduling fifo

    # your own exported model dir:
    python tools/load_gen.py --model-dir /models/ranker --rate 100
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_synthetic(seed, dim=16, classes=64):
    """One tiny dense scorer program (f32, softmax head) + its scope —
    the same padding-neutral shape the serving perf gates use."""
    import paddle_tpu.fluid as fluid
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data('x', shape=[-1, dim], dtype='float32')
        pooled = fluid.layers.reduce_sum(x, dim=1)
        pred = fluid.layers.fc(pooled, classes, act='softmax')
    place = (fluid.TPUPlace() if fluid.core.is_compiled_with_tpu()
             else fluid.CPUPlace())
    exe = fluid.Executor(place)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    return prog.clone(for_test=True), pred, scope, place


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument('--rate', type=float, default=None,
                   help='offered req/s (Poisson intensity); default: '
                        'measured capacity x --overload')
    p.add_argument('--overload', type=float, default=2.0,
                   help='rate multiplier over measured capacity when '
                        '--rate is not given (default 2.0)')
    p.add_argument('--requests', type=int, default=200)
    p.add_argument('--duration', type=float, default=None,
                   help='offered seconds (overrides --requests when set)')
    p.add_argument('--deadline-ms', type=float, default=None,
                   help='per-request deadline; unset = no deadlines '
                        '(everything counts toward goodput)')
    p.add_argument('--priority-frac', type=float, default=0.0,
                   help='fraction of traffic submitted at priority 1 '
                        '(the rest at 0)')
    p.add_argument('--models', type=int, default=1,
                   help='number of synthetic models to mix across')
    p.add_argument('--model-dir', default=None,
                   help='serve this save_inference_model dir instead '
                        'of synthetic models (single feed)')
    p.add_argument('--rows', type=int, default=4,
                   help='rows per request')
    p.add_argument('--seq', type=int, default=12,
                   help='synthetic request trailing extent')
    p.add_argument('--max-batch', type=int, default=16)
    p.add_argument('--max-wait-ms', type=float, default=2.0)
    p.add_argument('--scheduling', choices=['edf', 'fifo'], default='edf')
    p.add_argument('--admit-depth', type=int, default=None,
                   help='overload admission watermark: queue depth')
    p.add_argument('--admit-age-ms', type=float, default=None,
                   help='overload admission watermark: oldest queue age')
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args(argv)

    import numpy as np
    import paddle_tpu.fluid as fluid  # noqa: F401 (registers flags)
    from paddle_tpu import serving

    cfg = serving.ServingConfig(
        max_batch_size=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        scheduling=args.scheduling,
        admit_queue_depth=args.admit_depth,
        admit_queue_age_ms=args.admit_age_ms)
    reg = serving.ModelRegistry(config=cfg)
    names = []
    if args.model_dir:
        reg.load('model', args.model_dir)
        names.append('model')
        feed_name = reg._entry('model').engine._feed_names[0]

        def feed_fn(rng, _dim=None):
            # the exported model declares its own feed shape; fall back
            # to a flat f32 vector when dims are dynamic
            var = (reg._entry('model').engine._program
                   .global_block().vars[feed_name])
            shape = [int(d) if int(d) > 0 else args.seq
                     for d in var.shape]
            shape[0] = args.rows
            return {feed_name: rng.rand(*shape).astype('float32')}
    else:
        dim = 16
        for i in range(max(args.models, 1)):
            name = 'syn%d' % i
            prog, pred, scope, place = _build_synthetic(seed=i + 1,
                                                        dim=dim)
            reg.load(name, program=prog, feed_names=['x'],
                     fetch_list=[pred], scope=scope)
            names.append(name)

        def feed_fn(rng, _dim=dim):
            return {'x': rng.rand(args.rows, args.seq,
                                  _dim).astype('float32')}

    classes = []
    for name in names:
        if args.priority_frac > 0:
            classes.append(serving.TrafficClass(
                feed_fn, model=name, weight=args.priority_frac,
                deadline_ms=args.deadline_ms, priority=1,
                name=name + ':p1'))
        classes.append(serving.TrafficClass(
            feed_fn, model=name,
            weight=max(1.0 - args.priority_frac, 1e-6),
            deadline_ms=args.deadline_ms, priority=0,
            name=name + ':p0'))

    with reg:
        # warm every model's serving signature, then measure capacity
        # with a short closed burst (the rate anchor for --overload)
        rng = np.random.RandomState(args.seed)
        for name in names:
            reg.infer(name, feed_fn(rng), timeout=600)
        t0 = time.time()
        burst = [reg.submit(names[i % len(names)], feed_fn(rng))
                 for i in range(16)]
        for f in burst:
            f.result(600)
        capacity = 16 / max(time.time() - t0, 1e-9)
        rate = args.rate if args.rate else capacity * args.overload
        gen = serving.OpenLoopLoadGen(
            reg, classes, rate=rate,
            # --duration overrides --requests (which always has its
            # default); the loadgen only reads duration_s when
            # n_requests is None
            n_requests=None if args.duration else args.requests,
            duration_s=args.duration, seed=args.seed)
        report = gen.run()
        report['measured_capacity_req_s'] = round(capacity, 3)
        metrics = reg.metrics()
        report['registry'] = {
            'overload_rejects': metrics['overload_rejects'],
            'models': {
                n: {k: metrics['models'][n][k]
                    for k in ('shed', 'queue_depth', 'compiles',
                              'p50_latency_ms', 'p99_latency_ms')}
                for n in names
            },
        }
    reg.stop()
    print(json.dumps(report), flush=True)
    return report


if __name__ == '__main__':
    main()
