"""Generate API.spec: the pinned public Python API surface.

Reference: tools/diff_api.py + paddle/fluid/API.spec — CI fails when a
public signature changes without updating the spec.  Run:

    python tools/gen_api_spec.py > paddle_tpu/API.spec
"""

import inspect
import sys


def _spec_of(fn):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return '(unavailable)'
    return str(sig)


def _walk(prefix, mod, names):
    lines = []
    for name in names:
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        full = '%s.%s' % (prefix, name)
        if inspect.isclass(obj):
            lines.append('%s.__init__ %s' % (full, _spec_of(obj.__init__)))
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith('_'):
                    continue
                if callable(meth):
                    lines.append('%s.%s %s' % (full, mname, _spec_of(meth)))
        elif callable(obj):
            lines.append('%s %s' % (full, _spec_of(obj)))
    return lines


def generate():
    import paddle_tpu.fluid as fluid
    import paddle_tpu.serving as serving

    lines = []
    lines += _walk('paddle_tpu.serving', serving,
                   sorted(serving.__all__))
    lines += _walk('paddle_tpu.fluid.layers', fluid.layers,
                   sorted(fluid.layers.__all__))
    lines += _walk('paddle_tpu.fluid.optimizer', fluid.optimizer,
                   sorted(fluid.optimizer.__all__))
    lines += _walk('paddle_tpu.fluid', fluid, [
        'Executor', 'ParallelExecutor', 'Program', 'Operator', 'Variable',
        'Parameter', 'DataFeeder', 'DistributeTranspiler',
        'DistributeTranspilerConfig', 'InferenceTranspiler', 'Trainer',
        'Inferencer', 'CheckpointConfig', 'BeginEpochEvent',
        'EndEpochEvent', 'BeginStepEvent', 'EndStepEvent', 'CPUPlace',
        'TPUPlace', 'CUDAPlace', 'CUDAPinnedPlace', 'LoDTensor',
        'LoDTensorArray', 'Scope', 'ParamAttr', 'WeightNormParamAttr',
        'ExecutionStrategy', 'BuildStrategy', 'scope_guard',
        'program_guard', 'name_scope', 'append_backward', 'get_var',
        'global_scope', 'create_lod_tensor', 'create_random_int_lodtensor',
        'default_main_program', 'default_startup_program',
        'memory_optimize', 'release_memory', 'Go', 'Select', 'make_channel',
        'channel_send', 'channel_recv', 'channel_close',
    ])
    lines += _walk('paddle_tpu.fluid.dataflow', fluid.dataflow,
                   sorted(fluid.dataflow.__all__))
    lines += _walk('paddle_tpu.fluid.trace', fluid.trace,
                   sorted(fluid.trace.__all__))
    lines += _walk('paddle_tpu.fluid.io', fluid.io, sorted(
        n for n in fluid.io.__all__ if not n.startswith('_')))
    lines += _walk('paddle_tpu.fluid.metrics', fluid.metrics, [
        'Accuracy', 'Auc', 'ChunkEvaluator', 'CompositeMetric',
        'DetectionMAP', 'EditDistance', 'Precision', 'Recall',
    ])
    lines += _walk('paddle_tpu.fluid.nets', fluid.nets,
                   sorted(fluid.nets.__all__))
    lines += _walk('paddle_tpu.fluid.initializer', fluid.initializer, [
        'Constant', 'Uniform', 'Normal', 'Xavier', 'MSRA', 'Bilinear',
        'ConstantInitializer', 'UniformInitializer', 'NormalInitializer',
        'XavierInitializer', 'MSRAInitializer', 'BilinearInitializer',
        'force_init_on_cpu', 'init_on_cpu',
    ])
    lines += _walk('paddle_tpu.fluid.regularizer', fluid.regularizer, [
        'L1Decay', 'L2Decay', 'L1DecayRegularizer', 'L2DecayRegularizer',
    ])
    lines += _walk('paddle_tpu.fluid.clip', fluid.clip, [
        'ErrorClipByValue', 'GradientClipByValue', 'GradientClipByNorm',
        'GradientClipByGlobalNorm',
    ])
    lines += _walk('paddle_tpu.fluid.profiler', fluid.profiler, [
        'profiler', 'cuda_profiler', 'reset_profiler', 'start_profiler',
        'stop_profiler',
    ])
    lines += _walk('paddle_tpu.fluid.unique_name', fluid.unique_name, [
        'generate', 'guard', 'switch',
    ])
    lines += _walk('paddle_tpu.fluid.backward', fluid.backward, [
        'append_backward', 'calc_gradient',
    ])
    lines += _walk('paddle_tpu.fluid.transpiler', fluid.transpiler, [
        'DistributeTranspiler', 'DistributeTranspilerConfig',
        'InferenceTranspiler', 'HashName', 'RoundRobin', 'memory_optimize',
        'release_memory',
    ])
    lines += _walk('paddle_tpu.fluid.contrib', fluid.contrib, [
        'InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder',
        'memory_usage',
    ])
    lines += _walk('paddle_tpu.fluid.recordio_writer', fluid.recordio_writer,
                   ['convert_reader_to_recordio_file',
                    'convert_reader_to_recordio_files'])
    # the distributed runtime surface (ISSUE 12: the two-tier embedding
    # cache lives here next to its AsyncSparseEmbedding host tier;
    # ISSUE 13: the elastic job + its checkpoint store and the master's
    # membership/snapshot doors; ISSUE 15: the resilient transport
    # lane + the fault-injection seam + snapshot replication; ISSUE 17:
    # the transport generalized into a service-agnostic substrate —
    # the Master* error names are back-compat aliases; ISSUE 19: the
    # parameter-server embedding tier — sharded row-range pservers
    # behind that substrate)
    import paddle_tpu.distributed as distributed
    lines += _walk('paddle_tpu.distributed', distributed, [
        'AsyncSparseEmbedding', 'AsyncSparseClosedError',
        'CachedEmbeddingTable', 'EmbedCacheCapacityError',
        'optimizer_accumulator_vars',
        'ElasticTrainJob', 'AsyncShardedCheckpoint',
        'CheckpointWriteError', 'ElasticJobError',
        'Master', 'MasterServer', 'MasterClient',
        'ResilientMasterClient', 'ResilientServiceClient',
        'RetryPolicy', 'ServiceServer', 'DedupWindow',
        'MasterUnavailableError', 'MasterProtocolError',
        'ServiceUnavailableError', 'ServiceProtocolError',
        'FaultInjector', 'InjectedFault', 'SnapshotReplica',
        'PServerShard', 'ShardedEmbeddingClient',
        'shard_row_ranges', 'sharded_cache_from_scope',
    ])
    return sorted(set(lines))


if __name__ == '__main__':
    sys.stdout.write('\n'.join(generate()) + '\n')
