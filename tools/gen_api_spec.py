"""Generate API.spec: the pinned public Python API surface.

Reference: tools/diff_api.py + paddle/fluid/API.spec — CI fails when a
public signature changes without updating the spec.  Run:

    python tools/gen_api_spec.py > paddle_tpu/API.spec
"""

import inspect
import sys


def _spec_of(fn):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return '(unavailable)'
    return str(sig)


def _walk(prefix, mod, names):
    lines = []
    for name in names:
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        full = '%s.%s' % (prefix, name)
        if inspect.isclass(obj):
            lines.append('%s.__init__ %s' % (full, _spec_of(obj.__init__)))
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith('_'):
                    continue
                if callable(meth):
                    lines.append('%s.%s %s' % (full, mname, _spec_of(meth)))
        elif callable(obj):
            lines.append('%s %s' % (full, _spec_of(obj)))
    return lines


def generate():
    import paddle_tpu.fluid as fluid

    lines = []
    lines += _walk('paddle_tpu.fluid.layers', fluid.layers,
                   sorted(fluid.layers.__all__))
    lines += _walk('paddle_tpu.fluid.optimizer', fluid.optimizer,
                   sorted(fluid.optimizer.__all__))
    lines += _walk('paddle_tpu.fluid', fluid, [
        'Executor', 'ParallelExecutor', 'Program', 'DataFeeder',
        'DistributeTranspiler', 'Trainer', 'Inferencer', 'scope_guard',
        'program_guard', 'append_backward', 'Go', 'Select', 'make_channel',
        'channel_send', 'channel_recv', 'channel_close',
    ])
    lines += _walk('paddle_tpu.fluid.io', fluid.io, sorted(
        n for n in fluid.io.__all__ if not n.startswith('_')))
    lines += _walk('paddle_tpu.fluid.metrics', fluid.metrics, [
        'Accuracy', 'Auc', 'ChunkEvaluator', 'CompositeMetric',
        'DetectionMAP', 'EditDistance', 'Precision', 'Recall',
    ])
    lines += _walk('paddle_tpu.fluid.nets', fluid.nets,
                   sorted(fluid.nets.__all__))
    return sorted(set(lines))


if __name__ == '__main__':
    sys.stdout.write('\n'.join(generate()) + '\n')
