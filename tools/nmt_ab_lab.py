"""Same-process A/B: NMT train step with FLAGS_fused_lstm never vs auto.

Cross-process NMT numbers on the axon dev tunnel are noise (observed
±30% minute-to-minute for dispatch-heavy steps), so — like
tools/perf_gate.py — both variants are built, compiled, and timed in ONE
process with interleaved timing blocks; only the ratio is meaningful.

Run: python tools/nmt_ab_lab.py
Prints one JSON line: ms/step per variant per block, plus the
fused/scan speedup ratio from the best (min) block of each.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_and_run():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import flags
    from paddle_tpu.models import seq2seq

    batch, seq_len, dict_dim, dim = 512, 32, 30000, 512
    rng = np.random.RandomState(0)

    def lod(rows):
        return fluid.create_lod_tensor(rows, [[len(r) for r in rows]])

    src = [rng.randint(3, dict_dim, size=(seq_len, 1)).tolist()
           for _ in range(batch)]
    trg = [rng.randint(3, dict_dim, size=(seq_len, 1)).tolist()
           for _ in range(batch)]
    feed = {'src_word_id': lod(src), 'target_language_word': lod(trg),
            'target_language_next_word': lod(trg)}

    variants = {}
    for name, mode in [('scan', 'never'), ('fused', 'auto')]:
        flags.FLAGS.fused_lstm = mode
        model = seq2seq.build(src_dict_dim=dict_dim, trg_dict_dim=dict_dim,
                              embedding_dim=dim, encoder_size=dim,
                              decoder_size=dim)
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.core.Scope()
        variants[name] = (exe, scope, model)
        with fluid.scope_guard(scope), fluid.amp_guard(True):
            exe.run(model['startup'])
            # compile + warm
            exe.run(model['main'], feed=feed, fetch_list=[model['loss']])
            exe.run(model['main'], feed=feed, fetch_list=[])

    def timed_block(name, steps=12):
        exe, scope, model = variants[name]
        with fluid.scope_guard(scope), fluid.amp_guard(True):
            # sync point so the previous variant's queue drains first
            exe.run(model['main'], feed=feed, fetch_list=[model['loss']])
            t0 = time.time()
            for _ in range(steps - 1):
                exe.run(model['main'], feed=feed, fetch_list=[])
            v = exe.run(model['main'], feed=feed, fetch_list=[model['loss']])
            el = time.time() - t0
        assert np.isfinite(float(np.asarray(v[0]).flatten()[0]))
        return el / steps * 1000.0

    blocks = {'scan': [], 'fused': []}
    for _ in range(3):
        for name in ('scan', 'fused'):
            blocks[name].append(round(timed_block(name), 2))

    best = {k: min(v) for k, v in blocks.items()}
    tok = batch * seq_len
    print(json.dumps({
        'blocks_ms': blocks,
        'best_ms': best,
        'tokens_per_sec': {k: round(tok / (m / 1000.0), 1)
                           for k, m in best.items()},
        'fused_over_scan': round(best['scan'] / best['fused'], 4),
    }), flush=True)


if __name__ == '__main__':
    build_and_run()
